"""Bucketed frame batching: static shapes for the serving path.

Requests arrive one frame at a time; jit compiles one program per distinct
input shape.  Serving therefore quantizes every dispatch to a fixed bucket
list (``RansacConfig.frame_buckets``): the dispatcher picks the smallest
bucket that holds the pending frames and pads the tail, so the number of
compiled programs is bounded by ``len(frame_buckets)`` no matter how
traffic arrives (the compile-once property is pinned by
tests/test_serve.py's cache-miss counter).

Two invariants make padding safe:

- **Lane independence**: the frames-major entry points are ``vmap``s of the
  per-frame pipeline, so a padded lane cannot perturb a real frame's
  result — selection and refine are per-lane; there is no cross-frame
  reduction.  Pad content is the last real frame repeated (well-conditioned
  by construction), but even degenerate pad data only produces finite
  garbage in its own discarded lane (the utils.num total-function
  discipline).
- **Bucket invariance, bitwise**: XLA specializes a collapsed (B=1) batch
  axis differently enough to change float results, while every width >= 2
  compiles to bit-identical per-lane programs (measured on CPU across
  widths 2..64 for both the dsac and esac paths).  Every dispatch therefore
  carries at least ``MIN_LANES`` physical lanes — a single-frame dispatch
  pads to 2 — so a request's result is bit-identical no matter which bucket
  it rides.  The cost is one wasted lane on bucket-1 dispatches, recorded
  honestly as ``physical_lanes`` in the serve bench artifact.
"""

from __future__ import annotations

import threading

import numpy as np

from esac_tpu.serve.slo import ConfigError

# Smallest physical frame-batch any dispatch runs at (see module docstring).
MIN_LANES = 2


def pick_bucket(n: int, buckets: tuple[int, ...]) -> int:
    """Smallest bucket >= n.  ``n`` above the largest bucket is a planning
    error — :func:`plan_dispatches` splits bulk requests first."""
    if n < 1:
        raise ConfigError(f"need at least one frame, got {n}")
    for b in sorted(set(buckets)):
        if b >= n:
            return b
    raise ConfigError(f"{n} frames exceed the largest bucket {max(buckets)}")


def _lanes(chunks: list[int], buckets: tuple[int, ...]) -> int:
    """Total physical lanes a chunk list costs after bucket padding."""
    return sum(max(pick_bucket(c, buckets), MIN_LANES) for c in chunks)


def _plan_tail(rem: int, buckets: tuple[int, ...]) -> list[int]:
    """Plan the sub-largest-bucket tail: either ONE padded dispatch, or the
    largest fitting bucket plus a recursively planned remainder — whichever
    costs fewer physical lanes (padded compute is real compute); ties go to
    fewer dispatches (each dispatch pays the serial chain's op-latency
    floor, the very cost this subsystem amortizes).  E.g. with buckets
    (1, 4, 16, 64): 17 -> [16, 1] (18 lanes, not 64), 5 -> [4, 1], but
    63 -> [63] (one 64-lane dispatch beats [16,16,16,15]'s four)."""
    single = [rem]
    fit = [b for b in sorted(set(buckets)) if b <= rem]
    if not fit or rem in fit:
        return single
    split = [fit[-1]] + _plan_tail(rem - fit[-1], buckets)
    if _lanes(split, buckets) < _lanes(single, buckets):
        return split
    return single


def plan_dispatches(n: int, buckets: tuple[int, ...]) -> list[int]:
    """Split ``n`` frames into per-dispatch valid-frame counts: full
    largest-bucket dispatches, then a minimal-waste tail plan
    (:func:`_plan_tail`).  Returns counts summing to ``n``; each count is
    padded up by the caller via :func:`pick_bucket`."""
    if n < 1:
        raise ConfigError(f"need at least one frame, got {n}")
    big = max(buckets)
    plan = [big] * (n // big)
    rem = n - big * len(plan)
    if rem:
        plan += _plan_tail(rem, buckets)
    return plan


def _pad_leaf(x, extra: int):
    """Append ``extra`` copies of the last frame along axis 0.  numpy leaves
    stay on host (staging assembles there); jax arrays — typed PRNG keys
    included — pad with jnp so the dtype survives."""
    if extra == 0:
        return x
    if isinstance(x, np.ndarray):
        return np.concatenate([x] + [x[-1:]] * extra, axis=0)
    import jax.numpy as jnp

    return jnp.concatenate([x] + [x[-1:]] * extra, axis=0)


def stack_frames(frames: list[dict]) -> dict:
    """Stack per-frame trees (dicts of arrays/scalars) along a new leading
    frame axis.  numpy-stackable leaves stack on host; jax-typed leaves
    (PRNG keys) via jnp."""
    out = {}
    for name in frames[0]:
        leaves = [fr[name] for fr in frames]
        try:
            out[name] = np.stack([np.asarray(v) for v in leaves])
        except (TypeError, ValueError):
            import jax.numpy as jnp

            out[name] = jnp.stack(leaves)
    return out


def pad_batch(batch: dict, bucket: int) -> tuple[dict, int]:
    """Pad a frame-stacked tree up to ``max(bucket, MIN_LANES)`` physical
    lanes by repeating the last real frame.  Returns (padded tree,
    n_valid); results beyond ``n_valid`` are padding and must be dropped.
    """
    n_valid = len(next(iter(batch.values())))
    lanes = max(bucket, MIN_LANES)
    if n_valid > bucket:
        raise ConfigError(f"{n_valid} frames do not fit bucket {bucket}")
    extra = lanes - n_valid
    return {k: _pad_leaf(v, extra) for k, v in batch.items()}, n_valid


def _stage_leaf_slow(leaves: list, lanes: int):
    """One leaf through the allocation path: exactly the
    :func:`stack_frames` + :func:`_pad_leaf` composition (the staging
    cache's bit-identity fallback for leaves its buffers cannot hold —
    typed PRNG keys, mixed dtypes)."""
    try:
        x = np.stack([np.asarray(v) for v in leaves])
    except (TypeError, ValueError):
        import jax.numpy as jnp

        x = jnp.stack(leaves)
    return _pad_leaf(x, lanes - len(leaves))


class _BufferPool:
    """A fixed rotation of preallocated staging buffers (one shape/dtype)."""

    __slots__ = ("bufs", "i")

    def __init__(self, bufs: list[np.ndarray]):
        self.bufs = bufs
        self.i = 0

    def take(self) -> np.ndarray:
        buf = self.bufs[self.i]
        self.i = (self.i + 1) % len(self.bufs)
        return buf


class StagingCache:
    """Pooled staging: the zero-allocation fast path of
    ``pad_batch(stack_frames(frames), bucket)``.

    The dispatch hot path used to rebuild its padded host batch from
    scratch every dispatch — per-leaf ``np.stack`` allocations plus a
    ``np.concatenate`` for the pad tail.  This cache keeps per-thread
    pools of preallocated ``(lanes, *frame_shape)`` numpy buffers keyed
    by (leaf name, lanes, dtype, shape): staging becomes row copies into
    an existing buffer and a broadcast fill of the pad tail.  Leaves the
    buffers cannot hold bit-exactly — typed PRNG keys (not
    numpy-convertible), a dtype/shape drift mid-stream (``np.stack``
    would promote; a buffer write would silently cast) — fall back to
    :func:`_stage_leaf_slow`, the verbatim old composition, per leaf per
    call.  The result is bit-identical to ``pad_batch(stack_frames(..))``
    in every case (pinned by tests/test_serve.py).

    **Aliasing discipline** (why ``depth`` exists and must be >= 2): on
    the CPU backend ``jax.device_put`` ZERO-COPIES — the device array
    aliases the staging buffer — so a buffer may only be rewritten once
    the dispatch that staged from it has completed.  Every dispatch path
    runs ``block_until_ready`` before its thread stages again, and the
    double-buffered ``infer_many`` overlaps at most ONE staging with one
    in-flight dispatch, so a rotation of two buffers is exactly
    sufficient: the buffer reused at dispatch N was staged at N-2, whose
    compute the N-1 boundary already synced.  Pools are thread-local
    (``threading.local``), which also isolates a watchdog-replaced
    worker from a predecessor wedged mid-dispatch on the same lane — no
    lock, no lock-graph node (R12), nothing shared to race (R10).

    **R8 (donated buffers)**: these host templates never occupy a
    donated position.  On accelerators ``device_put`` copies host->HBM,
    so the donated operand is the device copy; on CPU the registry entry
    points do not donate at all (donation is accelerator-only).  The
    pooled buffer is therefore never the buffer XLA writes into.
    """

    def __init__(self, depth: int = 2):
        if depth < 2:
            raise ConfigError(
                f"staging depth {depth} < 2: device_put may alias the "
                "staging buffer (CPU zero-copy), so the buffer feeding an "
                "in-flight dispatch must never be the next one rewritten"
            )
        self._depth = depth
        self._tls = threading.local()

    def stage(self, frames: list[dict], bucket: int) -> tuple[dict, int]:
        """``pad_batch(stack_frames(frames), bucket)``, bit-identical,
        through the per-thread buffer pools.  Returns (tree, n_valid).
        The returned tree aliases pooled buffers: consume it (device_put)
        before this thread stages ``depth`` more batches."""
        n_valid = len(frames)
        lanes = max(bucket, MIN_LANES)
        if n_valid > bucket:
            raise ConfigError(f"{n_valid} frames do not fit bucket {bucket}")
        pools = getattr(self._tls, "pools", None)
        if pools is None:
            pools = self._tls.pools = {}
        out = {}
        for name in frames[0]:
            leaves = [fr[name] for fr in frames]
            buf = None
            try:
                row = np.asarray(leaves[0])
            except (TypeError, ValueError):
                row = None  # not numpy-convertible (typed PRNG keys)
            if row is not None:
                key = (name, lanes, row.dtype.str, row.shape)
                pool = pools.get(key)
                if pool is None:
                    pool = pools[key] = _BufferPool([
                        np.empty((lanes,) + row.shape, row.dtype)
                        for _ in range(self._depth)
                    ])
                buf = pool.take()
                buf[0] = row
                for j in range(1, n_valid):
                    try:
                        a = np.asarray(leaves[j])
                    except (TypeError, ValueError):
                        buf = None
                        break
                    if a.dtype != buf.dtype or a.shape != buf.shape[1:]:
                        buf = None  # np.stack would promote; don't cast
                        break
                    buf[j] = a
            if buf is None:
                out[name] = _stage_leaf_slow(leaves, lanes)
            else:
                if n_valid < lanes:
                    buf[n_valid:] = buf[n_valid - 1]
                out[name] = buf
        return out, n_valid

    def unalias(self, arrays: list) -> list:
        """Copy every host result array that may alias one of this
        thread's pooled staging buffers.

        A compiled program that passes an input straight through to an
        output (echo fields, request keys) returns an array that — via
        the CPU zero-copy chain device_put -> execute -> np.asarray —
        can BE the pooled buffer, and a result must stay valid after the
        pool rewrites that buffer.  The old allocate-per-dispatch path
        got this for free (inputs were fresh arrays nobody reused);
        the dispatch paths call this on their host leaves to restore
        exactly that guarantee.  ``may_share_memory`` is a cheap bounds
        check; a false positive just buys one defensive copy."""
        pools = getattr(self._tls, "pools", None)
        if pools is None:
            return list(arrays)
        bufs = [b for p in pools.values() for b in p.bufs]
        return [
            a.copy()
            if isinstance(a, np.ndarray)
            and any(np.may_share_memory(a, b) for b in bufs)
            else a
            for a in arrays
        ]
