"""Bucketed frame batching: static shapes for the serving path.

Requests arrive one frame at a time; jit compiles one program per distinct
input shape.  Serving therefore quantizes every dispatch to a fixed bucket
list (``RansacConfig.frame_buckets``): the dispatcher picks the smallest
bucket that holds the pending frames and pads the tail, so the number of
compiled programs is bounded by ``len(frame_buckets)`` no matter how
traffic arrives (the compile-once property is pinned by
tests/test_serve.py's cache-miss counter).

Two invariants make padding safe:

- **Lane independence**: the frames-major entry points are ``vmap``s of the
  per-frame pipeline, so a padded lane cannot perturb a real frame's
  result — selection and refine are per-lane; there is no cross-frame
  reduction.  Pad content is the last real frame repeated (well-conditioned
  by construction), but even degenerate pad data only produces finite
  garbage in its own discarded lane (the utils.num total-function
  discipline).
- **Bucket invariance, bitwise**: XLA specializes a collapsed (B=1) batch
  axis differently enough to change float results, while every width >= 2
  compiles to bit-identical per-lane programs (measured on CPU across
  widths 2..64 for both the dsac and esac paths).  Every dispatch therefore
  carries at least ``MIN_LANES`` physical lanes — a single-frame dispatch
  pads to 2 — so a request's result is bit-identical no matter which bucket
  it rides.  The cost is one wasted lane on bucket-1 dispatches, recorded
  honestly as ``physical_lanes`` in the serve bench artifact.
"""

from __future__ import annotations

import numpy as np

from esac_tpu.serve.slo import ConfigError

# Smallest physical frame-batch any dispatch runs at (see module docstring).
MIN_LANES = 2


def pick_bucket(n: int, buckets: tuple[int, ...]) -> int:
    """Smallest bucket >= n.  ``n`` above the largest bucket is a planning
    error — :func:`plan_dispatches` splits bulk requests first."""
    if n < 1:
        raise ConfigError(f"need at least one frame, got {n}")
    for b in sorted(set(buckets)):
        if b >= n:
            return b
    raise ConfigError(f"{n} frames exceed the largest bucket {max(buckets)}")


def _lanes(chunks: list[int], buckets: tuple[int, ...]) -> int:
    """Total physical lanes a chunk list costs after bucket padding."""
    return sum(max(pick_bucket(c, buckets), MIN_LANES) for c in chunks)


def _plan_tail(rem: int, buckets: tuple[int, ...]) -> list[int]:
    """Plan the sub-largest-bucket tail: either ONE padded dispatch, or the
    largest fitting bucket plus a recursively planned remainder — whichever
    costs fewer physical lanes (padded compute is real compute); ties go to
    fewer dispatches (each dispatch pays the serial chain's op-latency
    floor, the very cost this subsystem amortizes).  E.g. with buckets
    (1, 4, 16, 64): 17 -> [16, 1] (18 lanes, not 64), 5 -> [4, 1], but
    63 -> [63] (one 64-lane dispatch beats [16,16,16,15]'s four)."""
    single = [rem]
    fit = [b for b in sorted(set(buckets)) if b <= rem]
    if not fit or rem in fit:
        return single
    split = [fit[-1]] + _plan_tail(rem - fit[-1], buckets)
    if _lanes(split, buckets) < _lanes(single, buckets):
        return split
    return single


def plan_dispatches(n: int, buckets: tuple[int, ...]) -> list[int]:
    """Split ``n`` frames into per-dispatch valid-frame counts: full
    largest-bucket dispatches, then a minimal-waste tail plan
    (:func:`_plan_tail`).  Returns counts summing to ``n``; each count is
    padded up by the caller via :func:`pick_bucket`."""
    if n < 1:
        raise ConfigError(f"need at least one frame, got {n}")
    big = max(buckets)
    plan = [big] * (n // big)
    rem = n - big * len(plan)
    if rem:
        plan += _plan_tail(rem, buckets)
    return plan


def _pad_leaf(x, extra: int):
    """Append ``extra`` copies of the last frame along axis 0.  numpy leaves
    stay on host (staging assembles there); jax arrays — typed PRNG keys
    included — pad with jnp so the dtype survives."""
    if extra == 0:
        return x
    if isinstance(x, np.ndarray):
        return np.concatenate([x] + [x[-1:]] * extra, axis=0)
    import jax.numpy as jnp

    return jnp.concatenate([x] + [x[-1:]] * extra, axis=0)


def stack_frames(frames: list[dict]) -> dict:
    """Stack per-frame trees (dicts of arrays/scalars) along a new leading
    frame axis.  numpy-stackable leaves stack on host; jax-typed leaves
    (PRNG keys) via jnp."""
    out = {}
    for name in frames[0]:
        leaves = [fr[name] for fr in frames]
        try:
            out[name] = np.stack([np.asarray(v) for v in leaves])
        except (TypeError, ValueError):
            import jax.numpy as jnp

            out[name] = jnp.stack(leaves)
    return out


def pad_batch(batch: dict, bucket: int) -> tuple[dict, int]:
    """Pad a frame-stacked tree up to ``max(bucket, MIN_LANES)`` physical
    lanes by repeating the last real frame.  Returns (padded tree,
    n_valid); results beyond ``n_valid`` are padding and must be dropped.
    """
    n_valid = len(next(iter(batch.values())))
    lanes = max(bucket, MIN_LANES)
    if n_valid > bucket:
        raise ConfigError(f"{n_valid} frames do not fit bucket {bucket}")
    extra = lanes - n_valid
    return {k: _pad_leaf(v, extra) for k, v in batch.items()}, n_valid
