"""Open-loop load generation over the micro-batching dispatcher.

Every serve number before DESIGN.md §12 was CLOSED-loop: one dispatch
timed at a time, the next request waiting for the last answer.  Real
traffic does not wait — requests arrive on the *arrival process's* clock,
pile up when the server falls behind, and the interesting numbers
(sustained hyps/s, p50/p99 vs offered load, where the knee is) only exist
under that regime.  This module is the open-loop driver:

- :func:`poisson_arrivals` / :func:`uniform_arrivals` build an arrival
  schedule (cumulative seconds) for a target offered rate — Poisson for
  memoryless traffic, uniform for a deterministic trace.
- :func:`run_open_loop` replays a schedule against a dispatcher:
  ``submit`` fires at each arrival time regardless of completions (the
  open-loop property — admission control, not caller blocking, is what
  bounds the queue, so the dispatcher should carry an
  :class:`~esac_tpu.serve.slo.SLOPolicy`), outcomes are collected from
  the requests themselves, and the summary reports achieved offered
  rate, the outcome accounting (which must sum to offered — the
  tests/test_serve_slo.py invariant), served-latency quantiles and
  sustained throughput.

Pure host code: no jax, no jitted surfaces (nothing here is an R11
entry point).  Requests can mix scenes, ``route_k`` values and frame
shapes — lanes are the dispatcher's problem — via the ``make_request``
callback, which maps an arrival index to ``(frame, scene, route_k)``.
"""

from __future__ import annotations

import gc
import time

import numpy as np

from esac_tpu.serve.slo import ConfigError, DeadlineExceededError, ShedError

# Outcome classes a request can end in (the accounting invariant's terms).
OUTCOMES = ("served", "degraded", "shed", "expired", "failed")


def poisson_arrivals(rate_rps: float, n: int, seed: int = 0) -> np.ndarray:
    """Cumulative arrival times (seconds) of ``n`` Poisson arrivals at
    ``rate_rps``: i.i.d. exponential gaps, deterministic per seed."""
    if rate_rps <= 0:
        raise ConfigError(f"rate_rps {rate_rps} <= 0")
    gaps = np.random.RandomState(seed).exponential(1.0 / rate_rps, size=n)
    return np.cumsum(gaps)


def uniform_arrivals(rate_rps: float, n: int) -> np.ndarray:
    """Cumulative arrival times of a deterministic constant-rate trace."""
    if rate_rps <= 0:
        raise ConfigError(f"rate_rps {rate_rps} <= 0")
    return (np.arange(n, dtype=np.float64) + 1.0) / rate_rps


def run_open_loop(
    disp,
    make_request,
    arrivals,
    deadline_ms: float | None = None,
    hyps_per_request: int = 1,
    settle_s: float = 30.0,
    freeze_gc: bool = True,
) -> dict:
    """Replay an open-loop arrival schedule against ``disp``.

    Unless ``freeze_gc=False``, the run executes with the garbage
    collector's existing heap FROZEN (``gc.collect()`` then
    ``gc.freeze()``, unfrozen after): the PR-7 review measured gen-2
    collection pauses as ~100 ms "server stalls" in the latency tail,
    and every long-lived object at run start — compiled programs,
    weight caches, the dispatcher itself — is prewarm state that a
    mid-run gen-2 pass can only waste time re-scanning.  The summary's
    ``gc`` block records the provenance (frozen flag + per-generation
    collection counts during the run) so an artifact states the regime
    its tail was measured under.

    ``make_request(i) -> (frame, scene, route_k)`` builds request ``i``;
    ``arrivals`` is the cumulative schedule (seconds from start).  Submits
    never block on completions: a shed (typed
    :class:`~esac_tpu.serve.slo.ShedError`) is recorded and the generator
    moves on — exactly the admission-control contract.  After the last
    arrival, every admitted request is awaited for its remaining deadline
    plus ``settle_s`` grace (the dispatcher wakes waiters on watchdog
    abandonment, worker death and close, so the grace is slack for
    scheduling, not a correctness crutch).

    Returns a summary dict: achieved offered rate, per-outcome counts
    (summing to ``offered``), served+degraded latency quantiles,
    sustained goodput in requests/s and hyps/s over the span from first
    arrival to last completion, and the raw per-request outcome list.
    When the dispatcher carries an obs metrics registry (DESIGN.md §14 —
    every ``MicroBatchDispatcher`` does), the summary also breaks
    latency down ``per_scene`` and ``per_route_k``, sourced from the
    registry's streaming ``serve_lane_latency_seconds`` histogram:
    fleet-wide percentiles hide a single degraded scene inside healthy
    aggregate numbers, and the per-lane view is what surfaces it.  That
    lane histogram is RESET at run start, so the blocks cover exactly
    the run this summary describes — warmup traffic or a previous run
    on the same dispatcher cannot contaminate them (the fleet
    ``serve_request_latency_seconds`` instrument and the accounting
    counters are untouched; note that on a SHARED obs registry the lane
    histogram is shared too, so driving the load harness against one
    dispatcher restarts the lane-latency window for its peers — one
    more reason the aggregation mode is opt-in).  Those quantiles are sketch estimates
    within the histogram's pinned tolerance and cover every COMPLETED
    request of the run; the fleet-wide ``p50_ms``/``p99_ms`` stay exact
    over the served+degraded latencies, unchanged.
    """
    arrivals = np.asarray(arrivals, np.float64)
    if len(arrivals) == 0:
        raise ConfigError("empty arrival schedule")
    frozen = False
    if freeze_gc:
        gc.collect()
        gc.freeze()
        frozen = True
    gc_before = gc.get_stats()
    try:
        out = _run_paced(disp, make_request, arrivals, deadline_ms,
                         hyps_per_request, settle_s)
    finally:
        if frozen:
            gc.unfreeze()
    out["gc"] = {
        "frozen": frozen,
        "collections_during_run": [
            int(a["collections"] - b["collections"])
            for a, b in zip(gc.get_stats(), gc_before)
        ],
    }
    return out


def _run_paced(disp, make_request, arrivals, deadline_ms,
               hyps_per_request, settle_s) -> dict:
    """The paced replay itself (see :func:`run_open_loop`)."""
    n = len(arrivals)
    lane_hist = _lane_hist(disp)
    if lane_hist is not None:
        # Run-local lane views (see docstring): the per-lane histogram
        # restarts with the run; nothing else is reset.
        lane_hist.reset()
    admitted = []          # (index, request)
    outcomes = [None] * n  # per-request outcome string
    # Typed-error class name per request (None for clean serves): the
    # chaos drill's per-fault accounting keys on WHICH typed fault ended
    # a request, not just its outcome class.
    err_types = [None] * n
    # Pacing runs on the harness clock; every latency/deadline quantity
    # below comes from the REQUESTS' own timestamps (the dispatcher's
    # clock domain) — mixing the two would corrupt wait budgets the
    # moment either side used a non-default clock.
    t0 = time.perf_counter()
    for i in range(n):
        target = t0 + float(arrivals[i])
        while True:
            # Sleep-until with a cap so coarse schedulers cannot overshoot
            # a whole burst of arrivals.
            now = time.perf_counter()
            if now >= target:
                break
            time.sleep(min(target - now, 0.01))
        frame, scene, route_k = make_request(i)
        try:
            req = disp.submit(frame, scene=scene, route_k=route_k,
                              deadline_ms=deadline_ms)
        except ShedError as e:
            outcomes[i] = "shed"
            err_types[i] = type(e).__name__
            continue
        except DeadlineExceededError:
            # A no-SLO dispatcher's bounded space wait expires instead of
            # shedding; the request's fate is recorded, never a harness
            # crash that loses the whole point's outcomes.
            outcomes[i] = "expired"
            err_types[i] = "DeadlineExceededError"
            continue
        admitted.append((i, req, time.perf_counter()))
    t_last_arrival = time.perf_counter()

    latencies = []
    t_end = t_last_arrival
    for i, req, t_sub_h in admitted:
        # The request's FULL deadline window (its own clock domain) plus
        # grace; the event is guaranteed to fire eventually, the bound
        # keeps a broken dispatcher from hanging the harness.
        budget = settle_s
        if req.deadline is not None:
            budget += max(0.0, req.deadline - req.t_submit)
        if not req.event.wait(budget):
            outcomes[i] = "lost"  # should be impossible; surfaced, not hidden
            continue
        outcomes[i] = req.outcome
        if req.error is not None:
            err_types[i] = type(req.error).__name__
        if req.outcome in ("served", "degraded"):
            # Latency in the dispatcher's clock domain; the completion
            # instant anchored on the ACTUAL submit time (a generator
            # running behind schedule must not shrink the span and
            # inflate sustained throughput).
            latencies.append(req.t_done - req.t_submit)
            t_end = max(t_end, t_sub_h + (req.t_done - req.t_submit))

    counts = {o: outcomes.count(o) for o in OUTCOMES}
    counts["lost"] = outcomes.count("lost")
    good = counts["served"] + counts["degraded"]
    span = max(t_end - t0, 1e-9)
    lat = np.sort(np.asarray(latencies)) if latencies else None

    def q(p):
        if lat is None:
            return float("nan")
        return float(lat[min(len(lat) - 1, round(p * (len(lat) - 1)))])

    # Per-request trace ids (ISSUE 15): when the dispatcher/router
    # samples causal traces, every admitted request's id rides the
    # summary so an artifact's slow points can be joined back to their
    # exemplar traces (None for unsampled/shed requests).
    trace_ids: list = [None] * n
    for i, req, _t in admitted:
        tr = getattr(req, "trace", None)
        if tr is not None:
            trace_ids[i] = tr.trace_id

    out = {
        "offered": n,
        "offered_rps_target": round(n / float(arrivals[-1]), 2),
        "offered_rps_achieved": round(n / max(t_last_arrival - t0, 1e-9), 2),
        "outcomes": counts,
        "goodput_ratio": round(good / n, 4),
        "served_rps": round(good / span, 2),
        "sustained_hyps_per_s": round(good * hyps_per_request / span, 1),
        "p50_ms": round(q(0.5) * 1e3, 2),
        "p99_ms": round(q(0.99) * 1e3, 2),
        "span_s": round(span, 3),
        "per_request_outcomes": outcomes,
        "per_request_error_types": err_types,
        "per_request_trace_ids": trace_ids,
    }
    obs = getattr(disp, "obs", None)
    store = obs.get_trace_store() if obs is not None \
        and hasattr(obs, "get_trace_store") else None
    if store is not None:
        # Exemplar slow traces for the artifact (the loadtest/fleet
        # artifacts' "where did the tail go" evidence).
        out["exemplar_slow_traces"] = store.slowest(3)
    per_scene, per_route = _lane_latency_views(disp)
    if per_scene is not None:
        out["per_scene"] = per_scene
        out["per_route_k"] = per_route
    return out


def _lane_hist(disp):
    """The dispatcher's per-lane latency histogram, or None when the
    dispatcher carries no obs registry (a foreign/minimal dispatcher)."""
    obs = getattr(disp, "obs", None)
    return obs.get("serve_lane_latency_seconds") if obs is not None \
        else None


def _lane_latency_views(disp):
    """(per_scene, per_route_k) latency breakdowns from the dispatcher's
    obs registry, or (None, None) for a dispatcher without one.  Each
    entry merges the streaming histogram's children over the OTHER label
    (a scene's number spans its route_k lanes and vice versa); keys are
    stringified so the blocks ride json artifacts as-is."""
    hist = _lane_hist(disp)
    if hist is None:
        return None, None

    def view(label: str) -> dict:
        values = sorted(
            {c.get(label) for c in hist.labelsets()},
            key=lambda v: (v is None, str(v)),
        )
        out = {}
        for v in values:
            s = hist.summary(quantiles=(0.5, 0.99), **{label: v})
            if not s["count"]:
                continue  # a lane from BEFORE the run (reset keeps the
                # child object); a count-0 NaN row is noise, not data
            out[str(v)] = {
                "count": s["count"],
                "p50_ms": round(s["p50"] * 1e3, 2),
                "p99_ms": round(s["p99"] * 1e3, 2),
            }
        return out

    return view("scene"), view("route_k")
