"""Temporal sessions: warm-start streaming relocalization (ISSUE 20).

Real traffic is video, not i.i.d. frames (ROADMAP item 4, DESIGN.md §23):
a tracked frame whose pose is within a motion model of the previous
winner does not need the full sampled hypothesis budget.  This module is
the HOST side of that bargain — three pieces:

- :class:`SessionPolicy`: the frozen knob set (prior slot count, tracked
  hypothesis budget, track-loss threshold, table capacity).
- :class:`SessionTable`: per-session last-winner pose + soft-inlier
  score under its OWN leaf lock (``.lock_graph.json``: no other lock is
  ever taken inside it), with LRU eviction and the session obs counters.
- :class:`SessionRouter`: the serving wrapper over a
  :class:`~esac_tpu.serve.dispatcher.MicroBatchDispatcher` or a
  :class:`~esac_tpu.fleet.router.FleetRouter`.  Per frame it (1)
  propagates the session's motion model into a STATIC-count prior-pose
  slate riding the frame tree (``prior_rvec``/``prior_tvec``/
  ``prior_valid`` leaves — traced arguments of the prior-slot jitted
  entries, so tracked / cold / lost frames share one compiled program),
  (2) dispatches tracked frames at the shrunken ``n_hyps`` override on
  their own coalescing lane, and (3) reads the winner's soft-inlier
  fraction back as the track detector: below the threshold the session
  drops to ``lost`` and the NEXT frame runs the full routed budget
  (recovery-after-loss within one frame).

The device side never branches: the validity mask — not the batch tree
shape, not a recompile — carries the tracked/cold/lost distinction, and
an all-invalid mask is bit-identical to the plain dense/routed programs
(the parity pin; see ``ransac.esac.esac_infer_prior``).

Lock discipline (R13): every dispatch and every result wait happens
OUTSIDE the table lock — the lock only snapshots and updates host state.
Two threads streaming the same session id are not an error (last writer
wins on the motion state), but sessions are meant to be single-stream.
"""

from __future__ import annotations

import collections
import dataclasses
import threading

import numpy as np

from esac_tpu.serve.slo import ConfigError, ServeError, ShedError


class SessionEvictedError(ShedError):
    """The session was LRU-evicted from a full :class:`SessionTable`
    before this frame arrived; the caller must ``open()`` a new session
    (the next frame then runs cold — full budget, no priors).  A shed:
    admission said no before any dispatch."""

    retryable = True
    wire_name = "session_evicted"


class SessionUnknownError(ConfigError):
    """Caller misuse: a frame for a session id that was never opened (or
    was closed, or evicted long enough ago to leave the eviction ring).
    Deterministic — retrying the same call cannot help."""

    retryable = False
    wire_name = "session_unknown"


@dataclasses.dataclass(frozen=True)
class SessionPolicy:
    """Host-side session knobs (frozen, like
    :class:`~esac_tpu.serve.slo.SLOPolicy` — none of these touch the
    compiled-program hash; ``prior_slots``/``track_n_hyps`` select
    among PREWARMED static programs, they do not shape new ones).

    ``prior_slots``: P, the static prior-pose slot count of the session
    lane's batch trees (``SceneRegistry.prewarm_programs(prior_slots=P)``
    compiles the siblings up front).  Slot 0 is the last winner, slot 1
    the constant-velocity extrapolation; further slots ride invalid
    (headroom for richer motion models without recompiling).

    ``track_n_hyps``: the shrunken per-expert hypothesis budget of a
    TRACKED frame (the PR-8 per-dispatch override; prewarm it via
    ``n_hyps_overrides``).  Cold and lost frames run the scene's full
    configured budget.

    ``track_loss_frac``: winner soft-inlier fraction below which the
    track is declared lost — the same signal the §13 breaker consumes.
    ``track_enter_frac``: fraction a FULL-budget winner must reach to
    (re)enter tracked mode; defaults to ``track_loss_frac`` (enter and
    exit at the same bar) and may be set higher for hysteresis.

    ``max_sessions``: LRU table capacity; the eviction ring remembers
    the last ``evicted_ring`` evicted ids so their next frame raises the
    typed :class:`SessionEvictedError` instead of the generic unknown.
    """

    prior_slots: int = 4
    track_n_hyps: int = 32
    track_loss_frac: float = 0.10
    track_enter_frac: float | None = None
    max_sessions: int = 1024
    evicted_ring: int = 256

    def __post_init__(self):
        if self.prior_slots < 1:
            raise ValueError("prior_slots must be >= 1")
        if self.track_n_hyps < 1:
            raise ValueError("track_n_hyps must be >= 1")
        if not 0.0 < self.track_loss_frac < 1.0:
            raise ValueError("track_loss_frac must be in (0, 1)")
        if self.track_enter_frac is not None \
                and not 0.0 < self.track_enter_frac < 1.0:
            raise ValueError("track_enter_frac must be in (0, 1)")
        if self.max_sessions < 1:
            raise ValueError("max_sessions must be >= 1")
        if self.evicted_ring < 0:
            raise ValueError("evicted_ring must be >= 0")

    @property
    def enter_frac(self) -> float:
        return (self.track_enter_frac if self.track_enter_frac is not None
                else self.track_loss_frac)


class _SessionState:
    """One session's host motion state (mutated only under the table
    lock)."""

    __slots__ = ("scene", "route_k", "full_n_hyps", "last_rvec",
                 "last_tvec", "prev_rvec", "prev_tvec", "last_frac",
                 "tracked", "frames", "tracked_frames", "losses")

    def __init__(self, scene, route_k, full_n_hyps):
        self.scene = scene
        self.route_k = route_k
        self.full_n_hyps = full_n_hyps  # budget restored after loss/cold
        self.last_rvec = None           # np (3,) — None until first winner
        self.last_tvec = None
        self.prev_rvec = None           # the winner before last
        self.prev_tvec = None
        self.last_frac = 0.0
        self.tracked = False
        self.frames = 0
        self.tracked_frames = 0
        self.losses = 0


class SessionTable:
    """Per-session motion state + counters under one LEAF lock.

    The lock is a committed leaf of ``.lock_graph.json``: no code path
    acquires any other lock while holding it (snapshot under the lock,
    dispatch/wait outside — R13), so it can be taken from dispatcher or
    fleet callbacks without extending the lock partial order.
    """

    def __init__(self, policy: SessionPolicy = SessionPolicy()):
        self.policy = policy
        self._lock = threading.Lock()
        self._sessions: collections.OrderedDict[str, _SessionState] = \
            collections.OrderedDict()
        self._evicted: collections.deque[str] = collections.deque(
            maxlen=policy.evicted_ring
        )
        # Counters (plain ints under the lock; the `session` collector
        # snapshots them).
        self.opened = 0
        self.evicted_count = 0
        self.closed = 0
        self.frames = 0
        self.tracked_frames = 0
        self.full_frames = 0
        self.track_losses = 0
        self.track_entries = 0
        self.budget_saved_hyps = 0
        self.dispatch_errors = 0

    # -- lifecycle --

    def open(self, session_id: str, scene=None, route_k=None,
             full_n_hyps: int | None = None) -> None:
        """Register a session (idempotent: re-opening resets its motion
        state).  ``full_n_hyps`` is the scene's configured full budget —
        used only for the ``budget_saved_hyps`` accounting (None skips
        that counter).  Evicts the LRU session beyond capacity."""
        with self._lock:
            old = self._sessions.pop(session_id, None)
            self._sessions[session_id] = _SessionState(
                scene, route_k, full_n_hyps
            )
            if old is None:
                self.opened += 1
            while len(self._sessions) > self.policy.max_sessions:
                evicted_id, _ = self._sessions.popitem(last=False)
                self._evicted.append(evicted_id)
                self.evicted_count += 1

    def close(self, session_id: str) -> bool:
        """Drop a session; True if it existed.  A closed id raises
        :class:`SessionUnknownError` on its next frame (closing is the
        caller's OWN action — the typed evicted error is reserved for
        table-pressure evictions the caller did not perform)."""
        with self._lock:
            existed = self._sessions.pop(session_id, None) is not None
            if existed:
                self.closed += 1
            return existed

    # -- per-frame host steps (each one short critical section) --

    def plan(self, session_id: str):
        """Snapshot one frame's dispatch decision: returns
        ``(scene, route_k, n_hyps, prior_rvecs, prior_tvecs,
        prior_valid, tracked)`` with the priors as host numpy
        (P, 3)/(P,) arrays.  Touches the LRU order.  Raises the typed
        session errors for evicted/unknown ids."""
        P = self.policy.prior_slots
        with self._lock:
            st = self._sessions.get(session_id)
            if st is None:
                if session_id in self._evicted:
                    raise SessionEvictedError(
                        f"session {session_id!r} was evicted "
                        f"(table capacity {self.policy.max_sessions}); "
                        "open() it again to resume cold"
                    )
                raise SessionUnknownError(
                    f"unknown session {session_id!r}: open() it first"
                )
            self._sessions.move_to_end(session_id)
            rv = np.zeros((P, 3), np.float32)
            tv = np.zeros((P, 3), np.float32)
            valid = np.zeros((P,), bool)
            if st.tracked and st.last_rvec is not None:
                rv[0], tv[0] = st.last_rvec, st.last_tvec
                valid[0] = True
                if P > 1 and st.prev_rvec is not None:
                    # Constant-velocity extrapolation, linear in the
                    # rvec/tvec coordinates — exact for the translation
                    # rate, first-order in the rotation vector (fine at
                    # video frame spacing; a wrong prior only costs its
                    # slot, never correctness).
                    rv[1] = 2.0 * st.last_rvec - st.prev_rvec
                    tv[1] = 2.0 * st.last_tvec - st.prev_tvec
                    valid[1] = True
            n_hyps = self.policy.track_n_hyps if st.tracked \
                else st.full_n_hyps
            return (st.scene, st.route_k, n_hyps, rv, tv, valid,
                    st.tracked)

    def observe(self, session_id: str, rvec, tvec, inlier_frac: float,
                was_tracked: bool) -> str:
        """Fold one served frame's winner back into the session.  Returns
        the transition: ``"tracked"`` (still/again tracking), ``"lost"``
        (track-loss event: the NEXT frame runs full budget), or
        ``"cold"`` (full-budget frame that did not reach the entry bar).
        A session evicted while the frame was in flight is a no-op
        (``"evicted"``) — its dispatch already happened; only state
        publication is skipped."""
        pol = self.policy
        # Materialize the winner pose to host numpy BEFORE the critical
        # section: rvec/tvec may still be device arrays and np.asarray on
        # one is an implicit device sync (R13 — never block under a lock).
        rvec_h = np.asarray(rvec, np.float32).copy()
        tvec_h = np.asarray(tvec, np.float32).copy()
        with self._lock:
            st = self._sessions.get(session_id)
            if st is None:
                return "evicted"
            st.frames += 1
            self.frames += 1
            st.last_frac = float(inlier_frac)
            st.prev_rvec, st.prev_tvec = st.last_rvec, st.last_tvec
            st.last_rvec = rvec_h
            st.last_tvec = tvec_h
            if was_tracked:
                st.tracked_frames += 1
                self.tracked_frames += 1
                if st.full_n_hyps is not None:
                    self.budget_saved_hyps += max(
                        0, st.full_n_hyps - pol.track_n_hyps
                    )
                if st.last_frac < pol.track_loss_frac:
                    st.tracked = False
                    # A lost track's stale motion state must not seed
                    # the recovery frame's priors.
                    st.prev_rvec = st.prev_tvec = None
                    st.last_rvec = st.last_tvec = None
                    st.losses += 1
                    self.track_losses += 1
                    return "lost"
                return "tracked"
            self.full_frames += 1
            if st.last_frac >= pol.enter_frac:
                if not st.tracked:
                    self.track_entries += 1
                st.tracked = True
                return "tracked"
            return "cold"

    def note_error(self, session_id: str) -> None:
        """A dispatch for this session failed with a typed serve error:
        drop to lost (its motion state may be stale by the time the
        caller retries) and count — the broad-handler disposal the
        fault-flow contract requires (R17: count + re-raise)."""
        with self._lock:
            self.dispatch_errors += 1
            st = self._sessions.get(session_id)
            if st is not None and st.tracked:
                st.tracked = False
                st.prev_rvec = st.prev_tvec = None
                st.last_rvec = st.last_tvec = None
                st.losses += 1
                self.track_losses += 1

    # -- obs --

    def stats(self) -> dict:
        """The ``session`` collector snapshot (one lock pass)."""
        with self._lock:
            frames = self.frames
            return {
                "sessions": len(self._sessions),
                "opened": self.opened,
                "closed": self.closed,
                "evicted": self.evicted_count,
                "frames": frames,
                "tracked_frames": self.tracked_frames,
                "full_frames": self.full_frames,
                "tracked_frac": (self.tracked_frames / frames
                                 if frames else 0.0),
                "track_losses": self.track_losses,
                "track_entries": self.track_entries,
                "budget_saved_hyps": self.budget_saved_hyps,
                "dispatch_errors": self.dispatch_errors,
            }


class SessionRouter:
    """Session-aware serving lane over a dispatcher or fleet router.

    ``target`` needs the shared serve surface: ``submit(frame, scene=,
    route_k=, deadline_ms=, n_hyps=)`` returning a request with
    ``.get(timeout)`` (worker-backed
    :class:`~esac_tpu.serve.dispatcher.MicroBatchDispatcher`,
    :class:`~esac_tpu.fleet.router.FleetRouter`) — or, for worker-less
    sync dispatchers, ``infer_one(...)`` (detected via the dispatcher's
    published ``_worker`` state).  The table registers itself as the
    ``session`` obs collector on ``target.obs``.

    Per ``infer_frame``: plan under the table lock, attach the prior
    leaves to a SHALLOW COPY of the caller's frame tree, dispatch on the
    explicit ``n_hyps`` lane (session lanes are ALWAYS 3-tuples, so
    their prior-carrying batch trees never coalesce with plain
    traffic), wait outside every lock, then fold the winner back.  A
    track loss lands in the session counters and — when the fleet
    sampled this request (§19) — as a ``session:track_loss`` event span
    on the causal trace.
    """

    def __init__(self, target, policy: SessionPolicy = SessionPolicy(),
                 clock=None):
        self.target = target
        self.policy = policy
        self.table = SessionTable(policy)
        self._clock = clock if clock is not None \
            else getattr(target, "_clock", None)
        obs = getattr(target, "obs", None)
        if obs is not None:
            obs.register_collector("session", self.table.stats)

    # -- lifecycle passthrough --

    def open(self, session_id: str, scene=None, route_k=None,
             full_n_hyps: int | None = None) -> None:
        self.table.open(session_id, scene, route_k, full_n_hyps)

    def close(self, session_id: str) -> bool:
        return self.table.close(session_id)

    # -- the per-frame serve call --

    def infer_frame(self, session_id: str, frame: dict,
                    timeout: float | None = None,
                    deadline_ms: float | None = None) -> dict:
        """Serve one frame of a session.  Returns the per-frame result
        tree with two host fields added: ``session_tracked`` (was this
        dispatch on the shrunken tracked lane) and ``session_transition``
        (``tracked``/``lost``/``cold``/``evicted``).  Raises the
        session-typed errors at admission and the target's typed
        :class:`~esac_tpu.serve.slo.ServeError` tree from the dispatch
        (after dropping the session to lost — fail toward the full
        budget, never toward a stale prior)."""
        scene, route_k, n_hyps, p_rv, p_tv, p_valid, tracked = \
            self.table.plan(session_id)
        sframe = dict(frame)
        sframe["prior_rvec"] = p_rv
        sframe["prior_tvec"] = p_tv
        sframe["prior_valid"] = p_valid
        trace = None
        try:
            result, trace = self._dispatch(
                sframe, scene, route_k, n_hyps, timeout, deadline_ms
            )
        except ServeError:
            # Disposal (R17): publish the loss + count, then re-raise —
            # the caller sees exactly the target's typed error.
            self.table.note_error(session_id)
            raise
        transition = self.table.observe(
            session_id,
            np.asarray(result["rvec"]),
            np.asarray(result["tvec"]),
            float(np.asarray(result["inlier_frac"])),
            was_tracked=tracked,
        )
        if transition == "lost" and trace is not None:
            t = self._clock() if self._clock is not None else 0.0
            trace.add_event(
                "session:track_loss", t, session=session_id,
                inlier_frac=float(np.asarray(result["inlier_frac"])),
            )
        result = dict(result)
        result["session_tracked"] = tracked
        result["session_transition"] = transition
        return result

    def _dispatch(self, frame, scene, route_k, n_hyps, timeout,
                  deadline_ms):
        """One dispatch through the target, outside every session lock.
        Returns ``(result tree, sampled trace or None)``."""
        if getattr(self.target, "_worker", True) is None:
            # Worker-less sync dispatcher: the dispatch runs in THIS
            # thread via infer_one; no queue, no request object.
            return self.target.infer_one(
                frame, scene=scene, route_k=route_k, timeout=timeout,
                deadline_ms=deadline_ms, n_hyps=n_hyps,
            ), None
        if deadline_ms is None and timeout is not None:
            deadline_ms = timeout * 1e3
        req = self.target.submit(
            frame, scene=scene, route_k=route_k,
            deadline_ms=deadline_ms, n_hyps=n_hyps,
        )
        return req.get(timeout), getattr(req, "trace", None)
