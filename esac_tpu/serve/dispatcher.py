"""Micro-batching dispatcher: single-frame requests -> frames-major dispatches.

The serving front-end of DESIGN.md §9: the serial small-tensor chain (P3P,
selection, winner-only IRLS) amortizes only by adding *frames* to a
dispatch, so requests that arrive one frame at a time must be coalesced
into fixed frame-batch shapes before they reach the chip.  This module is
that coalescer:

- ``infer_one`` — blocking single-request API.  A background worker holds
  the first queued request up to ``cfg.serve_max_wait_ms`` while more
  arrive, packs the queue into the smallest ``cfg.frame_buckets`` bucket,
  pads the tail (serve.batching), and fans results back out.
- ``infer_many`` — bulk API: plans bucket-sized dispatches and
  double-buffers host-side staging against in-flight device compute (the
  CLAUDE.md pre-stage/batch-work pattern generalized: while dispatch *i*
  runs on device, dispatch *i+1* is stacked, padded and ``device_put``).

The dispatcher is generic over the batched entry point: ``infer_fn`` takes
one frame-stacked tree (every leaf with a leading physical-lane axis) and
returns a tree with the same leading axis.  Builders for the shipped paths
are below (``make_dsac_serve_fn``, ``make_esac_serve_fn``,
``make_sharded_serve_fn``); each is a single ``jax.jit`` callable so one
program compiles per bucket and the compile count is observable
(``cache_size``, pinned by tests/test_serve.py).

Multi-scene serving (esac_tpu.registry): every request may carry a
``scene`` key and, for gating-first routed serving (DESIGN.md §11), a
``route_k`` top-K value.  Requests coalesce per (scene, route_k,
frame-bucket) lane — a dispatch is always single-scene, because the scene
decides which weights ride the program, and single-K, because K is a
STATIC argument of the routed programs — and the worker round-robins
across lanes with pending work, so a hot lane cannot starve a cold one.
Scene-carrying dispatches call ``infer_fn(tree, scene)`` (the registry's
serve fn resolves weights from its device cache per dispatch), routed
ones ``infer_fn(tree, scene, route_k)``; scene-less requests keep the
original ``infer_fn(tree)`` contract, byte-for-byte.

Every stat the dispatcher keeps (latencies, dispatch/scene/route logs) is
a ring buffer sized by ``stats_window``; the per-lane ``dispatch_counts``
totals are keyed by (scene, route_k), bounded by the fleet, not by
traffic — a week-long server's host memory stays flat (regression-pinned
in tests/test_serve.py).
"""

from __future__ import annotations

import collections
import threading
import time

from esac_tpu.ransac.config import RansacConfig
from esac_tpu.serve.batching import (
    pad_batch,
    pick_bucket,
    plan_dispatches,
    stack_frames,
)


class _Request:
    __slots__ = ("frame", "scene", "route_k", "event", "result", "error",
                 "t_submit")

    def __init__(self, frame, t_submit, scene=None, route_k=None):
        self.frame = frame
        self.scene = scene
        self.route_k = route_k
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.t_submit = t_submit


class MicroBatchDispatcher:
    """Accumulate single-frame requests into bucketed frames-major dispatches.

    ``infer_fn``: batched callable, frame-stacked tree -> tree (leading axis
    = physical lanes).  ``cfg`` supplies the static serving knobs
    (``frame_buckets``, ``serve_max_wait_ms``, ``serve_queue_depth``).
    ``start_worker=False`` skips the background thread: ``infer_one``
    dispatches synchronously (per-frame-call semantics) and ``infer_many``
    is unaffected — the mode used by benchmarks and equivalence tests.
    """

    def __init__(
        self,
        infer_fn,
        cfg: RansacConfig = RansacConfig(),
        start_worker: bool = True,
        clock=time.perf_counter,
        stats_window: int = 10_000,
    ):
        if stats_window < 1:
            raise ValueError(f"stats_window {stats_window} < 1")
        self._infer = infer_fn
        self._buckets = tuple(sorted(set(cfg.frame_buckets)))
        self._max_wait_s = cfg.serve_max_wait_ms / 1e3
        self._depth = cfg.serve_queue_depth
        self._clock = clock
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)   # waiters: worker
        self._space = threading.Condition(self._lock)  # waiters: submitters
        # Per-(scene, route_k) lane queues in round-robin order (lane
        # (None, None) = the legacy single-scene mode); a dispatch never
        # mixes scenes — the scene decides the weights — and never mixes
        # route_k values, because K is a STATIC arg of the routed programs:
        # one dispatch rides exactly one compiled program.
        self._pending: "collections.OrderedDict[tuple, collections.deque[_Request]]" = (
            collections.OrderedDict()
        )
        self._n_pending = 0
        self._closed = False
        # Bounded stats: a serving process runs for days — EVERY per-request
        # and per-dispatch record here is a ring buffer, sized by
        # ``stats_window`` dispatches, or latency_quantiles() would sort an
        # unbounded history under the dispatch lock and host memory would
        # grow without limit (pinned by the long-stream regression test in
        # tests/test_serve.py).  Quantiles are over the recent window; the
        # only unbounded-looking structure left is ``dispatch_counts``,
        # which is keyed by (scene, route_k) lane and therefore bounded by
        # the fleet's scene count, not by traffic.
        self.latencies_s: collections.deque[float] = collections.deque(
            maxlen=10 * stats_window
        )
        self.dispatch_log: collections.deque[tuple[int, int]] = (
            collections.deque(maxlen=stats_window)  # (bucket, n_valid)
        )
        # Scene / route_k of each dispatch, aligned with dispatch_log (None
        # entries for scene-less / dense traffic) — fairness tests zip them.
        self.scene_log: collections.deque = collections.deque(
            maxlen=stats_window
        )
        self.route_log: collections.deque = collections.deque(
            maxlen=stats_window
        )
        # Lifetime totals per lane (fairness monitoring without a log).
        self.dispatch_counts: collections.Counter = collections.Counter()
        self._worker = None
        if start_worker:
            self.start()

    def start(self):
        """Start the background worker (idempotent).  Requests may be
        ``submit``ted before start() — they dispatch on the first wakeup,
        the deterministic sequencing the coalescing tests rely on.  Don't
        race start() against ``infer_one`` from other threads: infer_one
        picks its (sync vs queued) path by whether a worker exists."""
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._worker_loop, daemon=True, name="esac-serve"
            )
            self._worker.start()

    # ---------------- request path ----------------

    def submit(self, frame: dict, scene=None, route_k=None) -> _Request:
        """Enqueue one frame tree (optionally for a registry ``scene`` and
        a routed top-K program ``route_k``); returns a request whose
        ``event`` fires when ``result`` (or ``error``) is set.  Blocks for
        queue space — backpressure across ALL lanes, never drops."""
        req = _Request(frame, self._clock(), scene, route_k)
        lane = (scene, route_k)
        with self._work:
            while self._n_pending >= self._depth and not self._closed:
                self._space.wait()
            if self._closed:
                raise RuntimeError("dispatcher is closed")
            q = self._pending.get(lane)
            if q is None:
                q = self._pending[lane] = collections.deque()
            q.append(req)
            self._n_pending += 1
            self._work.notify()
        return req

    def infer_one(self, frame: dict, scene=None, route_k=None) -> dict:
        """Blocking single-frame inference through the batching queue."""
        if self._worker is None:
            req = _Request(frame, self._clock(), scene, route_k)
            self._run([req], scene, route_k)
        else:
            req = self.submit(frame, scene, route_k)
            req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def infer_many(self, frames: list[dict], scene=None,
                   route_k=None) -> list[dict]:
        """Bulk inference: bucket-planned dispatches, staging double-buffered
        against in-flight compute.  Returns per-frame result trees (host
        numpy), in input order."""
        import jax
        import numpy as np

        t_submit = self._clock()
        plan = plan_dispatches(len(frames), self._buckets)
        bounds = []
        lo = 0
        for n in plan:
            bounds.append((lo, lo + n))
            lo += n

        def stage(lo, hi):
            padded, n_valid = pad_batch(
                stack_frames(frames[lo:hi]), pick_bucket(hi - lo, self._buckets)
            )
            return jax.device_put(padded), n_valid

        results: list[dict] = []
        staged = stage(*bounds[0])
        for i in range(len(bounds)):
            tree, n_valid = staged
            # async dispatch: compute starts
            out = self._call(tree, scene, route_k)
            if i + 1 < len(bounds):
                staged = stage(*bounds[i + 1])  # host staging overlaps compute
            out = jax.block_until_ready(out)
            t_done = self._clock()
            host = jax.tree.map(np.asarray, out)
            with self._lock:
                self._record(
                    pick_bucket(n_valid, self._buckets), n_valid, scene,
                    route_k, [t_done - t_submit] * n_valid,
                )
            results.extend(
                jax.tree.map(lambda x: x[j], host) for j in range(n_valid)
            )
        return results

    # ---------------- worker ----------------

    def _call(self, tree, scene, route_k=None):
        """Invoke the entry point: scene-carrying dispatches pass the scene
        (and, for routed programs, ``route_k``) through — registry serve
        fns take ``(tree, scene[, route_k])``; legacy traffic keeps the
        one-argument contract byte-for-byte."""
        if route_k is not None:
            return self._infer(tree, scene, route_k)
        if scene is None:
            return self._infer(tree)
        return self._infer(tree, scene)

    def _record(self, bucket, n_valid, scene, route_k, latencies):
        """Append one dispatch to the bounded stat rings (lock held)."""
        self.dispatch_log.append((bucket, n_valid))
        self.scene_log.append(scene)
        self.route_log.append(route_k)
        self.dispatch_counts[(scene, route_k)] += 1
        self.latencies_s.extend(latencies)

    def _worker_loop(self):
        big = self._buckets[-1]
        while True:
            with self._work:
                while not self._n_pending and not self._closed:
                    self._work.wait()
                if not self._n_pending:
                    return  # closed and drained
                # Fairness: serve the lane at the head of the round-robin
                # order; if it still has pending work afterwards it moves to
                # the back, so a flooding lane cannot starve the others.
                lane, q = next(iter(self._pending.items()))
                deadline = q[0].t_submit + self._max_wait_s
                while len(q) < big and not self._closed:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        break
                    self._work.wait(remaining)
                # serve_max_wait_ms == 0 means coalescing is OFF: exactly one
                # request per dispatch (per-frame-call semantics), even when
                # a burst is already queued.
                take = 1 if self._max_wait_s == 0 else min(len(q), big)
                batch = [q.popleft() for _ in range(take)]
                self._n_pending -= take
                if q:
                    self._pending.move_to_end(lane)
                else:
                    del self._pending[lane]
                self._space.notify_all()
            self._run(batch, *lane)

    def _run(self, reqs: list[_Request], scene=None, route_k=None):
        try:
            self._dispatch(reqs, scene, route_k)
        except Exception as e:  # noqa: BLE001 — fan the failure out
            for r in reqs:
                r.error = e
                r.event.set()

    def _dispatch(self, reqs: list[_Request], scene=None, route_k=None):
        import jax
        import numpy as np

        bucket = pick_bucket(len(reqs), self._buckets)
        padded, n_valid = pad_batch(
            stack_frames([r.frame for r in reqs]), bucket
        )
        out = self._call(jax.device_put(padded), scene, route_k)
        out = jax.block_until_ready(out)
        t_done = self._clock()
        host = jax.tree.map(np.asarray, out)
        with self._lock:
            self._record(bucket, n_valid, scene, route_k,
                         [t_done - r.t_submit for r in reqs])
        for i, r in enumerate(reqs):
            r.result = jax.tree.map(lambda x: x[i], host)
            r.event.set()

    # ---------------- stats / lifecycle ----------------

    def latency_quantiles(self, qs=(0.5, 0.99)) -> dict[float, float]:
        """Per-request latency quantiles (seconds), nearest-rank."""
        with self._lock:
            lat = sorted(self.latencies_s)
        if not lat:
            return {q: float("nan") for q in qs}
        return {q: lat[min(len(lat) - 1, round(q * (len(lat) - 1)))] for q in qs}

    def dispatch_totals(self) -> dict:
        """Per-(scene, route_k) lifetime dispatch counts, snapshotted under
        the lock — the accessor concurrent monitors must use (iterating
        ``dispatch_counts`` raw while the worker appends is a torn read;
        graft-lint R10 discipline applies to callers too)."""
        with self._lock:
            return dict(self.dispatch_counts)

    def reset_stats(self):
        with self._lock:
            self.latencies_s.clear()
            self.dispatch_log.clear()
            self.scene_log.clear()
            self.route_log.clear()
            self.dispatch_counts.clear()

    def cache_size(self) -> int | None:
        """Compiled-program count of the jitted entry point (None when the
        infer fn does not expose jit cache introspection)."""
        probe = getattr(self._infer, "_cache_size", None)
        return probe() if callable(probe) else None

    def close(self):
        """Drain the queue, stop the worker, reject new submissions."""
        with self._work:
            self._closed = True
            self._work.notify_all()
            self._space.notify_all()
        if self._worker is not None:
            self._worker.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def make_dsac_serve_fn(c, cfg: RansacConfig = RansacConfig()):
    """Jitted frames-major single-map (dsac) entry over a frame tree with
    leaves ``key`` (typed PRNG), ``coords`` (N, 3), ``pixels`` (N, 2),
    ``f`` (scalar focal).  One compile per bucket."""
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.kernel import dsac_infer_frames

    c = jnp.asarray(c)

    @jax.jit
    def serve_dsac(batch):
        return dsac_infer_frames(
            batch["key"], batch["coords"], batch["pixels"], batch["f"], c, cfg
        )

    return serve_dsac


def make_esac_serve_fn(c, cfg: RansacConfig = RansacConfig()):
    """Jitted frames-major multi-expert (esac) entry over a frame tree with
    leaves ``key``, ``gating_logits`` (M,), ``coords_all`` (M, N, 3),
    ``pixels`` (N, 2), ``f``."""
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.esac import esac_infer_frames

    c = jnp.asarray(c)

    @jax.jit
    def serve_esac(batch):
        return esac_infer_frames(
            batch["key"], batch["gating_logits"], batch["coords_all"],
            batch["pixels"], batch["f"], c, cfg,
        )

    return serve_esac


def make_sharded_serve_fn(mesh, c, cfg: RansacConfig = RansacConfig()):
    """Jitted frames-major EXPERT-SHARDED entry (config #4's mesh) over a
    frame tree with leaves ``key``, ``coords_all`` (M, N, 3), ``pixels``,
    ``f`` — the same micro-batching front-end reused for the sharded path;
    M must divide the mesh's expert axis."""
    from esac_tpu.parallel.esac_sharded import make_esac_infer_sharded_frames

    return make_esac_infer_sharded_frames(mesh, c, cfg, as_tree=True)
