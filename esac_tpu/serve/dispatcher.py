"""Micro-batching dispatcher: single-frame requests -> frames-major dispatches.

The serving front-end of DESIGN.md §9: the serial small-tensor chain (P3P,
selection, winner-only IRLS) amortizes only by adding *frames* to a
dispatch, so requests that arrive one frame at a time must be coalesced
into fixed frame-batch shapes before they reach the chip.  This module is
that coalescer:

- ``infer_one`` — blocking single-request API.  A background worker holds
  the first queued request up to ``cfg.serve_max_wait_ms`` while more
  arrive, packs the queue into the smallest ``cfg.frame_buckets`` bucket,
  pads the tail (serve.batching), and fans results back out.
- ``infer_many`` — bulk API: plans bucket-sized dispatches and
  double-buffers host-side staging against in-flight device compute (the
  CLAUDE.md pre-stage/batch-work pattern generalized: while dispatch *i*
  runs on device, dispatch *i+1* is stacked, padded and ``device_put``).

The dispatcher is generic over the batched entry point: ``infer_fn`` takes
one frame-stacked tree (every leaf with a leading physical-lane axis) and
returns a tree with the same leading axis.  Builders for the shipped paths
are below (``make_dsac_serve_fn``, ``make_esac_serve_fn``,
``make_sharded_serve_fn``); each is a single ``jax.jit`` callable so one
program compiles per bucket and the compile count is observable
(``cache_size``, pinned by tests/test_serve.py).

Multi-scene serving (esac_tpu.registry): every request may carry a
``scene`` key and, for gating-first routed serving (DESIGN.md §11), a
``route_k`` top-K value.  Requests coalesce per (scene, route_k,
frame-bucket) lane — a dispatch is always single-scene, because the scene
decides which weights ride the program, and single-K, because K is a
STATIC argument of the routed programs — and the worker round-robins
across lanes with pending work, so a hot lane cannot starve a cold one.
Scene-carrying dispatches call ``infer_fn(tree, scene)`` (the registry's
serve fn resolves weights from its device cache per dispatch), routed
ones ``infer_fn(tree, scene, route_k)``; scene-less requests keep the
original ``infer_fn(tree)`` contract, byte-for-byte.

SLO serving (DESIGN.md §12; esac_tpu.serve.slo): passing an ``slo``
policy opts the request path into per-request deadlines
(``submit``/``infer_one`` take ``deadline_ms``/``timeout``), bounded-queue
admission control (a full queue or a predicted deadline miss SHEDS with a
typed :class:`~esac_tpu.serve.slo.ShedError` instead of blocking — the
open-loop contract; bulk ``infer_many`` keeps blocking backpressure),
graceful degradation (under overload a lane's ``route_k`` downshifts one
rung of ``slo.degrade_route_k`` — a cheaper ALREADY-COMPILED static
program, never a recompile), and a watchdog thread that bounds the
environment's observed relay-stall failure mode: a dispatch that makes no
progress within ``slo.watchdog_ms`` has its requests failed with
:class:`~esac_tpu.serve.slo.DispatchStalledError` *within their
deadline*, its lane quarantined, and a replacement worker takes over the
healthy lanes instead of the whole server hanging.  Every request's fate
lands in the outcome accounting — served / shed / expired / degraded /
failed — which sums exactly to ``offered`` (pinned in
tests/test_serve_slo.py).  Whether or not a policy is set, ``close()``
and a dying worker wake every pending caller with a typed error; nobody
strands forever on a dead server.

Every stat the dispatcher keeps (latencies, dispatch/scene/route/outcome
logs) is a ring buffer sized by ``stats_window``; the per-lane
``dispatch_counts`` / outcome totals are keyed by (scene, route_k),
bounded by the fleet, not by traffic — a week-long server's host memory
stays flat (regression-pinned in tests/test_serve.py).

Observability (DESIGN.md §14; esac_tpu.obs): every dispatcher publishes
its accounting into a :class:`~esac_tpu.obs.MetricsRegistry` (``obs``
attribute; pass one in to aggregate, default private) — offered/outcome
counters, per-lane dispatch counters, and streaming-quantile latency
histograms that replaced ``latency_quantiles()``'s per-call sort of the
whole ``latencies_s`` deque.  ``dispatch_totals``/``slo_totals`` are
thin views over those counters (updated in the same locked sections as
the legacy attributes, so the accounting invariant is one truth).  With
``trace=True`` every request additionally carries a
:class:`~esac_tpu.obs.SpanChain` stamped at the existing choke points
(admitted -> coalesced -> staged -> dispatched -> device -> sliced ->
outcome); the stamps reuse timestamps the dispatch path already takes —
zero added host syncs, zero jit surface — and per-stage durations land
in the ``serve_stage_seconds`` histogram at ``_finish``.  The overhead
is gated by ``python bench.py obs`` (.obs_overhead.json).  NOTE on
sharing: give two dispatchers one registry only if you want
fleet-AGGREGATED counters — ``slo_totals`` then spans both dispatchers
while ``pending`` stays per-instance, so the per-dispatcher accounting
invariant intentionally applies to private registries (the default).
Two more shared-mode caveats: collector registration is last-wins (the
snapshot's ``serve_*`` collector blocks come from the most recent
dispatcher), and ``reset_stats`` subtracts only the CALLING
dispatcher's contribution from the shared counters (the other's
history survives) but clears the shared latency/stage histograms —
as does ``serve.loadgen.run_open_loop``'s run-start reset of the
shared lane-latency histogram.
"""

from __future__ import annotations

import collections
import threading
import time

from esac_tpu.obs import MetricsRegistry, SpanChain, Trace, trace_scope
from esac_tpu.ransac.config import RansacConfig
from esac_tpu.serve.batching import (
    StagingCache,
    pick_bucket,
    plan_dispatches,
)
from esac_tpu.serve.slo import (
    DeadlineExceededError,
    DispatcherClosedError,
    DispatchStalledError,
    LaneQuarantinedError,
    ShedError,
    SLOPolicy,
    WorkerDiedError,
)

# close() join budgets, seconds (graft-lint R18: every join is bounded —
# a thread wedged on the TPU relay can never be killed, only abandoned).
# Legacy mode (no SLOPolicy) drains the whole queue, so its window is
# generous; the watchdog exits within one poll of _closed.  Module-level
# so tests can shrink them to drill the wedged-close path.
_LEGACY_DRAIN_JOIN_S = 60.0
_WATCHDOG_JOIN_S = 2.0


class _Request:
    """One queued frame.  ``result``/``error`` are plain attributes for
    back-compat; :meth:`get` is the timeout-taking accessor every new
    caller should use (a bare ``event.wait()`` on a dead server is the
    exact unbounded-blocking bug this layer exists to kill)."""

    __slots__ = ("frame", "scene", "route_k", "n_hyps", "event", "result",
                 "error", "t_submit", "t_done", "deadline", "done", "outcome",
                 "owner", "spans", "trace")

    def __init__(self, frame, t_submit, scene=None, route_k=None,
                 deadline=None, owner=None, n_hyps=None):
        self.frame = frame
        self.scene = scene
        self.route_k = route_k
        self.n_hyps = n_hyps
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.t_submit = t_submit
        self.t_done = None
        self.deadline = deadline  # absolute clock() time, or None
        self.done = False
        self.outcome = None       # served|shed|expired|degraded|failed
        self.owner = owner        # dispatcher, for timeout abandonment
        self.spans = None         # obs.SpanChain when tracing is on
        self.trace = None         # obs.Trace: dispatcher-minted, or the
        #                           fleet trace riding in via trace_ctx

    def get(self, timeout: float | None = None):
        """Wait up to ``timeout`` seconds for the result; raises the
        request's typed error, or :class:`DeadlineExceededError` on
        timeout.  A timeout ABANDONS the request — same semantics as
        ``infer_one``'s timeout: it is marked expired, a late result is
        discarded, and the accounting agrees with what this call raised.
        The dispatcher guarantees the event fires on close, worker death
        and watchdog abandonment, so a bounded wait here is a real
        bound, not a hope."""
        if not self.event.wait(timeout):
            err = DeadlineExceededError(
                f"no result within {timeout}s — request abandoned"
            )
            if self.owner is not None:
                self.owner._abandon(self, err)
            if self.error is not None:  # resolved in the race window
                raise self.error
            if not self.done:
                raise err  # ownerless request (sync path): nothing to mark
            return self.result
        if self.error is not None:
            raise self.error
        return self.result


class _Inflight:
    __slots__ = ("gen", "lane", "reqs", "t_start")

    def __init__(self, gen, lane, reqs, t_start):
        self.gen = gen
        self.lane = lane
        self.reqs = reqs
        self.t_start = t_start


class MicroBatchDispatcher:
    """Accumulate single-frame requests into bucketed frames-major dispatches.

    ``infer_fn``: batched callable, frame-stacked tree -> tree (leading axis
    = physical lanes).  ``cfg`` supplies the static serving knobs
    (``frame_buckets``, ``serve_max_wait_ms``, ``serve_queue_depth``).
    ``start_worker=False`` skips the background thread: ``infer_one``
    dispatches synchronously (per-frame-call semantics) and ``infer_many``
    is unaffected — the mode used by benchmarks and equivalence tests.
    ``slo`` (an :class:`~esac_tpu.serve.slo.SLOPolicy`) opts into the
    deadline / admission-control / degradation / watchdog machinery; None
    preserves the PR-2 blocking contract byte-for-byte.
    """

    def __init__(
        self,
        infer_fn,
        cfg: RansacConfig = RansacConfig(),
        start_worker: bool = True,
        clock=time.perf_counter,
        stats_window: int = 10_000,
        slo: SLOPolicy | None = None,
        obs: MetricsRegistry | None = None,
        trace: bool = False,
        arrival_sink=None,
    ):
        if stats_window < 1:
            raise ValueError(f"stats_window {stats_window} < 1")
        self._infer = infer_fn
        # Per-scene arrival tap (DESIGN.md §17): ``arrival_sink(scene)``
        # is called once per scene-carrying submission, OUTSIDE the
        # dispatcher lock, BEFORE admission — the predictive weight
        # prefetcher's feed (registry/prefetch.py).  The sink contract:
        # non-blocking, never raises (WeightPrefetcher.observe is a
        # bounded deque append).  Immutable post-init; None = no tap,
        # zero cost beyond one attribute check.
        self._arrival_sink = arrival_sink
        self._buckets = tuple(sorted(set(cfg.frame_buckets)))
        # Pooled host staging (per-thread buffers, see batching.py):
        # padding templates are built once per (leaf, lanes, dtype,
        # shape), not rebuilt every dispatch.
        self._staging = StagingCache()
        self._max_wait_s = cfg.serve_max_wait_ms / 1e3
        self._depth = cfg.serve_queue_depth
        self._clock = clock
        self._slo = slo
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)   # waiters: worker
        self._space = threading.Condition(self._lock)  # waiters: submitters
        # Per-(scene, route_k) lane queues in round-robin order (lane
        # (None, None) = the legacy single-scene mode); a dispatch never
        # mixes scenes — the scene decides the weights — and never mixes
        # route_k values, because K is a STATIC arg of the routed programs:
        # one dispatch rides exactly one compiled program.
        self._pending: "collections.OrderedDict[tuple, collections.deque[_Request]]" = (
            collections.OrderedDict()
        )
        self._n_pending = 0
        self._closed = False
        # SLO state (all guarded by self._lock, graft-lint R10; the
        # fleet-level nesting this class takes — dispatcher lock ->
        # obs instrument locks, never anything else — is the committed
        # .lock_graph.json order, R12/R13 + DESIGN.md §15): the worker
        # generation counter lets the watchdog abandon a wedged worker — a
        # stale-generation worker discards whatever it eventually returns
        # and exits; quarantined maps lane -> reason; the dispatch-time EMA
        # feeds admission control's predicted-wait estimate.
        self._gen = 0
        self._inflight: _Inflight | None = None
        self._quarantined: dict[tuple, str] = {}
        self._fail_streak: collections.Counter = collections.Counter()
        self._ema_dispatch_s = 0.0
        self._ema_n = 0  # completed-dispatch samples behind the EMA
        self._worker_dead: str | None = None
        # Bounded stats: a serving process runs for days — EVERY per-request
        # and per-dispatch record here is a ring buffer, sized by
        # ``stats_window`` dispatches, or latency_quantiles() would sort an
        # unbounded history under the dispatch lock and host memory would
        # grow without limit (pinned by the long-stream regression test in
        # tests/test_serve.py).  Quantiles are over the recent window; the
        # only unbounded-looking structures left are ``dispatch_counts``
        # and the outcome counters, keyed by (scene, route_k) lane /
        # outcome class and therefore bounded by the fleet, not by traffic.
        self.latencies_s: collections.deque[float] = collections.deque(
            maxlen=10 * stats_window
        )
        self.dispatch_log: collections.deque[tuple[int, int]] = (
            collections.deque(maxlen=stats_window)  # (bucket, n_valid)
        )
        # Scene / route_k of each dispatch, aligned with dispatch_log (None
        # entries for scene-less / dense traffic) — fairness tests zip them.
        self.scene_log: collections.deque = collections.deque(
            maxlen=stats_window
        )
        self.route_log: collections.deque = collections.deque(
            maxlen=stats_window
        )
        # Lifetime totals per lane (fairness monitoring without a log).
        self.dispatch_counts: collections.Counter = collections.Counter()
        # SLO accounting: every request ever offered ends in exactly one
        # outcome class — served / shed / expired / degraded / failed —
        # and the classes sum to ``offered`` (the acceptance invariant,
        # pinned in tests/test_serve_slo.py).  ``outcome_log`` is the
        # ring-bounded per-request trail (outcome, scene, route_k, eff_k).
        self.offered = 0
        self.outcome_counts: collections.Counter = collections.Counter()
        self.outcome_log: collections.deque = collections.deque(
            maxlen=stats_window
        )
        # Observability (DESIGN.md §14): the unified metrics registry this
        # dispatcher publishes into.  The instruments are created once
        # here and cached as handles — the hot path never takes the
        # registry lock, only per-instrument locks, always nested INSIDE
        # the dispatcher lock (acyclic order: registry -> dispatcher ->
        # instrument; see esac_tpu/obs/metrics.py).  ``trace`` gates the
        # per-request span chains; everything else is always on.
        self.obs = obs if obs is not None else MetricsRegistry()
        self._trace = bool(trace)
        # Completed dispatcher-MINTED traces land here (the ``traces``
        # collector; python -m esac_tpu.obs --traces).  Fleet traces
        # riding in via submit(trace_ctx=...) belong to the router's
        # store — this dispatcher only stamps their child chains.
        self._trace_store = self.obs.trace_store() if self._trace else None
        # Fast-path gate for _stamp: stays False until either this
        # dispatcher traces everything or a trace-carrying request has
        # been seen, so the tracing-off request path keeps its exact
        # pre-ISSUE-15 instruction count.
        self._tracing_any = self._trace
        self._m_offered = self.obs.counter(
            "serve_offered_total",
            "requests ever offered (re-based by reset_stats)",
        )
        self._m_outcomes = self.obs.counter(
            "serve_outcomes_total",
            "terminal outcome classes; with pending they sum to offered",
        )
        self._m_dispatches = self.obs.counter(
            "serve_dispatches_total",
            "completed dispatches per (scene, route_k) lane",
        )
        # Two latency instruments on purpose: the FLEET histogram is one
        # unlabeled child whose window is the most recent 10*stats_window
        # samples GLOBALLY — the exact recent-window semantics of the
        # latencies_s deque it replaced (per-lane windows alone would let
        # an idle lane's stale samples dominate merged quantiles forever,
        # review finding) — while the LANE histogram carries the
        # per-(scene, route_k) breakdown the open-loop views read.
        self._m_latency = self.obs.histogram(
            "serve_request_latency_seconds",
            "fleet-wide per-request completion latency (recent window)",
            window=10 * stats_window,
        )
        self._m_lane_latency = self.obs.histogram(
            "serve_lane_latency_seconds",
            "per-request completion latency by (scene, route_k) lane",
            window=10 * stats_window,
        )
        self._m_stage = self.obs.histogram(
            "serve_stage_seconds",
            "per-stage span durations of traced requests",
            window=10 * stats_window,
        )
        self.obs.register_collector("serve_slo_totals", self.slo_totals)
        self.obs.register_collector("serve_dispatch_totals",
                                    self.dispatch_totals)
        self.obs.register_collector("serve_quarantined_lanes",
                                    self.quarantined_lanes)
        self._worker = None
        self._watchdog = None
        if start_worker:
            self.start()

    def start(self):
        """Start the background worker (idempotent).  Requests may be
        ``submit``ted before start() — they dispatch on the first wakeup,
        the deterministic sequencing the coalescing tests rely on.  Don't
        race start() against ``infer_one`` from other threads: infer_one
        picks its (sync vs queued) path by whether a worker exists."""
        with self._work:
            if self._worker is None:
                self._worker = self._spawn_worker()
            if self._slo is not None and self._watchdog is None:
                self._watchdog = threading.Thread(
                    target=self._watchdog_loop, daemon=True,
                    name="esac-serve-watchdog",
                )
                self._watchdog.start()

    def _spawn_worker(self) -> threading.Thread:
        """Build + start a worker thread bound to the CURRENT generation
        (lock held)."""
        t = threading.Thread(
            target=self._worker_loop, args=(self._gen,), daemon=True,
            name="esac-serve",
        )
        t.start()
        return t

    # ---------------- request path ----------------

    def submit(self, frame: dict, scene=None, route_k=None,
               deadline_ms: float | None = None,
               trace_ctx: Trace | None = None,
               n_hyps: int | None = None) -> _Request:
        """Enqueue one frame tree (optionally for a registry ``scene`` and
        a routed top-K program ``route_k``); returns a request whose
        ``event`` fires when ``result`` (or ``error``) is set.

        Without an SLO policy: blocks for queue space — backpressure
        across ALL lanes, never drops (the PR-2 contract).  With one:
        admission control instead — a full queue, a quarantined lane, or
        a predicted deadline miss raises a typed
        :class:`~esac_tpu.serve.slo.ShedError` subclass immediately, and
        the request carries ``deadline_ms`` (default
        ``slo.deadline_ms``).

        ``trace_ctx`` is a fleet :class:`~esac_tpu.obs.Trace` minted one
        tier up (FleetRouter sampling, ISSUE 15): the request gets a
        span chain and rides the registry fault path traced regardless
        of this dispatcher's own ``trace`` flag — the dispatcher stamps
        the CHILD chain, the router owns the root and the store.

        ``n_hyps`` rides the PR-8 per-dispatch hypothesis-budget override
        into the registry serve fn (the session lane's shrunken-budget
        knob, ISSUE 20).  An explicit ``n_hyps`` puts the request on its
        own coalescing lane — ``(scene, route_k, n_hyps)`` — so requests
        with different budgets (or different batch tree structures: the
        session lane's frames carry prior-pose leaves) never share a
        dispatch; outcome accounting stays keyed ``(scene, route_k)``."""
        t_submit = self._clock()
        if self._arrival_sink is not None and scene is not None:
            # Arrival tap for the prefetcher: outside the lock, before
            # admission — a shed request is still demand evidence.
            self._arrival_sink(scene)
        # An EXPLICIT deadline_ms is honored with or without a policy —
        # silently ignoring a requested bound would reintroduce the
        # unbounded-blocking bug for exactly the caller who asked not to
        # have it; the policy only supplies the default.
        if deadline_ms is None and self._slo is not None:
            deadline_ms = self._slo.deadline_ms
        deadline = (t_submit + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = _Request(frame, t_submit, scene, route_k, deadline, owner=self,
                       n_hyps=n_hyps)
        self._init_trace(req, trace_ctx, t_submit, scene)
        lane = (scene, route_k) if n_hyps is None \
            else (scene, route_k, n_hyps)
        with self._work:
            if self._slo is None:
                # Legacy backpressure — but a request WITH a deadline must
                # not strand in the space wait either: the bound applies
                # from the first instant, not only once queued.
                while self._n_pending >= self._depth and not self._closed \
                        and self._worker_dead is None:
                    remaining = (None if deadline is None
                                 else deadline - self._clock())
                    if remaining is not None and remaining <= 0:
                        self._count_offered()
                        self._count_outcome("expired", scene, route_k, None)
                        raise DeadlineExceededError(
                            "deadline expired waiting for queue space"
                        )
                    self._space.wait(remaining)
            self._raise_if_unservable()
            self._count_offered()
            if self._slo is not None:
                why = self._admission_reject(lane, req, t_submit)
                if why is not None:
                    self._count_outcome("shed", scene, route_k, None)
                    raise why
            q = self._pending.get(lane)
            if q is None:
                q = self._pending[lane] = collections.deque()
            q.append(req)
            self._n_pending += 1
            self._work.notify()
        return req

    def _init_trace(self, req: _Request, trace_ctx, t_submit, scene):
        """Arm tracing for one request: a fleet ``trace_ctx`` gets a
        fresh CHILD chain (the router owns the root); a standalone
        traced dispatcher mints its own :class:`~esac_tpu.obs.Trace`
        whose ROOT chain is the request's chain (``req.spans is
        req.trace.root`` marks dispatcher ownership — that is what
        _finish keys store publication on)."""
        if trace_ctx is not None:
            req.trace = trace_ctx
            req.spans = SpanChain("admitted", t_submit)
            if not self._tracing_any:
                self._tracing_any = True
        elif self._trace:
            req.trace = Trace(t_submit, scene=scene, root_stage="admitted")
            req.spans = req.trace.root

    def _raise_if_unservable(self):
        """Reject submissions to a server that can no longer serve them
        (lock held): closed, or the worker thread died with the queue —
        the typed replacement for stranding callers forever."""
        if self._worker_dead is not None:
            raise WorkerDiedError(self._worker_dead)
        if self._closed:
            raise DispatcherClosedError("dispatcher is closed")

    def _abandon(self, req: _Request, err) -> None:
        """Caller-side timeout: mark ``req`` expired so the worker skips
        it (if still queued) or its late result is discarded (if in
        flight) — the accounting then agrees with the error the caller
        saw.  No-op if the request already resolved."""
        with self._work:
            self._finish(req, error=err, outcome="expired")

    def _admission_reject(self, lane, req, now):
        """SLO admission control (lock held): the typed error to raise, or
        None to admit.  Sheds on quarantine, a full bounded queue, and a
        predicted deadline miss (dispatch-time EMA x dispatches queued
        ahead — rejecting in microseconds beats serving a corpse late).
        Predicted-miss shedding needs >= 2 completed dispatches behind
        the EMA: a single sample may be a compile-inflated outlier, and
        shedding on it would poison a healthy server forever (nothing
        would ever dispatch to correct the estimate)."""
        reason = self._quarantined.get(lane)
        if reason is not None:
            return LaneQuarantinedError(
                f"lane {lane} is quarantined ({reason}); release_lane() "
                "after the fault is cleared"
            )
        if self._n_pending >= self._depth:
            return ShedError(
                f"queue full ({self._n_pending}/{self._depth} pending)"
            )
        if (self._slo.shed_on_predicted_miss and req.deadline is not None
                and self._ema_n >= 2):
            # Dispatches needed before this request's own dispatch lands:
            # everything already queued, bucket-coalesced, plus its own.
            ahead = 1 + self._n_pending // self._buckets[-1]
            predicted = now + ahead * self._ema_dispatch_s
            if predicted > req.deadline:
                return ShedError(
                    f"predicted wait {ahead * self._ema_dispatch_s * 1e3:.1f}ms "
                    f"exceeds deadline "
                    f"({(req.deadline - now) * 1e3:.1f}ms remaining)"
                )
        return None

    def infer_one(self, frame: dict, scene=None, route_k=None,
                  timeout: float | None = None,
                  deadline_ms: float | None = None,
                  n_hyps: int | None = None) -> dict:
        """Blocking single-frame inference through the batching queue.

        ``timeout`` bounds the wait in seconds (independent of any SLO);
        ``deadline_ms`` rides the request into the queue (SLO mode).  On a
        deadline/timeout the request is abandoned — marked expired so a
        late result is discarded — and a typed
        :class:`DeadlineExceededError` is raised: no caller ever blocks
        past its deadline, even when the dispatch path is wedged.

        The worker-less sync mode (``start_worker=False``) executes the
        dispatch in the CALLER's thread, so a wedged ``infer_fn`` cannot
        be interrupted there; the bounds are instead enforced at
        completion — a result landing past ``deadline_ms``/``timeout``
        raises :class:`DeadlineExceededError` (outcome expired) rather
        than being returned as served."""
        with self._work:
            has_worker = self._worker is not None
        if not has_worker:
            t_submit = self._clock()
            if self._arrival_sink is not None and scene is not None:
                self._arrival_sink(scene)  # sync path: same tap as submit()
            if deadline_ms is None and self._slo is not None:
                deadline_ms = self._slo.deadline_ms
            bounds = ([t_submit + deadline_ms / 1e3]
                      if deadline_ms is not None else [])
            bounds += [t_submit + timeout] if timeout is not None else []
            req = _Request(frame, t_submit, scene, route_k,
                           min(bounds) if bounds else None, owner=self,
                           n_hyps=n_hyps)
            self._init_trace(req, None, t_submit, scene)
            lane = (scene, route_k) if n_hyps is None \
                else (scene, route_k, n_hyps)
            with self._work:
                self._raise_if_unservable()
                self._count_offered()
                # Same lock acquisition as the offered count: the request
                # must never be observable in neither table (the invariant
                # holds at every instant on the sync path too).
                self._inflight = _Inflight(None, lane, [req], t_submit)
            self._run([req], lane, route_k, False, None)
        else:
            if deadline_ms is None and timeout is not None:
                # The timeout is an end-to-end bound: riding it into the
                # queue as the deadline bounds the space wait and queue
                # residency too, not just the event wait at the end.
                deadline_ms = timeout * 1e3
            req = self.submit(frame, scene, route_k, deadline_ms,
                              n_hyps=n_hyps)
            limit = timeout
            if req.deadline is not None:
                # Clamp to the REMAINING deadline window: submit() may
                # have consumed part of it in the space wait, and a fresh
                # full `timeout` anchored here would let the caller block
                # up to ~2x the requested end-to-end bound.
                remaining = max(0.0, req.deadline - self._clock())
                limit = remaining if limit is None else min(limit, remaining)
            if not req.event.wait(limit):
                self._abandon(
                    req,
                    DeadlineExceededError(
                        f"request exceeded its "
                        f"{'deadline' if timeout is None else 'timeout'} "
                        f"after {(self._clock() - req.t_submit) * 1e3:.1f}ms"
                    ),
                )
        if req.error is not None:
            raise req.error
        return req.result

    def infer_many(self, frames: list[dict], scene=None,
                   route_k=None, n_hyps=None) -> list[dict]:
        """Bulk inference: bucket-planned dispatches, staging double-buffered
        against in-flight compute.  Returns per-frame result trees (host
        numpy), in input order.  Bulk submission is inherently
        backpressured — each dispatch blocks the caller — so SLO admission
        control does not apply here; outcomes still land in the
        accounting."""
        import jax
        import numpy as np

        t_submit = self._clock()
        if self._arrival_sink is not None and scene is not None:
            for _ in frames:  # bulk arrivals weigh their frame count
                self._arrival_sink(scene)
        plan = plan_dispatches(len(frames), self._buckets)
        bounds = []
        lo = 0
        for n in plan:
            bounds.append((lo, lo + n))
            lo += n

        def stage(lo, hi):
            bucket = pick_bucket(hi - lo, self._buckets)
            padded, n_valid = self._staging.stage(frames[lo:hi], bucket)
            return jax.device_put(padded), n_valid, bucket

        results: list[dict] = []
        staged = stage(*bounds[0])
        for i in range(len(bounds)):
            tree, n_valid, bucket = staged
            # async dispatch: compute starts
            out = self._call(tree, scene, route_k, n_hyps)
            if i + 1 < len(bounds):
                staged = stage(*bounds[i + 1])  # host staging overlaps compute
            out = jax.block_until_ready(out)
            t_done = self._clock()
            # Flatten-once transfer + leaf-indexed slicing (same fast
            # path as _dispatch).
            leaves, treedef = jax.tree.flatten(out)
            host_leaves = self._staging.unalias(
                [np.asarray(x) for x in leaves]
            )
            with self._lock:
                self._record(
                    bucket, n_valid, scene,
                    route_k, [t_done - t_submit] * n_valid,
                )
                self._count_offered(n_valid)
                # Bulk serves ride the per-request trail too: the ring and
                # the counters must tell one story on a mixed-traffic
                # server.
                self._count_outcome("served", scene, route_k, route_k,
                                    n=n_valid)
            results.extend(
                treedef.unflatten([hl[j] for hl in host_leaves])
                for j in range(n_valid)
            )
        return results

    # ---------------- worker ----------------

    def _call(self, tree, scene, route_k=None, n_hyps=None):
        """Invoke the entry point: scene-carrying dispatches pass the scene
        (and, for routed programs, ``route_k``; for budget-override lanes,
        ``n_hyps``) through — registry serve fns take
        ``(tree, scene[, route_k[, n_hyps]])``; legacy traffic keeps the
        one-argument contract byte-for-byte."""
        if n_hyps is not None:
            return self._infer(tree, scene, route_k, n_hyps)
        if route_k is not None:
            return self._infer(tree, scene, route_k)
        if scene is None:
            return self._infer(tree)
        return self._infer(tree, scene)

    def _count_offered(self, n: int = 1):
        """Count ``n`` offered requests (lock held): legacy attribute and
        obs counter move in the same critical section, so the two can
        never tell different stories."""
        self.offered += n
        self._m_offered.inc(n)

    def _count_outcome(self, outcome, scene, route_k, eff_k, n: int = 1):
        """Count ``n`` requests into one terminal outcome class (lock
        held): Counter + ring trail + obs counter, one choke point."""
        self.outcome_counts[outcome] += n
        self.outcome_log.extend(
            (outcome, scene, route_k, eff_k) for _ in range(n)
        )
        self._m_outcomes.inc(n, outcome=outcome)

    def _stamp(self, reqs, stage, t=None):
        """Span-stamp every traced request in ``reqs`` — a no-op (one
        attribute check) with tracing off.  Chains are only ever written
        by the thread that currently owns the request/batch, so no lock
        is involved.  Requests already resolved (abandoned by caller
        timeout / watchdog while this dispatch was in flight) are
        skipped best-effort; the unavoidable race remnant — a late stamp
        landing after the terminal one — is made inert by the chain's
        read-side truncation (obs.trace).  The gate covers fleet
        trace_ctx requests too (``_tracing_any`` flips on the first
        one); per-request ``spans`` checks below keep mixed batches
        correct."""
        if not self._tracing_any:
            return
        if t is None:
            t = self._clock()
        for r in reqs:
            if r.spans is not None and not r.done:
                r.spans.stamp(stage, t)

    def _record(self, bucket, n_valid, scene, route_k, latencies):
        """Append one dispatch to the bounded stat rings (lock held)."""
        self.dispatch_log.append((bucket, n_valid))
        self.scene_log.append(scene)
        self.route_log.append(route_k)
        self.dispatch_counts[(scene, route_k)] += 1
        self.latencies_s.extend(latencies)
        self._m_dispatches.inc(scene=scene, route_k=route_k)
        # Bulk publish: two histogram-lock acquisitions per DISPATCH
        # (was two per lane-latency sample).
        self._m_latency.observe_many(latencies)
        self._m_lane_latency.observe_many(latencies, scene=scene,
                                          route_k=route_k)

    def _finish(self, req: _Request, result=None, error=None,
                outcome: str = "served", eff_k=None,
                count: bool = True) -> bool:
        """Resolve one request exactly once (lock held).  Returns False if
        the request was already resolved — a late result from an abandoned
        (wedged, expired) dispatch is DISCARDED here, which is what makes
        watchdog/timeout abandonment safe against the worker eventually
        unsticking.  ``count=False`` defers the outcome accounting to the
        caller, which MUST publish one aggregate ``_count_outcome`` for
        every True return before releasing the lock (the batched
        completion path in ``_run``)."""
        if req.done:
            return False
        req.done = True
        req.result = result
        req.error = error
        req.outcome = outcome
        req.t_done = self._clock()
        if count:
            self._count_outcome(outcome, req.scene, req.route_k, eff_k)
        if req.spans is not None:
            # Terminal stamp at t_done: the chain's total now telescopes
            # to the measured end-to-end latency, and each stage duration
            # lands in the stage histogram.
            req.spans.stamp(outcome, req.t_done)
            for stage, dt in req.spans.durations().items():
                self._m_stage.observe(dt, stage=stage)
            if req.trace is not None and req.spans is req.trace.root:
                # Dispatcher-minted trace: the request's chain IS the
                # root (terminally stamped above, so the trace only
                # needs its outcome/done marks — parent None == root)
                # and this dispatcher's ring-bounded store is its home.
                # Fleet traces (trace_ctx) are finished by the router.
                req.trace.outcome = outcome
                req.trace.done = True
                if self._trace_store is not None:
                    self._trace_store.add(req.trace)
        req.event.set()
        return True

    def _drain_lane(self, lane, error_factory, outcome: str) -> None:
        """Fail every request still queued on ``lane`` (lock held) — used
        when the lane is quarantined so its backlog cannot re-wedge the
        replacement worker."""
        q = self._pending.pop(lane, None)
        if q is None:
            return
        for r in q:
            if r.done:
                self._n_pending -= 1
            elif self._finish(r, error=error_factory(), outcome=outcome):
                self._n_pending -= 1
        self._space.notify_all()

    def _prepare_batch(self, batch: list[_Request], lane):
        """SLO pre-dispatch pass (lock held): drop requests that are
        already resolved (abandoned by their caller) or past their
        deadline, and decide the dispatch's effective route_k — under
        overload the lane downshifts one rung of the degradation ladder
        (a cheaper static program from the SAME compiled family; never a
        recompile).  Returns (live requests, effective_k, degraded?)."""
        scene, route_k = lane[0], lane[1]
        now = self._clock()
        live = []
        for r in batch:
            if r.done:
                continue  # abandoned by its caller; outcome already counted
            # Drop only the ACTUALLY expired: a predicted-to-miss request
            # rides the dispatch anyway (padding makes the lane free, and
            # if the EMA was a compile-inflated outlier the completion
            # corrects it); a completion that really lands late counts
            # expired at fan-out, never served.
            if r.deadline is not None and now > r.deadline:
                self._finish(
                    r,
                    error=DeadlineExceededError(
                        f"expired in queue after "
                        f"{(now - r.t_submit) * 1e3:.1f}ms"
                    ),
                    outcome="expired",
                )
                continue
            live.append(r)
        eff_k, degraded = route_k, False
        if (live and self._slo is not None
                and (scene is not None or route_k is not None)
                and self._n_pending + len(live) >= max(
                    1, int(self._slo.degrade_queue_frac * self._depth))):
            down = self._slo.degrade_k(route_k)
            if down != route_k:
                eff_k, degraded = down, True
        return live, eff_k, degraded

    def _hold_deadline(self, first: _Request) -> float:
        """How long the worker may hold ``first`` to coalesce (lock held):
        the configured window, shrunk so that (hold + a dispatch with
        HEADROOM) still lands inside the request's deadline — adaptive
        serve_max_wait under SLO pressure.  The reserve is 1.5x the EMA
        (scheduling jitter margin), or half the request's remaining
        budget before any dispatch has been measured — a reserve of
        exactly the EMA (or zero) would hold a lone tight-deadline
        request right up to its deadline and deterministically expire it
        on an idle server."""
        hold = first.t_submit + self._max_wait_s
        if first.deadline is not None:
            if self._ema_n:
                reserve = 1.5 * self._ema_dispatch_s
            else:
                reserve = 0.5 * max(first.deadline - first.t_submit, 0.0)
            hold = min(hold, first.deadline - reserve)
        return hold

    def _worker_loop(self, gen: int):
        big = self._buckets[-1]
        try:
            while True:
                with self._work:
                    while not self._n_pending and not self._closed \
                            and gen == self._gen:
                        self._work.wait()
                    if gen != self._gen:
                        return  # abandoned by the watchdog: a new worker owns the queue
                    if not self._n_pending:
                        return  # closed and drained
                    # Fairness: serve the lane at the head of the round-robin
                    # order; if it still has pending work afterwards it moves to
                    # the back, so a flooding lane cannot starve the others.
                    lane, q = next(iter(self._pending.items()))
                    deadline = self._hold_deadline(q[0])
                    while len(q) < big and not self._closed \
                            and gen == self._gen:
                        remaining = deadline - self._clock()
                        if remaining <= 0:
                            break
                        self._work.wait(remaining)
                    if gen != self._gen:
                        return
                    # Re-fetch the lane: the watchdog's expiry sweep /
                    # quarantine drain may have emptied (or removed) it
                    # while the wait above had the lock released.
                    q = self._pending.get(lane)
                    if not q:
                        if q is not None:
                            del self._pending[lane]
                        continue
                    # serve_max_wait_ms == 0 means coalescing is OFF: exactly one
                    # request per dispatch (per-frame-call semantics), even when
                    # a burst is already queued.
                    take = 1 if self._max_wait_s == 0 else min(len(q), big)
                    batch = [q.popleft() for _ in range(take)]
                    self._n_pending -= take
                    if q:
                        self._pending.move_to_end(lane)
                    else:
                        del self._pending[lane]
                    self._space.notify_all()
                    batch, eff_k, degraded = self._prepare_batch(batch, lane)
                    if batch:
                        # Track the popped batch BEFORE the lock drops: in
                        # the gap until _run re-registers it, these
                        # requests are in neither _pending nor _inflight —
                        # a worker death there would strand their callers
                        # and the accounting would undercount pending.
                        self._inflight = _Inflight(gen, lane, batch,
                                                   self._clock())
                if batch:
                    self._run(batch, lane, eff_k, degraded, gen)
        except BaseException as e:  # noqa: BLE001 — a dying worker must not strand callers
            self._on_worker_death(gen, e)
            raise

    def _on_worker_death(self, gen, exc):
        """The worker thread is dying with the queue: fail every pending
        and in-flight request with a typed error and poison future
        submissions — callers wake instead of stranding forever."""
        with self._work:
            if gen is not None and gen != self._gen:
                return  # stale worker: the replacement owns the queue
            self._worker_dead = f"worker thread died: {exc!r}"
            err_reqs = []
            if self._inflight is not None:
                err_reqs += self._inflight.reqs
                self._inflight = None
            for q in self._pending.values():
                err_reqs += list(q)
            self._pending.clear()
            self._n_pending = 0
            for r in err_reqs:
                self._finish(r, error=WorkerDiedError(self._worker_dead),
                             outcome="failed")
            self._work.notify_all()
            self._space.notify_all()

    def _run(self, reqs: list[_Request], lane, eff_k, degraded, gen):
        """Execute one dispatch (worker thread or sync path), with SLO
        retry/quarantine handling.  ``gen`` is the worker generation (None
        on the sync path); a dispatch whose generation was abandoned by
        the watchdog discards its late outcome entirely."""
        scene, route_k = lane[0], lane[1]
        n_hyps = lane[2] if len(lane) > 2 else None
        self._stamp(reqs, "coalesced")
        # Trace context for the registry fault path (ISSUE 15): the
        # batch's traces ride a contextvar through the dispatch so the
        # weight cache / host tier / health machinery can record spans
        # without signature plumbing.  Zero-cost with tracing off (the
        # _tracing_any gate skips even the comprehension).
        traced = ([r.trace for r in reqs if r.trace is not None]
                  if self._tracing_any else [])
        attempt = 0
        while True:
            with self._work:
                if gen is not None and gen != self._gen:
                    return
                infl = _Inflight(gen, lane, reqs, self._clock())
                self._inflight = infl
            try:
                if traced:
                    with trace_scope(traced):
                        host, bucket, n_valid, t_done = self._dispatch(
                            reqs, scene, eff_k, n_hyps)
                else:
                    host, bucket, n_valid, t_done = self._dispatch(
                        reqs, scene, eff_k, n_hyps)
                # Host-side result slicing: inside the try — a malformed
                # result tree must fail THIS batch, never the worker — but
                # OUTSIDE the lock: admission control's microsecond-
                # rejection promise dies if submitters queue behind a
                # full bucket's fan-out.
                treedef, host_leaves = host
                results = [
                    treedef.unflatten([hl[i] for hl in host_leaves])
                    for i in range(len(reqs))
                ]
                self._stamp(reqs, "sliced")
            except Exception as e:  # noqa: BLE001 — fan the failure out
                attempt += 1
                with self._work:
                    stale = gen is not None and gen != self._gen
                    # Deterministic typed faults (retryable=False — e.g. a
                    # registry checksum mismatch or breaker shed, whose
                    # loader-level transients were already retried) fail
                    # the batch immediately: re-running the dispatch can
                    # only re-pay the fault and delay the typed outcome.
                    retrying = (not stale and self._slo is not None
                                and attempt <= self._slo.retry_max
                                and getattr(e, "retryable", True)
                                and not self._closed)
                    if retrying:
                        # Stay registered through the backoff (fresh age
                        # clock): the accounting invariant — outcomes +
                        # pending == offered — must hold at EVERY instant,
                        # and an unregistered in-flight batch would drop
                        # out of ``pending`` for the sleep window.
                        self._inflight = _Inflight(gen, lane, reqs,
                                                   self._clock())
                    elif not stale:
                        self._inflight = None
                        for r in reqs:
                            self._finish(r, error=e, outcome="failed")
                        if self._slo is not None:
                            self._fail_streak[lane] += 1
                            if self._fail_streak[lane] >= \
                                    self._slo.quarantine_after:
                                self._quarantined[lane] = (
                                    f"{self._fail_streak[lane]} consecutive "
                                    f"dispatch failures (last: {e!r})"
                                )
                                self._drain_lane(
                                    lane,
                                    lambda: LaneQuarantinedError(
                                        f"lane {lane} quarantined after "
                                        "repeated dispatch failures"
                                    ),
                                    "shed",
                                )
                if not retrying:
                    return
                time.sleep(self._slo.backoff_s(attempt))
                continue
            with self._work:
                if gen is not None and gen != self._gen:
                    return  # abandoned mid-dispatch: requests already failed
                self._inflight = None
                self._fail_streak[lane] = 0
                dt = t_done - infl.t_start
                self._ema_dispatch_s = (
                    dt if self._ema_n == 0
                    else 0.25 * dt + 0.75 * self._ema_dispatch_s
                )
                self._ema_n += 1
                self._record(bucket, n_valid, scene, route_k,
                             [t_done - r.t_submit for r in reqs])
                outcome = "degraded" if degraded else "served"
                n_ok = 0
                for r, res in zip(reqs, results):
                    if r.deadline is not None and t_done > r.deadline:
                        # Landed past the deadline: the SLO contract says
                        # this is not a serve — discard, count expired.
                        self._finish(
                            r,
                            error=DeadlineExceededError(
                                f"result landed "
                                f"{(t_done - r.deadline) * 1e3:.1f}ms past "
                                "the deadline"
                            ),
                            outcome="expired",
                        )
                    elif self._finish(r, result=res, outcome=outcome,
                                      eff_k=eff_k, count=False):
                        n_ok += 1
                if n_ok:
                    # Batched outcome publish: every cleanly-served
                    # request in this dispatch shares one outcome class,
                    # so ONE counter/ring update covers them all — still
                    # inside the same critical section as the _finish
                    # calls, so accounting and done-flags move together.
                    self._count_outcome(outcome, scene, route_k, eff_k,
                                        n=n_ok)
            return

    def _dispatch(self, reqs: list[_Request], scene, route_k, n_hyps=None):
        """Pad, stage and execute one dispatch; returns the host-side
        result tree + timing.  No dispatcher state is touched here — the
        caller owns locking and fan-out.  The span stamps reuse the
        timeline the dispatch path already walks (device_put, the async
        call, the block_until_ready the path ALWAYS performs) — tracing
        adds clock reads, never a sync."""
        import jax
        import numpy as np

        bucket = pick_bucket(len(reqs), self._buckets)
        padded, n_valid = self._staging.stage(
            [r.frame for r in reqs], bucket
        )
        staged = jax.device_put(padded)
        self._stamp(reqs, "staged")
        out = self._call(staged, scene, route_k, n_hyps)
        self._stamp(reqs, "dispatched")
        out = jax.block_until_ready(out)
        t_done = self._clock()
        self._stamp(reqs, "device", t_done)
        # Flatten ONCE for the whole batch: the device->host transfer is
        # one np.asarray per leaf, and per-request slicing becomes a
        # leaf-indexed unflatten (no per-request tree traversal).
        leaves, treedef = jax.tree.flatten(out)
        host_leaves = self._staging.unalias(
            [np.asarray(x) for x in leaves]
        )
        return (treedef, host_leaves), bucket, n_valid, t_done

    # ---------------- watchdog ----------------

    def _watchdog_loop(self):
        poll = self._slo.watchdog_poll_ms / 1e3
        limit = self._slo.watchdog_ms / 1e3
        while True:
            with self._work:
                if self._closed and self._inflight is None \
                        and not self._n_pending:
                    return
                now = self._clock()
                self._expire_queued(now)
                infl = self._inflight
                if infl is not None and now - infl.t_start > limit:
                    self._abandon_inflight(infl, now)
            time.sleep(poll)

    def _expire_queued(self, now):
        """Fail queued requests past their deadline (lock held) — the
        sweep that bounds waiting even while the worker is busy or
        wedged on another lane."""
        drop = []
        removed = 0
        for lane, q in self._pending.items():
            kept = []
            for r in q:
                if r.done:
                    self._n_pending -= 1
                    removed += 1
                elif r.deadline is not None and now > r.deadline:
                    self._finish(
                        r,
                        error=DeadlineExceededError(
                            f"expired in queue after "
                            f"{(now - r.t_submit) * 1e3:.1f}ms"
                        ),
                        outcome="expired",
                    )
                    self._n_pending -= 1
                    removed += 1
                else:
                    kept.append(r)
            if len(kept) != len(q):
                # Mutate IN PLACE: the worker may hold a reference to this
                # deque across a lock-released coalescing wait — swapping
                # the object under it would desync the pending count.
                q.clear()
                q.extend(kept)
            if not q:
                drop.append(lane)
        for lane in drop:
            del self._pending[lane]
        if removed:
            self._space.notify_all()

    def _abandon_inflight(self, infl: _Inflight, now):
        """Declare the in-flight dispatch wedged (lock held): fail its
        requests with a precise typed error INSIDE their deadline,
        quarantine the lane, abandon the stuck worker's generation and
        hand the healthy lanes to a replacement worker.  The stuck thread
        is never killed (CLAUDE.md: killing a process awaiting the relay
        wedges it permanently); when — if — it unsticks, its stale
        generation discards everything."""
        age_ms = (now - infl.t_start) * 1e3
        err = DispatchStalledError(
            f"dispatch on lane {infl.lane} made no progress for "
            f"{age_ms:.0f}ms (watchdog_ms={self._slo.watchdog_ms}); lane "
            "quarantined"
        )
        for r in infl.reqs:
            self._finish(r, error=err, outcome="failed")
        self._quarantined[infl.lane] = f"wedged dispatch ({age_ms:.0f}ms)"
        self._inflight = None
        # The quarantined lane's backlog must not re-wedge the replacement.
        self._drain_lane(
            infl.lane,
            lambda: LaneQuarantinedError(
                f"lane {infl.lane} quarantined (wedged dispatch)"
            ),
            "shed",
        )
        if infl.gen is not None and infl.gen == self._gen:
            self._gen += 1
            if not self._closed or self._n_pending:
                self._worker = self._spawn_worker()
            else:
                self._worker = None  # nothing left to drain: close() can stop joining the wedged thread
            self._work.notify_all()

    # ---------------- stats / lifecycle ----------------

    def latency_quantiles(self, qs=(0.5, 0.99)) -> dict[float, float]:
        """Per-request latency quantiles (seconds) over the recent
        window, read from the fleet obs streaming histogram in
        O(buckets) — the former implementation sorted the whole
        ``10*stats_window`` ``latencies_s`` deque under the dispatch
        lock on EVERY call, an O(n log n) hazard on a serving thread.
        The window is GLOBAL (most recent samples fleet-wide, matching
        the deque it replaced), not per-lane.  Values are sketch
        estimates within the histogram's pinned tolerance of exact
        nearest-rank (tests/test_obs.py); NaN when no samples, exactly
        as before."""
        return {q: self._m_latency.quantile(q) for q in qs}

    def dispatch_totals(self) -> dict:
        """Per-(scene, route_k) lifetime dispatch counts — a thin view
        over the obs ``serve_dispatches_total`` counter, snapshotted
        under the dispatch lock so it is write-consistent (every writer
        holds the lock; iterating ``dispatch_counts`` raw while the
        worker appends is a torn read; graft-lint R10 discipline applies
        to callers too)."""
        with self._lock:
            # Zero-valued children (a lane fully subtracted out by
            # reset_stats) are dropped: the legacy Counter never held
            # explicit zeros and the view's shape is pinned.
            return {
                (labels.get("scene"), labels.get("route_k")): int(v)
                for labels, v in self._m_dispatches.items() if v
            }

    def slo_totals(self) -> dict:
        """Locked snapshot of the outcome accounting — a thin view over
        the obs ``serve_offered_total``/``serve_outcomes_total`` counters
        (updated in the same critical sections as the legacy attributes)
        plus the live ``pending`` count.  The invariant — served + shed +
        expired + degraded + failed + pending == offered — is pinned by
        tests/test_serve_slo.py.  (A request abandoned by its caller
        stays physically queued until the next watchdog sweep; those are
        already counted in their outcome class, so only unresolved
        requests count as pending here.)"""
        with self._lock:
            out = {"offered": int(self._m_offered.total())}
            for o in ("served", "shed", "expired", "degraded", "failed"):
                out[o] = int(self._m_outcomes.get(outcome=o))
            out["pending"] = self._unresolved_count()
            return out

    def _unresolved_count(self) -> int:
        """Requests not yet in any outcome class (lock held): queued ones
        that are still live plus the not-yet-done in-flight batch.  BOTH
        ``slo_totals``'s pending and ``reset_stats``'s offered re-base
        depend on this exact computation — one definition, one truth."""
        infl = (sum(1 for r in self._inflight.reqs if not r.done)
                if self._inflight else 0)
        queued_done = sum(
            sum(1 for r in q if r.done) for q in self._pending.values()
        )
        return self._n_pending - queued_done + infl

    def quarantined_lanes(self) -> dict:
        """Locked snapshot: lane -> quarantine reason."""
        with self._lock:
            return dict(self._quarantined)

    def release_lane(self, scene=None, route_k=None, n_hyps=None) -> bool:
        """Operator action: clear a lane's quarantine + failure streak
        after the underlying fault (relay recovery, fixed weights) is
        resolved.  New submissions to the lane are admitted again.
        Idempotent — a double release (two operators racing the same
        runbook) is a no-op, and releasing a lane that a concurrent
        watchdog/fail-streak trip is about to quarantine is safe: both
        orders leave a consistent breaker state and exact accounting
        (pinned in tests/test_serve_slo.py).  True when a quarantine
        was actually cleared."""
        lane = (scene, route_k) if n_hyps is None \
            else (scene, route_k, n_hyps)
        with self._work:
            was = self._quarantined.pop(lane, None)
            self._fail_streak.pop(lane, None)
        return was is not None

    def reset_stats(self):
        """Clear the stat rings and outcome accounting.  ``offered`` is
        re-based to the requests still unresolved at reset time — they
        will land in the (now zeroed) outcome counts later, and a reset
        that set offered to 0 would break the accounting invariant
        forever on a busy server."""
        with self._lock:
            # The obs counter views re-base in the same critical section
            # by SUBTRACTING this dispatcher's own contribution (exactly
            # what the legacy books recorded): on a private registry
            # that leaves offered == unresolved and outcomes zero; on a
            # SHARED registry another dispatcher's history survives a
            # local reset instead of being wiped (review finding).
            # Histograms have no subtractable contribution — a local
            # reset clears them, one more shared-registry caveat the
            # class docstring states.
            unresolved = self._unresolved_count()
            self._m_offered.inc(-(self.offered - unresolved))
            for o, n in self.outcome_counts.items():
                if n:
                    self._m_outcomes.inc(-n, outcome=o)
            for (scene, route_k), n in self.dispatch_counts.items():
                if n:
                    self._m_dispatches.inc(-n, scene=scene,
                                           route_k=route_k)
            self._m_latency.reset()
            self._m_lane_latency.reset()
            self._m_stage.reset()
            self.latencies_s.clear()
            self.dispatch_log.clear()
            self.scene_log.clear()
            self.route_log.clear()
            self.dispatch_counts.clear()
            self.outcome_counts.clear()
            self.outcome_log.clear()
            self.offered = unresolved

    def cache_size(self) -> int | None:
        """Compiled-program count of the jitted entry point (None when the
        infer fn does not expose jit cache introspection)."""
        probe = getattr(self._infer, "_cache_size", None)
        return probe() if callable(probe) else None

    def close(self):
        """Drain the queue, stop the worker, reject new submissions.
        Anything a (dead, wedged, or never-started) worker cannot drain is
        failed with a typed error — close() never strands a caller."""
        with self._work:
            self._closed = True
            self._work.notify_all()
            self._space.notify_all()
        # Let the live worker drain.  Bounded join slices: if the watchdog
        # replaces a wedged worker mid-close, switch to joining the
        # replacement (the stuck daemon thread is abandoned, never killed).
        while True:
            with self._work:
                worker = self._worker
            if worker is None or worker is threading.current_thread() \
                    or not worker.is_alive():
                break
            worker.join(0.2)
            with self._work:
                replaced = self._worker is not worker
            if not replaced and not worker.is_alive():
                break
            if not replaced and self._slo is None:
                # Legacy mode drains the whole queue, but inside a
                # bounded window (R18): a wedged relay must not hang
                # close() forever — leftovers fail typed below and the
                # daemon thread is abandoned, never killed.
                worker.join(_LEGACY_DRAIN_JOIN_S)
                break
        # Fail whatever could not drain (no worker ever started, worker
        # dead, quarantined lanes) so every waiter wakes.
        with self._work:
            leftovers = []
            if self._inflight is not None:
                leftovers += self._inflight.reqs
                self._inflight = None
            for q in self._pending.values():
                leftovers += [r for r in q if not r.done]
            self._pending.clear()
            self._n_pending = 0
            for r in leftovers:
                self._finish(
                    r,
                    error=DispatcherClosedError(
                        "dispatcher closed with the request still pending"
                    ),
                    outcome="failed",
                )
            watchdog = self._watchdog
        if watchdog is not None and watchdog is not threading.current_thread():
            # Exits within one watchdog poll of _closed; bounded join
            # (R18) so even a wedged poll cannot hang close() — the
            # daemon thread is abandoned past the budget.
            watchdog.join(_WATCHDOG_JOIN_S)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def make_dsac_serve_fn(c, cfg: RansacConfig = RansacConfig()):
    """Jitted frames-major single-map (dsac) entry over a frame tree with
    leaves ``key`` (typed PRNG), ``coords`` (N, 3), ``pixels`` (N, 2),
    ``f`` (scalar focal).  One compile per bucket."""
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.kernel import dsac_infer_frames

    c = jnp.asarray(c)

    @jax.jit
    def serve_dsac(batch):
        return dsac_infer_frames(
            batch["key"], batch["coords"], batch["pixels"], batch["f"], c, cfg
        )

    return serve_dsac


def make_esac_serve_fn(c, cfg: RansacConfig = RansacConfig()):
    """Jitted frames-major multi-expert (esac) entry over a frame tree with
    leaves ``key``, ``gating_logits`` (M,), ``coords_all`` (M, N, 3),
    ``pixels`` (N, 2), ``f``."""
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.esac import esac_infer_frames

    c = jnp.asarray(c)

    @jax.jit
    def serve_esac(batch):
        return esac_infer_frames(
            batch["key"], batch["gating_logits"], batch["coords_all"],
            batch["pixels"], batch["f"], c, cfg,
        )

    return serve_esac


def make_sharded_serve_fn(mesh, c, cfg: RansacConfig = RansacConfig()):
    """Jitted frames-major EXPERT-SHARDED entry (config #4's mesh) over a
    frame tree with leaves ``key``, ``coords_all`` (M, N, 3), ``pixels``,
    ``f`` — the same micro-batching front-end reused for the sharded path;
    M must divide the mesh's expert axis."""
    from esac_tpu.parallel.esac_sharded import make_esac_infer_sharded_frames

    return make_esac_infer_sharded_frames(mesh, c, cfg, as_tree=True)
