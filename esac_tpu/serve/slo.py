"""SLO machinery for the serving stack: typed outcomes, policy, faults.

Everything the dispatcher needed to go from *benchmarked* to *operable*
under open-loop load (DESIGN.md §12).  Three pieces live here:

- **Typed request outcomes** (:class:`ServeError` tree): a caller of
  ``infer_one``/``submit`` can catch exactly the failure class it can act
  on — :class:`ShedError` (admission control said no; retry elsewhere),
  :class:`DeadlineExceededError` (the request expired; the answer is
  worthless now), :class:`DispatchStalledError` (the dispatch wedged and
  the watchdog failed it — the environment's observed relay-stall mode,
  CLAUDE.md hazards), :class:`LaneQuarantinedError` (the lane is known
  bad until an operator releases it), :class:`WorkerDiedError` /
  :class:`DispatcherClosedError` (the server is gone; nothing queued will
  ever run).

- **:class:`SLOPolicy`**: one frozen host-side knob set handed to the
  dispatcher.  It deliberately does NOT ride
  :class:`~esac_tpu.ransac.config.RansacConfig` — every field there is a
  static jit argument and participates in the compiled-program hash,
  while SLO knobs are pure host scheduling state that must be tunable on
  a live server without touching the jit cache.

- **:class:`FaultInjector`**: the injectable stall/failure shim on the
  dispatch path.  The relay stall this repo has actually observed (a
  trainer frozen mid-run, socket ESTAB, request outstanding forever) is
  indistinguishable from an ``infer_fn`` call that never returns, so the
  shim simulates exactly that: it wraps the dispatcher's ``infer_fn`` and
  can be armed to block on an Event (a stall the test releases later) or
  to raise (a transient failure) on the Nth dispatch.  Tests drive it;
  the watchdog in ``serve.dispatcher`` is what production relies on.
"""

from __future__ import annotations

import dataclasses
import threading


class ServeError(RuntimeError):
    """Base class of every typed serving failure.

    ``retryable`` tells the dispatcher's transient-failure retry loop
    whether re-running the dispatch could possibly change the outcome:
    subclasses representing *deterministic* faults (a checksum mismatch,
    a breaker-shed scene — see esac_tpu.registry.health) set it False
    and the dispatcher fails the batch immediately instead of re-paying
    the fault ``retry_max`` times.

    ``wire_name`` is the class's stable cross-process identity (the
    ROADMAP item-2 serialization seam): a typed error crossing an RPC
    wire is identified by this snake_case token, never by a Python
    qualname, so classes can move between modules without breaking
    peers.  Every taxonomy member declares BOTH attributes explicitly
    as literals — graft-audit v5 (LINT.md R16) enforces it statically,
    inheritance is deliberately not enough."""

    retryable = True
    wire_name = "serve"


class ShedError(ServeError):
    """Admission control rejected the request before it entered the queue
    (bounded queue full, or predicted wait exceeds the request's SLO)."""

    retryable = True
    wire_name = "shed"


class LaneQuarantinedError(ShedError):
    """The request's (scene, route_k) lane is quarantined after a wedged or
    repeatedly failing dispatch; an operator must ``release_lane`` it.
    A quarantine rejection is a shed (it happens at admission), so callers
    that only distinguish *admitted vs not* can catch :class:`ShedError`."""

    # Retryable: other lanes (and, one tier up, other replicas) still
    # serve — a re-submit routed elsewhere can succeed.
    retryable = True
    wire_name = "lane_quarantined"


class DeadlineExceededError(ServeError):
    """The request missed its deadline — expired in the queue, or the
    caller's wait timed out before a result landed."""

    retryable = True
    wire_name = "deadline_exceeded"


class DispatchStalledError(ServeError):
    """The watchdog declared the in-flight dispatch wedged (no progress
    within ``SLOPolicy.watchdog_ms``) and failed its requests rather than
    letting callers hang — the relay-stall failure mode made a bounded,
    typed error."""

    retryable = True
    wire_name = "dispatch_stalled"


class WorkerDiedError(ServeError):
    """The dispatcher's worker thread died with requests pending; nothing
    queued will ever dispatch.  Pending and future requests fail with
    this instead of stranding their callers forever."""

    # Retryable: the fleet tier treats a dead worker as a replica fault
    # and fails the request over to a surviving replica.
    retryable = True
    wire_name = "worker_died"


class DispatcherClosedError(ServeError):
    """``close()`` ran while requests were still pending and no worker
    could drain them."""

    # Closed is deliberate: nothing on THIS dispatcher will ever serve
    # again (the fleet tier may still fail over, but that is routing,
    # not a retry of the same dispatch).
    retryable = False
    wire_name = "dispatcher_closed"


class ConfigError(ServeError, ValueError):
    """Caller misuse of the serving API outside a constructor: a bad
    argument to an already-built dispatcher/router/loadgen surface
    (``route_k`` out of range, an unknown bucket, a non-positive rate).
    Deterministic — retrying the same call cannot help.  Subclasses
    ``ValueError`` too so pre-taxonomy callers (and tests) catching
    ``ValueError`` keep working; ``__init__`` validation itself stays on
    bare ``ValueError`` (the R16 sanctioned near-miss)."""

    retryable = False
    wire_name = "config"


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Host-side serving SLO knobs (see module docstring for why these do
    not live on RansacConfig).  Passing a policy to the dispatcher opts
    the request path into deadlines, admission control, degradation and
    the watchdog; without one the PR-2 contract (block-for-space, wait
    forever) is preserved byte-for-byte."""

    # Default per-request deadline, milliseconds; None = no deadline (the
    # other SLO machinery — shed-on-full, watchdog, degradation — still
    # applies).  ``submit``/``infer_one`` may override per request.
    deadline_ms: float | None = None
    # Admission control: with a bounded queue at capacity the dispatcher
    # SHEDS (typed ShedError) instead of blocking the submitter — open-loop
    # traffic must never convert overload into unbounded caller threads.
    # Additionally, a request whose PREDICTED wait (dispatch-time EMA x
    # queue occupancy ahead of it) already exceeds its deadline is shed at
    # submit time: rejecting in microseconds beats serving a corpse late.
    shed_on_predicted_miss: bool = True
    # Graceful degradation: when queue occupancy (pending / depth) reaches
    # this fraction, a lane's dispatches downshift ``route_k`` one rung
    # down ``degrade_route_k`` (ascending K ladder).  PR 4 made "cheaper"
    # a STATIC program we already compile — K is a static argument of the
    # routed bucket programs — so degrading swaps to an
    # already-compiled-family program and never recompiles (pinned in
    # tests/test_serve_slo.py).  Empty ladder = degradation off.
    degrade_queue_frac: float = 0.75
    degrade_route_k: tuple[int, ...] = ()
    # Watchdog: an in-flight dispatch older than this is declared wedged —
    # its requests fail with DispatchStalledError, the lane is
    # quarantined, and a replacement worker takes over the other lanes.
    # Size it to a few healthy dispatch times and BELOW deadline_ms, so
    # the watchdog (not the caller's own timeout) is what fires first.
    # PREWARM before serving (SceneRegistry.prewarm_programs, or drive
    # each program once through a worker-less dispatcher): a first-compile
    # dispatch takes seconds and is indistinguishable from a stall, so a
    # cold program under a tight watchdog gets its lane quarantined at
    # the first request — typed and bounded, but not what you wanted.
    watchdog_ms: float = 1_000.0
    # Watchdog poll interval (also bounds how stale queue-expiry is).
    watchdog_poll_ms: float = 20.0
    # Transient-failure retries per dispatch (an infer_fn that RAISES, as
    # opposed to one that hangs), with capped exponential backoff.
    retry_max: int = 1
    retry_backoff_ms: float = 10.0
    retry_backoff_max_ms: float = 200.0
    # Consecutive exhausted-retry dispatch failures on one lane before the
    # lane is quarantined (a wedged dispatch quarantines immediately).
    quarantine_after: int = 2

    def __post_init__(self):
        if self.deadline_ms is not None and self.deadline_ms <= 0:
            raise ValueError(f"deadline_ms {self.deadline_ms} <= 0")
        if not 0.0 < self.degrade_queue_frac <= 1.0:
            raise ValueError(
                f"degrade_queue_frac {self.degrade_queue_frac} outside (0, 1]"
            )
        if any(k < 1 for k in self.degrade_route_k):
            raise ValueError(
                f"degrade_route_k {self.degrade_route_k} has entries < 1"
            )
        if self.watchdog_ms <= 0 or self.watchdog_poll_ms <= 0:
            raise ValueError("watchdog_ms / watchdog_poll_ms must be > 0")
        if self.retry_max < 0 or self.quarantine_after < 1:
            raise ValueError("retry_max >= 0 and quarantine_after >= 1")

    def degrade_k(self, route_k: int | None) -> int | None:
        """The next-cheaper rung for a lane at ``route_k``: dense (None)
        downshifts to the ladder's LARGEST K (nearest-quality cheaper
        program); routed K to the largest rung strictly below K; already
        at/below the bottom rung stays put.  One rung per dispatch — the
        degradation is gradual, not a cliff."""
        ladder = sorted(set(self.degrade_route_k))
        if not ladder:
            return route_k
        if route_k is None:
            return ladder[-1]
        below = [k for k in ladder if k < route_k]
        return below[-1] if below else route_k

    def backoff_s(self, attempt: int) -> float:
        """Capped exponential backoff before retry ``attempt`` (1-based)."""
        return min(
            self.retry_backoff_ms * (2 ** (attempt - 1)),
            self.retry_backoff_max_ms,
        ) / 1e3


class FaultInjector:
    """Stall/failure shim wrapping a dispatcher ``infer_fn`` — and, since
    ISSUE 9, the registry's checkpoint-read path.

    Dispatch path: arm with :meth:`stall_once` (the Nth call blocks on an
    Event until the test releases it — byte-for-byte the observed relay
    stall from the worker thread's point of view) or :meth:`fail_times`
    (the next calls raise).  Unarmed calls pass straight through.

    Per-replica targeting (ISSUE 14): each injector may carry a ``tag``
    (the fleet drill tags one injector per replica with the replica
    name), and the dispatch-path armings take an optional ``match``
    predicate over the dispatch context ``{"tag", "scene", "route_k"}``
    — so one drill recipe can arm every replica's injector identically
    and still fault exactly one replica (or one scene on one replica)
    without touching the others.  Unmatched armed calls pass through
    untouched and are counted (``dispatch_unmatched`` in
    :meth:`stats`), so the drill can assert the fault landed where it
    aimed and nowhere else.

    Registry path: :meth:`checkpoint_reader` wraps a checkpoint-reading
    fn (``load_checkpoint``-shaped: path -> (params, config)), making
    every way a scene load can go bad drillable — :meth:`fail_loads`
    (transient IO fault: the loader's retry/backoff is what's under
    test), :meth:`corrupt_loads` (the read returns bit-flipped content,
    so manifest checksum verification must catch it), and
    :meth:`stall_loads` (a read that never returns — the relay-stall
    mode on the cold-load path; the weight cache's per-key load futures
    keep other scenes servable while the watchdog handles the wedge).
    Each arming takes an optional ``match`` predicate over the checkpoint
    path so a multi-scene drill can fault exactly one scene.

    All mutable state is guarded by the instance lock (graft-lint R10
    applies to this module); stall waits happen OUTSIDE the lock so
    stats stay readable while a dispatch or load is wedged.
    """

    def __init__(self, infer_fn=None, tag=None):
        self._infer = infer_fn
        self.tag = tag  # immutable identity (e.g. the replica name)
        self._cache_size = getattr(infer_fn, "_cache_size", None)
        self._lock = threading.Lock()
        self._stall_release: threading.Event | None = None
        self._stall_after = 0
        self._stall_match = None
        self._fail_exc: Exception | None = None
        self._fail_left = 0
        self._fail_match = None
        self._calls = 0
        self._stalls = 0
        self._failures = 0
        self._unmatched = 0
        # Registry-path (checkpoint read) arming + counters.
        self._load_fail_exc: Exception | None = None
        self._load_fail_left = 0
        self._load_fail_match = None
        self._load_corrupt_left = 0
        self._load_corrupt_match = None
        self._load_stall_release: threading.Event | None = None
        self._load_stall_after = 0
        self._load_stall_match = None
        self._load_calls = 0
        self._load_failures = 0
        self._load_corruptions = 0
        self._load_stalls = 0

    def stall_once(self, release: threading.Event, after: int = 0,
                   match=None) -> None:
        """Arm ONE stall: the ``after``-th MATCHING call from now blocks
        on ``release`` (0 = the very next one).  ``match`` is a
        predicate over the dispatch context dict (``tag``/``scene``/
        ``route_k``); None matches every call — the pre-ISSUE-14
        contract, byte-for-byte."""
        with self._lock:
            self._stall_release = release
            self._stall_after = after
            self._stall_match = match

    def fail_times(self, exc: Exception, times: int = 1,
                   match=None) -> None:
        """Arm ``times`` consecutive MATCHING failures raising ``exc``
        (``match`` as in :meth:`stall_once`)."""
        with self._lock:
            self._fail_exc = exc
            self._fail_left = times
            self._fail_match = match

    # ---- registry-path (checkpoint read) arming ----

    def fail_loads(self, exc: Exception, times: int = 1, match=None) -> None:
        """Arm ``times`` checkpoint reads (matching ``match``, a predicate
        over the path string; None = every read) to raise ``exc`` —
        the transient-IO-fault drill for the loader's retry/backoff."""
        with self._lock:
            self._load_fail_exc = exc
            self._load_fail_left = times
            self._load_fail_match = match

    def corrupt_loads(self, times: int = 1, match=None) -> None:
        """Arm ``times`` matching reads to return CORRUPTED content (the
        first float leaf bit-flipped): the read itself succeeds, so only
        manifest checksum verification stands between the corruption and
        served garbage — exactly the gap under drill."""
        with self._lock:
            self._load_corrupt_left = times
            self._load_corrupt_match = match

    def stall_loads(self, release: threading.Event, after: int = 0,
                    match=None) -> None:
        """Arm ONE matching read to block on ``release`` (``after`` later
        matching reads first) — the relay stall on the cold-load path."""
        with self._lock:
            self._load_stall_release = release
            self._load_stall_after = after
            self._load_stall_match = match

    def checkpoint_reader(self, read_fn):
        """Wrap a ``path -> (params, config)`` checkpoint reader with the
        armed registry faults.  Hand the result to
        ``load_scene_params(..., read_checkpoint=...)`` (or a
        functools.partial of it as a cache loader)."""

        def read(path):
            release = None
            corrupt = False
            p = str(path)
            with self._lock:
                self._load_calls += 1
                if self._load_stall_release is not None and (
                        self._load_stall_match is None
                        or self._load_stall_match(p)):
                    if self._load_stall_after <= 0:
                        release = self._load_stall_release
                        self._load_stall_release = None
                        self._load_stalls += 1
                    else:
                        self._load_stall_after -= 1
                if release is None and self._load_fail_left > 0 and (
                        self._load_fail_match is None
                        or self._load_fail_match(p)):
                    self._load_fail_left -= 1
                    self._load_failures += 1
                    exc = self._load_fail_exc
                    raise exc
                if release is None and self._load_corrupt_left > 0 and (
                        self._load_corrupt_match is None
                        or self._load_corrupt_match(p)):
                    self._load_corrupt_left -= 1
                    self._load_corruptions += 1
                    corrupt = True
            if release is not None:
                release.wait()  # the cold-load wedge
            params, config = read_fn(path)
            if corrupt:
                params = _bitflip_first_leaf(params)
            return params, config

        return read

    def stats(self) -> dict:
        with self._lock:
            return {
                "tag": self.tag,
                "calls": self._calls,
                "stalls": self._stalls,
                "failures": self._failures,
                "dispatch_unmatched": self._unmatched,
                "load_calls": self._load_calls,
                "load_failures": self._load_failures,
                "load_corruptions": self._load_corruptions,
                "load_stalls": self._load_stalls,
            }

    def __call__(self, tree, *rest):
        ctx = {
            "tag": self.tag,
            "scene": rest[0] if rest else None,
            "route_k": rest[1] if len(rest) > 1 else None,
        }
        release = None
        with self._lock:
            self._calls += 1
            armed = (self._stall_release is not None
                     or self._fail_left > 0)
            if self._stall_release is not None and (
                    self._stall_match is None or self._stall_match(ctx)):
                if self._stall_after <= 0:
                    release = self._stall_release
                    self._stall_release = None
                    self._stalls += 1
                else:
                    self._stall_after -= 1
            if release is None and self._fail_left > 0 and (
                    self._fail_match is None or self._fail_match(ctx)):
                self._fail_left -= 1
                self._failures += 1
                exc = self._fail_exc
                raise exc
            if release is None and armed:
                # An armed fault existed but this call passed clean:
                # either the predicate declined it, or the stall is
                # still counting down.  The drill's "nowhere else"
                # assertion reads this.
                self._unmatched += 1
        if release is not None:
            release.wait()  # the wedge: held until the test releases it
        return self._infer(tree, *rest)


def _bitflip_first_leaf(params):
    """Copy ``params`` with its first array leaf perturbed — simulated
    read corruption (content changes, structure survives, so the damage
    is invisible to everything EXCEPT a content checksum)."""
    import numpy as np

    done = {"flag": False}

    def walk(node):
        if isinstance(node, dict):
            return {k: walk(node[k]) for k in node}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        if not done["flag"] and hasattr(node, "shape"):
            arr = np.array(node, copy=True)
            if arr.size:
                flat = arr.reshape(-1)
                if np.issubdtype(arr.dtype, np.floating):
                    flat[0] = flat[0] + 1.0
                else:
                    flat[0] = ~flat[0]
                done["flag"] = True
                return arr
        return node

    return walk(params)

