"""Frame-axis batched serving: micro-batching dispatcher + bucketed shapes.

DESIGN.md §9's ≥2x lever made code: single-frame requests are coalesced
into fixed, bucketed frame-batch dispatches so the serial small-tensor
chain (P3P, argmax selection, winner-only IRLS) pays its op-latency floor
once per *dispatch* instead of once per frame.  See serve.batching for the
static-shape/padding invariants, serve.dispatcher for the request path,
serve.slo for the SLO machinery (deadlines, admission control, graceful
degradation, watchdog — DESIGN.md §12) and serve.loadgen for the
open-loop load harness that measures it all.
"""

from esac_tpu.serve.batching import (
    MIN_LANES,
    pad_batch,
    pick_bucket,
    plan_dispatches,
    stack_frames,
)
from esac_tpu.serve.dispatcher import (
    MicroBatchDispatcher,
    make_dsac_serve_fn,
    make_esac_serve_fn,
    make_sharded_serve_fn,
)
from esac_tpu.serve.loadgen import (
    poisson_arrivals,
    run_open_loop,
    uniform_arrivals,
)
from esac_tpu.serve.session import (
    SessionEvictedError,
    SessionPolicy,
    SessionRouter,
    SessionTable,
    SessionUnknownError,
)
from esac_tpu.serve.slo import (
    DeadlineExceededError,
    DispatcherClosedError,
    DispatchStalledError,
    FaultInjector,
    LaneQuarantinedError,
    ServeError,
    ShedError,
    SLOPolicy,
    WorkerDiedError,
)

__all__ = [
    "MIN_LANES",
    "MicroBatchDispatcher",
    "DeadlineExceededError",
    "DispatcherClosedError",
    "DispatchStalledError",
    "FaultInjector",
    "LaneQuarantinedError",
    "ServeError",
    "SessionEvictedError",
    "SessionPolicy",
    "SessionRouter",
    "SessionTable",
    "SessionUnknownError",
    "ShedError",
    "SLOPolicy",
    "WorkerDiedError",
    "make_dsac_serve_fn",
    "make_esac_serve_fn",
    "make_sharded_serve_fn",
    "pad_batch",
    "pick_bucket",
    "plan_dispatches",
    "poisson_arrivals",
    "run_open_loop",
    "stack_frames",
    "uniform_arrivals",
]
