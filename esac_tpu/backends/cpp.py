"""ctypes binding for the C++ hypothesis-loop backend.

Builds ``esac_cpp/esac.cpp`` into a shared library on first use (g++ -O3
-fopenmp; no OpenCV, no torch — the reference's build needs both, SURVEY.md
§2 #7).  pybind11 is unavailable in this environment, so the boundary is a
plain C ABI + ctypes, which also keeps the backend torch-free.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess

import numpy as np

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent
_SRC = _REPO / "esac_cpp" / "esac.cpp"
_LIB = _REPO / "esac_cpp" / "libesac_cpp.so"

_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _build() -> None:
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-fopenmp",
        str(_SRC), "-o", str(_LIB),
    ]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"esac_cpp build failed:\n{res.stderr}")


def _load() -> ctypes.CDLL:
    global _lib, _build_error
    if _lib is not None:
        return _lib
    if _build_error is not None:
        raise RuntimeError(_build_error)
    try:
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            _build()
        lib = ctypes.CDLL(str(_LIB))
    except Exception as e:  # remember the failure; don't retry every call
        _build_error = str(e)
        raise
    lib.esac_cpp_infer.restype = ctypes.c_int
    lib.esac_cpp_infer.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # coords
        ctypes.POINTER(ctypes.c_float),   # pixels
        ctypes.c_int,                     # n_cells
        ctypes.c_float, ctypes.c_float, ctypes.c_float,  # f, cx, cy
        ctypes.c_int,                     # n_hyps
        ctypes.c_float, ctypes.c_float,   # tau, beta
        ctypes.c_int,                     # refine_iters
        ctypes.c_uint64,                  # seed
        ctypes.POINTER(ctypes.c_double),  # out_R
        ctypes.POINTER(ctypes.c_double),  # out_t
        ctypes.POINTER(ctypes.c_double),  # out_score
        ctypes.POINTER(ctypes.c_double),  # out_scores (may be NULL)
    ]
    lib.esac_cpp_train.restype = ctypes.c_int
    lib.esac_cpp_train.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # coords_all
        ctypes.POINTER(ctypes.c_float),   # pixels
        ctypes.POINTER(ctypes.c_int32),   # idx
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # n_experts, n_cells, n_hyps
        ctypes.c_float, ctypes.c_float, ctypes.c_float,  # f, cx, cy
        ctypes.c_float, ctypes.c_float, ctypes.c_float,  # tau, beta, alpha
        ctypes.c_int,                     # train_refine_iters
        ctypes.POINTER(ctypes.c_double),  # R_gt
        ctypes.POINTER(ctypes.c_double),  # t_gt
        ctypes.c_float, ctypes.c_float,   # trans_scale, loss_clamp
        ctypes.POINTER(ctypes.c_double),  # out_expert_losses
        ctypes.POINTER(ctypes.c_double),  # out_scores (may be NULL)
        ctypes.POINTER(ctypes.c_double),  # out_losses (may be NULL)
        ctypes.POINTER(ctypes.c_float),   # out_grad_coords (may be NULL)
        ctypes.POINTER(ctypes.c_int32),   # out_valid (may be NULL)
    ]
    lib.esac_cpp_infer_gated.restype = ctypes.c_int
    lib.esac_cpp_infer_gated.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # coords_all
        ctypes.POINTER(ctypes.c_float),   # pixels
        ctypes.c_int, ctypes.c_int,       # n_experts, n_cells
        ctypes.POINTER(ctypes.c_float),   # gating probs
        ctypes.c_int,                     # n_hyps (total)
        ctypes.c_float, ctypes.c_float, ctypes.c_float,  # f, cx, cy
        ctypes.c_float, ctypes.c_float,   # tau, beta
        ctypes.c_int,                     # refine_iters
        ctypes.c_uint64,                  # seed
        ctypes.POINTER(ctypes.c_double),  # out_R
        ctypes.POINTER(ctypes.c_double),  # out_t
        ctypes.POINTER(ctypes.c_double),  # out_score
        ctypes.POINTER(ctypes.c_int32),   # out_counts (may be NULL)
        ctypes.POINTER(ctypes.c_double),  # out_scores (may be NULL)
    ]
    lib.esac_cpp_infer_multi.restype = ctypes.c_int
    lib.esac_cpp_infer_multi.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # coords_all
        ctypes.POINTER(ctypes.c_float),   # pixels
        ctypes.c_int, ctypes.c_int,       # n_experts, n_cells
        ctypes.c_float, ctypes.c_float, ctypes.c_float,  # f, cx, cy
        ctypes.c_int,                     # n_hyps_per_expert
        ctypes.c_float, ctypes.c_float,   # tau, beta
        ctypes.c_int,                     # refine_iters
        ctypes.c_uint64,                  # seed
        ctypes.POINTER(ctypes.c_double),  # out_R
        ctypes.POINTER(ctypes.c_double),  # out_t
        ctypes.POINTER(ctypes.c_double),  # out_score
        ctypes.POINTER(ctypes.c_double),  # out_expert_scores (may be NULL)
    ]
    _lib = lib
    return lib


def cpp_available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


def esac_infer_cpp(
    coords: np.ndarray,
    pixels: np.ndarray,
    f: float,
    c: tuple[float, float],
    n_hyps: int = 256,
    tau: float = 10.0,
    beta: float = 0.5,
    refine_iters: int = 8,
    seed: int = 0,
    return_scores: bool = False,
) -> dict:
    """Single-frame hypothesis loop on the CPU backend.

    coords: (N, 3) float32 scene coordinates; pixels: (N, 2) float32.
    Returns dict with 'R' (3,3), 't' (3,), 'score', 'n_valid' (+ 'scores').
    """
    lib = _load()
    coords = np.ascontiguousarray(coords, dtype=np.float32)
    pixels = np.ascontiguousarray(pixels, dtype=np.float32)
    n = coords.shape[0]
    out_R = np.zeros(9, dtype=np.float64)
    out_t = np.zeros(3, dtype=np.float64)
    out_score = np.zeros(1, dtype=np.float64)
    scores = np.zeros(n_hyps, dtype=np.float64) if return_scores else None

    def ptr(a, ty):
        return a.ctypes.data_as(ctypes.POINTER(ty)) if a is not None else None

    n_valid = lib.esac_cpp_infer(
        ptr(coords, ctypes.c_float), ptr(pixels, ctypes.c_float), n,
        f, c[0], c[1], n_hyps, tau, beta, refine_iters, seed,
        ptr(out_R, ctypes.c_double), ptr(out_t, ctypes.c_double),
        ptr(out_score, ctypes.c_double), ptr(scores, ctypes.c_double),
    )
    out = {
        "R": out_R.reshape(3, 3),
        "t": out_t,
        "score": float(out_score[0]),
        "n_valid": int(n_valid),
    }
    if return_scores:
        out["scores"] = scores
    return out


def esac_train_cpp(
    coords_all: np.ndarray,
    pixels: np.ndarray,
    idx: np.ndarray,
    f: float,
    c: tuple[float, float],
    R_gt: np.ndarray,
    t_gt: np.ndarray,
    tau: float = 10.0,
    beta: float = 0.5,
    alpha: float = 0.5,
    train_refine_iters: int = 2,
    trans_scale: float = 100.0,
    loss_clamp: float = 100.0,
    want_grad: bool = True,
) -> dict:
    """Training-mode forward (+ selection-path backward) on the CPU backend.

    coords_all: (M, N, 3) float32; idx: (M, n_hyps, 4) int32 injected
    correspondence sets (the sampling-contract injection point — generate
    them with esac_tpu.ransac.sampling so jax and cpp train on identical
    hypothesis sets).  Returns dict with 'expert_losses' (M,) expected pose
    loss per expert, 'scores'/'losses' (M, n_hyps), 'grad_coords' (M, N, 3)
    = d expert_losses[m] / d coords_all[m] through the selection path, and
    'n_valid'.
    """
    lib = _load()
    coords_all = np.ascontiguousarray(coords_all, dtype=np.float32)
    pixels = np.ascontiguousarray(pixels, dtype=np.float32)
    idx = np.ascontiguousarray(idx, dtype=np.int32)
    M, n = coords_all.shape[0], coords_all.shape[1]
    n_hyps = idx.shape[1]
    if idx.shape != (M, n_hyps, 4):
        raise ValueError(f"idx shape {idx.shape} != ({M}, n_hyps, 4)")
    if (idx < 0).any() or (idx >= n).any():
        raise ValueError("idx out of range")
    if pixels.shape != (n, 2):
        raise ValueError(f"pixels shape {pixels.shape} != ({n}, 2)")
    R_gt = np.ascontiguousarray(R_gt, dtype=np.float64).reshape(9)
    t_gt = np.ascontiguousarray(t_gt, dtype=np.float64).reshape(3)
    expert_losses = np.zeros(M, dtype=np.float64)
    scores = np.zeros((M, n_hyps), dtype=np.float64)
    losses = np.zeros((M, n_hyps), dtype=np.float64)
    grad = np.zeros((M, n, 3), dtype=np.float32) if want_grad else None
    valid = np.zeros((M, n_hyps), dtype=np.int32)

    def ptr(a, ty):
        return a.ctypes.data_as(ctypes.POINTER(ty)) if a is not None else None

    n_valid = lib.esac_cpp_train(
        ptr(coords_all, ctypes.c_float), ptr(pixels, ctypes.c_float),
        ptr(idx, ctypes.c_int32), M, n, n_hyps,
        f, c[0], c[1], tau, beta, alpha, train_refine_iters,
        ptr(R_gt, ctypes.c_double), ptr(t_gt, ctypes.c_double),
        trans_scale, loss_clamp,
        ptr(expert_losses, ctypes.c_double), ptr(scores, ctypes.c_double),
        ptr(losses, ctypes.c_double), ptr(grad, ctypes.c_float),
        ptr(valid, ctypes.c_int32),
    )
    out = {
        "expert_losses": expert_losses,
        "scores": scores,
        "losses": losses,
        "valid": valid.astype(bool),
        "n_valid": int(n_valid),
    }
    if want_grad:
        out["grad_coords"] = grad
    return out


def esac_infer_gated_cpp(
    coords_all: np.ndarray,
    pixels: np.ndarray,
    gating_probs: np.ndarray,
    f: float,
    c: tuple[float, float],
    n_hyps: int = 256,
    tau: float = 10.0,
    beta: float = 0.5,
    refine_iters: int = 8,
    seed: int = 0,
) -> dict:
    """Gating-faithful multi-expert loop: each hypothesis draws its expert
    from ``gating_probs`` (SURVEY.md §0 step 1 — the reference's sparse
    allocation), so a gating miss fails the frame like esac_infer_topk.

    coords_all: (M, N, 3) float32; gating_probs: (M,) nonnegative (need not
    be normalized).  ``n_hyps`` is the TOTAL budget across experts.  Returns
    dict with 'R', 't', 'score', 'expert' (-1 if all solves failed) and
    'counts' (M,) hypotheses allocated per expert.
    """
    lib = _load()
    coords_all = np.ascontiguousarray(coords_all, dtype=np.float32)
    pixels = np.ascontiguousarray(pixels, dtype=np.float32)
    gating = np.ascontiguousarray(gating_probs, dtype=np.float32)
    M, n = coords_all.shape[0], coords_all.shape[1]
    if gating.shape != (M,):
        raise ValueError(f"gating shape {gating.shape} != ({M},)")
    if pixels.shape != (n, 2):
        raise ValueError(f"pixels shape {pixels.shape} != ({n}, 2)")
    out_R = np.zeros(9, dtype=np.float64)
    out_t = np.zeros(3, dtype=np.float64)
    out_score = np.zeros(1, dtype=np.float64)
    counts = np.zeros(M, dtype=np.int32)

    def ptr(a, ty):
        return a.ctypes.data_as(ctypes.POINTER(ty))

    expert = lib.esac_cpp_infer_gated(
        ptr(coords_all, ctypes.c_float), ptr(pixels, ctypes.c_float), M, n,
        ptr(gating, ctypes.c_float), n_hyps, f, c[0], c[1], tau, beta,
        refine_iters, seed,
        ptr(out_R, ctypes.c_double), ptr(out_t, ctypes.c_double),
        ptr(out_score, ctypes.c_double), ptr(counts, ctypes.c_int32), None,
    )
    return {
        "R": out_R.reshape(3, 3),
        "t": out_t,
        "score": float(out_score[0]),
        "expert": int(expert),
        "counts": counts,
    }


def esac_infer_multi_cpp(
    coords_all: np.ndarray,
    pixels: np.ndarray,
    f: float,
    c: tuple[float, float],
    n_hyps_per_expert: int = 256,
    tau: float = 10.0,
    beta: float = 0.5,
    refine_iters: int = 8,
    seed: int = 0,
) -> dict:
    """Multi-expert hypothesis loop on the CPU backend.

    coords_all: (M, N, 3) float32 per-expert scene coordinates.
    Returns dict with 'R', 't', 'score', 'expert' (winner index, -1 if all
    solves failed) and 'expert_scores' (M,).
    """
    lib = _load()
    coords_all = np.ascontiguousarray(coords_all, dtype=np.float32)
    pixels = np.ascontiguousarray(pixels, dtype=np.float32)
    M, n = coords_all.shape[0], coords_all.shape[1]
    out_R = np.zeros(9, dtype=np.float64)
    out_t = np.zeros(3, dtype=np.float64)
    out_score = np.zeros(1, dtype=np.float64)
    expert_scores = np.zeros(M, dtype=np.float64)

    def ptr(a, ty):
        return a.ctypes.data_as(ctypes.POINTER(ty))

    expert = lib.esac_cpp_infer_multi(
        ptr(coords_all, ctypes.c_float), ptr(pixels, ctypes.c_float), M, n,
        f, c[0], c[1], n_hyps_per_expert, tau, beta, refine_iters, seed,
        ptr(out_R, ctypes.c_double), ptr(out_t, ctypes.c_double),
        ptr(out_score, ctypes.c_double), ptr(expert_scores, ctypes.c_double),
    )
    return {
        "R": out_R.reshape(3, 3),
        "t": out_t,
        "score": float(out_score[0]),
        "expert": int(expert),
        "expert_scores": expert_scores,
    }
