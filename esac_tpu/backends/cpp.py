"""ctypes binding for the C++ hypothesis-loop backend.

Builds ``esac_cpp/esac.cpp`` into a shared library on first use (g++ -O3
-fopenmp; no OpenCV, no torch — the reference's build needs both, SURVEY.md
§2 #7).  pybind11 is unavailable in this environment, so the boundary is a
plain C ABI + ctypes, which also keeps the backend torch-free.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess

import numpy as np

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent
_SRC = _REPO / "esac_cpp" / "esac.cpp"
_LIB = _REPO / "esac_cpp" / "libesac_cpp.so"

_lib: ctypes.CDLL | None = None
_build_error: str | None = None


def _build() -> None:
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-fopenmp",
        str(_SRC), "-o", str(_LIB),
    ]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"esac_cpp build failed:\n{res.stderr}")


def _load() -> ctypes.CDLL:
    global _lib, _build_error
    if _lib is not None:
        return _lib
    if _build_error is not None:
        raise RuntimeError(_build_error)
    try:
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            _build()
        lib = ctypes.CDLL(str(_LIB))
    except Exception as e:  # remember the failure; don't retry every call
        _build_error = str(e)
        raise
    lib.esac_cpp_infer.restype = ctypes.c_int
    lib.esac_cpp_infer.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # coords
        ctypes.POINTER(ctypes.c_float),   # pixels
        ctypes.c_int,                     # n_cells
        ctypes.c_float, ctypes.c_float, ctypes.c_float,  # f, cx, cy
        ctypes.c_int,                     # n_hyps
        ctypes.c_float, ctypes.c_float,   # tau, beta
        ctypes.c_int,                     # refine_iters
        ctypes.c_uint64,                  # seed
        ctypes.POINTER(ctypes.c_double),  # out_R
        ctypes.POINTER(ctypes.c_double),  # out_t
        ctypes.POINTER(ctypes.c_double),  # out_score
        ctypes.POINTER(ctypes.c_double),  # out_scores (may be NULL)
    ]
    lib.esac_cpp_infer_multi.restype = ctypes.c_int
    lib.esac_cpp_infer_multi.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # coords_all
        ctypes.POINTER(ctypes.c_float),   # pixels
        ctypes.c_int, ctypes.c_int,       # n_experts, n_cells
        ctypes.c_float, ctypes.c_float, ctypes.c_float,  # f, cx, cy
        ctypes.c_int,                     # n_hyps_per_expert
        ctypes.c_float, ctypes.c_float,   # tau, beta
        ctypes.c_int,                     # refine_iters
        ctypes.c_uint64,                  # seed
        ctypes.POINTER(ctypes.c_double),  # out_R
        ctypes.POINTER(ctypes.c_double),  # out_t
        ctypes.POINTER(ctypes.c_double),  # out_score
        ctypes.POINTER(ctypes.c_double),  # out_expert_scores (may be NULL)
    ]
    _lib = lib
    return lib


def cpp_available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


def esac_infer_cpp(
    coords: np.ndarray,
    pixels: np.ndarray,
    f: float,
    c: tuple[float, float],
    n_hyps: int = 256,
    tau: float = 10.0,
    beta: float = 0.5,
    refine_iters: int = 8,
    seed: int = 0,
    return_scores: bool = False,
) -> dict:
    """Single-frame hypothesis loop on the CPU backend.

    coords: (N, 3) float32 scene coordinates; pixels: (N, 2) float32.
    Returns dict with 'R' (3,3), 't' (3,), 'score', 'n_valid' (+ 'scores').
    """
    lib = _load()
    coords = np.ascontiguousarray(coords, dtype=np.float32)
    pixels = np.ascontiguousarray(pixels, dtype=np.float32)
    n = coords.shape[0]
    out_R = np.zeros(9, dtype=np.float64)
    out_t = np.zeros(3, dtype=np.float64)
    out_score = np.zeros(1, dtype=np.float64)
    scores = np.zeros(n_hyps, dtype=np.float64) if return_scores else None

    def ptr(a, ty):
        return a.ctypes.data_as(ctypes.POINTER(ty)) if a is not None else None

    n_valid = lib.esac_cpp_infer(
        ptr(coords, ctypes.c_float), ptr(pixels, ctypes.c_float), n,
        f, c[0], c[1], n_hyps, tau, beta, refine_iters, seed,
        ptr(out_R, ctypes.c_double), ptr(out_t, ctypes.c_double),
        ptr(out_score, ctypes.c_double), ptr(scores, ctypes.c_double),
    )
    out = {
        "R": out_R.reshape(3, 3),
        "t": out_t,
        "score": float(out_score[0]),
        "n_valid": int(n_valid),
    }
    if return_scores:
        out["scores"] = scores
    return out


def esac_infer_multi_cpp(
    coords_all: np.ndarray,
    pixels: np.ndarray,
    f: float,
    c: tuple[float, float],
    n_hyps_per_expert: int = 256,
    tau: float = 10.0,
    beta: float = 0.5,
    refine_iters: int = 8,
    seed: int = 0,
) -> dict:
    """Multi-expert hypothesis loop on the CPU backend.

    coords_all: (M, N, 3) float32 per-expert scene coordinates.
    Returns dict with 'R', 't', 'score', 'expert' (winner index, -1 if all
    solves failed) and 'expert_scores' (M,).
    """
    lib = _load()
    coords_all = np.ascontiguousarray(coords_all, dtype=np.float32)
    pixels = np.ascontiguousarray(pixels, dtype=np.float32)
    M, n = coords_all.shape[0], coords_all.shape[1]
    out_R = np.zeros(9, dtype=np.float64)
    out_t = np.zeros(3, dtype=np.float64)
    out_score = np.zeros(1, dtype=np.float64)
    expert_scores = np.zeros(M, dtype=np.float64)

    def ptr(a, ty):
        return a.ctypes.data_as(ctypes.POINTER(ty))

    expert = lib.esac_cpp_infer_multi(
        ptr(coords_all, ctypes.c_float), ptr(pixels, ctypes.c_float), M, n,
        f, c[0], c[1], n_hyps_per_expert, tau, beta, refine_iters, seed,
        ptr(out_R, ctypes.c_double), ptr(out_t, ctypes.c_double),
        ptr(out_score, ctypes.c_double), ptr(expert_scores, ctypes.c_double),
    )
    return {
        "R": out_R.reshape(3, 3),
        "t": out_t,
        "score": float(out_score[0]),
        "expert": int(expert),
        "expert_scores": expert_scores,
    }
