"""Backend dispatch: ``jax`` (TPU-native, default) and ``cpp`` (host CPU).

The reference exposes one C++ torch extension; here ``--backend {cpp,jax}``
(SURVEY.md "build target" column) selects between the XLA hypothesis kernel
and the self-contained C++/OpenMP reference path in ``esac_cpp/``, which is
also the measured baseline for the >=20x hypotheses/sec target.
"""

from esac_tpu.backends.cpp import (
    cpp_available,
    esac_infer_cpp,
    esac_infer_gated_cpp,
    esac_infer_multi_cpp,
    esac_train_cpp,
)

__all__ = [
    "cpp_available",
    "esac_infer_cpp",
    "esac_infer_gated_cpp",
    "esac_infer_multi_cpp",
    "esac_train_cpp",
]
