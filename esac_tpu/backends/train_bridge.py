"""JAX <-> C++ training bridge: the reference's extension-inside-autograd
architecture.

The reference calls its C++ extension per batch inside the torch autograd
graph — forward returns the expected pose loss, backward injects the
extension's gradients into the network backprop (SURVEY.md §3.3).  This
module reproduces that wiring for ``train_esac.py --backend cpp``: a
``jax.custom_vjp`` whose forward runs ``esac_cpp_train`` through
``jax.pure_callback`` (host round-trip per frame — the exact cost the
TPU-native path exists to eliminate) and whose backward returns the
extension's analytic + finite-difference coordinate gradients.

Gating gradients need no bridge: in dense mode the total loss is
``sum_m softmax(logits)_m * E_m`` with ``E_m`` from the extension, so the
logits gradient is exact with ``E`` held constant.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from esac_tpu.ransac.config import RansacConfig


def make_cpp_expert_losses(pixels: jnp.ndarray, f: float, c: tuple[float, float],
                           cfg: RansacConfig):
    """Build ``expert_losses(coords_all, R_gt, t_gt, idx) -> (M,)`` running
    the C++ training extension, differentiable wrt ``coords_all``.

    pixels: (N, 2) cell centers (static per run).  idx: (M, n_hyps, 4) int32
    correspondence sets drawn by the caller — the sampling contract stays in
    jax; the extension consumes the sets.  Works under jit and batch vmap
    (sequential host callbacks, one per frame, like the reference's per-frame
    extension calls).
    """
    px_host = np.asarray(pixels, np.float32)

    def _host_call(want_grad, coords_all, R_gt, t_gt, idx):
        from esac_tpu.backends.cpp import esac_train_cpp

        out = esac_train_cpp(
            np.asarray(coords_all), px_host, np.asarray(idx), float(f),
            (float(c[0]), float(c[1])), np.asarray(R_gt), np.asarray(t_gt),
            tau=cfg.tau, beta=cfg.beta, alpha=cfg.alpha,
            train_refine_iters=cfg.train_refine_iters,
            trans_scale=cfg.trans_scale, loss_clamp=cfg.loss_clamp,
            want_grad=want_grad,
        )
        E = out["expert_losses"].astype(np.float32)
        if not want_grad:
            return E
        return E, out["grad_coords"].astype(np.float32)

    def _call(coords_all, R_gt, t_gt, idx, want_grad):
        M, N = coords_all.shape[0], coords_all.shape[1]
        E_shape = jax.ShapeDtypeStruct((M,), jnp.float32)
        shapes = (
            (E_shape, jax.ShapeDtypeStruct((M, N, 3), jnp.float32))
            if want_grad else E_shape
        )
        return jax.pure_callback(
            lambda *a: _host_call(want_grad, *a),
            shapes,
            coords_all, R_gt, t_gt, idx,
            vmap_method="sequential",
        )

    @jax.custom_vjp
    def expert_losses(coords_all, R_gt, t_gt, idx):
        # Forward-only use skips the dominant FD-backward cost entirely.
        return _call(coords_all, R_gt, t_gt, idx, want_grad=False)

    def fwd(coords_all, R_gt, t_gt, idx):
        E, grad = _call(coords_all, R_gt, t_gt, idx, want_grad=True)
        return E, (grad, idx.shape)

    def bwd(res, ct):
        grad, idx_shape = res
        return (
            ct[:, None, None] * grad,
            jnp.zeros((3, 3), grad.dtype),   # R_gt: ground truth, no gradient
            jnp.zeros((3,), grad.dtype),     # t_gt
            np.zeros(idx_shape, jax.dtypes.float0),  # int input -> float0
        )

    expert_losses.defvjp(fwd, bwd)
    return expert_losses
