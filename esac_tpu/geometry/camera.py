"""Pinhole camera: point transforms, projection, reprojection & pose errors.

The reference computes reprojection errors for soft-inlier scoring inside its
C++ extension (SURVEY.md §3.5: ``score_j = sum_px sigmoid(beta*(tau - r))``).
Here projection is a pure function so the whole scoring grid vmaps over
hypotheses in one XLA dispatch.

Conventions
-----------
- Scene coordinates ``X`` live in the scene/world frame; the pose ``(R, t)``
  maps scene -> camera: ``Y = R X + t``.  This is the "ground-truth pose" in
  the scene-coordinate-regression sense; the camera pose in the world is its
  inverse, and pose errors are computed on the inverse (camera-in-world)
  translation as in the 5cm/5deg protocol.
- Intrinsics: focal ``f`` (square pixels) and principal point ``(cx, cy)``.
- Points behind the camera get a clamped depth so projection stays finite and
  differentiable; their reprojection error is driven large by the clamp.
"""

from __future__ import annotations

import jax.numpy as jnp

from esac_tpu.geometry.rotations import rot_error_deg
from esac_tpu.utils.num import safe_norm
from esac_tpu.utils.precision import heinsum, hmm

# Minimum camera-frame depth (meters) used to keep the perspective division
# finite for points at/behind the camera plane.
MIN_DEPTH = 0.1


def transform_points(R: jnp.ndarray, t: jnp.ndarray, X: jnp.ndarray) -> jnp.ndarray:
    """Apply pose to points: R (..., 3, 3) rotation *matrix*, t (..., 3), X (..., N, 3).

    Takes a matrix, not an axis-angle vector — convert with ``rodrigues`` first.
    """
    return hmm(X, jnp.swapaxes(R, -1, -2)) + t[..., None, :]


def project(Y: jnp.ndarray, f: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """Project camera-frame points to pixels. (..., N, 3) -> (..., N, 2).

    Depth is clamped to MIN_DEPTH so the op is total and differentiable.
    """
    z = jnp.maximum(Y[..., 2:3], MIN_DEPTH)
    return Y[..., :2] / z * f + c


def reprojection_errors(
    R: jnp.ndarray,
    t: jnp.ndarray,
    X: jnp.ndarray,
    x2d: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
) -> jnp.ndarray:
    """Per-point pixel reprojection error. Returns (..., N) distances in px.

    Points that fall at/behind the clamped depth plane keep a finite but large
    error, so soft-inlier scoring naturally rejects them (the reference's C++
    loop does the same with an explicit z>0 check; SURVEY.md §3.5).
    """
    Y = transform_points(R, t, X)
    xp = project(Y, f, c)
    # safe_norm: this is differentiated in soft-inlier scoring, and a perfect
    # correspondence (zero error) would make a plain norm's gradient NaN.
    err = safe_norm(xp - x2d)
    behind = Y[..., 2] < MIN_DEPTH
    # Keep gradients alive through the clamped projection but make sure
    # behind-camera points can never look like inliers.
    return jnp.where(behind, err + 1000.0, err)


def backproject_at_depth(
    R: jnp.ndarray,
    t: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    depth: jnp.ndarray,
) -> jnp.ndarray:
    """Scene points observed at a constant camera-frame depth.

    The heuristic stage-1 init target for scenes WITHOUT depth GT — the
    reference's outdoor (Aachen) recipe initializes experts against targets
    back-projected at a constant depth along each pixel ray (SURVEY.md §0
    training stage 1, §2 #15 "heuristic-depth targets").

    R (..., 3, 3) / t (..., 3): scene->camera pose (as everywhere in
    esac_tpu.geometry); pixels (N, 2); depth: scalar meters.
    Returns (..., N, 3) scene-frame points: X = R^T (Y - t) with
    Y = depth * ray(pixel).
    """
    xy = (pixels - c) / f
    Y = jnp.concatenate(
        [xy * depth, jnp.full_like(xy[..., :1], depth)], axis=-1
    )
    return hmm(Y - t[..., None, :], R)  # row-vector form of R^T (Y - t)


def pose_errors(
    R: jnp.ndarray,
    t: jnp.ndarray,
    R_gt: jnp.ndarray,
    t_gt: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(rotation error deg, translation error m) for scene->camera poses.

    Translation error follows the re-localization protocol: distance between
    camera centers, i.e. between ``-R^T t`` of estimate and ground truth.
    """
    rot_err = rot_error_deg(R, R_gt)
    cam_center = -heinsum("...ij,...i->...j", R, t)
    cam_center_gt = -heinsum("...ij,...i->...j", R_gt, t_gt)
    # safe_norm: sits under jax.grad in the pose loss; a plain norm's
    # gradient is NaN at exactly zero error.
    trans_err = safe_norm(cam_center - cam_center_gt)
    return rot_err, trans_err
