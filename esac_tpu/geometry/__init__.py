"""Pure-JAX geometry core: rotations, projection, pose errors, PnP.

Everything here is functional, static-shaped, and safe under ``jax.vmap`` /
``jax.jit`` — the building blocks of the hypothesis kernel.
"""

from esac_tpu.geometry.rotations import (
    skew,
    rodrigues,
    so3_log,
    rotation_angle_deg,
    rot_error_deg,
)
from esac_tpu.geometry.camera import (
    transform_points,
    project,
    reprojection_errors,
    backproject_at_depth,
    pose_errors,
)
from esac_tpu.geometry.pnp import (
    solve_pnp_minimal,
    refine_pose_gn,
)

__all__ = [
    "skew",
    "rodrigues",
    "so3_log",
    "rotation_angle_deg",
    "rot_error_deg",
    "transform_points",
    "project",
    "reprojection_errors",
    "backproject_at_depth",
    "pose_errors",
    "solve_pnp_minimal",
    "refine_pose_gn",
]
