"""Rotation utilities: axis-angle (Rodrigues) <-> matrix, angle errors.

The reference uses ``cv::Rodrigues`` inside its C++ extension for every PnP
solve and pose-error computation (SURVEY.md §2 #3, §3.5; reference mount was
empty so no file:line is citable).  Here the same math is written branchless
so it is differentiable and safe under ``vmap``: the small-angle limit is
handled with a Taylor-series blend instead of an ``if``.

All functions broadcast over leading batch dimensions.
"""

from __future__ import annotations

import jax.numpy as jnp

from esac_tpu.utils.num import safe_norm
from esac_tpu.utils.precision import hmm

# Below this angle (radians) the sin(x)/x style factors switch to their
# Taylor expansions to avoid 0/0.
_SMALL_ANGLE = 1e-6


def skew(v: jnp.ndarray) -> jnp.ndarray:
    """Skew-symmetric cross-product matrix. (..., 3) -> (..., 3, 3)."""
    x, y, z = v[..., 0], v[..., 1], v[..., 2]
    zero = jnp.zeros_like(x)
    return jnp.stack(
        [
            jnp.stack([zero, -z, y], axis=-1),
            jnp.stack([z, zero, -x], axis=-1),
            jnp.stack([-y, x, zero], axis=-1),
        ],
        axis=-2,
    )


def rodrigues(rvec: jnp.ndarray) -> jnp.ndarray:
    """Axis-angle vector -> rotation matrix. (..., 3) -> (..., 3, 3).

    R = I + a K + b K^2 with K = skew(rvec), a = sin(t)/t, b = (1-cos(t))/t^2.
    Branchless small-angle handling: for t -> 0, a -> 1 - t^2/6 and
    b -> 1/2 - t^2/24.
    """
    theta2 = jnp.sum(rvec * rvec, axis=-1)
    theta = jnp.sqrt(theta2 + 1e-32)
    small = theta < _SMALL_ANGLE
    # Safe denominators: where `small`, the Taylor branch is used, so the
    # division result is discarded, but it must not be NaN.
    safe_theta = jnp.where(small, 1.0, theta)
    safe_theta2 = jnp.where(small, 1.0, theta2)
    a = jnp.where(small, 1.0 - theta2 / 6.0, jnp.sin(theta) / safe_theta)
    b = jnp.where(small, 0.5 - theta2 / 24.0, (1.0 - jnp.cos(theta)) / safe_theta2)
    K = skew(rvec)
    eye = jnp.broadcast_to(jnp.eye(3, dtype=rvec.dtype), K.shape)
    return eye + a[..., None, None] * K + b[..., None, None] * hmm(K, K)


def so3_log(R: jnp.ndarray) -> jnp.ndarray:
    """Rotation matrix -> axis-angle vector. (..., 3, 3) -> (..., 3).

    Uses the skew-part formula away from 0 and pi; near pi falls back to the
    outer-product formula for the axis.  Branchless via ``where``, and — the
    part that matters in this codebase — NaN-free in the *backward* pass at
    every input, including exact identity: a ``where`` does not stop NaNs
    produced inside the untaken branch's VJP (0 * inf = NaN), so every norm /
    arccos / division below is epsilon-guarded.  Called under jax.grad inside
    vmapped minimal solves where degenerate samples do hit exact identity.
    """
    trace = R[..., 0, 0] + R[..., 1, 1] + R[..., 2, 2]
    cos_t = jnp.clip((trace - 1.0) * 0.5, -1.0, 1.0)
    # Vector from the skew-symmetric part: (R - R^T)/2 = sin(t) * skew(axis).
    w = jnp.stack(
        [
            R[..., 2, 1] - R[..., 1, 2],
            R[..., 0, 2] - R[..., 2, 0],
            R[..., 1, 0] - R[..., 0, 1],
        ],
        axis=-1,
    )
    two_sin = safe_norm(w)  # = 2 sin(t), grad-safe at 0
    # atan2 instead of arccos: finite derivative at cos_t = +-1.
    theta = jnp.arctan2(two_sin, trace - 1.0)
    small = two_sin < 2.0 * _SMALL_ANGLE
    near_pi = cos_t < -0.999
    axis_generic = w / two_sin[..., None]
    # Near pi: R + R^T = 2 cos(t) I + 2 (1 - cos(t)) a a^T, so the outer
    # product a a^T is recoverable with a well-conditioned denominator
    # (1 - cos(t) ~ 2).  Take its largest column as +-a, then orient the sign
    # with the skew part w = 2 sin(t) a (sin(t) > 0 for t < pi).
    denom_pi = 2.0 * (1.0 - cos_t)
    safe_denom_pi = jnp.where(near_pi, denom_pi, 1.0)
    eye = jnp.broadcast_to(jnp.eye(3, dtype=R.dtype), R.shape)
    M = (R + jnp.swapaxes(R, -1, -2) - 2.0 * cos_t[..., None, None] * eye) / (
        safe_denom_pi[..., None, None]
    )
    diag = jnp.stack([M[..., 0, 0], M[..., 1, 1], M[..., 2, 2]], axis=-1)
    k = jnp.argmax(diag, axis=-1)
    col = jnp.take_along_axis(M, k[..., None, None], axis=-1)[..., 0]
    axis_pi = col / safe_norm(col)[..., None]
    orient = jnp.sum(w * axis_pi, axis=-1, keepdims=True)
    axis_pi = axis_pi * jnp.where(orient < 0, -1.0, 1.0)
    axis = jnp.where(near_pi[..., None], axis_pi, axis_generic)
    # At theta ~ 0 the axis is arbitrary; rvec -> 0 regardless.
    small_total = theta < _SMALL_ANGLE
    rvec = jnp.where(small_total[..., None], w * 0.5, axis * theta[..., None])
    return rvec


def quaternion_to_matrix(q: jnp.ndarray) -> jnp.ndarray:
    """Unit quaternion (w, x, y, z) -> rotation matrix. (..., 4) -> (..., 3, 3).

    Used by the Aachen/SfM pose import (datasets/setup_aachen.py;
    reconstruction formats store quaternions); normalizes defensively.
    """
    q = q / safe_norm(q)[..., None]
    w, x, y, z = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    return jnp.stack(
        [
            jnp.stack([1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y)], -1),
            jnp.stack([2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x)], -1),
            jnp.stack([2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)], -1),
        ],
        axis=-2,
    )


def rotation_angle_deg(R: jnp.ndarray) -> jnp.ndarray:
    """Rotation angle of R in degrees. (..., 3, 3) -> (...).

    atan2 formulation, not arccos: the angle sits under ``jax.grad`` in the
    training pose loss, and d/dx arccos(x) is infinite at x = +-1 — exactly
    where a perfectly-refined hypothesis lands.  With
    ||skew part|| = 2 sin(t) and trace - 1 = 2 cos(t), atan2 has finite
    gradients everywhere (the eps keeps the sqrt differentiable at t = 0).
    """
    trace = R[..., 0, 0] + R[..., 1, 1] + R[..., 2, 2]
    w = jnp.stack(
        [
            R[..., 2, 1] - R[..., 1, 2],
            R[..., 0, 2] - R[..., 2, 0],
            R[..., 1, 0] - R[..., 0, 1],
        ],
        axis=-1,
    )
    two_sin = safe_norm(w)
    return jnp.degrees(jnp.arctan2(two_sin, trace - 1.0))


def rot_error_deg(R1: jnp.ndarray, R2: jnp.ndarray) -> jnp.ndarray:
    """Relative rotation angle between two rotations, in degrees."""
    return rotation_angle_deg(hmm(R1, jnp.swapaxes(R2, -1, -2)))
