"""Differentiable, vmap-safe PnP: minimal 4-point solve + Gauss-Newton refine.

The reference solves every minimal set with ``cv::solvePnP`` (P3P + iterative
refinement) inside an OpenMP loop, and differentiates the refined pose by
central finite differences (SURVEY.md §2 #3-4, §3.5; reference mount empty so
paths are reconstructed).  Neither maps to a TPU: OpenCV is host code and
finite differences re-run the solver O(dim) times.

The TPU-native design here is different end to end:

1.  **Minimal solve (4 points)** — algebraic P3P on the first three
    correspondences (Grunert's quartic, solved in closed form by the
    branchless complex Ferrari solver in ``quartic.py`` since XLA-on-TPU has
    no nonsymmetric eig), all four root branches evaluated in parallel and
    disambiguated by the 4th point's reprojection error, pose recovered per
    branch with a differentiable orthonormal-triad alignment, then polished with a
    few Gauss-Newton steps on reprojection error.
2.  **Refinement (N points, soft weights)** — weighted Gauss-Newton on the
    6-DoF axis-angle pose; fixed iteration counts, LM damping.  Because every
    step is a total, differentiable function, ``jax.grad`` replaces the
    reference's central-difference machinery for free.

Everything has static shapes and fixed loop lengths, so the whole solver
``vmap``s over thousands of hypotheses and compiles into one XLA program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from esac_tpu.geometry.camera import MIN_DEPTH, reprojection_errors
from esac_tpu.geometry.quartic import solve_quartic
from esac_tpu.geometry.rotations import rodrigues, so3_log
from esac_tpu.utils.num import safe_norm, safe_sqrt
from esac_tpu.utils.precision import hmm

def bearings(x2d: jnp.ndarray, f: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """Pixels -> unit bearing vectors in the camera frame. (..., N, 2) -> (..., N, 3).

    safe_norm, not jnp.linalg.norm: the solve is differentiated wrt the
    pixels/intrinsics in the training expectation, and a raw norm's VJP is
    NaN at zero input — the z=1 homogeneous coordinate keeps the *forward*
    norm >= 1, but the eps-inside-sqrt form costs nothing and keeps every
    input (including garbage from upstream degeneracies) finite in both
    passes, per the total + grad-safe convention.
    """
    # The focal length is a physical intrinsic, O(10..1e3) px by dataset
    # construction and never a quantity optimized toward 0; flooring it here
    # would perturb every committed bit-parity pin for an input that cannot
    # occur (DESIGN.md §16 carries the full argument).
    xy = (x2d - c) / f  # graft-lint: disable=R14(focal bounded away from 0 by construction; a floor would break bit-parity pins)
    ones = jnp.ones_like(xy[..., :1])
    rays = jnp.concatenate([xy, ones], axis=-1)
    return rays / safe_norm(rays)[..., None]


def _p3p_depths(b3: jnp.ndarray, X3: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Algebraic P3P (Grunert): depths of 3 rays for up to 4 solutions.

    b3: (3, 3) unit bearings, X3: (3, 3) scene points.
    Returns (depths (4, 3), penalty (4,)) — penalty is 0 for clean real
    positive-depth solutions and grows for complex/negative/degenerate ones,
    so a downstream argmin ignores invalid branches without any control flow.

    Derivation (classic triangle-side elimination): with depths s1, s2=u*s1,
    s3=v*s1, side lengths a=|X2-X3|, b=|X1-X3|, c=|X1-X2| and ray cosines
    ca=b2.b3, cb=b1.b3, cg=b1.b2, eliminating u between the two distance
    equations leaves u = -E(v)/D(v) and a quartic Q(v) = 0 with
    D = 2 b^2 (ca v - cg),   E = (w - b^2) v^2 - 2 w cb v + (b^2 + w),
    G = -c^2 v^2 + 2 c^2 cb v + (b^2 - c^2),   w = a^2 - c^2,
    Q = b^2 E^2 + 2 b^2 cg E D + G D^2.
    """
    # hmm, not bare jnp.dot: a dot_general on TPU defaults to bf16 MXU
    # inputs, and these cosines seed the quartic — exactly the corruption
    # hmm/heinsum exist to prevent (graft-lint R4/J3).  1-D x 1-D matmul is
    # the inner product, bit-identical to the old jnp.dot on CPU (an
    # elementwise mul+sum variant was tried and rejected: its one-ULP
    # rounding difference flips the argmin between near-tied quartic
    # branches on marginal P3P instances and regressed test_pnp seed 2).
    ca = hmm(b3[1], b3[2])
    cb = hmm(b3[0], b3[2])
    cg = hmm(b3[0], b3[1])
    asq = jnp.sum((X3[1] - X3[2]) ** 2)
    bsq = jnp.sum((X3[0] - X3[2]) ** 2)
    csq = jnp.sum((X3[0] - X3[1]) ** 2)
    w = asq - csq

    d1, d0 = 2.0 * bsq * ca, -2.0 * bsq * cg
    e2, e1, e0 = w - bsq, -2.0 * w * cb, bsq + w
    g2, g1, g0 = -csq, 2.0 * csq * cb, bsq - csq

    # Polynomial products by explicit convolution (highest degree first).
    E2 = jnp.array(
        [e2 * e2, 2 * e2 * e1, 2 * e2 * e0 + e1 * e1, 2 * e1 * e0, e0 * e0]
    )
    ED = jnp.array([0.0, e2 * d1, e2 * d0 + e1 * d1, e1 * d0 + e0 * d1, e0 * d0])
    A2, B2, C2 = d1 * d1, 2 * d1 * d0, d0 * d0
    GD2 = jnp.array(
        [g2 * A2, g2 * B2 + g1 * A2, g2 * C2 + g1 * B2 + g0 * A2, g1 * C2 + g0 * B2, g0 * C2]
    )
    Q = bsq * E2 + 2.0 * bsq * cg * ED + GD2
    # No pre-normalization here: solve_quartic scales internally, and stacking
    # two divisions lets XLA fuse them into one whose combined denominator
    # underflows float32 for all-zero Q (0/0 = NaN under jit, fine in eager).

    roots = solve_quartic(Q)  # (4,) complex
    v = jnp.real(roots)
    imag_pen = jnp.abs(jnp.imag(roots))

    Dv = d1 * v + d0
    Ev = (e2 * v + e1) * v + e0
    # Sign-preserving clamp (sign(0) -> +1): replacing a tiny negative Dv by
    # +1e-9 would silently flip u's sign; instead clamp toward the same sign
    # and penalize the branch like the other degeneracies.
    Dv_sign = jnp.where(Dv < 0, -1.0, 1.0)
    Dv_safe = jnp.where(jnp.abs(Dv) < 1e-9, Dv_sign * 1e-9, Dv)
    u = -Ev / Dv_safe
    denom = 1.0 + v * v - 2.0 * v * cb
    # safe_sqrt: bsq = 0 for a degenerate sample, and sqrt's VJP at 0 is inf —
    # one such sample would NaN the whole vmapped batch gradient.
    s1 = safe_sqrt(bsq / jnp.maximum(denom, 1e-9))
    depths = jnp.stack([s1, u * s1, v * s1], axis=-1)  # (4 roots, 3 points)

    penalty = (
        imag_pen
        + 1e3 * jnp.sum(jnp.maximum(MIN_DEPTH - depths, 0.0), axis=-1)
        + 1e3 * (denom < 1e-9).astype(v.dtype)
        + 1e3 * (jnp.abs(Dv) < 1e-9).astype(v.dtype)
    )
    return depths, penalty


def _triad_align(X: jnp.ndarray, Y: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Rigid pose (R, t) with Y ~= R X + t from exactly 3 correspondences.

    Orthonormal-triad method: build a frame from the two difference vectors
    in each point set, R maps one basis to the other.  Exact for the exact
    correspondences P3P produces, and — unlike Procrustes/SVD — made of pure
    elementwise arithmetic, which matters: batched 3x3 SVDs lower to scalar
    loops on TPU and dominated the minimal-solve profile.  Degenerate
    (collinear) triples produce a finite garbage pose via the safe_norm
    guards; downstream penalties reject it.
    """
    ux, vx = X[1] - X[0], X[2] - X[0]
    uy, vy = Y[1] - Y[0], Y[2] - Y[0]
    nx = jnp.cross(ux, vx)
    ny = jnp.cross(uy, vy)
    e1x = ux / safe_norm(ux)
    e3x = nx / safe_norm(nx)
    e2x = jnp.cross(e3x, e1x)
    e1y = uy / safe_norm(uy)
    e3y = ny / safe_norm(ny)
    e2y = jnp.cross(e3y, e1y)
    Bx = jnp.stack([e1x, e2x, e3x], axis=-1)  # columns
    By = jnp.stack([e1y, e2y, e3y], axis=-1)
    R = hmm(By, Bx.T)
    t = Y.mean(axis=0) - hmm(R, X.mean(axis=0)[:, None])[:, 0]
    return R, t


def _solve6_spd(A: jnp.ndarray, g: jnp.ndarray) -> jnp.ndarray:
    """Solve the damped SPD 6x6 normal equations by unrolled Gauss-Jordan.

    ``jnp.linalg.solve`` lowers to a pivoting LU with scalar loops on TPU —
    catastrophic when vmapped over thousands of hypotheses.  Six unrolled
    elimination steps are pure vectorized arithmetic.  No pivoting needed:
    A is SPD + Levenberg damping, so diagonals stay positive.
    """
    M = jnp.concatenate([A, g[:, None]], axis=1)  # (6, 7)
    for i in range(6):
        piv = M[i, i]
        piv = jnp.where(jnp.abs(piv) < 1e-12, 1e-12, piv)
        row = M[i] / piv
        factors = M[:, i].at[i].set(0.0)
        M = M - factors[:, None] * row[None, :]
        M = M.at[i].set(row)
    return M[:, 6]


def _gn_pose_step(
    R: jnp.ndarray,
    t: jnp.ndarray,
    X: jnp.ndarray,
    x2d: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    w: jnp.ndarray,
    damping: float,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One weighted GN/LM step with a hand-derived Jacobian.

    Left-multiplicative rotation update (R <- exp(delta) R): the Jacobian of
    the projected point wrt the rotation perturbation is built from
    d(exp(d) W)/dd = -skew(W) with W = R X, all elementwise — no jacfwd
    re-tracing of Rodrigues, which dominated the original profile.
    """
    Y = hmm(X, R.T) + t  # (N, 3)
    z = jnp.maximum(Y[:, 2], MIN_DEPTH)
    inv_z = 1.0 / z
    u = f * Y[:, 0] * inv_z + c[0]
    v = f * Y[:, 1] * inv_z + c[1]
    ru = u - x2d[:, 0]
    rv = v - x2d[:, 1]
    # du/dY = f * [1/z, 0, -Y0/z^2]; dv/dY = f * [0, 1/z, -Y1/z^2].
    # Where the depth clamp is active (point at/behind the camera plane) the
    # residual is constant in Y2, so its z-derivative must be zero — autodiff
    # through jnp.maximum gave exactly that, and the hand-derived Jacobian
    # must match or GN chases a phantom gradient on clamped points.
    clamped = Y[:, 2] < MIN_DEPTH
    fu0 = f * inv_z
    fu2 = jnp.where(clamped, 0.0, -f * Y[:, 0] * inv_z * inv_z)
    fv2 = jnp.where(clamped, 0.0, -f * Y[:, 1] * inv_z * inv_z)
    W = Y - t  # = R X
    # d(exp(d) W)/dd_k = e_k x W:
    # e0 x W = (0, -W2, W1);  e1 x W = (W2, 0, -W0);  e2 x W = (-W1, W0, 0)
    ju_d0 = fu2 * W[:, 1]
    ju_d1 = fu0 * W[:, 2] - fu2 * W[:, 0]
    ju_d2 = -fu0 * W[:, 1]
    jv_d0 = -fu0 * W[:, 2] + fv2 * W[:, 1]
    jv_d1 = -fv2 * W[:, 0]
    jv_d2 = fu0 * W[:, 0]
    rowu = jnp.stack([ju_d0, ju_d1, ju_d2, fu0, jnp.zeros_like(fu0), fu2], axis=-1)
    rowv = jnp.stack([jv_d0, jv_d1, jv_d2, jnp.zeros_like(fu0), fu0, fv2], axis=-1)
    wu = w[:, None] * rowu
    wv = w[:, None] * rowv
    A = hmm(rowu.T, wu) + hmm(rowv.T, wv)  # (6, 6)
    g = hmm(wu.T, ru[:, None])[:, 0] + hmm(wv.T, rv[:, None])[:, 0]
    mu = damping * (jnp.trace(A) / 6.0 + 1e-6)
    delta = _solve6_spd(A + mu * jnp.eye(6, dtype=A.dtype), g)
    R_new = hmm(rodrigues(-delta[:3]), R)
    t_new = t - delta[3:]
    return R_new, t_new


def refine_pose_gn_R(
    R: jnp.ndarray,
    tvec: jnp.ndarray,
    X: jnp.ndarray,
    x2d: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    weights: jnp.ndarray | None = None,
    iters: int = 5,
    damping: float = 1e-4,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """R-in/R-out weighted GN — the hot-path entry, no axis-angle round-trips."""
    w = jnp.ones(X.shape[0], dtype=X.dtype) if weights is None else weights

    def step(carry, _):
        Ri, ti = carry
        return _gn_pose_step(Ri, ti, X, x2d, f, c, w, damping), None

    (R, t), _ = jax.lax.scan(step, (R, tvec), None, length=iters)
    return R, t


@partial(jax.jit, static_argnames=("iters",))
def refine_pose_gn(
    rvec: jnp.ndarray,
    tvec: jnp.ndarray,
    X: jnp.ndarray,
    x2d: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    weights: jnp.ndarray | None = None,
    iters: int = 5,
    damping: float = 1e-4,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Weighted Gauss-Newton on the 6-DoF pose, fixed iterations.

    Replaces the reference's iterative cv::solvePnP refinement loop
    (SURVEY.md §3.5 "refine winner") with a differentiable, fixed-length LM.
    ``weights`` is (N,) per-point (soft-inlier) weights; None = uniform.
    Axis-angle boundary; inside the vmapped kernel use ``refine_pose_gn_R``.
    """
    R, t = refine_pose_gn_R(
        rodrigues(rvec), tvec, X, x2d, f, c, weights, iters, damping
    )
    return so3_log(R), t


@partial(jax.jit, static_argnames=("polish_iters",))
def solve_pnp_minimal(
    X4: jnp.ndarray,
    x4: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    polish_iters: int = 3,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Minimal 4-point PnP. X4: (4, 3) scene points, x4: (4, 2) pixels.

    Returns (rvec, tvec) with scene->camera convention Y = R X + t.
    Degenerate samples (collinear points, coincident pixels) produce *some*
    finite pose; RANSAC scoring rejects them, mirroring the reference's
    retry-on-bad-sample policy without data-dependent control flow.
    """
    b = bearings(x4, f, c)
    depths, penalty = _p3p_depths(b[:3], X4[:3])  # (4, 3), (4,)

    def candidate(lam3):
        Y3 = lam3[:, None] * b[:3]
        R, t = _triad_align(X4[:3], Y3)
        # Disambiguate with the 4th correspondence.
        err4 = reprojection_errors(R, t, X4[3:4], x4[3:4], f, c)[0]
        return R, t, err4

    Rs, ts, err4s = jax.vmap(candidate)(depths)
    # A NaN branch (pathological geometry) must never win the argmin.
    cost = err4s + penalty
    best = jnp.argmin(jnp.where(jnp.isnan(cost), jnp.inf, cost))
    R, t = refine_pose_gn_R(
        Rs[best], ts[best], X4, x4, f, c, weights=None, iters=polish_iters
    )
    return so3_log(R), t


def pnp_success(
    rvec: jnp.ndarray,
    tvec: jnp.ndarray,
    X4: jnp.ndarray,
    x4: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    threshold: float,
) -> jnp.ndarray:
    """Did the minimal solve fit its own 4 points within `threshold` px?

    The reference accepts a hypothesis only if the 4 sampled correspondences
    reproject within threshold (SURVEY.md §3.5); we compute the same predicate
    as a differentiable-free boolean for masking/diagnostics.
    """
    errs = reprojection_errors(rodrigues(rvec), tvec, X4, x4, f, c)
    return jnp.all(errs < threshold)
