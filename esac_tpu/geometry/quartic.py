"""Closed-form cubic/quartic root solvers in complex arithmetic.

TPU XLA has no nonsymmetric eigendecomposition, so the companion-matrix trick
for polynomial roots is unavailable; Cardano/Ferrari in complex64 is fully
branchless, vmap-safe, and differentiable away from root collisions.  Used by
the algebraic P3P minimal solver (the reference gets its roots from OpenCV's
``solvePnP`` P3P path on the host, SURVEY.md §3.5).

Precision note: complex64 root extraction is good to ~1e-3 relative; the PnP
pipeline always polishes with Gauss-Newton afterwards, which removes the
residual error.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12
# Complex-sqrt epsilon: sqrt's VJP is g/(2 sqrt(z)), infinite at z = 0 (double
# roots, all-zero degenerate polynomials).  Adding a tiny real eps keeps the
# backward finite (large-but-finite is safe; inf turns into NaN under the
# masked selects downstream).  1e-18 shifts roots by ~1e-9 — far below the
# float32 accuracy of the solver itself.
_SQRT_EPS = 1e-18


def _safe_csqrt(z: jnp.ndarray) -> jnp.ndarray:
    return jnp.sqrt(z + _SQRT_EPS)


def _cbrt(z: jnp.ndarray) -> jnp.ndarray:
    """Principal complex cube root, total at 0."""
    mag = jnp.abs(z)
    safe = jnp.where(mag < _EPS, 1.0 + 0j, z)
    out = jnp.exp(jnp.log(safe) / 3.0)
    return jnp.where(mag < _EPS, 0.0 + 0j, out)


def solve_cubic(B: jnp.ndarray, C: jnp.ndarray, D: jnp.ndarray) -> jnp.ndarray:
    """Roots of m^3 + B m^2 + C m + D. Scalars (complex or real) -> (3,) complex."""
    B = B.astype(jnp.complex64)
    C = C.astype(jnp.complex64)
    D = D.astype(jnp.complex64)
    P = C - B * B / 3.0
    Q = 2.0 * B**3 / 27.0 - B * C / 3.0 + D
    S = _safe_csqrt((Q / 2.0) ** 2 + (P / 3.0) ** 3)
    z1 = -Q / 2.0 + S
    z2 = -Q / 2.0 - S
    # Use the larger branch for the cube root to avoid cancellation.
    z = jnp.where(jnp.abs(z1) >= jnp.abs(z2), z1, z2)
    U = _cbrt(z)
    W = jnp.where(jnp.abs(U) < _EPS, 0.0 + 0j, -P / (3.0 * jnp.where(jnp.abs(U) < _EPS, 1.0, U)))
    omega = jnp.exp(2j * jnp.pi / 3.0).astype(jnp.complex64)
    ks = jnp.array([1.0 + 0j, omega, omega**2])
    roots = ks * U + jnp.conj(ks) * W - B / 3.0
    return roots


def _ferrari(a3: jnp.ndarray, a2: jnp.ndarray, a1: jnp.ndarray, a0: jnp.ndarray) -> jnp.ndarray:
    """Roots of the monic quartic v^4 + a3 v^3 + a2 v^2 + a1 v + a0 (complex)."""
    # Depressed quartic y^4 + p y^2 + q y + r with v = y - a3/4.
    p = a2 - 3.0 * a3 * a3 / 8.0
    q = a1 - a3 * a2 / 2.0 + a3**3 / 8.0
    r = a0 - a3 * a1 / 4.0 + a3 * a3 * a2 / 16.0 - 3.0 * a3**4 / 256.0

    # Resolvent cubic m^3 + p m^2 + (p^2 - 4r)/4 m - q^2/8 = 0.
    m_roots = solve_cubic(p, (p * p - 4.0 * r) / 4.0, -q * q / 8.0)
    # Largest |m| keeps s = sqrt(2m) well away from zero (m=0 happens iff q=0,
    # where the biquadratic factorization is exact anyway).
    m = m_roots[jnp.argmax(jnp.abs(m_roots))]
    s = _safe_csqrt(2.0 * m)
    s_safe = jnp.where(jnp.abs(s) < _EPS, 1.0 + 0j, s)
    qs = jnp.where(jnp.abs(s) < _EPS, 0.0 + 0j, q / (2.0 * s_safe))

    t1 = p / 2.0 + m - qs
    t2 = p / 2.0 + m + qs
    d1 = _safe_csqrt(s * s - 4.0 * t1)
    d2 = _safe_csqrt(s * s - 4.0 * t2)
    y = jnp.stack(
        [
            (-s + d1) / 2.0,
            (-s - d1) / 2.0,
            (s + d2) / 2.0,
            (s - d2) / 2.0,
        ]
    )
    return y - a3 / 4.0


def solve_quartic(coeffs: jnp.ndarray) -> jnp.ndarray:
    """Roots of q4 v^4 + q3 v^3 + q2 v^2 + q1 v + q0.

    coeffs: (5,) [q4, q3, q2, q1, q0] real. Returns (4,) complex roots.

    Stability: Ferrari needs a healthy leading coefficient.  When |q0| > |q4|
    the *reversed* polynomial (whose roots are 1/v) is better conditioned, so
    both ends are solved and the better-conditioned branch is selected —
    branchless, and total even for cubic-degenerate quartics (q4 -> 0), whose
    "root at infinity" comes back as a clamped large value that downstream
    penalties reject.  A relative floor keeps the untaken branch finite so no
    NaN can leak through ``where``.
    """
    # Scale selection, not scale + eps: the divide VJP computes -g*x/y^2, and
    # a tiny additive epsilon squares into float32 underflow (0/0 = NaN in
    # the backward pass at an all-zero polynomial).  A `where` keeps the
    # denominator O(1) in the degenerate case and exact otherwise.
    mx = jnp.max(jnp.abs(coeffs))
    scale = jnp.where(mx > 1e-15, mx, 1.0)
    c = (coeffs / scale).astype(jnp.float32)
    q4, q0 = c[0], c[4]

    def lead_safe(q):
        # Floor at 1e-2 of the max coefficient: keeps a3 <= 100 so Ferrari's
        # worst intermediate (~|a3|^6 in the resolvent) stays in float32 range.
        return jnp.where(jnp.abs(q) < 1e-2, jnp.where(q < 0, -1e-2, 1e-2), q)

    q4s = lead_safe(q4)
    q0s = lead_safe(q0)
    fwd = _ferrari(
        (c[1] / q4s).astype(jnp.complex64),
        (c[2] / q4s).astype(jnp.complex64),
        (c[3] / q4s).astype(jnp.complex64),
        (c[4] / q4s).astype(jnp.complex64),
    )
    rev_w = _ferrari(
        (c[3] / q0s).astype(jnp.complex64),
        (c[2] / q0s).astype(jnp.complex64),
        (c[1] / q0s).astype(jnp.complex64),
        (c[0] / q0s).astype(jnp.complex64),
    )
    w_safe = jnp.where(jnp.abs(rev_w) < 1e-8, 1e-8 + 0j, rev_w)
    rev = 1.0 / w_safe
    return jnp.where(jnp.abs(q4) >= jnp.abs(q0), fwd, rev)
