"""Layer 1, R7: shell scripts must never timeout/kill a jax python process.

The TPU relay of this environment wedges permanently when a jax process
holding or awaiting the device is killed (CLAUDE.md environment hazards) —
and ``timeout`` IS a kill after a countdown.  The sanctioned pattern is
bench.py's: launch the chip-touching python as a detached child, poll a
result file, and on deadline ORPHAN the child (never kill, never wait).

Line rules over every ``*.sh`` in the repo:

- ``timeout … python …`` on one line -> finding (the wrapped python gets
  SIGTERM/SIGKILL on expiry).
- ``kill`` / ``pkill`` / ``killall`` -> finding, EXCEPT ``kill -0`` (signal
  0 is a pure liveness probe, delivered nowhere) — the probe loops in
  tools/chip_recovery.sh and the experiment queues depend on it.

Suppress a sanctioned line with a trailing
``# graft-lint: disable=R7(reason)`` or file-wide with ``disable-file=``.
"""

from __future__ import annotations

import pathlib
import re

from esac_tpu.lint.findings import Finding
from esac_tpu.lint.suppress import is_suppressed, parse_suppressions

_SKIP_DIRS = {".git", "__pycache__", ".venv", "build", "ckpts", "node_modules"}

_TIMEOUT_PYTHON = re.compile(r"\btimeout\b.*\bpython[0-9.]*\b")
_KILL = re.compile(r"\b(?P<cmd>kill|pkill|killall)\b(?P<rest>[^|;&]*)")
_KILL_LIVENESS = re.compile(r"^\s+-0\b")


def iter_shell_files(root: pathlib.Path, files=None):
    if files is not None:
        for f in files:
            rel = pathlib.Path(f)
            if rel.is_absolute():
                rel = rel.relative_to(root)
            if rel.suffix == ".sh" and (root / rel).exists():
                yield rel.as_posix()
        return
    for p in sorted(root.rglob("*.sh")):
        rel = p.relative_to(root)
        if any(part in _SKIP_DIRS for part in rel.parts):
            continue
        yield rel.as_posix()


def _scan_line(rel: str, lineno: int, line: str) -> list[Finding]:
    # Full-line comments carry prose about killing ("never kill…"), not
    # commands; strip the comment tail before matching, but keep the raw
    # stripped line as the finding's baseline identity.
    code = line.split("#", 1)[0]
    if not code.strip():
        return []
    out = []
    if _TIMEOUT_PYTHON.search(code):
        out.append(Finding(
            "R7", rel, lineno, line.strip(),
            "timeout-wrapped python in a shell script: timeout kills on "
            "expiry, and killing a jax-on-TPU process wedges the relay "
            "permanently; use the bench.py detached-child + poll pattern",
        ))
    for m in _KILL.finditer(code):
        if _KILL_LIVENESS.match(m.group("rest")):
            continue  # kill -0: liveness probe, no signal delivered
        out.append(Finding(
            "R7", rel, lineno, line.strip(),
            f"{m.group('cmd')} in a shell script: killing a jax-on-TPU "
            "process wedges the relay permanently; orphan instead "
            "(bench.py pattern), or suppress with a reviewed reason if no "
            "jax process can be the target",
        ))
    return out


def run_shell_rules(root, files=None) -> list[Finding]:
    root = pathlib.Path(root)
    findings: list[Finding] = []
    for rel in iter_shell_files(root, files):
        try:
            source = (root / rel).read_text()
        except UnicodeDecodeError:
            continue
        per_line, per_file = parse_suppressions(source)
        for lineno, line in enumerate(source.splitlines(), start=1):
            for f in _scan_line(rel, lineno, line):
                if not is_suppressed(f.rule, f.line, per_line, per_file,
                                     path=rel):
                    findings.append(f)
    return findings
