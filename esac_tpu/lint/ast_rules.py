"""Layer 1: AST rules R1-R6 over the repo's Python sources.

Pure ``ast`` — no jax import, no execution — so the whole tree lints in
well under a second.  Each rule is scoped by repo-relative path (the scope
table mirrors LINT.md); inline ``# graft-lint: disable=RULE(reason)``
suppressions are honored here, while the committed baseline is applied by
the caller (:mod:`esac_tpu.lint.cli`).

R3 is the one cross-file rule: a lightweight intra-package call graph marks
every function reachable from a ``jax.jit``/``jax.vmap``/``shard_map``
wrapper (decorator or call-site) and flags scalar-looping linalg inside the
reachable set.  The graph over-approximates callees (any name called inside
a reachable function body, nested lambdas included) and under-approximates
dynamic dispatch (method calls through instances are not resolved) — the
right trade for a lint: no false positives from dead code, and the hot
paths here are plain functions.
"""

from __future__ import annotations

import ast
import pathlib

from esac_tpu.lint.findings import Finding
from esac_tpu.lint.suppress import is_suppressed, parse_suppressions

# --------------------------------------------------------------------------
# shared helpers

_SKIP_DIRS = {".git", "__pycache__", ".venv", "build", "ckpts", "node_modules"}

# Top-level packages whose import makes a script "jax-adjacent" (R6): their
# import can reach jax backend init.  Repo-root entry scripts count — they
# import jax transitively.
_JAX_ADJACENT = {
    "jax", "flax", "optax", "orbax", "esac_tpu",
    "bench", "bench_accuracy", "train_esac", "train_expert", "train_gating",
    "test_esac", "convert_checkpoint",
}

# Callables that make an argument function part of a jit/vmap hot path (R3).
_JIT_WRAPPERS = {
    "jax.jit", "jax.vmap", "jax.pmap", "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "esac_tpu.parallel.mesh.shard_map",  # the repo's compat alias
    "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "jax.lax.map", "jax.grad", "jax.value_and_grad",
    "jax.custom_vjp", "jax.custom_jvp",
}

# jnp.linalg / scipy.linalg callables that lower to scalar loops on TPU (R3).
_SCALAR_LINALG = {
    "svd", "solve", "inv", "pinv", "qr", "eig", "eigh", "eigvals",
    "eigvalsh", "lstsq", "cholesky", "matrix_power", "slogdet",
}

# Unpinned contraction entry points (R4).
_CONTRACTIONS = {
    "jax.numpy.matmul", "jax.numpy.einsum", "jax.numpy.dot",
    "jax.numpy.tensordot", "jax.numpy.inner", "jax.numpy.vdot",
}


def iter_python_files(root: pathlib.Path, files=None):
    """Repo-relative posix paths of the .py files to lint."""
    if files is not None:
        for f in files:
            rel = pathlib.Path(f)
            if rel.is_absolute():
                rel = rel.relative_to(root)
            if rel.suffix == ".py" and (root / rel).exists():
                yield rel.as_posix()
        return
    for p in sorted(root.rglob("*.py")):
        rel = p.relative_to(root)
        if any(part in _SKIP_DIRS for part in rel.parts):
            continue
        yield rel.as_posix()


def _alias_map(tree: ast.AST) -> dict[str, str]:
    """Name bound by an import -> fully dotted target, whole file."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _dotted(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """Resolve an expression to a dotted name with import aliases expanded.

    ``jnp.linalg.norm`` -> ``jax.numpy.linalg.norm`` (under
    ``import jax.numpy as jnp``); returns None for non-name expressions.
    """
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    parts.reverse()
    head = aliases.get(parts[0], parts[0])
    return ".".join([head] + parts[1:])


def _walk_no_functions(node: ast.AST):
    """ast.walk that does not descend into function/lambda bodies (but does
    visit their decorators and default-argument expressions, which execute
    at import time)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(n.decorator_list)
            stack.extend(n.args.defaults)
            stack.extend(d for d in n.args.kw_defaults if d is not None)
            continue
        if isinstance(n, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _force_cpu_guard_line(
    tree: ast.AST, aliases: dict[str, str], module_level_only: bool = False
) -> int | None:
    """Line of ``jax.config.update("jax_platforms", "cpu")``, or None.

    R1's import-time exemption needs ``module_level_only=True``: a guard
    buried in a function body never runs at import, so it cannot make a
    module-level array constant safe.  R6 accepts any placement — a script
    that forces CPU at the top of ``main()`` still does so before first
    device use.
    """
    walker = _walk_no_functions(tree) if module_level_only else ast.walk(tree)
    for node in walker:
        if not isinstance(node, ast.Call):
            continue
        if _dotted(node.func, aliases) != "jax.config.update":
            continue
        args = node.args
        if (
            len(args) >= 2
            and isinstance(args[0], ast.Constant)
            and args[0].value == "jax_platforms"
            and isinstance(args[1], ast.Constant)
            and args[1].value == "cpu"
        ):
            return node.lineno
    return None


def _line_text(lines: list[str], lineno: int) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


# --------------------------------------------------------------------------
# rule scopes (repo-relative posix paths)

def _in_tests(rel: str) -> bool:
    return rel.startswith("tests/")


def _r1_scope(rel: str) -> bool:
    # tests/ is exempt: tests/conftest.py pins the CPU backend before jax is
    # imported anywhere, so import-time constants there cannot touch the TPU.
    return not _in_tests(rel)


def _r2_scope(rel: str) -> bool:
    return rel.startswith(
        ("esac_tpu/geometry/", "esac_tpu/ransac/", "esac_tpu/train/")
    )


def _r4_scope(rel: str) -> bool:
    return rel.startswith("esac_tpu/geometry/") or rel == "esac_tpu/ransac/refine.py"


def _r5_scope(rel: str) -> bool:
    return rel.startswith("esac_tpu/")


def _r6_scope(rel: str) -> bool:
    return rel.startswith(("tools/", "experiments/")) and rel.endswith(".py")


def _r3_scope(rel: str) -> bool:
    return rel.startswith("esac_tpu/")


def _r8_scope(rel: str) -> bool:
    # Donation misuse crashes wherever it happens (the PR-4 instance was in
    # bench.py, not the package) — everything but tests/, which constructs
    # adversarial trees on purpose.
    return not _in_tests(rel)


def _r9_scope(rel: str) -> bool:
    # Retrace hazards matter where code runs repeatedly: the package.  Root
    # scripts are one-shot trainers/probes whose single extra trace is not
    # a serving regression.
    return rel.startswith("esac_tpu/")


# --------------------------------------------------------------------------
# per-file rules

def _rule_r1(rel, tree, aliases, lines):
    """Module-level jnp/jax array creation = import-time backend init."""
    guard = _force_cpu_guard_line(tree, aliases, module_level_only=True)
    out = []
    for node in _walk_no_functions(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func, aliases)
        if dotted is None:
            continue
        if dotted.startswith(("jax.numpy.", "jax.random.")) or dotted in (
            "jax.device_put", "jax.devices", "jax.local_devices",
        ):
            # A module-level force-CPU guard executed first makes the init
            # CPU-only — the sanctioned pattern for ad-hoc scripts.
            if guard is not None and guard < node.lineno:
                continue
            out.append(Finding(
                "R1", rel, node.lineno, _line_text(lines, node.lineno),
                f"module-level {dotted.replace('jax.numpy', 'jnp')} call "
                "initializes the device backend at import time; build with "
                "numpy (or move inside a function)",
            ))
    return out


def _eps_guarded(arg: ast.AST) -> bool:
    """True for ``x + eps``-shaped sqrt arguments (eps inside the sqrt)."""
    if not (isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add)):
        return False
    for side in (arg.left, arg.right):
        if isinstance(side, ast.Constant) and isinstance(side.value, (int, float)):
            return True
        name = None
        if isinstance(side, ast.Name):
            name = side.id
        elif isinstance(side, ast.Attribute):
            name = side.attr
        if name is not None and "eps" in name.lower():
            return True
    return False


def _rule_r2(rel, tree, aliases, lines):
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func, aliases)
        if dotted == "jax.numpy.linalg.norm":
            out.append(Finding(
                "R2", rel, node.lineno, _line_text(lines, node.lineno),
                "raw jnp.linalg.norm in differentiated geometry NaNs the "
                "VJP at zero input; use utils.num.safe_norm",
            ))
        elif dotted == "jax.numpy.sqrt":
            if node.args and _eps_guarded(node.args[0]):
                continue
            out.append(Finding(
                "R2", rel, node.lineno, _line_text(lines, node.lineno),
                "bare jnp.sqrt has an infinite VJP at 0; use "
                "utils.num.safe_sqrt or put an eps inside the sqrt",
            ))
    return out


def _rule_r4(rel, tree, aliases, lines):
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
            out.append(Finding(
                "R4", rel, node.lineno, _line_text(lines, node.lineno),
                "raw @ matmul in a precision-pinned module runs at "
                "bf16-default MXU precision; use utils.precision.hmm",
            ))
        elif isinstance(node, ast.Call):
            dotted = _dotted(node.func, aliases)
            if dotted in _CONTRACTIONS:
                if any(kw.arg == "precision" for kw in node.keywords):
                    continue
                short = dotted.replace("jax.numpy", "jnp")
                out.append(Finding(
                    "R4", rel, node.lineno, _line_text(lines, node.lineno),
                    f"{short} without precision= in a precision-pinned "
                    "module; use utils.precision.hmm/heinsum (or pass "
                    "precision explicitly)",
                ))
    return out


def _rule_r5(rel, tree, aliases, lines):
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef) or not node.name.endswith("Config"):
            continue
        for dec in node.decorator_list:
            call = dec if isinstance(dec, ast.Call) else None
            target = dec.func if call is not None else dec
            dotted = _dotted(target, aliases)
            if dotted is None or not dotted.endswith("dataclass"):
                continue
            if "struct.dataclass" in dotted:
                continue  # flax.struct.dataclass is frozen by construction
            frozen = call is not None and any(
                kw.arg == "frozen"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in call.keywords
            )
            if not frozen:
                out.append(Finding(
                    "R5", rel, node.lineno, _line_text(lines, node.lineno),
                    f"config dataclass {node.name} must be frozen=True to "
                    "be hashable as a static jit arg",
                ))
    return out


def _rule_r6(rel, tree, aliases, lines):
    first_import = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            tops = [a.name.split(".")[0] for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            tops = [node.module.split(".")[0]]
        else:
            continue
        if any(t in _JAX_ADJACENT for t in tops):
            if first_import is None or node.lineno < first_import:
                first_import = node.lineno
    if first_import is None:
        return []
    if _force_cpu_guard_line(tree, aliases) is not None:
        return []
    return [Finding(
        "R6", rel, first_import, _line_text(lines, first_import),
        "ad-hoc script imports jax-adjacent modules without the force-CPU "
        'guard; add jax.config.update("jax_platforms", "cpu") before first '
        "device use (or an inline suppression if the script is sanctioned "
        "to touch the chip)",
    )]


# --------------------------------------------------------------------------
# R8: donation safety / R9: retrace safety

def _loop_walk(body, loops=()):
    """Yield ``(node, loop_stack)`` over ``body`` without descending into
    nested function/lambda scopes (they are analyzed as their own scopes)."""
    for node in body:
        yield node, loops
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        inner = loops + (node,) if isinstance(node, (ast.For, ast.While)) else loops
        yield from _loop_walk(list(ast.iter_child_nodes(node)), inner)


def _donate_positions(node, scope_values) -> set[int]:
    """Resolve a ``donate_argnums=`` expression to a set of positions.

    Handles int/tuple literals, one level of Name indirection into the same
    scope, and the repo's conditional idiom
    ``donate = (1,) if backend != "cpu" else ()`` (union of branches).
    Unresolvable expressions yield the empty set — R8 under-approximates
    rather than false-positive.
    """
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
        return out
    if isinstance(node, ast.IfExp):
        return _donate_positions(node.body, scope_values) | \
            _donate_positions(node.orelse, scope_values)
    if isinstance(node, ast.Name):
        vals = scope_values.get(node.id, [])
        if len(vals) == 1:
            return _donate_positions(vals[0], {})
        return set()
    return set()


def _jit_donate_call(node, aliases) -> ast.Call | None:
    """The ``jax.jit(...)`` Call carrying a donate_argnums kwarg, or None."""
    if not isinstance(node, ast.Call) or _dotted(node.func, aliases) != "jax.jit":
        return None
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            return node
    return None


def _is_cache_get(node, aliases) -> bool:
    """True for ``<anything>.cache.get(...)`` / ``cache.get(...)`` — the
    registry weight-cache access idiom (registry/cache.py invariant: cached
    trees are reused across dispatches and must never be donated)."""
    if not isinstance(node, ast.Call):
        return False
    dotted = _dotted(node.func, aliases) or ""
    return dotted == "cache.get" or dotted.endswith(".cache.get")


def _donating_factories(tree: ast.AST, aliases) -> dict[str, set[int]]:
    """Top-level functions returning a donating ``jax.jit`` wrapper."""
    out: dict[str, set[int]] = {}
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        values: dict[str, list] = {}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                values.setdefault(sub.targets[0].id, []).append(sub.value)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Return) or sub.value is None:
                continue
            call = _jit_donate_call(sub.value, aliases)
            if call is None:
                continue
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    pos = _donate_positions(kw.value, values)
                    if pos:
                        out[node.name] = pos
    return out


def _scopes(tree: ast.AST):
    """Module body + every function body, each as its own analysis scope."""
    yield tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body


def _rule_r8(rel, tree, aliases, lines):
    factories = _donating_factories(tree, aliases)
    out = []
    for body in _scopes(tree):
        assigns: dict[str, list] = {}        # name -> [(loops, line)]: ANY
        #   binding site — plain assign, tuple unpack, for/with targets —
        #   so `batch, labels = next(it)` and `for batch in it:` count as
        #   restaging (reaching-def / loop-intersection inputs).
        values: dict[str, list] = {}         # name -> [value] (single-target)
        loads: dict[str, list] = {}          # name -> [(lineno, col)]
        calls = []                           # (call, loops)
        for node, loops in _loop_walk(body):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                values.setdefault(node.targets[0].id, []).append(node.value)
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append(
                        (node.lineno, node.col_offset)
                    )
                elif isinstance(node.ctx, ast.Store):
                    assigns.setdefault(node.id, []).append(
                        (loops, node.lineno)
                    )
            if isinstance(node, ast.Call):
                calls.append((node, loops))

        donating: dict[str, set[int]] = {}
        cached: set[str] = set()
        for name, vals in values.items():
            for v in vals:
                call = _jit_donate_call(v, aliases)
                if call is not None:
                    for kw in call.keywords:
                        if kw.arg == "donate_argnums":
                            pos = _donate_positions(kw.value, values)
                            if pos:
                                donating[name] = pos
                if isinstance(v, ast.Call) and isinstance(v.func, ast.Name) \
                        and v.func.id in factories:
                    donating[name] = factories[v.func.id]
                if _is_cache_get(v, aliases):
                    cached.add(name)

        if not donating:
            continue
        for call, loops in calls:
            if not isinstance(call.func, ast.Name) or call.func.id not in donating:
                continue
            fn_name = call.func.id
            for p in sorted(donating[fn_name]):
                if p >= len(call.args):
                    continue
                arg = call.args[p]
                if _is_cache_get(arg, aliases) or (
                    isinstance(arg, ast.Name) and arg.id in cached
                ):
                    out.append(Finding(
                        "R8", rel, call.lineno, _line_text(lines, call.lineno),
                        f"donated position {p} of '{fn_name}' receives a "
                        "cached/registry-held param tree: donation would "
                        "silently invalidate the cache's device buffers for "
                        "every later dispatch — donate only per-dispatch "
                        "data (registry/cache.py invariant)",
                    ))
                    continue
                if not isinstance(arg, ast.Name):
                    continue
                n = arg.id
                if loops and not any(
                    set(loops) & set(a_loops)
                    for a_loops, _ln in assigns.get(n, [])
                ):
                    out.append(Finding(
                        "R8", rel, call.lineno, _line_text(lines, call.lineno),
                        f"'{n}' is staged once outside the loop but passed "
                        f"in donated position {p} of '{fn_name}' every "
                        "iteration: after the first dispatch its buffers "
                        "are invalidated (the PR-4 bench bug) — restage a "
                        "fresh tree per call",
                    ))
                    continue
                # "Later use" means beyond the CALL's full span (a
                # multi-line call's own argument load is not a reuse), and
                # before any re-assignment of the name (a restaged tree is
                # a new buffer — reaching-def cutoff).
                call_end = getattr(call, "end_lineno", None) or call.lineno
                next_assign = min(
                    (ln for _l, ln in assigns.get(n, [])
                     if ln > call_end),
                    default=None,
                )
                if any(
                    ln > call_end
                    and (next_assign is None or ln < next_assign)
                    for ln, _ in loads.get(n, [])
                ):
                    out.append(Finding(
                        "R8", rel, call.lineno, _line_text(lines, call.lineno),
                        f"'{n}' is used again after being passed in donated "
                        f"position {p} of '{fn_name}': donation invalidates "
                        "the buffers at the call — on accelerators any "
                        "later use reads freed memory",
                    ))
    return out


_UNHASHABLE_NODES = (
    ast.Dict, ast.List, ast.Set, ast.ListComp, ast.SetComp, ast.DictComp,
    ast.GeneratorExp,
)


def _static_positions_of_jitted_defs(tree, aliases):
    """name -> (static positions, static argname->position) for same-module
    functions decorated ``@partial(jax.jit, static_arg...)``."""
    out: dict[str, tuple[set[int], dict[str, int]]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            if _dotted(dec.func, aliases) not in ("functools.partial", "partial"):
                continue
            if not (dec.args and _dotted(dec.args[0], aliases) == "jax.jit"):
                continue
            positions: set[int] = set()
            by_name: dict[str, int] = {}
            params = [a.arg for a in node.args.posonlyargs + node.args.args]
            for kw in dec.keywords:
                if kw.arg == "static_argnums":
                    positions |= _donate_positions(kw.value, {})
                elif kw.arg == "static_argnames" and isinstance(
                    kw.value, (ast.Tuple, ast.List)
                ):
                    for elt in kw.value.elts:
                        if isinstance(elt, ast.Constant) \
                                and isinstance(elt.value, str):
                            name = elt.value
                            if name in params:
                                by_name[name] = params.index(name)
            if positions or by_name:
                out[node.name] = (positions | set(by_name.values()), by_name)
    return out


def _rule_r9(rel, tree, aliases, lines):
    out = []
    static_map = _static_positions_of_jitted_defs(tree, aliases)

    def is_jit_maker(node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        dotted = _dotted(node.func, aliases)
        if dotted == "jax.jit":
            return True
        return (
            dotted in ("functools.partial", "partial")
            and bool(node.args)
            and _dotted(node.args[0], aliases) == "jax.jit"
        )

    for body in _scopes(tree):
        for node, loops in _loop_walk(body):
            if not isinstance(node, ast.Call):
                continue
            if is_jit_maker(node) and loops:
                out.append(Finding(
                    "R9", rel, node.lineno, _line_text(lines, node.lineno),
                    "jit wrapper constructed inside a loop: each iteration "
                    "builds a fresh callable with an empty compile cache "
                    "(retrace + recompile per pass) — hoist the jax.jit out "
                    "of the loop or cache the wrapper",
                ))
            elif not loops and isinstance(node.func, ast.Call) and \
                    _dotted(node.func.func, aliases) == "jax.jit":
                # Direct jax.jit(f)(x) only: the outer call INVOKES the
                # program.  partial(jax.jit, ...)(f) is the non-decorator
                # spelling of the @partial idiom — the outer call merely
                # PRODUCES the wrapper (bound once) and is not a hazard.
                # Inside a loop the inner jax.jit(...) call already carries
                # the jit-in-loop finding: one report per expression.
                out.append(Finding(
                    "R9", rel, node.lineno, _line_text(lines, node.lineno),
                    "jax.jit(...)(...) builds and invokes a fresh program "
                    "in one expression: nothing holds the wrapper, so every "
                    "call retraces and recompiles — bind the jitted "
                    "callable once (module level or an lru_cached builder) "
                    "and reuse it",
                ))
            if isinstance(node.func, ast.Name) and node.func.id in static_map:
                positions, by_name = static_map[node.func.id]
                for p in sorted(positions):
                    if p < len(node.args) and isinstance(
                        node.args[p], _UNHASHABLE_NODES
                    ):
                        out.append(Finding(
                            "R9", rel, node.lineno,
                            _line_text(lines, node.lineno),
                            f"unhashable literal in static position {p} of "
                            f"jitted '{node.func.id}': static jit arguments "
                            "are hashed per call — this TypeErrors (or "
                            "retraces forever with a custom hash); pass a "
                            "frozen dataclass / tuple",
                        ))
                for kw in node.keywords:
                    if kw.arg in by_name and isinstance(
                        kw.value, _UNHASHABLE_NODES
                    ):
                        out.append(Finding(
                            "R9", rel, node.lineno,
                            _line_text(lines, node.lineno),
                            f"unhashable literal for static argument "
                            f"'{kw.arg}' of jitted '{node.func.id}': static "
                            "jit arguments are hashed per call — pass a "
                            "frozen dataclass / tuple",
                        ))
    return out


# --------------------------------------------------------------------------
# R3: package-wide call graph

class _Module:
    def __init__(self, rel: str, tree: ast.AST, lines: list[str]):
        self.rel = rel
        self.tree = tree
        self.lines = lines
        self.aliases = _alias_map(tree)
        # "esac_tpu/geometry/pnp.py" -> "esac_tpu.geometry.pnp"
        self.dotted = rel[:-3].replace("/", ".")
        if self.dotted.endswith(".__init__"):
            self.dotted = self.dotted[: -len(".__init__")]
        self.functions: dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)


def _resolve_function(dotted: str, modules: dict[str, "_Module"], _depth=0):
    """Dotted callable name -> (module, funcname) inside the package.

    Follows one level of package-``__init__`` re-exports
    (``from esac_tpu.ransac import dsac_infer``)."""
    if not dotted.startswith("esac_tpu.") or _depth > 4:
        return None
    mod_path, _, func = dotted.rpartition(".")
    m = modules.get(mod_path)
    if m is None:
        return None
    if func in m.functions:
        return (mod_path, func)
    target = m.aliases.get(func)
    if target is not None and target != dotted:
        return _resolve_function(target, modules, _depth + 1)
    return None


def _callees(
    mod: _Module, body: ast.AST, modules: dict[str, "_Module"]
) -> set[tuple[str, str]]:
    out = set()
    for node in ast.walk(body):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func, mod.aliases)
        if dotted is None:
            continue
        if "." not in dotted and dotted in mod.functions:
            out.add((mod.dotted, dotted))
            continue
        resolved = _resolve_function(dotted, modules)
        if resolved:
            out.add(resolved)
    return out


def _r3_roots(modules: dict[str, _Module]) -> set[tuple[str, str]]:
    roots: set[tuple[str, str]] = set()
    for mod in modules.values():
        for name, fn in mod.functions.items():
            for dec in fn.decorator_list:
                for sub in ast.walk(dec):
                    d = _dotted(sub, mod.aliases)
                    if d in _JIT_WRAPPERS:
                        roots.add((mod.dotted, name))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _dotted(node.func, mod.aliases) not in _JIT_WRAPPERS:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                names = []
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    names.append(arg)
                elif isinstance(arg, ast.Lambda):
                    names.extend(
                        n for n in ast.walk(arg.body)
                        if isinstance(n, (ast.Name, ast.Attribute))
                    )
                for n in names:
                    d = _dotted(n, mod.aliases)
                    if d is None:
                        continue
                    if "." not in d and d in mod.functions:
                        roots.add((mod.dotted, d))
                    else:
                        resolved = _resolve_function(d, modules)
                        if resolved:
                            roots.add(resolved)
    return roots


def _rule_r3(modules: dict[str, _Module]):
    roots = _r3_roots(modules)
    reachable: set[tuple[str, str]] = set()
    frontier = list(roots)
    while frontier:
        key = frontier.pop()
        if key in reachable:
            continue
        reachable.add(key)
        mod = modules.get(key[0])
        if mod is None:
            continue
        fn = mod.functions.get(key[1])
        if fn is None:
            continue
        frontier.extend(_callees(mod, fn, modules))

    out = []
    for mod_dotted, func in sorted(reachable):
        mod = modules[mod_dotted]
        fn = mod.functions[func]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func, mod.aliases)
            if dotted is None:
                continue
            short = dotted.replace("jax.numpy", "jnp")
            if dotted == "jax.lax.while_loop":
                out.append(Finding(
                    "R3", mod.rel, node.lineno,
                    _line_text(mod.lines, node.lineno),
                    f"{short} inside {func}() which is reachable from a "
                    "jit/vmap hot path; its trip count is data-dependent — "
                    "use a fixed-length jax.lax.scan",
                ))
            elif (
                dotted.startswith(("jax.numpy.linalg.", "jax.scipy.linalg.",
                                   "jax.lax.linalg."))
                and dotted.rpartition(".")[2] in _SCALAR_LINALG
            ):
                out.append(Finding(
                    "R3", mod.rel, node.lineno,
                    _line_text(mod.lines, node.lineno),
                    f"{short} inside {func}() which is reachable from a "
                    "jit/vmap hot path; lowers to scalar loops on TPU — "
                    "use the unrolled/triad patterns in geometry/pnp.py",
                ))
    return out


# --------------------------------------------------------------------------
# R11: jaxpr-audit registry coverage gate

def _r11_discover(root: pathlib.Path):
    """Public jitted entry points package-wide: ``[(rel, lineno, name)]``.

    Two shapes count as a compiled surface: a public top-level function
    decorated ``@jax.jit`` / ``@partial(jax.jit, ...)``, and a public
    top-level ``make_*`` factory that builds a ``jax.jit`` wrapper (call or
    inner decorated def).  esac_tpu/lint/ itself is excluded — the auditor
    is not an audited surface.
    """
    out = []
    for p in sorted((root / "esac_tpu").rglob("*.py")):
        rel = p.relative_to(root).as_posix()
        if rel.startswith("esac_tpu/lint/") or \
                any(part in _SKIP_DIRS for part in p.relative_to(root).parts):
            continue
        try:
            tree = ast.parse(p.read_text(), filename=rel)
        except (SyntaxError, UnicodeDecodeError):
            continue  # R0 is reported by the per-file pass
        aliases = _alias_map(tree)

        def _is_jit_dec(dec) -> bool:
            for sub in ast.walk(dec):
                if isinstance(sub, (ast.Name, ast.Attribute)) \
                        and _dotted(sub, aliases) == "jax.jit":
                    return True
            return False

        for node in tree.body:
            if not isinstance(node, ast.FunctionDef) or \
                    node.name.startswith("_"):
                continue
            jitted = any(_is_jit_dec(d) for d in node.decorator_list)
            factory = False
            if node.name.startswith("make_"):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) \
                            and _dotted(sub.func, aliases) == "jax.jit":
                        factory = True
                    elif isinstance(sub, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
                            and sub is not node:
                        if any(_is_jit_dec(d) for d in sub.decorator_list):
                            factory = True
            if jitted or factory:
                out.append((rel, node.lineno, node.name))
    return out


def _r11_registry_names(registry_source: str) -> tuple[set[str], dict[str, str]]:
    """-> (identifiers referenced by lint/registry.py, R11_WAIVED map)."""
    tree = ast.parse(registry_source)
    names: set[str] = set()
    waived: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            names.add(node.attr)
        elif isinstance(node, ast.ImportFrom):
            names.update(a.name for a in node.names)
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if not any(isinstance(t, ast.Name) and t.id == "R11_WAIVED"
                       for t in targets):
                continue
            value = node.value
            if isinstance(value, ast.Dict):
                for k, v in zip(value.keys, value.values):
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        try:
                            waived[k.value] = ast.literal_eval(v)
                        except ValueError:
                            waived[k.value] = ""
    return names, waived


def run_registry_coverage(root, files=None) -> list[Finding]:
    """R11 over the whole package (tree-global: scoped runs still check the
    full matrix whenever any package file is in scope)."""
    root = pathlib.Path(root)
    registry_path = root / "esac_tpu" / "lint" / "registry.py"
    if not registry_path.exists():
        return []  # not an audited tree (fixture roots without a registry)
    if files is not None and not any(
        f.startswith("esac_tpu/") and f.endswith(".py") for f in files
    ):
        return []
    registered, waived = _r11_registry_names(registry_path.read_text())
    findings = []
    for rel, lineno, name in _r11_discover(root):
        if name in registered or name in waived:
            continue
        source = (root / rel).read_text()
        per_line, per_file = parse_suppressions(source)
        f = Finding(
            "R11", rel, lineno,
            _line_text(source.splitlines(), lineno),
            f"public jitted entry point '{name}' is neither registered in "
            "esac_tpu/lint/registry.py nor waived in R11_WAIVED: every "
            "compiled surface must ride the jaxpr audit + resource ledger "
            "(add a registry Entry, or a waiver with a reviewed reason)",
        )
        if not is_suppressed("R11", lineno, per_line, per_file, path=rel):
            findings.append(f)
    return findings


def stale_r11_waivers(root) -> list[str]:
    """Notes for R11_WAIVED entries naming no discovered entry point —
    the waived function was removed or renamed, so the waiver is a
    dangling reviewed-exception that would silently cover a FUTURE
    function reusing the name (graft-audit v3 stale-suppression sweep)."""
    root = pathlib.Path(root)
    registry_path = root / "esac_tpu" / "lint" / "registry.py"
    if not registry_path.exists():
        return []
    _, waived = _r11_registry_names(registry_path.read_text())
    discovered = {name for _, _, name in _r11_discover(root)}
    return [
        f"stale R11 waiver '{name}': no public jitted entry point of "
        "that name is discovered any more — prune it from R11_WAIVED "
        "(esac_tpu/lint/registry.py)"
        for name in sorted(waived) if name not in discovered
    ]


# --------------------------------------------------------------------------
# driver

def run_python_rules(root, files=None) -> list[Finding]:
    root = pathlib.Path(root)
    findings: list[Finding] = []
    r3_modules: dict[str, _Module] = {}
    suppressions: dict[str, tuple[dict, set]] = {}

    for rel in iter_python_files(root, files):
        try:
            source = (root / rel).read_text()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                "R0", rel, getattr(e, "lineno", 0) or 0, "",
                f"unparsable python: {e}",
            ))
            continue
        lines = source.splitlines()
        aliases = _alias_map(tree)
        suppressions[rel] = parse_suppressions(source)

        if _r1_scope(rel):
            findings += _rule_r1(rel, tree, aliases, lines)
        if _r2_scope(rel):
            findings += _rule_r2(rel, tree, aliases, lines)
        if _r4_scope(rel):
            findings += _rule_r4(rel, tree, aliases, lines)
        if _r5_scope(rel):
            findings += _rule_r5(rel, tree, aliases, lines)
        if _r6_scope(rel):
            findings += _rule_r6(rel, tree, aliases, lines)
        if _r8_scope(rel):
            findings += _rule_r8(rel, tree, aliases, lines)
        if _r9_scope(rel):
            findings += _rule_r9(rel, tree, aliases, lines)
        if _r3_scope(rel):
            m = _Module(rel, tree, lines)
            r3_modules[m.dotted] = m

    if r3_modules:
        # Every R3 path was parsed in the loop above, so its suppressions
        # are already in the table.
        findings += _rule_r3(r3_modules)

    out = []
    for f in findings:
        per_line, per_file = suppressions.get(f.path, ({}, set()))
        if not is_suppressed(f.rule, f.line, per_line, per_file,
                             path=f.path):
            out.append(f)
    return out
