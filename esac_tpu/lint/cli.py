"""graft-lint CLI: ``python -m esac_tpu.lint``.

Exit codes follow the driver contract: 0 clean, 1 findings, 2 internal
error.

Modes
-----
- default           : layer 1 over the full tree (incl. the graft-audit v3
                      R12/R13 fleet concurrency analysis + the lock-graph
                      diff vs the committed .lock_graph.json, the
                      graft-audit v4 R14/R15 grad-safety dataflow pass
                      over the differentiated geometry/ransac/train
                      scope, and the graft-audit v5 R16/R17/R18
                      fault-flow pass + taxonomy diff vs the committed
                      .fault_taxonomy.json) + layer 2 (jaxpr audit +
                      resource-ledger
                      diff vs the committed .jaxpr_ledger.json, incl. the
                      J5 backward-jaxpr grad-hazard census); full-tree
                      runs also sweep for stale inline suppressions and
                      stale R11 waivers
- ``--changed``     : layer 1 over git-modified/untracked files only; the
                      jaxpr audit AND the ledger run only when a traced
                      package file changed, the lock-graph and
                      fault-flow passes only when a
                      serve/registry/obs/fleet/lint file changed, and
                      the grad-safety pass only when a
                      geometry/ransac/train/lint file changed (fast
                      pre-commit mode)
- ``PATHS…``        : layer 1 over the given files/dirs; layer 2 only when
                      they include package (esac_tpu/) files
- ``--no-jaxpr``    : skip layer 2 (audit + ledger) anywhere
- ``--format json`` : machine-readable output — one JSON object per
                      finding per line on stdout (stable ``id`` field);
                      notes and the summary go to stderr
- ``--write-baseline``: regenerate lint_baseline.json from current
                      layer-1 findings (review the diff before committing!)
- ``--write-ledger``: regenerate .jaxpr_ledger.json from the current
                      registry traces (review the diff before committing!)
- ``--write-lock-graph``: regenerate .lock_graph.json from the current
                      fleet lock analysis (review the edges before
                      committing!)
- ``--write-fault-taxonomy``: regenerate .fault_taxonomy.json from the
                      current fleet fault-flow analysis (review the
                      error catalog + raise->outcome edges before
                      committing!)

The jaxpr audit itself forces the CPU backend before any device use — the
lint must never become the second stuck TPU client it lints against
(CLAUDE.md environment hazards).
"""

from __future__ import annotations

import argparse
import pathlib
import subprocess
import sys

from esac_tpu.lint import run_layer1
from esac_tpu.lint import faultflow
from esac_tpu.lint import lockgraph
from esac_tpu.lint.findings import RULES, Finding
from esac_tpu.lint.suppress import (
    Baseline,
    declared_suppressions,
    record_usage,
    stale_suppressions,
)

BASELINE_NAME = "lint_baseline.json"


def find_repo_root(start: pathlib.Path | None = None) -> pathlib.Path:
    p = (start or pathlib.Path.cwd()).resolve()
    for cand in (p, *p.parents):
        if (cand / "pyproject.toml").exists() or (cand / ".git").exists():
            return cand
    return p


def _changed_files(root: pathlib.Path) -> list[str]:
    """Tracked-modified + staged + untracked paths, repo-relative."""
    out: set[str] = set()
    for args in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        res = subprocess.run(
            args, cwd=root, capture_output=True, text=True, check=False
        )
        if res.returncode == 0:
            out.update(line for line in res.stdout.splitlines() if line)
    return sorted(out)


def _expand_paths(root: pathlib.Path, paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        full = (root / p) if not pathlib.Path(p).is_absolute() else pathlib.Path(p)
        if full.is_dir():
            files.extend(
                f.relative_to(root).as_posix()
                for f in sorted(full.rglob("*"))
                if f.suffix in (".py", ".sh")
            )
        else:
            files.append(full.resolve().relative_to(root.resolve()).as_posix())
    return files


def _audit_needed(files: list[str] | None) -> bool:
    # Any package file can shift what the registry entries trace — not least
    # esac_tpu/utils/{precision,num}.py, whose invariants ARE the audit.
    # The resource ledger rides the same condition (--changed skips it
    # unless a traced package file changed).
    if files is None:
        return True
    return any(
        f.startswith("esac_tpu/") and f.endswith(".py") for f in files
    )


def _note(msg: str) -> None:
    print(msg, file=sys.stderr)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m esac_tpu.lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: full tree)")
    parser.add_argument("--changed", action="store_true",
                        help="lint only git-modified/untracked files")
    parser.add_argument("--no-jaxpr", action="store_true",
                        help="skip the layer-2 jaxpr audit + ledger")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="finding output format (json: one object per "
                             "line, stable ids, notes on stderr)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detect)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline path (default: <root>/{BASELINE_NAME})")
    parser.add_argument("--write-baseline", action="store_true",
                        help="regenerate the baseline from current findings")
    parser.add_argument("--write-ledger", action="store_true",
                        help="regenerate .jaxpr_ledger.json from the "
                             "current registry traces")
    parser.add_argument("--write-lock-graph", action="store_true",
                        help="regenerate .lock_graph.json from the "
                             "current fleet lock analysis")
    parser.add_argument("--write-fault-taxonomy", action="store_true",
                        help="regenerate .fault_taxonomy.json from the "
                             "current fleet fault-flow analysis")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, (summary, rationale) in RULES.items():
            print(f"{rule}: {summary}\n    ({rationale})")
        return 0

    root = pathlib.Path(args.root).resolve() if args.root else find_repo_root()
    baseline_path = (
        pathlib.Path(args.baseline) if args.baseline else root / BASELINE_NAME
    )

    seen_ids: dict[str, int] = {}

    def emit(f: Finding) -> None:
        if args.format == "json":
            ordinal = seen_ids.get(f.id, 0)
            seen_ids[f.id] = ordinal + 1
            print(f.to_json(ordinal))
        else:
            print(f.format())

    if args.write_lock_graph:
        try:
            graph = lockgraph.build_graph(root)
            lockgraph.write_graph(root / lockgraph.LOCK_GRAPH_NAME, graph)
        except Exception as e:
            _note(f"graft-lint: internal error writing lock graph: {e!r}")
            return 2
        _note(
            f"graft-lint: wrote {len(graph['nodes'])} lock node(s) / "
            f"{len(graph['edges'])} edge(s) to "
            f"{root / lockgraph.LOCK_GRAPH_NAME} — review the diff before "
            "committing"
        )
        return 0

    if args.write_fault_taxonomy:
        try:
            taxonomy = faultflow.build_taxonomy(root)
            faultflow.write_taxonomy(
                root / faultflow.FAULT_TAXONOMY_NAME, taxonomy
            )
        except Exception as e:
            _note(f"graft-lint: internal error writing fault taxonomy: {e!r}")
            return 2
        _note(
            f"graft-lint: wrote {len(taxonomy['errors'])} error class(es) / "
            f"{len(taxonomy['edges'])} raise->outcome edge(s) to "
            f"{root / faultflow.FAULT_TAXONOMY_NAME} — review the catalog "
            "before committing"
        )
        return 0

    if args.write_ledger:
        if args.no_jaxpr:
            _note("graft-lint: --write-ledger needs the jaxpr layer "
                  "(drop --no-jaxpr)")
            return 2
        try:
            from esac_tpu.lint import ledger as ledger_mod
            from esac_tpu.lint.jaxpr_audit import trace_entries

            entries, skipped = ledger_mod.build_ledger(trace_entries())
            ledger_mod.write_ledger(root / ledger_mod.LEDGER_NAME, entries)
        except Exception as e:
            _note(f"graft-lint: internal error writing ledger: {e!r}")
            return 2
        _note(f"graft-lint: wrote {len(entries)} ledger entries to "
              f"{root / ledger_mod.LEDGER_NAME}"
              + (f" (skipped untraceable: {sorted(skipped)})" if skipped else ""))
        return 0

    # Everything up to the verdict is "internal": a crash anywhere here
    # (unreadable path, malformed baseline JSON) must exit 2, never be
    # mistaken for findings (exit 1).
    try:
        files: list[str] | None = None
        if args.changed:
            files = _changed_files(root)
            if not files:
                _note("graft-lint: no changed files")
                return 0
        elif args.paths:
            files = _expand_paths(root, args.paths)

        with record_usage() as used_suppressions:
            findings = run_layer1(root, files=files)

        if args.write_baseline:
            if files is not None:
                # A scoped run sees only a slice of the tree; writing it out
                # would silently drop every entry for the unscanned files.
                _note(
                    "graft-lint: --write-baseline requires a full-tree run "
                    "(drop --changed / PATHS)"
                )
                return 2
            Baseline.from_findings(findings).write(baseline_path)
            _note(
                f"graft-lint: wrote {len(findings)} entries to {baseline_path}"
            )
            return 0

        baseline = Baseline.load(baseline_path)
        findings, stale = baseline.apply(findings)
    except Exception as e:  # internal error, not a finding
        _note(f"graft-lint: internal error in layer 1: {e!r}")
        return 2
    # In scoped runs most baseline entries legitimately match nothing
    # (their files weren't linted) — only report staleness on full runs.
    # The same logic governs the suppression sweep: only a full run sees
    # every finding a directive could mask, so only a full run may call
    # one stale (graft-audit v3; tests/ and esac_tpu/lint/ are excluded —
    # fixture source strings and the lint's own docstrings contain
    # directive-SHAPED text that is documentation, not directives).
    if files is None:
        for e in stale:
            _note(
                f"graft-lint: stale baseline entry ({e.rule} {e.path}): "
                "expired or no longer matches — remove it from "
                f"{baseline_path.name}"
            )
        try:
            declared = {
                d for d in declared_suppressions(root)
                if not d[0].startswith(("tests/", "esac_tpu/lint/"))
            }
            for note in stale_suppressions(declared, used_suppressions):
                _note(f"graft-lint: {note}")
            from esac_tpu.lint.ast_rules import stale_r11_waivers

            for note in stale_r11_waivers(root):
                _note(f"graft-lint: {note}")
        except Exception as e:  # notes only — never block the verdict
            _note(f"graft-lint: suppression sweep failed: {e!r}")

    for f in findings:
        emit(f)

    # Lock-graph diff gate (graft-audit v3, ledger pattern): the R12/R13
    # analysis findings already rode run_layer1; here the CURRENT edge
    # set is held to the committed .lock_graph.json — an unreviewed new
    # edge fails, drift reports stale.  Only audited trees (those with a
    # lint registry) carry the artifact.
    lock_findings: list[Finding] = []
    lock_ran = False
    if lockgraph.lock_pass_needed(files) and \
            (root / "esac_tpu" / "lint" / "registry.py").exists():
        try:
            current_graph = lockgraph.build_graph(root)
            lock_ran = True
            committed_graph = lockgraph.load_graph(
                root / lockgraph.LOCK_GRAPH_NAME
            )
            if committed_graph is None:
                lock_findings = [Finding(
                    "R12", lockgraph.LOCK_GRAPH_NAME, 0,
                    "missing-lock-graph",
                    "no committed lock-order graph; run "
                    "`python -m esac_tpu.lint --write-lock-graph`, review "
                    "the edges, and commit the file",
                )]
            else:
                lock_findings, lock_stale = lockgraph.diff_graph(
                    committed_graph, current_graph
                )
                for note in lock_stale:
                    _note(f"graft-lint: {note}")
        except Exception as e:
            _note(f"graft-lint: internal error in lock-graph gate: {e!r}")
            return 2
        for f in lock_findings:
            emit(f)

    # Fault-taxonomy diff gate (graft-audit v5, same ledger pattern):
    # the R16/R17/R18 analysis findings already rode run_layer1; here
    # the CURRENT error catalog + raise->outcome edge set is held to
    # the committed .fault_taxonomy.json — an unreviewed new error
    # class or edge fails, site/provenance drift reports stale.
    fault_findings: list[Finding] = []
    fault_ran = False
    if faultflow.fault_pass_needed(files) and \
            (root / "esac_tpu" / "lint" / "registry.py").exists():
        try:
            current_tax = faultflow.build_taxonomy(root)
            fault_ran = True
            committed_tax = faultflow.load_taxonomy(
                root / faultflow.FAULT_TAXONOMY_NAME
            )
            if committed_tax is None:
                # An EMPTY current catalog has nothing to gate (tiny
                # audited trees in tests); any error or edge demands
                # the committed artifact.
                if current_tax["errors"] or current_tax["edges"]:
                    fault_findings = [Finding(
                        "R16", faultflow.FAULT_TAXONOMY_NAME, 0,
                        "missing-fault-taxonomy",
                        "no committed fault taxonomy; run "
                        "`python -m esac_tpu.lint "
                        "--write-fault-taxonomy`, review the error "
                        "catalog and raise->outcome edges, and commit "
                        "the file",
                    )]
            else:
                fault_findings, fault_stale = faultflow.diff_taxonomy(
                    committed_tax, current_tax
                )
                for note in fault_stale:
                    _note(f"graft-lint: {note}")
        except Exception as e:
            _note(f"graft-lint: internal error in fault-taxonomy gate: {e!r}")
            return 2
        for f in fault_findings:
            emit(f)

    audit_failures: list[Finding] = []
    ledger_findings: list[Finding] = []
    if not args.no_jaxpr and _audit_needed(files):
        try:
            from esac_tpu.lint import ledger as ledger_mod
            from esac_tpu.lint.jaxpr_audit import run_audit, trace_entries

            traced = trace_entries()
            audit_failures = run_audit(traced=traced)
            current, skipped = ledger_mod.build_ledger(traced)
            committed = ledger_mod.load_ledger(root / ledger_mod.LEDGER_NAME)
            if committed is None:
                ledger_findings = [Finding(
                    "J4", ledger_mod.LEDGER_NAME, 0, "missing-ledger",
                    "no committed jaxpr resource ledger; run "
                    "`python -m esac_tpu.lint --write-ledger`, review the "
                    "numbers, and commit the file",
                )]
            else:
                ledger_findings, ledger_stale = ledger_mod.diff_ledger(
                    committed, current, skipped
                )
                for note in ledger_stale:
                    _note(f"graft-lint: {note}")
        except Exception as e:
            _note(f"graft-lint: internal error in jaxpr audit: {e!r}")
            return 2
        for f in audit_failures + ledger_findings:
            emit(f)

    n = (len(findings) + len(lock_findings) + len(fault_findings)
         + len(audit_failures) + len(ledger_findings))
    scope = "changed files" if args.changed else ("paths" if args.paths else "tree")
    extras = []
    if lock_ran:
        extras.append("lock graph")
    if fault_ran:
        extras.append("fault taxonomy")
    if not args.no_jaxpr and _audit_needed(files):
        extras.append("jaxpr audit + ledger")
    summary = (f"graft-lint: {n} finding(s) over {scope}"
               + (f" (incl. {', '.join(extras)})" if extras else ""))
    if args.format == "json":
        _note(summary)
    else:
        print(summary)
    return 1 if n else 0
