"""Layer 2: audit the jaxprs of registered entry points on the CPU backend.

AST rules see what the source *says*; this sees what XLA will actually be
asked to *compile*.  Each registry entry is traced with ``jax.make_jaxpr``
(abstract evaluation only — nothing executes, nothing compiles) and the
resulting jaxpr is walked recursively (pjit / scan / cond / shard_map /
custom_vjp sub-jaxprs included) for three checks:

- **J1** — disallowed primitives: decompositions that lower to scalar
  loops on TPU (svd/lu/eig/tridiagonal/triangular_solve/linear_solve) and
  ``while`` (a data-dependent trip count; every loop in this codebase must
  be a fixed-length ``scan``).
- **J2** — non-static shapes: every dimension of every aval must be a
  concrete int (CLAUDE.md: static shapes everywhere under jit).
- **J3** — ``dot_general`` precision in ``pinned=True`` call graphs: the
  precision pair must be HIGHEST and the output dtype float32
  (utils.precision.hmm/heinsum discipline; bf16-default MXU corrupts
  rotation math).

The audit forces the CPU backend (and an 8-device virtual mesh for the
sharded entry) BEFORE first device use — per CLAUDE.md, an ad-hoc process
touching ``jax.devices()`` while the relay is unhealthy becomes a second
permanently-stuck client.  The "pallas" scoring impl is deliberately not
registered: off-TPU it traces through interpret mode whose jaxpr is not
the shipped kernel; its parity is covered by tests/test_pallas_scoring.py.
"""

from __future__ import annotations

from esac_tpu.lint.findings import Finding

# Primitives that lower to scalar loops on TPU, or have data-dependent trip
# counts.  Names are jaxpr primitive names.
DISALLOWED_PRIMITIVES = {
    "svd", "lu", "eig", "eigh", "schur", "tridiagonal", "tridiagonal_solve",
    "triangular_solve", "custom_linear_solve", "linear_solve", "while",
}


def _force_cpu() -> None:
    import jax

    # Env-var JAX_PLATFORMS is overridden by the container sitecustomize;
    # the config update after import is the one that sticks (CLAUDE.md).
    jax.config.update("jax_platforms", "cpu")
    # 8 virtual devices so the sharded registry entry can trace.
    from esac_tpu.parallel.mesh import ensure_virtual_devices

    ensure_virtual_devices(8)


def _is_jaxpr(obj) -> bool:
    return hasattr(obj, "eqns") and hasattr(obj, "invars")


def _sub_jaxprs(params: dict):
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            if _is_jaxpr(item):
                yield item
            elif hasattr(item, "jaxpr") and _is_jaxpr(item.jaxpr):
                yield item.jaxpr


def iter_eqns(jaxpr):
    """Depth-first over all equations, sub-jaxprs included."""
    stack = [jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr]
    seen: set[int] = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            yield eqn
            stack.extend(_sub_jaxprs(eqn.params))


def _precision_is_highest(precision) -> bool:
    import jax

    hi = jax.lax.Precision.HIGHEST
    if precision == hi:
        return True
    return (
        isinstance(precision, (tuple, list))
        and len(precision) == 2
        and all(p == hi for p in precision)
    )


def audit_jaxpr(name: str, closed_jaxpr, pinned: bool) -> list[Finding]:
    """All J-findings for one entry's jaxpr.  ``name`` doubles as the
    finding path so reports read ``<entry>:0: J1 …``."""
    import numpy as np

    findings = []
    seen_texts: set[tuple[str, str]] = set()

    def add(rule: str, text: str, message: str) -> None:
        # One report per (rule, identity): a primitive repeated through a
        # scan body would otherwise flood the output.
        if (rule, text) in seen_texts:
            return
        seen_texts.add((rule, text))
        findings.append(Finding(rule, name, 0, text, message))

    for eqn in iter_eqns(closed_jaxpr):
        prim = eqn.primitive.name
        if prim in DISALLOWED_PRIMITIVES:
            add("J1", prim,
                f"disallowed primitive '{prim}' in traced entry point "
                "(scalar-loop lowering or data-dependent trip count on TPU)")
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            shape = getattr(aval, "shape", ())
            for d in shape:
                if not isinstance(d, (int, np.integer)):
                    add("J2", f"{prim}:{shape}",
                        f"non-static dimension {d!r} in '{prim}' "
                        "(static shapes required under jit)")
        if pinned and prim == "dot_general":
            precision = eqn.params.get("precision")
            out_dtype = eqn.outvars[0].aval.dtype
            if not _precision_is_highest(precision):
                add("J3", f"dot_general:precision={precision}",
                    f"dot_general with precision={precision} in pinned "
                    "call graph; route through utils.precision.hmm/heinsum "
                    "(Precision.HIGHEST)")
            elif str(out_dtype) != "float32":
                add("J3", f"dot_general:dtype={out_dtype}",
                    f"dot_general output dtype {out_dtype} in pinned call "
                    "graph; rotation algebra must stay f32")
    return findings


def trace_entries(entries=None) -> list:
    """Trace every registry entry ONCE: ``[(Entry, ClosedJaxpr | None)]``.

    The shared tracing pass behind the J1-J3 audit, the resource ledger
    (:mod:`esac_tpu.lint.ledger`) AND the graft-audit v4 grad-hazard
    census: tracing dominates layer-2 cost (~20s full registry), so
    callers needing several must not trace twice.  The census's VJP leg
    rides this same pass by construction — every ``Entry.grad=True``
    builder traces a ``jax.make_jaxpr(jax.grad(...))`` program, so its
    ClosedJaxpr IS forward + backward in one jaxpr (there is no separate
    backward trace to take), and ``ledger.grad_hazard_census`` walks the
    domain-edge primitives the autodiff transform emitted into it.
    ``None`` marks an entry not traceable in this process (e.g. no
    8-device mesh) — consumers skip it rather than failing.
    """
    _force_cpu()
    from esac_tpu.lint.registry import ENTRIES

    return [
        (entry, entry.build())
        for entry in (entries if entries is not None else ENTRIES)
    ]


def run_audit(entries=None, traced=None) -> list[Finding]:
    """All J1-J3 findings over the registry (or a pre-traced list)."""
    if traced is None:
        traced = trace_entries(entries)
    findings: list[Finding] = []
    for entry, closed in traced:
        if closed is None:
            continue  # entry not traceable in this process (e.g. no mesh)
        findings += audit_jaxpr(entry.name, closed, entry.pinned)
    return findings
