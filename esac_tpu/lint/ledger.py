"""Layer 2b: the jaxpr resource ledger (graft-audit v2).

The J1-J3 audit answers "is this jaxpr *allowed*?"; the ledger answers
"what does this jaxpr *cost*?" — and pins the answer.  For every registered
entry point it walks the traced jaxpr (the same shared tracing pass as the
audit, :func:`esac_tpu.lint.jaxpr_audit.trace_entries`) and emits:

- **flops** — an analytic estimate with scan trip counts multiplied in:
  ``2*out*contract`` for ``dot_general``, ``2*out*kernel/out_features``
  for convolutions, one flop per output element for everything else.
- **peak_intermediate_bytes** — a linear-scan liveness analysis over
  eqn-produced values (inputs and consts excluded), recursing into
  pjit/scan/cond/shard_map sub-jaxprs.  This is the materialization the
  *jaxpr implies* — an upper bound XLA fusion then improves on — which is
  exactly the number DESIGN.md §9's fusion argument needs: the scoring
  path's per-hypothesis errmap shows up here as a committed byte count
  instead of an ~80%-of-pipeline prose claim.
- **dot census** — ``dot_general`` counts keyed by ``precision:out_dtype``
  so a dropped HIGHEST pin is a *diff*, not a hope (J3 only covers
  ``pinned=True`` entries; the census also guards the HIGHEST geometry
  core inside unpinned CNN-bearing programs).
- **top_intermediates** — the largest eqn-produced tensors with their
  primitives, so "what materializes" is readable per entry.

All numbers are computed at the registry's fixed tiny trace shapes, so the
committed ``.jaxpr_ledger.json`` is deterministic on this container: the
tier-1 gate asserts the recomputed ledger matches it exactly, and
:func:`diff_ledger` turns *regressions* (bytes/flops growth beyond
tolerance, a HIGHEST pin dropped, an unregistered new entry) into J4
findings (exit 1) while mere drift is reported stale (regenerate with
``python -m esac_tpu.lint --write-ledger`` and review the diff, exactly
like the findings baseline).

Everything imports jax lazily; the tracing pass forces the CPU backend
first (CLAUDE.md environment hazards).
"""

from __future__ import annotations

import json
import math
import pathlib

from esac_tpu.lint.findings import Finding

LEDGER_NAME = ".jaxpr_ledger.json"

# Growth beyond these factors is a J4 regression; anything smaller is
# reported as a stale (regenerate-and-review) entry.  "Silently doubling an
# entry's materialization" must fail with margin.
BYTES_TOL = 1.25
FLOPS_TOL = 1.25

_TOP_N = 5

# Entries audited for the per-hypothesis reprojection-error map that the
# selection argmax immediately consumes (the DESIGN.md §9 fusion target).
# Dims are the registry builders' trace shapes; the ledger records the
# implied errmap bytes and whether a tensor of exactly that footprint is
# present in the trace.  Since ISSUE 8 every INFERENCE entry streams
# scoring+selection through (score_chunk, n_cells) tiles, so
# ``present_in_trace`` must read false there — the committed record IS the
# "errmap materialization gone" evidence; the materializing training path
# (scoring_errmap_grad) keeps it true.
_ERRMAP_DIMS = {
    "dsac_infer": {"n_hyps": 8, "n_cells": 128},
    "dsac_infer_fused_select": {"n_hyps": 8, "n_cells": 128},
    "dsac_infer_frames": {"B": 2, "n_hyps": 8, "n_cells": 128},
    "esac_infer_frames": {"B": 2, "M": 2, "n_hyps": 8, "n_cells": 128},
    "esac_infer_topk_frames": {"B": 2, "k": 2, "n_hyps": 8, "n_cells": 128},
    # Routed trace: K=2 of M=4 experts, budget reallocated to
    # n_hyps * M // K = 16 per evaluated slot.
    "esac_infer_routed_frames": {"B": 2, "K": 2, "n_hyps": 16,
                                 "n_cells": 128},
    "scoring_errmap_grad": {"n_hyps": 4, "n_cells": 16},
}


# --------------------------------------------------------------------------
# jaxpr walking

def _is_jaxpr(obj) -> bool:
    return hasattr(obj, "eqns") and hasattr(obj, "invars")


def _as_jaxpr(obj):
    """ClosedJaxpr | Jaxpr -> Jaxpr (or None)."""
    if _is_jaxpr(obj):
        return obj
    inner = getattr(obj, "jaxpr", None)
    return inner if _is_jaxpr(inner) else None


def _sub_jaxprs(eqn):
    """-> [(sub_jaxpr, trip_multiplier)] for one equation."""
    name = eqn.primitive.name
    params = eqn.params
    if name == "scan":
        j = _as_jaxpr(params.get("jaxpr"))
        return [(j, int(params.get("length", 1)))] if j is not None else []
    if name == "cond":
        # One branch executes; cost is the max, so return branches with a
        # marker multiplier handled by the caller.
        return [(_as_jaxpr(b), -1) for b in params.get("branches", ())
                if _as_jaxpr(b) is not None]
    out = []
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            j = _as_jaxpr(item)
            if j is not None:
                out.append((j, 1))
    return out


def _nelems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if not shape:
        return 1
    return int(math.prod(int(d) for d in shape))


def _nbytes(aval) -> int:
    dtype = getattr(aval, "dtype", None)
    itemsize = getattr(dtype, "itemsize", 0)
    return _nelems(aval) * int(itemsize)


def _eqn_self_flops(eqn) -> int:
    """Flops of one equation, sub-jaxprs excluded."""
    name = eqn.primitive.name
    out_elems = sum(_nelems(v.aval) for v in eqn.outvars)
    if name == "dot_general":
        (lc, _), _ = eqn.params["dimension_numbers"]
        lhs_shape = eqn.invars[0].aval.shape
        contract = 1
        for d in lc:
            contract *= int(lhs_shape[d])
        return 2 * _nelems(eqn.outvars[0].aval) * contract
    if name == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        rhs = eqn.invars[1].aval.shape
        out_feat = int(rhs[dn.rhs_spec[0]])
        per_out = max(1, _nelems(eqn.invars[1].aval) // max(1, out_feat))
        return 2 * _nelems(eqn.outvars[0].aval) * per_out
    return out_elems  # elementwise proxy: one flop per output element


def _precision_label(precision) -> str:
    from esac_tpu.lint.jaxpr_audit import _precision_is_highest

    if _precision_is_highest(precision):
        return "HIGHEST"
    if precision is None:
        return "DEFAULT"
    return str(precision)


def _walk(jaxpr, census: dict, tops: list, mult: int = 1) -> tuple[int, int]:
    """-> (flops, peak_intermediate_bytes) of one Jaxpr, recursive.

    ``census``/``tops`` accumulate across the whole walk (census counts are
    *static* — one per compiled eqn, not per scan trip; flops multiply the
    trip count in).  Peak bytes is a liveness scan over eqn-produced values
    only — jaxpr invars and consts are the caller's storage, not this
    program's intermediates.
    """
    eqns = list(jaxpr.eqns)

    # Last-use position of every eqn-produced var (jaxpr outvars live to
    # the end).
    import jax.core as jc

    def _is_var(v) -> bool:
        return isinstance(v, jc.Var) and not isinstance(v, jc.DropVar)

    produced: set = set()
    for eqn in eqns:
        produced.update(v for v in eqn.outvars if _is_var(v))
    last_use: dict = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if _is_var(v) and v in produced:
                last_use[v] = i
    for v in jaxpr.outvars:
        if _is_var(v) and v in produced:
            last_use[v] = len(eqns)

    flops = 0
    live_bytes = 0
    peak = 0
    alive: set = set()
    for i, eqn in enumerate(eqns):
        prim = eqn.primitive.name
        if prim == "dot_general":
            key = (f"{_precision_label(eqn.params.get('precision'))}:"
                   f"{eqn.outvars[0].aval.dtype}")
            census[key] = census.get(key, 0) + 1

        subs = _sub_jaxprs(eqn)
        if prim == "cond":
            branch_stats = [_walk(j, census, tops, mult) for j, _ in subs]
            sub_flops = max((f for f, _ in branch_stats), default=0)
            sub_peak = max((p for _, p in branch_stats), default=0)
        else:
            sub_flops = 0
            sub_peak = 0
            for j, trip in subs:
                f, p = _walk(j, census, tops, mult * trip)
                sub_flops += f
                sub_peak = max(sub_peak, p)
        flops += mult * _eqn_self_flops(eqn) + sub_flops

        out_bytes = 0
        for v in eqn.outvars:
            if not _is_var(v):
                continue
            b = _nbytes(v.aval)
            out_bytes += b
            shape = tuple(int(d) for d in getattr(v.aval, "shape", ()))
            tops.append((b, prim, shape, str(getattr(v.aval, "dtype", "?"))))
        peak = max(peak, live_bytes + out_bytes + sub_peak)
        for v in eqn.outvars:
            if _is_var(v) and last_use.get(v, -1) > i:
                alive.add(v)
                live_bytes += _nbytes(v.aval)
        retired = set()
        for v in eqn.invars:
            if not _is_var(v) or id(v) in retired:
                continue
            retired.add(id(v))
            if v in alive and last_use.get(v) == i:
                alive.discard(v)
                live_bytes -= _nbytes(v.aval)
    return flops, peak


def entry_stats(closed_jaxpr) -> dict:
    """Resource stats for one traced entry point."""
    jaxpr = _as_jaxpr(closed_jaxpr)
    census: dict = {}
    tops: list = []
    flops, peak = _walk(jaxpr, census, tops)
    tops.sort(key=lambda t: (-t[0], t[1], t[2], t[3]))
    seen = set()
    top_intermediates = []
    for b, prim, shape, dtype in tops:
        key = (prim, shape, dtype)
        if key in seen:
            continue
        seen.add(key)
        top_intermediates.append(
            {"primitive": prim, "shape": list(shape), "dtype": dtype,
             "bytes": b}
        )
        if len(top_intermediates) >= _TOP_N:
            break
    return {
        "flops": int(flops),
        "peak_intermediate_bytes": int(peak),
        "dot_general_count": sum(census.values()),
        "dot_census": dict(sorted(census.items())),
        "top_intermediates": top_intermediates,
        "_all_tensors": tops,  # stripped before serialization
    }


def _errmap_record(name: str, stats: dict) -> dict | None:
    dims = _ERRMAP_DIMS.get(name)
    if dims is None:
        return None
    elems = math.prod(dims.values())
    nbytes = 4 * elems  # f32 reprojection errors
    # An errmap is a tensor whose TRAILING axes are (n_hyps, n_cells) at
    # the full trace element count — matching on byte count alone
    # false-positives on unrelated same-size tensors (e.g. projection
    # tiles), which is exactly the record this field must not corrupt.
    nh, nc = dims["n_hyps"], dims["n_cells"]
    present = any(
        dtype == "float32" and b == nbytes
        and len(shape) >= 2 and tuple(shape[-2:]) == (nh, nc)
        for b, _, shape, dtype in stats["_all_tensors"]
    )
    return {
        "bytes_at_trace_shapes": nbytes,
        "present_in_trace": present,
        "formula": "prod(trace_dims) * 4 bytes (f32 error per "
                   "(hypothesis, cell)); scales linearly to serve shapes",
        "trace_dims": dims,
    }


# --------------------------------------------------------------------------
# ledger build / io / diff

def build_ledger(traced) -> tuple[dict, set]:
    """``trace_entries()`` output -> (name -> stats dict, skipped names)."""
    entries: dict = {}
    skipped: set = set()
    for entry, closed in traced:
        if closed is None:
            skipped.add(entry.name)
            continue
        stats = entry_stats(closed)
        errmap = _errmap_record(entry.name, stats)
        del stats["_all_tensors"]
        stats = {"pinned": entry.pinned, **stats}
        if errmap is not None:
            stats["errmap"] = errmap
        entries[entry.name] = stats
    return entries, skipped


def write_ledger(path: pathlib.Path, entries: dict) -> None:
    data = {
        "comment": "graft-audit v2 jaxpr resource ledger; see LINT.md. "
                   "Per registered entry point at fixed tiny trace shapes: "
                   "analytic flops, peak intermediate bytes (liveness over "
                   "the jaxpr — the pre-fusion materialization bound), and "
                   "the dot_general precision census.  Regenerate with "
                   "`python -m esac_tpu.lint --write-ledger` and review "
                   "the diff; regressions beyond tolerance fail tier-1.",
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


def load_ledger(path: pathlib.Path) -> dict | None:
    if not path.exists():
        return None
    return json.loads(path.read_text()).get("entries", {})


def _census_counts(stats: dict) -> tuple[int, int]:
    highest = 0
    other = 0
    for key, n in stats.get("dot_census", {}).items():
        if key.startswith("HIGHEST:"):
            highest += n
        else:
            other += n
    return highest, other


def diff_ledger(
    committed: dict, current: dict, skipped: set = frozenset()
) -> tuple[list[Finding], list[str]]:
    """-> (J4 regression findings, stale-entry notes).

    Regressions fail the lint: an entry missing from the committed ledger,
    peak bytes / flops growth beyond tolerance, or a precision-census
    regression (HIGHEST dots lost while non-HIGHEST appear).  Everything
    else that mismatches — improvements, drift inside tolerance, entries no
    longer in the registry — is stale: the committed file must be
    regenerated (and the diff reviewed), but the tree is not worse.
    """
    findings: list[Finding] = []
    stale: list[str] = []

    def add(name: str, text: str, message: str) -> None:
        findings.append(Finding("J4", name, 0, text, message))

    for name, cur in current.items():
        old = committed.get(name)
        if old is None:
            add(name, "missing-entry",
                "entry has no committed ledger record; run "
                "`python -m esac_tpu.lint --write-ledger`, review the "
                "numbers, and commit the diff")
            continue
        drift = False
        for field, tol in (("peak_intermediate_bytes", BYTES_TOL),
                           ("flops", FLOPS_TOL)):
            was, now = old.get(field, 0), cur.get(field, 0)
            if now > was * tol:
                add(name, f"{field}:{was}->{now}",
                    f"{field} grew {was} -> {now} "
                    f"(> {tol}x committed): this entry now materializes/"
                    "computes more than the committed budget — if "
                    "intentional, regenerate the ledger and review")
            elif now != was:
                drift = True
        old_hi, old_other = _census_counts(old)
        new_hi, new_other = _census_counts(cur)
        if new_hi < old_hi and new_other > old_other:
            add(name,
                f"census:HIGHEST {old_hi}->{new_hi}, "
                f"other {old_other}->{new_other}",
                "precision census regression: HIGHEST dot_generals were "
                "lost while unpinned ones appeared — a HIGHEST pin was "
                "dropped (route contractions through "
                "utils.precision.hmm/heinsum)")
        elif (new_hi, new_other) != (old_hi, old_other):
            drift = True
        if cur.get("dot_census") != old.get("dot_census"):
            drift = True
        if drift:
            stale.append(
                f"ledger entry '{name}' drifted from the committed record "
                "(within tolerance) — regenerate with --write-ledger and "
                "review the diff"
            )
    for name in committed:
        if name not in current and name not in skipped:
            stale.append(
                f"ledger entry '{name}' no longer matches any registry "
                "entry — regenerate with --write-ledger"
            )
    return findings, stale
