"""Layer 2b: the jaxpr resource ledger (graft-audit v2).

The J1-J3 audit answers "is this jaxpr *allowed*?"; the ledger answers
"what does this jaxpr *cost*?" — and pins the answer.  For every registered
entry point it walks the traced jaxpr (the same shared tracing pass as the
audit, :func:`esac_tpu.lint.jaxpr_audit.trace_entries`) and emits:

- **flops** — an analytic estimate with scan trip counts multiplied in:
  ``2*out*contract`` for ``dot_general``, ``2*out*kernel/out_features``
  for convolutions, one flop per output element for everything else.
- **peak_intermediate_bytes** — a linear-scan liveness analysis over
  eqn-produced values (inputs and consts excluded), recursing into
  pjit/scan/cond/shard_map sub-jaxprs.  This is the materialization the
  *jaxpr implies* — an upper bound XLA fusion then improves on — which is
  exactly the number DESIGN.md §9's fusion argument needs: the scoring
  path's per-hypothesis errmap shows up here as a committed byte count
  instead of an ~80%-of-pipeline prose claim.
- **dot census** — ``dot_general`` counts keyed by ``precision:out_dtype``
  so a dropped HIGHEST pin is a *diff*, not a hope (J3 only covers
  ``pinned=True`` entries; the census also guards the HIGHEST geometry
  core inside unpinned CNN-bearing programs).
- **top_intermediates** — the largest eqn-produced tensors with their
  primitives, so "what materializes" is readable per entry.

All numbers are computed at the registry's fixed tiny trace shapes, so the
committed ``.jaxpr_ledger.json`` is deterministic on this container: the
tier-1 gate asserts the recomputed ledger matches it exactly, and
:func:`diff_ledger` turns *regressions* (bytes/flops growth beyond
tolerance, a HIGHEST pin dropped, an unregistered new entry) into J4
findings (exit 1) while mere drift is reported stale (regenerate with
``python -m esac_tpu.lint --write-ledger`` and review the diff, exactly
like the findings baseline).

graft-audit v4 adds the **backward-jaxpr grad-hazard census** (rule
**J5**): for every grad-registered entry (``Entry.grad=True`` — its build
traces a ``jax.grad`` program, so the traced jaxpr IS forward + VJP), the
walk additionally counts the domain-edge primitives the grad-safety
convention polices — ``div``, ``rsqrt``, ``pow``, ``log``, ``acos``,
``asin``, ``atan2`` — keyed by whether an eps-add / constant floor /
clamp / select dominates the vulnerable operand (the producer chain is
followed through broadcasts, reshapes, sqrt, mul and across
pjit/scan/cond/custom-vjp boundaries).  The counts are committed per
entry under ``grad_hazards``; :func:`diff_ledger` turns a NEW unguarded
site into a J5 finding (exit 1) while improvements and guarded-count
drift report stale — the J4 workflow verbatim.  This is the jaxpr-level
sibling of the R14/R15 AST pass: the AST sees what the source says, the
census sees every division the *autodiff transform itself* emits.

Everything imports jax lazily; the tracing pass forces the CPU backend
first (CLAUDE.md environment hazards).
"""

from __future__ import annotations

import json
import math
import pathlib

from esac_tpu.lint.findings import Finding

LEDGER_NAME = ".jaxpr_ledger.json"

# Growth beyond these factors is a J4 regression; anything smaller is
# reported as a stale (regenerate-and-review) entry.  "Silently doubling an
# entry's materialization" must fail with margin.
BYTES_TOL = 1.25
FLOPS_TOL = 1.25

_TOP_N = 5

# Entries audited for the per-hypothesis reprojection-error map that the
# selection argmax immediately consumes (the DESIGN.md §9 fusion target).
# Dims are the registry builders' trace shapes; the ledger records the
# implied errmap bytes and whether a tensor of exactly that footprint is
# present in the trace.  Since ISSUE 8 every INFERENCE entry streams
# scoring+selection through (score_chunk, n_cells) tiles, so
# ``present_in_trace`` must read false there — the committed record IS the
# "errmap materialization gone" evidence; the materializing training path
# (scoring_errmap_grad) keeps it true.
_ERRMAP_DIMS = {
    "dsac_infer": {"n_hyps": 8, "n_cells": 128},
    "dsac_infer_fused_select": {"n_hyps": 8, "n_cells": 128},
    "dsac_infer_frames": {"B": 2, "n_hyps": 8, "n_cells": 128},
    "esac_infer_frames": {"B": 2, "M": 2, "n_hyps": 8, "n_cells": 128},
    "esac_infer_topk_frames": {"B": 2, "k": 2, "n_hyps": 8, "n_cells": 128},
    # Routed trace: K=2 of M=4 experts, budget reallocated to
    # n_hyps * M // K = 16 per evaluated slot.
    "esac_infer_routed_frames": {"B": 2, "K": 2, "n_hyps": 16,
                                 "n_cells": 128},
    "scoring_errmap_grad": {"n_hyps": 4, "n_cells": 16},
}


# --------------------------------------------------------------------------
# jaxpr walking

def _is_jaxpr(obj) -> bool:
    return hasattr(obj, "eqns") and hasattr(obj, "invars")


def _as_jaxpr(obj):
    """ClosedJaxpr | Jaxpr -> Jaxpr (or None)."""
    if _is_jaxpr(obj):
        return obj
    inner = getattr(obj, "jaxpr", None)
    return inner if _is_jaxpr(inner) else None


def _sub_jaxprs(eqn):
    """-> [(sub_jaxpr, trip_multiplier)] for one equation."""
    name = eqn.primitive.name
    params = eqn.params
    if name == "scan":
        j = _as_jaxpr(params.get("jaxpr"))
        return [(j, int(params.get("length", 1)))] if j is not None else []
    if name == "cond":
        # One branch executes; cost is the max, so return branches with a
        # marker multiplier handled by the caller.
        return [(_as_jaxpr(b), -1) for b in params.get("branches", ())
                if _as_jaxpr(b) is not None]
    out = []
    for v in params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for item in vals:
            j = _as_jaxpr(item)
            if j is not None:
                out.append((j, 1))
    return out


def _nelems(aval) -> int:
    shape = getattr(aval, "shape", None)
    if not shape:
        return 1
    return int(math.prod(int(d) for d in shape))


def _nbytes(aval) -> int:
    dtype = getattr(aval, "dtype", None)
    itemsize = getattr(dtype, "itemsize", 0)
    return _nelems(aval) * int(itemsize)


def _eqn_self_flops(eqn) -> int:
    """Flops of one equation, sub-jaxprs excluded."""
    name = eqn.primitive.name
    out_elems = sum(_nelems(v.aval) for v in eqn.outvars)
    if name == "dot_general":
        (lc, _), _ = eqn.params["dimension_numbers"]
        lhs_shape = eqn.invars[0].aval.shape
        contract = 1
        for d in lc:
            contract *= int(lhs_shape[d])
        return 2 * _nelems(eqn.outvars[0].aval) * contract
    if name == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        rhs = eqn.invars[1].aval.shape
        out_feat = int(rhs[dn.rhs_spec[0]])
        per_out = max(1, _nelems(eqn.invars[1].aval) // max(1, out_feat))
        return 2 * _nelems(eqn.outvars[0].aval) * per_out
    return out_elems  # elementwise proxy: one flop per output element


def _precision_label(precision) -> str:
    from esac_tpu.lint.jaxpr_audit import _precision_is_highest

    if _precision_is_highest(precision):
        return "HIGHEST"
    if precision is None:
        return "DEFAULT"
    return str(precision)


def _walk(jaxpr, census: dict, tops: list, mult: int = 1) -> tuple[int, int]:
    """-> (flops, peak_intermediate_bytes) of one Jaxpr, recursive.

    ``census``/``tops`` accumulate across the whole walk (census counts are
    *static* — one per compiled eqn, not per scan trip; flops multiply the
    trip count in).  Peak bytes is a liveness scan over eqn-produced values
    only — jaxpr invars and consts are the caller's storage, not this
    program's intermediates.
    """
    eqns = list(jaxpr.eqns)

    # Last-use position of every eqn-produced var (jaxpr outvars live to
    # the end).
    import jax.core as jc

    def _is_var(v) -> bool:
        return isinstance(v, jc.Var) and not isinstance(v, jc.DropVar)

    produced: set = set()
    for eqn in eqns:
        produced.update(v for v in eqn.outvars if _is_var(v))
    last_use: dict = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if _is_var(v) and v in produced:
                last_use[v] = i
    for v in jaxpr.outvars:
        if _is_var(v) and v in produced:
            last_use[v] = len(eqns)

    flops = 0
    live_bytes = 0
    peak = 0
    alive: set = set()
    for i, eqn in enumerate(eqns):
        prim = eqn.primitive.name
        if prim == "dot_general":
            key = (f"{_precision_label(eqn.params.get('precision'))}:"
                   f"{eqn.outvars[0].aval.dtype}")
            census[key] = census.get(key, 0) + 1

        subs = _sub_jaxprs(eqn)
        if prim == "cond":
            branch_stats = [_walk(j, census, tops, mult) for j, _ in subs]
            sub_flops = max((f for f, _ in branch_stats), default=0)
            sub_peak = max((p for _, p in branch_stats), default=0)
        else:
            sub_flops = 0
            sub_peak = 0
            for j, trip in subs:
                f, p = _walk(j, census, tops, mult * trip)
                sub_flops += f
                sub_peak = max(sub_peak, p)
        flops += mult * _eqn_self_flops(eqn) + sub_flops

        out_bytes = 0
        for v in eqn.outvars:
            if not _is_var(v):
                continue
            b = _nbytes(v.aval)
            out_bytes += b
            shape = tuple(int(d) for d in getattr(v.aval, "shape", ()))
            tops.append((b, prim, shape, str(getattr(v.aval, "dtype", "?"))))
        peak = max(peak, live_bytes + out_bytes + sub_peak)
        for v in eqn.outvars:
            if _is_var(v) and last_use.get(v, -1) > i:
                alive.add(v)
                live_bytes += _nbytes(v.aval)
        retired = set()
        for v in eqn.invars:
            if not _is_var(v) or id(v) in retired:
                continue
            retired.add(id(v))
            if v in alive and last_use.get(v) == i:
                alive.discard(v)
                live_bytes -= _nbytes(v.aval)
    return flops, peak


def entry_stats(closed_jaxpr) -> dict:
    """Resource stats for one traced entry point."""
    jaxpr = _as_jaxpr(closed_jaxpr)
    census: dict = {}
    tops: list = []
    flops, peak = _walk(jaxpr, census, tops)
    tops.sort(key=lambda t: (-t[0], t[1], t[2], t[3]))
    seen = set()
    top_intermediates = []
    for b, prim, shape, dtype in tops:
        key = (prim, shape, dtype)
        if key in seen:
            continue
        seen.add(key)
        top_intermediates.append(
            {"primitive": prim, "shape": list(shape), "dtype": dtype,
             "bytes": b}
        )
        if len(top_intermediates) >= _TOP_N:
            break
    return {
        "flops": int(flops),
        "peak_intermediate_bytes": int(peak),
        "dot_general_count": sum(census.values()),
        "dot_census": dict(sorted(census.items())),
        "top_intermediates": top_intermediates,
        "_all_tensors": tops,  # stripped before serialization
    }


def _errmap_record(name: str, stats: dict) -> dict | None:
    dims = _ERRMAP_DIMS.get(name)
    if dims is None:
        return None
    elems = math.prod(dims.values())
    nbytes = 4 * elems  # f32 reprojection errors
    # An errmap is a tensor whose TRAILING axes are (n_hyps, n_cells) at
    # the full trace element count — matching on byte count alone
    # false-positives on unrelated same-size tensors (e.g. projection
    # tiles), which is exactly the record this field must not corrupt.
    nh, nc = dims["n_hyps"], dims["n_cells"]
    present = any(
        dtype == "float32" and b == nbytes
        and len(shape) >= 2 and tuple(shape[-2:]) == (nh, nc)
        for b, _, shape, dtype in stats["_all_tensors"]
    )
    return {
        "bytes_at_trace_shapes": nbytes,
        "present_in_trace": present,
        "formula": "prod(trace_dims) * 4 bytes (f32 error per "
                   "(hypothesis, cell)); scales linearly to serve shapes",
        "trace_dims": dims,
    }


# --------------------------------------------------------------------------
# graft-audit v4: the backward-jaxpr grad-hazard census (J5)

# Domain-edge primitive -> index of the vulnerable operand.  None = any
# operand being dominated suffices (atan2 is singular only at the ORIGIN,
# so one bounded-away operand guards it — the so3_log idiom).  acos/asin
# are singular at +-1, NOT at 0: their guardedness goes through
# range_dominated (a clamp/min-max sandwich with in-range bounds or a
# [-1,1]-ranged producer), never the eps-add/floor rules.
_HAZARD_PRIMS: dict[str, int | None] = {
    "div": 1, "rsqrt": 0, "pow": 0, "log": 0, "acos": 0, "asin": 0,
    "atan2": None,
}
_RANGE_EDGE_PRIMS = {"acos", "asin"}

# Producer chains are followed transparently through these (they preserve
# "bounded away from the edge" for the operands we track).
_TRANSPARENT_PRIMS = {
    "broadcast_in_dim", "convert_element_type", "reshape", "transpose",
    "expand_dims", "squeeze", "slice", "rev", "copy", "neg", "abs",
    "reduce_max", "reduce_min", "stop_gradient",
}
_CENSUS_DEPTH = 40


def _is_literal(v) -> bool:
    return hasattr(v, "val") and not hasattr(v, "count")


def _nonzero_literal(v) -> bool:
    if not _is_literal(v):
        return False
    try:
        import numpy as np

        return bool(np.all(np.asarray(v.val) != 0))
    except Exception:
        return False


class _CensusIndex:
    """Flattened var->producer map over a recursive jaxpr, with sub-jaxpr
    invars aliased back onto the outer equation's operands so eps-adds
    computed outside a scan/pjit body still dominate hazards inside it."""

    def __init__(self, closed):
        self.producer: dict[int, object] = {}   # id(var) -> eqn
        self.alias: dict[int, object] = {}      # id(var) -> outer var/lit
        self.consts: set[int] = set()           # id(var) of constvars
        jaxpr = _as_jaxpr(closed)
        self._visit(jaxpr, bindings=None)

    def _visit(self, jaxpr, bindings) -> None:
        for cv in getattr(jaxpr, "constvars", ()):
            self.consts.add(id(cv))
        if bindings:
            for inner, outer in bindings:
                self.alias[id(inner)] = outer
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                self.producer[id(v)] = eqn
            name = eqn.primitive.name
            params = eqn.params
            if name == "scan":
                sub = _as_jaxpr(params.get("jaxpr"))
                if sub is not None:
                    # scan invars = consts + init + xs; body invars line up
                    # positionally (the xs slice aliases the stacked arg —
                    # a per-step slice of a dominated stack is dominated).
                    self._visit(sub, list(zip(sub.invars, eqn.invars)))
            elif name == "cond":
                for b in params.get("branches", ()):
                    sub = _as_jaxpr(b)
                    if sub is not None:
                        self._visit(sub, list(zip(sub.invars, eqn.invars[1:])))
            else:
                for v in params.values():
                    vals = v if isinstance(v, (list, tuple)) else (v,)
                    for item in vals:
                        sub = _as_jaxpr(item)
                        if sub is None:
                            continue
                        binds = list(zip(sub.invars, eqn.invars)) \
                            if len(sub.invars) == len(eqn.invars) else None
                        self._visit(sub, binds)

    def _resolve(self, v, depth: int):
        seen = set()
        while id(v) in self.alias and id(v) not in seen and depth > 0:
            seen.add(id(v))
            v = self.alias[id(v)]
            depth -= 1
        return v

    @staticmethod
    def _through_sub(eqn, v) -> list | None:
        """Map an outer var produced by a sub-jaxpr-bearing eqn (pjit,
        scan, cond, custom_vjp/remat...) onto the positionally matching
        sub-jaxpr outvar(s): jnp.where itself lowers to a pjit around
        select_n, so guard chains MUST cross these boundaries.  None =
        no mapping (unknown layout)."""
        try:
            pos = next(
                i for i, ov in enumerate(eqn.outvars) if ov is v
            )
        except StopIteration:
            return None
        name = eqn.primitive.name
        params = eqn.params
        if name == "cond":
            subs = [_as_jaxpr(b) for b in params.get("branches", ())]
            out = []
            for sub in subs:
                if sub is None or len(sub.outvars) != len(eqn.outvars):
                    return None
                out.append(sub.outvars[pos])
            return out or None
        subs = []
        for val in params.values():
            vals = val if isinstance(val, (list, tuple)) else (val,)
            for item in vals:
                sub = _as_jaxpr(item)
                if sub is not None:
                    subs.append(sub)
        if len(subs) == 1 and len(subs[0].outvars) == len(eqn.outvars):
            return [subs[0].outvars[pos]]
        return None

    def const_chain(self, v, depth: int = _CENSUS_DEPTH) -> bool:
        """Value is a compile-time constant (literal / constvar, possibly
        broadcast/cast/reshaped)."""
        if depth <= 0:
            return False
        v = self._resolve(v, depth)
        if _is_literal(v):
            return True
        if id(v) in self.consts:
            return True
        eqn = self.producer.get(id(v))
        if eqn is None:
            return False
        if eqn.primitive.name in _TRANSPARENT_PRIMS or \
                eqn.primitive.name == "mul":
            return all(self.const_chain(iv, depth - 1) for iv in eqn.invars)
        inner = self._through_sub(eqn, v)
        if inner is not None:
            return all(
                (_is_literal(iv) and _nonzero_literal(iv))
                or (not _is_literal(iv) and self.const_chain(iv, depth - 1))
                for iv in inner
            )
        return False

    def nonneg(self, v, depth: int = _CENSUS_DEPTH) -> bool:
        """Provably nonnegative: squares (mul of a var with itself,
        integer_pow with an even exponent), abs, exp, and sums/chains
        thereof.  Used by the floored-plus-nonnegative add rule."""
        if depth <= 0:
            return False
        v = self._resolve(v, depth)
        if _is_literal(v):
            try:
                import numpy as np

                return bool(np.all(np.asarray(v.val) >= 0))
            except Exception:
                return False
        eqn = self.producer.get(id(v))
        if eqn is None:
            return False
        name = eqn.primitive.name
        if name in ("abs", "exp", "square"):
            return True
        if name == "mul":
            if len(eqn.invars) == 2 and eqn.invars[0] is eqn.invars[1]:
                return True
            return all(self.nonneg(iv, depth - 1) for iv in eqn.invars)
        if name == "integer_pow":
            y = eqn.params.get("y")
            if isinstance(y, int) and y % 2 == 0:
                return True
            return self.nonneg(eqn.invars[0], depth - 1)
        if name in ("add", "reduce_sum", "max", "sqrt"):
            return all(self.nonneg(iv, depth - 1) for iv in eqn.invars)
        if name in _TRANSPARENT_PRIMS and name not in ("neg",):
            return self.nonneg(eqn.invars[0], depth - 1)
        inner = self._through_sub(eqn, v)
        if inner is not None:
            return all(self.nonneg(iv, depth - 1) for iv in inner)
        return False

    def _literal_in(self, v, lo: float, hi: float) -> bool:
        if not _is_literal(v):
            return False
        try:
            import numpy as np

            arr = np.asarray(v.val)
            return bool(np.all(arr >= lo) and np.all(arr <= hi))
        except Exception:
            return False

    def _range_bounded(self, v, need: str, depth: int) -> bool:
        """Provably >= -1 (``need='lo'``) or <= 1 (``need='hi'``) — the
        acos/asin domain, whose edge is +-1, not 0."""
        if depth <= 0:
            return False
        v = self._resolve(v, depth)
        if _is_literal(v):
            return self._literal_in(
                v, -1.0, float("inf")
            ) if need == "lo" else self._literal_in(v, float("-inf"), 1.0)
        eqn = self.producer.get(id(v))
        if eqn is None:
            return False
        name = eqn.primitive.name
        if name in ("cos", "sin", "tanh"):
            return True
        if name == "clamp":
            # lax.clamp(min, x, max): the relevant bound must be a literal
            # actually inside [-1, 1] — clamp(-2, x, 2) guards nothing.
            bound = eqn.invars[0] if need == "lo" else eqn.invars[2]
            return self._literal_in(bound, -1.0, 1.0) or \
                self._range_bounded(bound, need, depth - 1)
        if name == "max":
            check = any if need == "lo" else all
            return check(
                self._range_bounded(iv, need, depth - 1)
                for iv in eqn.invars
            )
        if name == "min":
            check = all if need == "lo" else any
            return check(
                self._range_bounded(iv, need, depth - 1)
                for iv in eqn.invars
            )
        if name in ("convert_element_type", "broadcast_in_dim", "reshape",
                    "transpose", "expand_dims", "squeeze", "slice", "rev",
                    "copy", "stop_gradient"):
            return self._range_bounded(eqn.invars[0], need, depth - 1)
        inner = self._through_sub(eqn, v)
        if inner is not None:
            return all(
                self._range_bounded(iv, need, depth - 1) for iv in inner
            )
        return False

    def range_dominated(self, v, depth: int = _CENSUS_DEPTH) -> bool:
        """acos/asin guardedness: the operand provably sits in [-1, 1]."""
        return self._range_bounded(v, "lo", depth) and \
            self._range_bounded(v, "hi", depth)

    def _reaches_extremum(self, v, depth: int) -> bool:
        if depth <= 0:
            return False
        v = self._resolve(v, depth)
        eqn = self.producer.get(id(v))
        if eqn is None:
            return False
        name = eqn.primitive.name
        if name in ("reduce_max", "reduce_min"):
            return True
        if name in _TRANSPARENT_PRIMS:
            return self._reaches_extremum(eqn.invars[0], depth - 1)
        inner = self._through_sub(eqn, v)
        if inner is not None:
            return all(self._reaches_extremum(iv, depth - 1) for iv in inner)
        return False

    def _tie_count(self, v, depth: int) -> bool:
        """The jnp.max/min VJP's denominator: convert_element_type(eq(x,
        broadcast(reduce_max(x)))) summed over the reduced axis — at least
        one element attains the extremum, so the count is >= 1."""
        if depth <= 0:
            return False
        v = self._resolve(v, depth)
        eqn = self.producer.get(id(v))
        if eqn is None:
            return False
        name = eqn.primitive.name
        if name == "convert_element_type":
            return self._tie_count(eqn.invars[0], depth - 1)
        if name == "eq":
            return any(
                not _is_literal(iv) and self._reaches_extremum(iv, depth - 1)
                for iv in eqn.invars
            )
        return False

    def dominated(self, v, depth: int = _CENSUS_DEPTH) -> bool:
        """Is this value's producer chain dominated by an eps-add, constant
        floor/clamp, or select?  False on reaching an entry input or an
        unrecognized producer — unguarded over-approximates, like R14."""
        if depth <= 0:
            return False
        v = self._resolve(v, depth)
        if _is_literal(v):
            return _nonzero_literal(v)
        eqn = self.producer.get(id(v))
        if eqn is None:
            return False  # entry input or const capture: maybe-degenerate
        name = eqn.primitive.name
        if name == "add":
            # x + eps (either operand a broadcast of a nonzero constant),
            # or floored + nonnegative: x^2 + y^2 with x dominated stays
            # >= x^2 > 0 — the atan2-VJP denominator every rotation-angle
            # path in this codebase rests on.
            if any(
                _nonzero_literal(iv)
                or (not _is_literal(iv) and self.const_chain(iv, depth - 1))
                for iv in eqn.invars
            ):
                return True
            a, b = eqn.invars[0], eqn.invars[1]
            return (
                (self.dominated(a, depth - 1) and self.nonneg(b, depth - 1))
                or (self.dominated(b, depth - 1) and self.nonneg(a, depth - 1))
            )
        if name in ("max", "min"):
            return any(
                _is_literal(iv) or self.const_chain(iv, depth - 1)
                or self.dominated(iv, depth - 1)
                for iv in eqn.invars
            )
        if name in ("clamp", "select_n"):
            return True  # the select-clamp idiom: the edge was handled
        if name == "exp":
            return True
        if name in ("sqrt", "rsqrt", "integer_pow", "square"):
            return self.dominated(eqn.invars[0], depth - 1)
        if name == "reduce_sum":
            # sum of a dominated, nonnegative field stays above the floor
            # (the softmax denominator: reduce_sum of exp); and the
            # max/min-VJP tie count — reduce_sum of an equality indicator
            # against the reduced extremum — is >= 1 by construction (the
            # extremum is attained), the one division autodiff itself
            # emits for every jnp.max/argmax-free reduction.
            if self.dominated(eqn.invars[0], depth - 1) and \
                    self.nonneg(eqn.invars[0], depth - 1):
                return True
            return self._tie_count(eqn.invars[0], depth - 1)
        if name == "mul":
            return all(
                _nonzero_literal(iv) or self.const_chain(iv, depth - 1)
                or self.dominated(iv, depth - 1)
                for iv in eqn.invars
            )
        if name == "div":
            return self.dominated(eqn.invars[0], depth - 1)
        if name in _TRANSPARENT_PRIMS:
            return self.dominated(eqn.invars[0], depth - 1)
        inner = self._through_sub(eqn, v)
        if inner is not None:
            return all(
                (_nonzero_literal(iv) if _is_literal(iv)
                 else self.dominated(iv, depth - 1))
                for iv in inner
            )
        return False


def grad_hazard_census(closed) -> dict:
    """Per-primitive guarded/unguarded counts over one grad entry's traced
    jaxpr (forward + VJP).  Counts are static equation counts — one per
    compiled eqn, not per scan trip — so the committed record is exactly
    reproducible (the tier-1 exact-match gate)."""
    index = _CensusIndex(closed)
    census: dict[str, dict[str, int]] = {}

    def visit(jaxpr):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _HAZARD_PRIMS:
                pos = _HAZARD_PRIMS[name]
                if name in _RANGE_EDGE_PRIMS:
                    # acos/asin: the edge is +-1, so an eps-add/floor
                    # proves nothing — require a real range bound.
                    guarded = index.range_dominated(eqn.invars[pos])
                elif pos is None:
                    guarded = any(index.dominated(iv) for iv in eqn.invars)
                else:
                    guarded = index.dominated(eqn.invars[pos])
                slot = census.setdefault(name, {"guarded": 0, "unguarded": 0})
                slot["guarded" if guarded else "unguarded"] += 1
            if name == "scan":
                sub = _as_jaxpr(eqn.params.get("jaxpr"))
                if sub is not None:
                    visit(sub)
            elif name == "cond":
                for b in eqn.params.get("branches", ()):
                    sub = _as_jaxpr(b)
                    if sub is not None:
                        visit(sub)
            else:
                for v in eqn.params.values():
                    vals = v if isinstance(v, (list, tuple)) else (v,)
                    for item in vals:
                        sub = _as_jaxpr(item)
                        if sub is not None:
                            visit(sub)

    visit(_as_jaxpr(closed))
    return {k: census[k] for k in sorted(census)}


# --------------------------------------------------------------------------
# ledger build / io / diff

def build_ledger(traced) -> tuple[dict, set]:
    """``trace_entries()`` output -> (name -> stats dict, skipped names)."""
    entries: dict = {}
    skipped: set = set()
    for entry, closed in traced:
        if closed is None:
            skipped.add(entry.name)
            continue
        stats = entry_stats(closed)
        errmap = _errmap_record(entry.name, stats)
        del stats["_all_tensors"]
        stats = {"pinned": entry.pinned, **stats}
        if errmap is not None:
            stats["errmap"] = errmap
        if getattr(entry, "grad", False):
            # Grad-registered entry: the traced jaxpr carries the VJP, so
            # the hazard census below IS the backward-pass record (J5).
            stats["grad"] = True
            stats["grad_hazards"] = grad_hazard_census(closed)
        entries[entry.name] = stats
    return entries, skipped


def write_ledger(path: pathlib.Path, entries: dict) -> None:
    data = {
        "comment": "graft-audit v2/v4 jaxpr resource ledger; see LINT.md. "
                   "Per registered entry point at fixed tiny trace shapes: "
                   "analytic flops, peak intermediate bytes (liveness over "
                   "the jaxpr — the pre-fusion materialization bound), the "
                   "dot_general precision census, and — for grad-registered "
                   "entries — the backward-jaxpr grad-hazard census "
                   "(grad_hazards: domain-edge primitives keyed by whether "
                   "an eps-add/floor/clamp dominates the vulnerable "
                   "operand; a NEW unguarded site fails as J5).  Regenerate "
                   "with `python -m esac_tpu.lint --write-ledger` and "
                   "review the diff; regressions fail tier-1.",
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


def load_ledger(path: pathlib.Path) -> dict | None:
    if not path.exists():
        return None
    return json.loads(path.read_text()).get("entries", {})


def _census_counts(stats: dict) -> tuple[int, int]:
    highest = 0
    other = 0
    for key, n in stats.get("dot_census", {}).items():
        if key.startswith("HIGHEST:"):
            highest += n
        else:
            other += n
    return highest, other


def diff_ledger(
    committed: dict, current: dict, skipped: set = frozenset()
) -> tuple[list[Finding], list[str]]:
    """-> (J4/J5 regression findings, stale-entry notes).

    Regressions fail the lint: an entry missing from the committed ledger,
    peak bytes / flops growth beyond tolerance, or a precision-census
    regression (HIGHEST dots lost while non-HIGHEST appear).  Everything
    else that mismatches — improvements, drift inside tolerance, entries no
    longer in the registry — is stale: the committed file must be
    regenerated (and the diff reviewed), but the tree is not worse.
    """
    findings: list[Finding] = []
    stale: list[str] = []

    def add(name: str, text: str, message: str) -> None:
        findings.append(Finding("J4", name, 0, text, message))

    for name, cur in current.items():
        old = committed.get(name)
        if old is None:
            add(name, "missing-entry",
                "entry has no committed ledger record; run "
                "`python -m esac_tpu.lint --write-ledger`, review the "
                "numbers, and commit the diff")
            continue
        drift = False
        for field, tol in (("peak_intermediate_bytes", BYTES_TOL),
                           ("flops", FLOPS_TOL)):
            was, now = old.get(field, 0), cur.get(field, 0)
            if now > was * tol:
                add(name, f"{field}:{was}->{now}",
                    f"{field} grew {was} -> {now} "
                    f"(> {tol}x committed): this entry now materializes/"
                    "computes more than the committed budget — if "
                    "intentional, regenerate the ledger and review")
            elif now != was:
                drift = True
        # J5: the backward-jaxpr grad-hazard census (graft-audit v4).  A
        # NEW unguarded domain-edge site in a grad entry fails; guarded
        # drift, improvements, and a (de)registered census report stale.
        old_h = old.get("grad_hazards")
        cur_h = cur.get("grad_hazards")
        if cur_h is not None:
            if old_h is None:
                findings.append(Finding(
                    "J5", name, 0, "missing-hazard-census",
                    "grad-registered entry has no committed grad_hazards "
                    "census; run `python -m esac_tpu.lint --write-ledger`, "
                    "review the unguarded counts, and commit the diff",
                ))
            else:
                for prim, counts in cur_h.items():
                    was = old_h.get(prim, {"guarded": 0, "unguarded": 0})
                    if counts.get("unguarded", 0) > was.get("unguarded", 0):
                        findings.append(Finding(
                            "J5", name,
                            0,
                            f"{prim}:unguarded "
                            f"{was.get('unguarded', 0)}->"
                            f"{counts.get('unguarded', 0)}",
                            f"new unguarded '{prim}' site in this entry's "
                            "backward jaxpr: a domain-edge primitive whose "
                            "vulnerable operand no eps-add/floor/clamp "
                            "dominates — guard the operand (utils.num, "
                            "select-clamp) or, if reviewed safe, "
                            "regenerate the ledger and commit the diff",
                        ))
                    elif counts != was:
                        drift = True
                if any(p not in cur_h for p in old_h):
                    drift = True
        elif old_h is not None:
            drift = True
        old_hi, old_other = _census_counts(old)
        new_hi, new_other = _census_counts(cur)
        if new_hi < old_hi and new_other > old_other:
            add(name,
                f"census:HIGHEST {old_hi}->{new_hi}, "
                f"other {old_other}->{new_other}",
                "precision census regression: HIGHEST dot_generals were "
                "lost while unpinned ones appeared — a HIGHEST pin was "
                "dropped (route contractions through "
                "utils.precision.hmm/heinsum)")
        elif (new_hi, new_other) != (old_hi, old_other):
            drift = True
        if cur.get("dot_census") != old.get("dot_census"):
            drift = True
        if drift:
            stale.append(
                f"ledger entry '{name}' drifted from the committed record "
                "(within tolerance) — regenerate with --write-ledger and "
                "review the diff"
            )
    for name in committed:
        if name not in current and name not in skipped:
            stale.append(
                f"ledger entry '{name}' no longer matches any registry "
                "entry — regenerate with --write-ledger"
            )
    return findings, stale
