"""Suppression comments + the committed findings baseline.

Two escape hatches, with different intents:

- **Inline suppression** — ``# graft-lint: disable=R6(reason)`` on the
  offending line (or ``disable-file=`` near the top of the file for
  whole-file rules).  For *reviewed, permanent* exceptions: code that is
  sanctioned to violate a rule by design (e.g. tools/tpu_probe.py exists to
  touch the chip).  A reason is required by convention; the parser accepts
  its absence but LINT.md review policy does not.
- **Baseline** (``lint_baseline.json``) — grandfathers *pre-existing*
  findings so the lint can land strict without blocking unrelated work.
  Entries match on (rule, path, stripped source line) — line-number
  independent — and may carry an ``expires: "YYYY-MM-DD"`` date after which
  they stop masking.  New code should never add baseline entries; fix or
  inline-suppress instead.

Both hatches can go stale — the code they excused gets fixed or deleted
while the directive lingers, silently ready to mask a FUTURE violation.
Full-tree runs therefore audit them: :func:`record_usage` collects which
directives actually masked a finding during a run, and
:func:`stale_suppressions` diffs that against every directive declared in
the tree (graft-audit v3; the CLI reports the leftovers so they get
pruned, the exact sweep the baseline already has).
"""

from __future__ import annotations

import contextlib
import dataclasses
import datetime
import json
import pathlib
import re

from esac_tpu.lint.findings import Finding

# "# graft-lint: disable=R1,R2(reason ...)" — comma-separated rule ids, an
# optional parenthesized reason after each (reasons may not contain ')').
_DIRECTIVE = re.compile(
    r"#\s*graft-lint:\s*(?P<kind>disable(?:-file)?)\s*=\s*(?P<rules>[^#]+)"
)
_RULE_HEAD = re.compile(r"\s*(?P<rule>[A-Z]\d+)\s*")
_RULE_SEP = re.compile(r"\s*,")


def _parse_rule_list(spec: str) -> set[str]:
    """Sequential parse of ``R1,R2(reason),R3`` — NOT a global token scan.

    A reason whose closing ')' is missing (it wraps to the next comment
    line) ends the list: rule ids mentioned inside the prose of a reason
    must never widen the suppression.
    """
    rules: set[str] = set()
    pos = 0
    while True:
        m = _RULE_HEAD.match(spec, pos)
        if not m:
            break
        rules.add(m.group("rule"))
        pos = m.end()
        if pos < len(spec) and spec[pos] == "(":
            close = spec.find(")", pos)
            if close == -1:
                break  # reason continues past this line; list ends here
            pos = close + 1
        m = _RULE_SEP.match(spec, pos)
        if not m:
            break
        pos = m.end()
    return rules

# File-level directives must sit in the header, not be buried mid-file.
_FILE_DIRECTIVE_MAX_LINE = 40


def parse_suppressions(source: str) -> tuple[dict[int, set[str]], set[str]]:
    """-> (line -> rules suppressed on that line, rules suppressed file-wide).

    Works for Python and shell alike: both comment with ``#``.
    """
    per_line: dict[int, set[str]] = {}
    per_file: set[str] = set()
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _DIRECTIVE.search(line)
        if not m:
            continue
        rules = _parse_rule_list(m.group("rules"))
        if m.group("kind") == "disable-file":
            if lineno <= _FILE_DIRECTIVE_MAX_LINE:
                per_file |= rules
        else:
            per_line.setdefault(lineno, set()).update(rules)
    return per_line, per_file


# Active usage recorder (None = off).  A set of (path, lineno, rule)
# triples — lineno 0 marks a file-level directive — filled by
# is_suppressed whenever a directive actually masks a finding, so a
# full-tree run can report directives that masked NOTHING (stale).
_USAGE: set[tuple[str, int, str]] | None = None


@contextlib.contextmanager
def record_usage():
    """Collect which suppression directives fire during the enclosed
    lint run; yields the live (path, lineno, rule) set."""
    global _USAGE
    prev, _USAGE = _USAGE, set()
    try:
        yield _USAGE
    finally:
        _USAGE = prev


def is_suppressed(
    rule: str,
    lineno: int,
    per_line: dict[int, set[str]],
    per_file: set[str],
    path: str | None = None,
) -> bool:
    hit_line = rule in per_line.get(lineno, set())
    hit_file = rule in per_file
    if _USAGE is not None and path is not None:
        if hit_line:
            _USAGE.add((path, lineno, rule))
        if hit_file:
            _USAGE.add((path, 0, rule))
    return hit_file or hit_line


def declared_suppressions(root: pathlib.Path, files=None):
    """Every inline directive in the tree: {(path, lineno, rule)} with
    lineno 0 for file-level directives (the universe the stale sweep
    diffs :func:`record_usage`'s hits against)."""
    from esac_tpu.lint.ast_rules import iter_python_files

    declared: set[tuple[str, int, str]] = set()
    root = pathlib.Path(root)
    rels = list(iter_python_files(root, files))
    if files is None:
        rels += [
            p.relative_to(root).as_posix()
            for p in sorted(root.rglob("*.sh"))
            if not any(part.startswith(".") for part in
                       p.relative_to(root).parts)
        ]
    for rel in rels:
        try:
            source = (root / rel).read_text()
        except (OSError, UnicodeDecodeError):
            continue
        per_line, per_file = parse_suppressions(source)
        for lineno, rules in per_line.items():
            declared.update((rel, lineno, r) for r in rules)
        declared.update((rel, 0, r) for r in per_file)
    return declared


def stale_suppressions(declared, used) -> list[str]:
    """Human-readable notes for directives that masked nothing this run
    — the violation was fixed (prune the directive) or the rule moved."""
    out = []
    for path, lineno, rule in sorted(declared - set(used)):
        where = f"{path}:{lineno}" if lineno else f"{path} (file-level)"
        out.append(
            f"stale inline suppression ({rule} at {where}): the rule no "
            "longer fires there — remove the directive (a lingering "
            "suppression silently masks the NEXT violation)"
        )
    return out


def filter_suppressed(findings, sources: dict[str, str]):
    """Drop findings whose file carries a matching inline directive.

    ``sources`` maps repo-relative path -> file text.  Rule modules normally
    check suppressions themselves while they still hold the AST; this is
    the generic fallback for callers composing rule outputs.
    """
    out = []
    cache: dict[str, tuple[dict[int, set[str]], set[str]]] = {}
    for f in findings:
        src = sources.get(f.path)
        if src is None:
            out.append(f)
            continue
        if f.path not in cache:
            cache[f.path] = parse_suppressions(src)
        per_line, per_file = cache[f.path]
        if not is_suppressed(f.rule, f.line, per_line, per_file,
                             path=f.path):
            out.append(f)
    return out


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str
    text: str
    expires: str | None = None  # "YYYY-MM-DD"; None = never

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.text)

    def expired(self, today: datetime.date) -> bool:
        if self.expires is None:
            return False
        return datetime.date.fromisoformat(self.expires) < today


class Baseline:
    """The committed grandfather list (lint_baseline.json)."""

    def __init__(self, entries: list[BaselineEntry]):
        self.entries = entries

    @classmethod
    def load(cls, path: pathlib.Path) -> "Baseline":
        if not path.exists():
            return cls([])
        data = json.loads(path.read_text())
        return cls([BaselineEntry(**e) for e in data.get("entries", [])])

    @classmethod
    def from_findings(cls, findings) -> "Baseline":
        return cls([
            BaselineEntry(rule=f.rule, path=f.path, text=f.text)
            for f in findings
        ])

    def write(self, path: pathlib.Path) -> None:
        data = {
            "comment": "graft-lint grandfathered findings; see LINT.md. "
                       "Matching is (rule, path, stripped source line), "
                       "line-number independent.  Do not add entries for "
                       "new code.",
            "entries": [dataclasses.asdict(e) for e in self.entries],
        }
        path.write_text(json.dumps(data, indent=2) + "\n")

    def apply(
        self, findings, today: datetime.date | None = None
    ) -> tuple[list[Finding], list[BaselineEntry]]:
        """-> (findings not masked by the baseline, stale entries).

        A stale entry matched nothing (the violation was fixed — the entry
        should be deleted) or has expired (it masks nothing anymore and its
        finding resurfaces).
        """
        today = today or datetime.date.today()
        live = {e.key(): e for e in self.entries if not e.expired(today)}
        matched: set[tuple[str, str, str]] = set()
        out = []
        for f in findings:
            key = (f.rule, f.path, f.text)
            if key in live:
                matched.add(key)
            else:
                out.append(f)
        stale = [
            e for e in self.entries
            if e.expired(today) or e.key() not in matched
        ]
        return out, stale
