"""Layer 1d, R14/R15: grad-safety dataflow analysis (graft-audit v4).

R2 is the only CLAUDE.md code convention that was still enforced purely
syntactically: it flags the *spellings* ``jnp.linalg.norm`` / bare
``jnp.sqrt``, but nothing verified that a backward pass cannot emit NaN
through the documented ``where``-VJP trap, an unguarded division, or a
trig/log/pow primitive at its domain edge.  This pass closes that gap with
a dataflow analysis over the differentiated packages
(``esac_tpu/{geometry,ransac,train}/``):

- **Differentiated scope** — the call graph reachable from the
  *grad-registered* entry points: the ``grad=True`` registry entries
  (their builders are parsed out of ``lint/registry.py``, so the root set
  stays in sync with the jaxpr audit by construction) plus every function
  fed to a ``jax.grad``/``value_and_grad``/``custom_vjp``/``defvjp``/
  ``jax.vjp`` wrapper inside the scope packages themselves (the Pallas
  custom-VJP forward/backward pairs).  Reachability propagates through the
  R3-style intra-package call graph; nested defs and lambdas inside a
  reachable function are scanned with it (a closure built in a
  differentiated function is differentiated).
- **Guardedness** — a value is *guarded* (bounded away from its domain
  edge in both passes) when it flows from ``safe_norm``/``safe_sqrt``, an
  eps-add (``x + 1e-9``, ``x + _EPS``), a floor (``jnp.maximum(x, k)``
  with a constant), the select-clamp idiom (``jnp.where(bad, floor, x)``
  — the author explicitly handled the edge; R15 separately polices the
  *misuse* of ``where``), ``exp``, a static shape (``x.shape[0]``), an
  ``int``/``bool``-annotated parameter (static under jit — no VJP
  exists), or a parameter with a nonzero numeric default.  One level of
  helper propagation: a call to a same-package function whose return
  expression is guard-shaped is guarded (the ``lead_safe`` idiom in
  geometry/quartic.py).  Anything unresolvable is *unguarded* — this rule
  deliberately over-approximates hazards (the opposite contract from
  R3/R8): a missed NaN poisons a whole batch gradient, a false positive
  costs one reviewed suppression.
- **R14** — an unguarded domain-edge primitive in differentiated scope:
  ``x / y`` with an unguarded denominator, ``arccos``/``arcsin`` without
  a clamp (``jnp.clip`` / min-max chain / a [-1,1]-bounded producer like
  ``cos``) dominating the input, ``log`` of a maybe-zero value, or a
  fractional/negative power of a maybe-zero base (integer powers >= 1
  are total).  ``log1p`` and ``sqrt`` are NOT R14's: ``log1p`` is total
  at 0 and sqrt is R2's (one rule per spelling).
- **R15** — the documented ``where``-VJP trap: an R14-style hazard
  expression **inside either branch** of a ``jnp.where``/``lax.select``.
  The forward value is masked; the untaken branch's VJP still runs and
  ``0 * inf = NaN`` poisons the batch gradient (utils/num.py docstring;
  CLAUDE.md conventions).  The sanctioned idiom — guarding the *operand*
  (``x / jnp.where(bad, 1.0, d)``) or an eps/const-guarded hazard inside
  the branch — classifies as a near-miss.

Pure stdlib ``ast`` — no jax import; rides ``run_layer1`` and therefore
the same suppression (``# graft-lint: disable=R14(reason)``), baseline,
``--format json`` and stale-sweep machinery as every other rule.  The
runtime half is :mod:`esac_tpu.lint.gradcheck` (the degenerate-input
gradient witness) and the J5 backward-jaxpr hazard census in
:mod:`esac_tpu.lint.ledger`.
"""

from __future__ import annotations

import ast
import pathlib

from esac_tpu.lint.ast_rules import (
    _Module,
    _alias_map,
    _callees,
    _dotted,
    _line_text,
    _resolve_function,
    iter_python_files,
)
from esac_tpu.lint.findings import Finding
from esac_tpu.lint.suppress import is_suppressed, parse_suppressions

# The differentiated packages the pass analyzes...
GRAD_SCOPE_PREFIXES = (
    "esac_tpu/geometry/", "esac_tpu/ransac/", "esac_tpu/train/",
)
# ...and what triggers the pass in --changed mode (editing the analysis
# itself must re-run it, the lock-pass convention).
PASS_PREFIXES = GRAD_SCOPE_PREFIXES + ("esac_tpu/lint/",)


def grad_pass_needed(files) -> bool:
    """Mirror of lockgraph.lock_pass_needed: full runs always analyze;
    scoped runs only when a geometry/ransac/train or lint file changed."""
    if files is None:
        return True
    return any(
        f.startswith(PASS_PREFIXES) and f.endswith(".py") for f in files
    )


# Wrappers whose function argument enters differentiated scope.
_GRAD_WRAPPERS = {
    "jax.grad", "jax.value_and_grad", "jax.vjp", "jax.jvp", "jax.linearize",
    "jax.jacobian", "jax.jacfwd", "jax.jacrev",
    "jax.custom_vjp", "jax.custom_jvp",
}

# Callable names (trailing attribute) treated as guard producers.
_SAFE_CALLS = {"safe_norm", "safe_sqrt"}
# where/select produce the select-clamp idiom; exp is strictly positive.
_SELECT_CALLS = {"where", "select"}
# Producers whose RANGE is within [-1, 1] (arccos/arcsin domination).
_BOUNDED_CALLS = {"cos", "sin", "tanh"}

_MAX_DEPTH = 12


def _is_eps_name(name: str) -> bool:
    """Names that denote a numeric guard constant by convention: anything
    containing 'eps', or an ALL-CAPS module constant (MIN_DEPTH, _EPS)."""
    bare = name.lstrip("_")
    return "eps" in name.lower() or (bare.isupper() and bare != "")


def _const_like(node: ast.AST) -> bool:
    """Nonzero numeric literal, eps-named constant, or a negation of one."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, complex)) and node.value != 0
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _const_like(node.operand)
    name = None
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    return name is not None and _is_eps_name(name)


class _Scope:
    """Per-function analysis scope: flow-ordered assignments, parameters
    (with annotations/defaults), and the owning module for helper and
    module-constant resolution."""

    def __init__(self, mod: _Module, fn: ast.AST):
        self.mod = mod
        self.fn = fn
        # name -> [(lineno, value expr)], flow-ordered single-target binds.
        self.assigns: dict[str, list[tuple[int, ast.AST]]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self.assigns.setdefault(node.targets[0].id, []).append(
                    (node.lineno, node.value)
                )
        for binds in self.assigns.values():
            binds.sort()
        # Parameters of the scanned function (nested-def params stay
        # unresolved -> tainted, the conservative direction).
        self.params: dict[str, tuple[ast.AST | None, ast.AST | None]] = {}
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = fn.args
            all_args = args.posonlyargs + args.args + args.kwonlyargs
            defaults = [None] * (
                len(args.posonlyargs) + len(args.args) - len(args.defaults)
            ) + list(args.defaults)
            defaults += list(args.kw_defaults)
            for a, d in zip(all_args, defaults):
                self.params[a.arg] = (a.annotation, d)

    def latest_bind(self, name: str, before: int) -> ast.AST | None:
        binds = self.assigns.get(name)
        if not binds:
            return None
        prior = [v for ln, v in binds if ln <= before]
        return prior[-1] if prior else binds[-1][1]


def _param_guarded(scope: _Scope, name: str) -> bool | None:
    """None = not a parameter; else its guardedness: int/bool annotation
    (static under jit, no VJP) or a nonzero numeric default."""
    if name not in scope.params:
        return None
    ann, default = scope.params[name]
    if isinstance(ann, ast.Name) and ann.id in ("int", "bool"):
        return True
    if isinstance(default, ast.Constant) and \
            isinstance(default.value, (int, float)) and default.value != 0:
        return True
    return False


def _helper_return_guarded(scope: _Scope, fname: str, depth: int) -> bool | None:
    """One level of helper propagation: a same-module function whose every
    return expression is guarded makes its call results guarded (the
    geometry/quartic.py ``lead_safe`` idiom).  None = not resolvable."""
    helper = scope.mod.functions.get(fname)
    if helper is None or depth > _MAX_DEPTH:
        return None
    returns = [
        n.value for n in ast.walk(helper)
        if isinstance(n, ast.Return) and n.value is not None
    ]
    if not returns:
        return None
    hscope = _Scope(scope.mod, helper)
    return all(
        _guarded(hscope, r, use_line=getattr(r, "lineno", 0),
                 depth=depth + 1)
        for r in returns
    )


def _guarded(scope: _Scope, node: ast.AST, use_line: int, depth: int = 0,
             _seen: frozenset = frozenset()) -> bool:
    """Is this expression's value bounded away from the domain edge in
    BOTH passes?  False whenever unresolvable (hazards over-approximate)."""
    if depth > _MAX_DEPTH:
        return False
    if _const_like(node):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _guarded(scope, node.operand, use_line, depth + 1, _seen)
    if isinstance(node, ast.IfExp):
        return (
            _guarded(scope, node.body, use_line, depth + 1, _seen)
            and _guarded(scope, node.orelse, use_line, depth + 1, _seen)
        )
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Add):
            # x + eps (either side): the canonical guard.
            return _const_like(node.left) or _const_like(node.right)
        if isinstance(node.op, (ast.Mult, ast.Div)):
            # nonzero * nonzero (const * where(...) etc.) stays nonzero.
            return (
                _guarded(scope, node.left, use_line, depth + 1, _seen)
                and _guarded(scope, node.right, use_line, depth + 1, _seen)
            )
        return False
    if isinstance(node, ast.Subscript):
        # Static shapes are nonzero ints; slicing a guarded array keeps the
        # elementwise floor.
        if isinstance(node.value, ast.Attribute) and node.value.attr == "shape":
            return True
        return _guarded(scope, node.value, use_line, depth + 1, _seen)
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func, scope.mod.aliases) or ""
        tail = dotted.rpartition(".")[2]
        if tail in _SAFE_CALLS:
            return True
        if tail in _SELECT_CALLS:
            # The select-clamp idiom: jnp.where(bad, floor, x).  Whether the
            # clamp is CORRECT is the runtime witness's job (gradcheck); the
            # static rule credits the author with handling the edge.
            return True
        if tail == "exp":
            return True
        if tail in ("maximum", "clip", "clamp"):
            # A floor needs a constant bound: maximum(x, 1e-9) or
            # maximum(x, MIN_DEPTH).  maximum(x, y) of two tainted values
            # floors nothing.
            return any(_const_like(a) for a in node.args[1:]) or \
                any(_const_like(kw.value) for kw in node.keywords)
        if tail in ("float32", "float64", "asarray", "astype"):
            return bool(node.args) and _guarded(
                scope, node.args[0], use_line, depth + 1, _seen
            )
        if isinstance(node.func, ast.Name):
            helper = _helper_return_guarded(scope, node.func.id, depth)
            if helper is not None:
                return helper
        return False
    if isinstance(node, ast.Attribute):
        return _is_eps_name(node.attr)
    if isinstance(node, ast.Name):
        if node.id in _seen:
            return False  # self-referential rebinding chain
        p = _param_guarded(scope, node.id)
        if p is not None:
            return p
        if _is_eps_name(node.id):
            return True
        bind = scope.latest_bind(node.id, use_line)
        if bind is not None:
            return _guarded(scope, bind, getattr(bind, "lineno", use_line),
                            depth + 1, _seen | {node.id})
        # Fall back to a module-level constant binding.
        for stmt in scope.mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == node.id:
                mscope = _Scope(scope.mod, scope.mod.tree)
                return _guarded(mscope, stmt.value, stmt.lineno, depth + 1,
                                _seen | {node.id})
        return False
    return False


def _const_value(node: ast.AST) -> float | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return float(node.value)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_value(node.operand)
        return None if inner is None else -inner
    return None


def _bounded(scope: _Scope, node: ast.AST, use_line: int, need: str,
             depth: int = 0) -> bool:
    """Is this expression provably bounded on one side of the arccos
    domain — ``need='lo'`` (value >= -1) or ``need='hi'`` (value <= 1)?

    Real interval reasoning, not clamp-spotting: ``maximum(x, c)`` bounds
    BELOW if either operand does but ABOVE only if both do, ``minimum``
    mirrors, and ``clip``'s literal bounds must actually sit inside
    [-1, 1] — ``clip(x, -2, 2)`` or a floor-only ``maximum(x, -1)``
    leaves the hazard live and must NOT silence it (this pass
    over-approximates hazards)."""
    if depth > _MAX_DEPTH:
        return False
    c = _const_value(node)
    if c is not None:
        return c >= -1.0 if need == "lo" else c <= 1.0
    if isinstance(node, ast.Call):
        tail = (_dotted(node.func, scope.mod.aliases) or "").rpartition(".")[2]
        if tail in _BOUNDED_CALLS:
            return True
        if tail == "clip" and len(node.args) >= 3:
            bound = node.args[1] if need == "lo" else node.args[2]
            bc = _const_value(bound)
            return bc is not None and (
                bc >= -1.0 if need == "lo" else bc <= 1.0
            )
        if tail == "maximum" and node.args:
            check = any if need == "lo" else all
            return check(
                _bounded(scope, a, use_line, need, depth + 1)
                for a in node.args
            )
        if tail == "minimum" and node.args:
            check = all if need == "lo" else any
            return check(
                _bounded(scope, a, use_line, need, depth + 1)
                for a in node.args
            )
        return False
    if isinstance(node, ast.Name):
        bind = scope.latest_bind(node.id, use_line)
        if bind is not None:
            return _bounded(scope, bind, getattr(bind, "lineno", use_line),
                            need, depth + 1)
    return False


def _clamp_guarded(scope: _Scope, node: ast.AST, use_line: int) -> bool:
    """arccos/arcsin domination: the input must provably sit in [-1, 1]
    on BOTH sides — a full clip/min-max sandwich with in-range literal
    bounds, or a range-bounded producer (cos/sin/tanh)."""
    return (
        _bounded(scope, node, use_line, "lo")
        and _bounded(scope, node, use_line, "hi")
    )


# --------------------------------------------------------------------------
# differentiated-scope roots

def _registry_grad_roots(root: pathlib.Path, modules) -> set:
    """Roots from lint/registry.py: every in-scope function referenced by
    the builder of a ``grad=True`` Entry.  Parsed, not imported — layer 1
    stays jax-free — and automatically in sync with the jaxpr audit's
    grad-registered set."""
    reg = root / "esac_tpu" / "lint" / "registry.py"
    if not reg.exists():
        return set()
    try:
        tree = ast.parse(reg.read_text())
    except (SyntaxError, UnicodeDecodeError, OSError):
        return set()
    aliases = _alias_map(tree)
    builders: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and (_dotted(node.func, aliases) or "").endswith("Entry")):
            continue
        kw = {k.arg: k.value for k in node.keywords}
        g = kw.get("grad")
        if not (isinstance(g, ast.Constant) and g.value is True):
            continue
        b = kw.get("build")
        if isinstance(b, ast.Name):
            builders.add(b.id)
        elif isinstance(b, ast.Call) and isinstance(b.func, ast.Name):
            builders.add(b.func.id)
    funcs = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    roots = set()
    for name in builders:
        fn = funcs.get(name)
        if fn is None:
            continue
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.Name, ast.Attribute)):
                d = _dotted(sub, aliases)
                if d is None:
                    continue
                resolved = _resolve_function(d, modules)
                if resolved:
                    roots.add(resolved)
    return roots


def _scope_grad_roots(modules) -> set:
    """Roots declared inside the scope packages themselves: functions fed
    to jax.grad/value_and_grad/vjp/custom_vjp (decorator or call-site) and
    the forward/backward pair of every ``defvjp`` registration."""
    roots = set()
    for mod in modules.values():
        for name, fn in mod.functions.items():
            for dec in fn.decorator_list:
                for sub in ast.walk(dec):
                    if _dotted(sub, mod.aliases) in _GRAD_WRAPPERS:
                        roots.add((mod.dotted, name))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func, mod.aliases)
            is_defvjp = (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("defvjp", "defjvp")
            )
            if dotted not in _GRAD_WRAPPERS and not is_defvjp:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                names = []
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    names.append(arg)
                elif isinstance(arg, ast.Lambda):
                    names.extend(
                        n for n in ast.walk(arg.body)
                        if isinstance(n, (ast.Name, ast.Attribute))
                    )
                for n in names:
                    d = _dotted(n, mod.aliases)
                    if d is None:
                        continue
                    if "." not in d and d in mod.functions:
                        roots.add((mod.dotted, d))
                    else:
                        resolved = _resolve_function(d, modules)
                        if resolved:
                            roots.add(resolved)
    return roots


def _reachable_functions(root: pathlib.Path, modules) -> set:
    roots = _registry_grad_roots(root, modules) | _scope_grad_roots(modules)
    reachable: set = set()
    frontier = list(roots)
    while frontier:
        key = frontier.pop()
        if key in reachable:
            continue
        reachable.add(key)
        mod = modules.get(key[0])
        if mod is None:
            continue
        fn = mod.functions.get(key[1])
        if fn is None:
            continue
        frontier.extend(_callees(mod, fn, modules))
    return reachable


# --------------------------------------------------------------------------
# hazard scan

_LOG_CALLS = {"log", "log2", "log10"}
_ACOS_CALLS = {"arccos", "arcsin", "acos", "asin"}
_DIV_CALLS = {"divide", "true_divide", "reciprocal"}


def _where_branch_nodes(fn: ast.AST, aliases) -> set[int]:
    """ids of every AST node inside a branch argument of a
    jnp.where/lax.select call — the R15 (VJP-trap) position."""
    out: set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        tail = (_dotted(node.func, aliases) or "").rpartition(".")[2]
        if tail not in _SELECT_CALLS:
            continue
        for branch in node.args[1:3]:
            for sub in ast.walk(branch):
                out.add(id(sub))
    return out


def _fractional_pow_hazard(node: ast.BinOp) -> bool:
    """x ** p is a domain-edge hazard iff p is fractional or negative (its
    VJP has x**(p-1)); integer powers >= 1 are total.  A non-constant
    exponent is not flagged (under-approximation documented in LINT.md:
    every power in this codebase is a literal)."""
    exp = node.right
    if isinstance(exp, ast.UnaryOp) and isinstance(exp.op, ast.USub):
        inner = exp.operand
        return isinstance(inner, ast.Constant) and \
            isinstance(inner.value, (int, float))
    if not (isinstance(exp, ast.Constant)
            and isinstance(exp.value, (int, float))):
        return False
    v = exp.value
    return v < 1 or float(v) != float(int(v))


def _scan_function(mod: _Module, fname: str, reported: set) -> list[Finding]:
    """All R14/R15 hazards in one reachable function (full subtree: nested
    defs and lambdas included — closures inherit differentiated scope)."""
    fn = mod.functions[fname]
    scope = _Scope(mod, fn)
    in_where = _where_branch_nodes(fn, mod.aliases)
    findings = []

    def add(node, kind: str, message: str) -> None:
        rule = "R15" if id(node) in in_where else "R14"
        key = (rule, mod.rel, node.lineno, getattr(node, "col_offset", 0),
               kind)
        if key in reported:
            return
        reported.add(key)
        if rule == "R15":
            message += (
                " — and it sits inside a jnp.where/select branch: the "
                "untaken branch's VJP still runs (0 * inf = NaN poisons "
                "the whole batch gradient); guard the operand instead "
                "(utils/num.py, CLAUDE.md conventions)"
            )
        findings.append(Finding(
            rule, mod.rel, node.lineno, _line_text(mod.lines, node.lineno),
            message,
        ))

    for node in ast.walk(fn):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
            if not _guarded(scope, node.right, node.lineno):
                add(node, "div",
                    "division with an eps-free denominator in "
                    "differentiated scope: the VJP multiplies by 1/y^2 and "
                    "NaNs the batch gradient at y = 0 — add an eps, floor "
                    "with jnp.maximum(y, k), or select-clamp the operand")
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Pow):
            if _fractional_pow_hazard(node) and \
                    not _guarded(scope, node.left, node.lineno):
                add(node, "pow",
                    "fractional/negative power of a maybe-zero base in "
                    "differentiated scope: d/dx x**p has x**(p-1), "
                    "infinite at 0 — add an eps to the base (or use "
                    "utils.num.safe_sqrt for p = 1/2)")
        elif isinstance(node, ast.Call):
            tail = (_dotted(node.func, mod.aliases) or "").rpartition(".")[2]
            if tail in _DIV_CALLS and node.args:
                den = node.args[1] if tail != "reciprocal" and \
                    len(node.args) > 1 else node.args[0]
                if not _guarded(scope, den, node.lineno):
                    add(node, "div",
                        f"jnp.{tail} with an eps-free denominator in "
                        "differentiated scope — add an eps or floor the "
                        "denominator")
            elif tail in _ACOS_CALLS and node.args:
                if not _clamp_guarded(scope, node.args[0], node.lineno):
                    add(node, "acos",
                        f"jnp.{tail} without a clamp dominating its input: "
                        "the derivative is infinite at +-1, exactly where "
                        "a perfectly-converged rotation lands — clip the "
                        "input (or use the atan2 formulation as in "
                        "geometry/rotations.py)")
            elif tail in _LOG_CALLS and node.args:
                if not _guarded(scope, node.args[0], node.lineno):
                    add(node, "log",
                        f"jnp.{tail} of a maybe-zero value in "
                        "differentiated scope: log and its VJP are "
                        "infinite at 0 — add an eps (x + 1e-12) or use "
                        "log1p for near-zero arguments")
    return findings


def run_gradsafety_rules(root, files=None) -> list[Finding]:
    """All R14/R15 findings (inline suppressions applied).  Tree-global
    over the grad scope, like R11: a scoped run that touched any
    geometry/ransac/train/lint file re-analyzes the whole scope (the call
    graph is cross-file); other scoped runs skip the pass entirely."""
    if not grad_pass_needed(files):
        return []
    root = pathlib.Path(root)
    modules: dict[str, _Module] = {}
    sources: dict[str, str] = {}
    for rel in iter_python_files(root, files=None):
        if not rel.startswith(GRAD_SCOPE_PREFIXES):
            continue
        try:
            source = (root / rel).read_text()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue  # R0 is reported by the per-file pass
        m = _Module(rel, tree, source.splitlines())
        modules[m.dotted] = m
        sources[rel] = source
    if not modules:
        return []

    reachable = _reachable_functions(root, modules)
    findings: list[Finding] = []
    reported: set = set()
    for mod_dotted, fname in sorted(reachable):
        mod = modules.get(mod_dotted)
        if mod is None or fname not in mod.functions:
            continue
        findings += _scan_function(mod, fname, reported)

    out = []
    cache: dict[str, tuple[dict, set]] = {}
    for f in findings:
        if f.path not in cache:
            cache[f.path] = parse_suppressions(sources[f.path])
        per_line, per_file = cache[f.path]
        if not is_suppressed(f.rule, f.line, per_line, per_file, path=f.path):
            out.append(f)
    return out
