"""The degenerate-input gradient witness (graft-audit v4, runtime half).

The static layers argue a NaN *cannot* be emitted (R14/R15 over the
source, the J5 census over the backward jaxprs); this module *runs* the
contract: every grad-registered entry point is evaluated with
``jax.value_and_grad`` on forced-CPU against a committed corpus of
degenerate inputs — collinear and coincident P3P sets, zero-length rays,
zero-depth cells, identity and pi rotations, all-equal scores forcing
selection ties, and the all-dropped routed frame — asserting that every
output AND every gradient is finite.  This is the CLAUDE.md convention
("degenerate inputs produce finite garbage + a penalty, never control
flow") made executable, and the rail ROADMAP item 5 (closed-loop fleet
learning: gradients on the serving path) requires before it can land.

Design notes:

- **One compiled program per witness.**  Every corpus case shares the
  same tiny shapes (16 cells, 4 hypotheses, 2 experts), so each witness
  compiles once and the whole corpus replays through the cached program —
  the reason the witness rides tier-1 un-slow-marked.
- **The corpus is committed** (``.grad_corpus.json``) with plain-float
  JSON arrays (exact round-trip), and ``default_corpus()`` must match it
  exactly — a corpus edit is a reviewed diff, like the ledger.
- **Witness coverage is pinned**: tests assert the witness set covers
  exactly the ``grad=True`` registry entries (plus the routed drop-mask
  witness, whose -inf score output is a *designed* failure signal and is
  therefore excluded from its finiteness checks — only the pose and its
  gradients are asserted there).
- Forced CPU before any device use, like every lint layer (CLAUDE.md
  environment hazards).
"""

from __future__ import annotations

import json
import pathlib

GRAD_CORPUS_NAME = ".grad_corpus.json"

N_CELLS = 16
N_HYPS = 4
N_EXPERTS = 2

_PI = 3.141592653589793


def _force_cpu() -> None:
    # One force-CPU mechanism for the whole lint package (jaxpr_audit owns
    # the why: the env var is overridden by the container sitecustomize;
    # only the post-import config update sticks).  The witness does not
    # need the 8-device mesh, but sharing the helper keeps the guarantee
    # in one place.
    from esac_tpu.lint.jaxpr_audit import _force_cpu as _audit_force_cpu

    _audit_force_cpu()


# --------------------------------------------------------------------------
# the corpus

def _grid_coords() -> list:
    """Deterministic well-posed scene points (plain floats: exact JSON)."""
    return [
        [((i * 7) % N_CELLS) / 8.0 - 1.0,
         ((i * 5) % N_CELLS) / 8.0 - 1.0,
         1.5 + (i % 4) * 0.25]
        for i in range(N_CELLS)
    ]


def _grid_pixels() -> list:
    return [[(i % 4) * 16.0 + 8.0, (i // 4) * 12.0 + 6.0]
            for i in range(N_CELLS)]


def default_corpus() -> dict:
    """The canonical degenerate-input corpus.  Every case shares shapes
    (coords (16, 3), pixels (16, 2), scalar f, c (2,), rvec/tvec (3,))
    so each witness compiles exactly once across the whole corpus."""
    base = {
        "f": 60.0, "c": [32.0, 24.0],
        "rvec": [0.1, -0.05, 0.02], "tvec": [0.0, 0.0, 2.0],
        "tie_hypotheses": False, "kept": [True, True],
    }
    cases = {
        "collinear_p3p_triad": {
            **base,
            "description": "every sampled minimal set is collinear: the "
                           "triad frame's cross products vanish and the "
                           "P3P side lengths degenerate (penalty-branch "
                           "territory, SURVEY.md retry-on-bad-sample)",
            "coords": [[i * 0.1, i * 0.05, 1.0 + i * 0.02]
                       for i in range(N_CELLS)],
            "pixels": _grid_pixels(),
        },
        "coincident_points": {
            **base,
            "description": "all scene points AND all pixels identical: "
                           "zero difference vectors, zero norms, an "
                           "all-zero quartic, and every hypothesis "
                           "scoring exactly equal",
            "coords": [[0.5, -0.25, 1.0]] * N_CELLS,
            "pixels": [[32.0, 24.0]] * N_CELLS,
        },
        "zero_rays": {
            **base,
            "description": "every pixel sits exactly on the principal "
                           "point: bearing xy components are exactly 0 "
                           "(the safe_norm-guarded ray normalization's "
                           "edge)",
            "coords": _grid_coords(),
            "pixels": [[32.0, 24.0]] * N_CELLS,
        },
        "zero_depth_cells": {
            **base,
            "description": "scene points on the camera plane (z = 0 at "
                           "the identity pose): the MIN_DEPTH clamp and "
                           "the behind-camera penalty branch carry both "
                           "passes",
            "coords": [[((i * 7) % N_CELLS) / 8.0 - 1.0,
                        ((i * 5) % N_CELLS) / 8.0 - 1.0, 0.0]
                       for i in range(N_CELLS)],
            "pixels": _grid_pixels(),
            "rvec": [0.0, 0.0, 0.0], "tvec": [0.0, 0.0, 0.0],
        },
        "identity_rotation": {
            **base,
            "description": "exact-identity rotation: so3_log's theta -> 0 "
                           "limit and the small-angle Taylor blends, in "
                           "both passes",
            "coords": _grid_coords(),
            "pixels": _grid_pixels(),
            "rvec": [0.0, 0.0, 0.0],
        },
        "pi_rotation": {
            **base,
            "description": "rotation by exactly pi: so3_log's near-pi "
                           "outer-product branch with the skew part "
                           "exactly zero",
            "coords": _grid_coords(),
            "pixels": _grid_pixels(),
            "rvec": [_PI, 0.0, 0.0],
        },
        "tie_scores": {
            **base,
            "description": "all hypotheses identical (zero per-hypothesis "
                           "offsets): every score exactly equal, forcing "
                           "the argmax/streamed-select tie-break and a "
                           "flat selection softmax",
            "coords": _grid_coords(),
            "pixels": _grid_pixels(),
            "tie_hypotheses": True,
        },
        "all_dropped_routed": {
            **base,
            "description": "every routed slot capacity-dropped (kept all "
                           "False): the -inf score masking is the "
                           "DESIGNED failure signal, and the pose must "
                           "still be finite garbage with finite gradients",
            "coords": _grid_coords(),
            "pixels": _grid_pixels(),
            "kept": [False, False],
        },
    }
    return {
        "comment": "graft-audit v4 degenerate-input gradient corpus; see "
                   "LINT.md.  Every grad-registered entry must produce "
                   "all-finite outputs AND gradients on every case "
                   "(tests/test_gradsafety.py).  Regenerate only via "
                   "lint/gradcheck.py default_corpus() and review the "
                   "diff — a removed case un-pins a degeneracy class.",
        "cases": cases,
    }


def write_corpus(path: pathlib.Path, corpus: dict | None = None) -> None:
    corpus = corpus or default_corpus()
    path.write_text(json.dumps(corpus, indent=2, sort_keys=True) + "\n")


def load_corpus(path: pathlib.Path) -> dict | None:
    if not path.exists():
        return None
    return json.loads(path.read_text())


# --------------------------------------------------------------------------
# finiteness checking

def tree_all_finite(tree) -> bool:
    """Every float leaf finite (bool/int leaves are vacuously finite)."""
    import jax
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.asarray(leaf)
        if arr.dtype.kind in "fc" and not np.all(np.isfinite(arr)):
            return False
    return True


def check_case(fn, arrays: dict) -> dict:
    """Run one compiled witness on one corpus case -> verdict record.
    Shared by :func:`run_gradcheck` and the planted-NaN fixture test (the
    proof the witness CATCHES a violation rides the same code path)."""
    outputs, grads = fn(**arrays)
    return {
        "outputs_finite": tree_all_finite(outputs),
        "grads_finite": tree_all_finite(grads),
    }


def _case_arrays(case: dict) -> dict:
    import jax.numpy as jnp
    import numpy as np

    offs = np.zeros((N_HYPS, 3), np.float32)
    if not case.get("tie_hypotheses", False):
        # Fixed, deterministic per-hypothesis pose offsets: distinct
        # hypotheses in the generic cases, all-equal when the case forces
        # ties.
        offs = np.asarray(
            [[0.0, 0.0, 0.0], [0.02, -0.01, 0.005],
             [-0.03, 0.015, 0.0], [0.01, 0.02, -0.01]], np.float32
        )
    return {
        "coords": jnp.asarray(case["coords"], jnp.float32),
        "pixels": jnp.asarray(case["pixels"], jnp.float32),
        "f": jnp.float32(case["f"]),
        "c": jnp.asarray(case["c"], jnp.float32),
        "rvec": jnp.asarray(case["rvec"], jnp.float32),
        "tvec": jnp.asarray(case["tvec"], jnp.float32),
        "offs": jnp.asarray(offs),
        "kept": jnp.asarray(case.get("kept", [True, True])),
    }


# --------------------------------------------------------------------------
# witnesses: one per grad-registered entry (+ the routed drop-mask leg)

def _make_pnp_minimal_grad():
    import jax
    import jax.numpy as jnp

    from esac_tpu.geometry.pnp import solve_pnp_minimal

    @jax.jit
    def run(coords, pixels, f, c, rvec, tvec, offs, kept):
        X4, x4 = coords[:4], pixels[:4]

        def loss(X4, x4):
            rv, tv = solve_pnp_minimal(X4, x4, f, c, polish_iters=1)
            return jnp.sum(rv) + jnp.sum(tv), (rv, tv)

        (val, (rv, tv)), grads = jax.value_and_grad(
            loss, argnums=(0, 1), has_aux=True
        )(X4, x4)
        return {"rvec": rv, "tvec": tv, "loss": val}, grads

    return run


def _make_refine_soft_inliers_grad():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.refine import refine_soft_inliers

    @jax.jit
    def run(coords, pixels, f, c, rvec, tvec, offs, kept):
        def loss(coords, rvec, tvec):
            rv, tv = refine_soft_inliers(
                rvec, tvec, coords, pixels, f, c, tau=10.0, beta=0.5,
                iters=2,
            )
            return jnp.sum(rv) + jnp.sum(tv), (rv, tv)

        (val, (rv, tv)), grads = jax.value_and_grad(
            loss, argnums=(0, 1, 2), has_aux=True
        )(coords, rvec, tvec)
        return {"rvec": rv, "tvec": tv, "loss": val}, grads

    return run


def _make_dsac_train_loss_grad():
    import jax

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.kernel import dsac_train_loss

    cfg = RansacConfig(n_hyps=N_HYPS, train_refine_iters=1, polish_iters=1)

    @jax.jit
    def run(coords, pixels, f, c, rvec, tvec, offs, kept):
        from esac_tpu.geometry.rotations import rodrigues

        key = jax.random.key(0)
        R_gt = rodrigues(rvec)

        def loss(coords):
            val, aux = dsac_train_loss(
                key, coords, pixels, f, c, R_gt, tvec, cfg
            )
            return val, aux

        (val, aux), g = jax.value_and_grad(loss, has_aux=True)(coords)
        return {"loss": val, "scores": aux["scores"],
                "probs": aux["selection_probs"]}, {"coords": g}

    return run


def _make_scoring_grad(impl: str):
    def make():
        import jax
        import jax.numpy as jnp

        from esac_tpu.ransac.config import RansacConfig
        from esac_tpu.ransac.kernel import _score_hypotheses

        cfg = RansacConfig(n_hyps=N_HYPS, scoring_impl=impl, score_chunk=2)

        @jax.jit
        def run(coords, pixels, f, c, rvec, tvec, offs, kept):
            key = jax.random.key(1)
            rvecs = rvec[None, :] + offs
            tvecs = jnp.tile(tvec, (N_HYPS, 1))

            def loss(coords, rvecs, tvecs):
                scores = _score_hypotheses(
                    key, rvecs, tvecs, coords, pixels, f, c, cfg
                )
                return jnp.sum(scores), scores

            (val, scores), grads = jax.value_and_grad(
                loss, argnums=(0, 1, 2), has_aux=True
            )(coords, rvecs, tvecs)
            return {"loss": val, "scores": scores}, grads

        return run

    return make


def _make_scoring_fused_select_grad():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.pallas_scoring import soft_inlier_score_select

    @jax.jit
    def run(coords, pixels, f, c, rvec, tvec, offs, kept):
        from esac_tpu.geometry.rotations import rodrigues

        rvecs = rvec[None, :] + offs
        tvecs = jnp.tile(tvec, (N_HYPS, 1))

        def loss(coords, rvecs, tvecs):
            Rs = jax.vmap(rodrigues)(rvecs)
            best_i, best_s = soft_inlier_score_select(
                Rs, tvecs, coords, pixels, f, c, 10.0, 0.5,
                use_pallas=False, chunk=2,
            )
            return best_s, best_i

        (best_s, best_i), grads = jax.value_and_grad(
            loss, argnums=(0, 1, 2), has_aux=True
        )(coords, rvecs, tvecs)
        return {"best_score": best_s, "best_idx": best_i}, grads

    return run


def _make_esac_train_loss_dense_grad():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.esac import esac_train_loss

    cfg = RansacConfig(n_hyps=N_HYPS, train_refine_iters=1, polish_iters=1)

    @jax.jit
    def run(coords, pixels, f, c, rvec, tvec, offs, kept):
        from esac_tpu.geometry.rotations import rodrigues

        key = jax.random.key(2)
        R_gt = rodrigues(rvec)
        # Two experts sharing the SAME degenerate map: the cross-expert
        # selection ties exactly like the within-expert ones.
        coords_all = jnp.stack([coords, coords])
        logits = jnp.zeros((N_EXPERTS,))

        def loss(coords_all, logits):
            val, aux = esac_train_loss(
                key, logits, coords_all, pixels, f, c, R_gt, tvec, cfg,
                "dense",
            )
            return val, aux

        (val, aux), grads = jax.value_and_grad(
            loss, argnums=(0, 1), has_aux=True
        )(coords_all, logits)
        return {"loss": val, "per_expert_loss": aux["per_expert_loss"],
                "gating_probs": aux["gating_probs"]}, grads

    return run


def _make_routed_drop_mask():
    """The all-dropped-routed leg: NOT a grad-registered entry, but the
    corpus's routed case needs a consumer.  Only the POSE and its
    gradients are asserted finite — the -inf winner score of an
    all-dropped frame is the documented failure signal, not a violation
    (ransac/esac.esac_infer_routed_frames docstring)."""
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.esac import esac_infer_routed_frames

    cfg = RansacConfig(n_hyps=2, refine_iters=1, polish_iters=1,
                       score_chunk=2)
    # M = K = 2 keeps the compiled program minimal; esac_infer_routed_frames
    # is ONE code path regardless of K vs M, and the drop-mask semantics
    # under test (-inf masking, slot-0 fallback, finite pose + grads) are
    # K-independent.
    M, K = 2, 2

    @jax.jit
    def run(coords, pixels, f, c, rvec, tvec, offs, kept):
        keys = jax.random.split(jax.random.key(3), 1)
        logits = jnp.zeros((1, M))
        selected = jnp.asarray([[0, 1]], jnp.int32)
        kept_B = kept[None, :]
        pixels_B = pixels[None]
        f_B = f[None]

        def loss(coords_sel):
            out = esac_infer_routed_frames(
                keys, logits, coords_sel, selected, kept_B, pixels_B,
                f_B, c, cfg,
            )
            return jnp.sum(out["rvec"]) + jnp.sum(out["tvec"]), out

        coords_sel = jnp.stack([coords, coords + 0.1])[None]  # (1, K, N, 3)
        (val, out), g = jax.value_and_grad(loss, has_aux=True)(coords_sel)
        return {"rvec": out["rvec"], "tvec": out["tvec"],
                "loss": val}, {"coords_sel": g}

    return run


# Witness registry: name -> builder of one jitted run(case arrays) fn.
# The names `*_grad` must cover EXACTLY the grad=True registry entries
# (pinned by tests/test_gradsafety.py); `routed_drop_mask` is the extra
# leg the all_dropped_routed corpus case exists for.
WITNESSES: dict = {
    "pnp_minimal_grad": _make_pnp_minimal_grad,
    "refine_soft_inliers_grad": _make_refine_soft_inliers_grad,
    "dsac_train_loss_grad": _make_dsac_train_loss_grad,
    "scoring_errmap_grad": _make_scoring_grad("errmap"),
    "scoring_fused_grad": _make_scoring_grad("fused"),
    "scoring_fused_select_train_grad": _make_scoring_grad("fused_select"),
    "scoring_fused_select_grad": _make_scoring_fused_select_grad,
    "esac_train_loss_dense_grad": _make_esac_train_loss_dense_grad,
    "routed_drop_mask": _make_routed_drop_mask,
}


def run_gradcheck(corpus: dict | None = None,
                  witnesses: dict | None = None) -> dict:
    """Evaluate every witness against every corpus case on forced CPU.

    Returns the per-entry verdict block::

        {entry: {case: {"outputs_finite": bool, "grads_finite": bool}},
         ...,
         "clean": bool}

    One compiled program per witness (cases share shapes), so the whole
    sweep is tier-1-cheap.
    """
    _force_cpu()
    if corpus is None:
        corpus = default_corpus()
    witnesses = witnesses if witnesses is not None else WITNESSES
    verdicts: dict = {}
    clean = True
    for name, make in witnesses.items():
        fn = make()
        per_case: dict = {}
        for case_name, case in sorted(corpus["cases"].items()):
            v = check_case(fn, _case_arrays(case))
            per_case[case_name] = v
            clean = clean and v["outputs_finite"] and v["grads_finite"]
        verdicts[name] = per_case
    verdicts["clean"] = clean
    return verdicts
