"""Layer 1, R10: lock discipline over the serve-layer classes.

The dispatcher and the device weight cache are the two places where
threads genuinely race (PR 2-4): a worker thread coalesces requests while
submitters block for queue space, and dispatch workers fault weights into
the LRU cache while operators read stats.  Their correctness rests on one
convention — every piece of mutable shared state is touched only under the
instance lock — which until now was prose in docstrings and a couple of
regression tests.

R10 checks it structurally, per class in ``esac_tpu/serve/``,
``esac_tpu/registry/`` and ``esac_tpu/obs/`` (the metric instruments and
the unified registry are read by monitor threads while serving threads
publish — ISSUE 10 put them under the same discipline):

- **Locks**: instance attributes assigned ``threading.Lock()`` /
  ``RLock()`` in ``__init__``, plus ``threading.Condition(...)`` aliases —
  a Condition built over an existing lock *is* that lock (the dispatcher's
  ``_work``/``_space`` waiters share ``_lock``).
- **Access map**: every ``self.<attr>`` read/mutation in every method,
  classified *locked* (lexically inside ``with self.<lock>:``) or
  *unlocked*.  Mutations are attribute assignment/aug-assign/del,
  subscript stores, and calls of known mutating methods
  (``append``/``pop``/``clear``/``move_to_end``/…).
- **Helper propagation**: a private method whose every intra-class call
  site is locked is analyzed as lock-held (the ``_record``/
  ``_evict_to_budget`` "(lock held)" idiom), to a fixpoint.
- **Verdict**: an attribute that is *mutated* after ``__init__`` and has
  both locked and unlocked access sites is a finding at each unlocked
  site.  Attributes never mutated post-init (config, clocks, the infer
  fn) are exempt — unlocked reads of immutable state are the point of
  making it immutable.  Single-writer attributes with *no* locked sites
  (e.g. the worker handle, guarded by documented call-order) are not
  flagged either: R10 polices *inconsistent* discipline, where the code
  already says the lock protects the attribute and then skips it.

Pure ``ast`` — no imports of the checked modules, no jax.
"""

from __future__ import annotations

import ast
import pathlib

from esac_tpu.lint.findings import Finding
from esac_tpu.lint.suppress import is_suppressed, parse_suppressions

_MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "move_to_end", "sort", "reverse", "rotate",
}

_EXEMPT_METHODS = {"__init__", "__new__", "__del__"}


def _r10_scope(rel: str) -> bool:
    return rel.startswith(
        ("esac_tpu/serve/", "esac_tpu/registry/", "esac_tpu/obs/",
         "esac_tpu/fleet/", "esac_tpu/retrieval/")
    )


def _self_attr(node) -> str | None:
    """'attr' for ``self.attr`` expressions, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _lock_aliases(cls: ast.ClassDef) -> set[str]:
    """Attributes that hold the instance lock (or a Condition over it)."""
    locks: set[str] = set()
    init = next(
        (n for n in cls.body
         if isinstance(n, ast.FunctionDef) and n.name == "__init__"),
        None,
    )
    if init is None:
        return locks
    for node in ast.walk(init):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        attr = _self_attr(node.targets[0])
        if attr is None or not isinstance(node.value, ast.Call):
            continue
        func = node.value.func
        dotted = ""
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name):
            dotted = f"{func.value.id}.{func.attr}"
        elif isinstance(func, ast.Name):
            dotted = func.id
        if dotted in ("threading.Lock", "threading.RLock", "Lock", "RLock"):
            locks.add(attr)
        elif dotted in ("threading.Condition", "Condition"):
            # Condition(self.X) shares X; bare Condition() owns its lock.
            arg_attr = _self_attr(node.value.args[0]) if node.value.args \
                else None
            if arg_attr is None or arg_attr in locks:
                locks.add(attr)
    return locks


class _Access:
    __slots__ = ("attr", "mutates", "locked", "method", "lineno")

    def __init__(self, attr, mutates, locked, method, lineno):
        self.attr = attr
        self.mutates = mutates
        self.locked = locked
        self.method = method
        self.lineno = lineno


def _method_accesses(method: ast.FunctionDef, locks: set[str]):
    """-> (accesses, call_sites): attribute touches and intra-class method
    calls, each tagged with lexical lock state.  Nested function bodies are
    analyzed as UNLOCKED — a closure built under the lock runs later,
    possibly without it."""
    accesses: list[_Access] = []
    call_sites: list[tuple[str, bool]] = []  # (callee method, locked)

    def visit(node, locked):
        if isinstance(node, ast.With):
            holds = any(
                _self_attr(item.context_expr) in locks
                for item in node.items
            )
            for item in node.items:
                visit(item.context_expr, locked)
            for child in node.body:
                visit(child, locked or holds)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not method:
            # A closure built here runs later, possibly without the lock:
            # its body starts over as unlocked (an inner `with self._lock:`
            # still counts).
            for child in ast.iter_child_nodes(node):
                visit(child, False)
            return

        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(node, (ast.Assign, ast.Delete)) \
                else [node.target]
            for t in targets:
                base = t
                while isinstance(base, ast.Subscript):
                    base = base.value
                attr = _self_attr(base)
                if attr is not None:
                    accesses.append(
                        _Access(attr, True, locked, method.name, t.lineno)
                    )
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATING_METHODS:
                owner = _self_attr(node.func.value)
                if owner is not None:
                    accesses.append(_Access(
                        owner, True, locked, method.name, node.lineno
                    ))
            callee = _self_attr(node.func)
            if callee is not None:
                call_sites.append((callee, locked))
        attr = _self_attr(node)
        if attr is not None and \
                isinstance(getattr(node, "ctx", None), ast.Load):
            accesses.append(
                _Access(attr, False, locked, method.name, node.lineno)
            )
        for child in ast.iter_child_nodes(node):
            if isinstance(node, ast.Call) and child is node.func and \
                    isinstance(child, ast.Attribute) and \
                    _self_attr(child) is not None:
                continue  # self._helper(...) is a call site, not a touch
            visit(child, locked)

    for stmt in method.body:
        visit(stmt, False)
    return accesses, call_sites


def _analyze_class(rel, cls: ast.ClassDef, lines, per_line, per_file):
    locks = _lock_aliases(cls)
    if not locks:
        return []
    methods = [
        n for n in cls.body
        if isinstance(n, ast.FunctionDef)
    ]
    raw = {
        m.name: _method_accesses(m, locks) for m in methods
    }
    # Fixpoint: a private helper whose every intra-class call site is
    # locked is itself analyzed as lock-held.
    locked_ctx: set[str] = set()
    while True:
        changed = False
        sites: dict[str, list[bool]] = {}
        for caller, (_, call_sites) in raw.items():
            for callee, locked in call_sites:
                effective = locked or caller in locked_ctx
                sites.setdefault(callee, []).append(effective)
        for m in methods:
            name = m.name
            if name in locked_ctx or not name.startswith("_") or \
                    name.startswith("__"):
                continue
            if sites.get(name) and all(sites[name]):
                locked_ctx.add(name)
                changed = True
        if not changed:
            break

    by_attr: dict[str, list[_Access]] = {}
    for name, (accesses, _) in raw.items():
        if name in _EXEMPT_METHODS:
            continue
        for a in accesses:
            if a.attr in locks:
                continue
            if name in locked_ctx:
                a.locked = True
            by_attr.setdefault(a.attr, []).append(a)

    out = []
    for attr, accesses in sorted(by_attr.items()):
        if not any(a.mutates for a in accesses):
            continue  # immutable post-init: unlocked reads are the design
        locked_sites = [a for a in accesses if a.locked]
        unlocked_sites = [a for a in accesses if not a.locked]
        if not locked_sites or not unlocked_sites:
            continue  # consistent discipline (all-in or all-out)
        guarded_in = sorted({a.method for a in locked_sites})
        # One report per site: a mutating-method call also registers the
        # underlying attribute read — collapse to the mutation.
        by_site: dict[tuple, _Access] = {}
        for a in unlocked_sites:
            key = (a.method, a.lineno)
            prev = by_site.get(key)
            if prev is None or (a.mutates and not prev.mutates):
                by_site[key] = a
        for a in sorted(by_site.values(), key=lambda a: a.lineno):
            f = Finding(
                "R10", rel, a.lineno, _line(lines, a.lineno),
                f"{cls.name}.{attr} is "
                f"{'mutated' if a.mutates else 'read'} in {a.method}() "
                "without the instance lock, but the same attribute is "
                f"lock-guarded in {', '.join(guarded_in)}(): every access "
                "to lock-protected mutable state must hold the lock "
                "(serve-layer concurrency invariant)",
            )
            if not is_suppressed("R10", a.lineno, per_line, per_file,
                                 path=rel):
                out.append(f)
    return out


def _line(lines, lineno):
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


def run_concurrency_rules(root, files=None) -> list[Finding]:
    from esac_tpu.lint.ast_rules import iter_python_files

    root = pathlib.Path(root)
    findings: list[Finding] = []
    for rel in iter_python_files(root, files):
        if not _r10_scope(rel):
            continue
        try:
            source = (root / rel).read_text()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError):
            continue  # R0 comes from the main python pass
        lines = source.splitlines()
        per_line, per_file = parse_suppressions(source)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                findings += _analyze_class(
                    rel, node, lines, per_line, per_file
                )
    return findings
