"""graft-lint: machine-checked TPU-safety invariants for this repo.

The correctness of esac_tpu rests on a catalog of invariants that used to
live only as prose (CLAUDE.md conventions, DESIGN.md, the SURVEY.md
behavioral spec): grad-safe geometry via ``safe_norm``/``safe_sqrt``,
precision pinned through ``hmm``/``heinsum``, no import-time device init,
no scalar-looping linalg in vmapped hot paths, force-CPU guards in ad-hoc
scripts, and never ``timeout``/``kill`` on a jax-on-TPU process.  This
package checks them statically, in two layers:

- **Layer 1** (:mod:`esac_tpu.lint.ast_rules`, :mod:`~.concurrency`,
  :mod:`~.shell_rules`): pure-AST rules R1-R6 plus the graft-audit v2
  rules — R8 donation safety, R9 retrace safety, R10 serve-layer lock
  discipline, R11 jaxpr-audit registry coverage — and a line rule R7 over
  shell scripts.  No jax import, runs in well under a second.
- **Layer 2** (:mod:`esac_tpu.lint.jaxpr_audit`): jit-traces a registry of
  real entry points on the CPU backend and audits the jaxprs themselves —
  disallowed primitives, dynamic shapes, unpinned ``dot_general`` precision.
- **Layer 2b** (:mod:`esac_tpu.lint.ledger`): the jaxpr resource ledger —
  per-entry flops / peak intermediate bytes / dot-precision census over
  the same traces, diffed against the committed ``.jaxpr_ledger.json``
  (J4 regression gate; ``--write-ledger`` to regenerate).

Run ``python -m esac_tpu.lint`` (full tree) or ``--changed`` (git-diff
scoped); ``--format json`` emits stable one-object-per-line findings for
drivers.  Rules support inline ``# graft-lint: disable=RULE(reason)``
suppressions and a committed ``lint_baseline.json`` for grandfathered
findings.  See LINT.md for the rule catalog and workflow.
"""

from esac_tpu.lint.findings import Finding, RULES
from esac_tpu.lint.ast_rules import run_python_rules, run_registry_coverage
from esac_tpu.lint.concurrency import run_concurrency_rules
from esac_tpu.lint.faultflow import run_faultflow_rules
from esac_tpu.lint.gradsafety import run_gradsafety_rules
from esac_tpu.lint.lockgraph import run_lock_rules
from esac_tpu.lint.shell_rules import run_shell_rules
from esac_tpu.lint.suppress import Baseline, filter_suppressed

__all__ = [
    "Finding",
    "RULES",
    "run_python_rules",
    "run_shell_rules",
    "run_concurrency_rules",
    "run_faultflow_rules",
    "run_gradsafety_rules",
    "run_lock_rules",
    "run_registry_coverage",
    "Baseline",
    "filter_suppressed",
    "run_layer1",
]


def run_layer1(root, files=None):
    """All layer-1 findings for the tree at ``root`` (inline suppressions
    already applied, baseline NOT applied — callers decide).  Includes the
    serve-layer concurrency rules (R10), the registry coverage gate
    (R11, tree-global whenever package files are in scope), and the
    graft-audit v3 fleet concurrency analysis (R12 lock-order cycles /
    self-deadlocks + R13 blocking-under-lock; the committed
    .lock_graph.json DIFF gate rides the CLI, ledger-style), and the
    graft-audit v4 grad-safety dataflow pass (R14 unguarded domain-edge
    primitives + R15 where-VJP trap over the differentiated
    geometry/ransac/train scope; its jaxpr-level sibling J5 rides the
    ledger), and the graft-audit v5 fault-flow pass (R16 untyped raise /
    taxonomy contract + R17 exception swallowing + R18 thread/future
    lifecycle over fleet scope; the committed .fault_taxonomy.json DIFF
    gate rides the CLI, ledger-style).  The lock and fault-flow passes
    are fleet-global but skipped when a scoped run touched no
    serve/registry/obs/fleet/lint file, and the grad pass likewise
    skips unless a geometry/ransac/train/lint file changed (--changed
    fast mode)."""
    findings = run_python_rules(root, files=files)
    findings += run_shell_rules(root, files=files)
    findings += run_concurrency_rules(root, files=files)
    findings += run_lock_rules(root, files=files)
    findings += run_faultflow_rules(root, files=files)
    findings += run_gradsafety_rules(root, files=files)
    findings += run_registry_coverage(root, files=files)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
