"""graft-lint: machine-checked TPU-safety invariants for this repo.

The correctness of esac_tpu rests on a catalog of invariants that used to
live only as prose (CLAUDE.md conventions, DESIGN.md, the SURVEY.md
behavioral spec): grad-safe geometry via ``safe_norm``/``safe_sqrt``,
precision pinned through ``hmm``/``heinsum``, no import-time device init,
no scalar-looping linalg in vmapped hot paths, force-CPU guards in ad-hoc
scripts, and never ``timeout``/``kill`` on a jax-on-TPU process.  This
package checks them statically, in two layers:

- **Layer 1** (:mod:`esac_tpu.lint.ast_rules`, :mod:`~.shell_rules`):
  pure-AST rules R1-R6 over Python sources plus a line rule R7 over shell
  scripts.  No jax import, runs in well under a second.
- **Layer 2** (:mod:`esac_tpu.lint.jaxpr_audit`): jit-traces a registry of
  real entry points on the CPU backend and audits the jaxprs themselves —
  disallowed primitives, dynamic shapes, unpinned ``dot_general`` precision.

Run ``python -m esac_tpu.lint`` (full tree) or ``--changed`` (git-diff
scoped).  Rules support inline ``# graft-lint: disable=RULE(reason)``
suppressions and a committed ``lint_baseline.json`` for grandfathered
findings.  See LINT.md for the rule catalog and workflow.
"""

from esac_tpu.lint.findings import Finding, RULES
from esac_tpu.lint.ast_rules import run_python_rules
from esac_tpu.lint.shell_rules import run_shell_rules
from esac_tpu.lint.suppress import Baseline, filter_suppressed

__all__ = [
    "Finding",
    "RULES",
    "run_python_rules",
    "run_shell_rules",
    "Baseline",
    "filter_suppressed",
    "run_layer1",
]


def run_layer1(root, files=None):
    """All layer-1 findings for the tree at ``root`` (inline suppressions
    already applied, baseline NOT applied — callers decide)."""
    findings = run_python_rules(root, files=files)
    findings += run_shell_rules(root, files=files)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
