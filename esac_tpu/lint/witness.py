"""Runtime witnesses: the dynamic halves of the committed-artifact gates.

Two witnesses live here — :class:`LockWitness` (graft-audit v3, the
dynamic half of R12/R13 vs ``.lock_graph.json``) and
:class:`OutcomeWitness` (graft-audit v5, the dynamic half of R16 vs
``.fault_taxonomy.json``: every error type a drill observes must be a
committed taxonomy member, and every observed (error type, outcome)
pair must ride a committed raise->outcome edge).  Both follow the same
contract: production code never imports this module; tests and benches
attach a witness, run the fleet, and assert against the committed
artifact.

Runtime lock witness (graft-audit v3): the dynamic half of R12/R13.

The static pass (:mod:`esac_tpu.lint.lockgraph`) derives the fleet's
lock-acquisition partial order from the AST; this module checks the
order the fleet ACTUALLY takes at runtime.  A :class:`LockWitness`
wraps the fleet's ``threading.Lock`` objects (Conditions are rebuilt
over the wrapped lock, so the dispatcher's ``_work``/``_space`` aliases
keep sharing one lock) and records:

- **acquisition edges** — every time a thread acquires lock B while
  holding lock A, keyed by the static node ids (``Class.attr``,
  instance-collapsed), so :meth:`violations` can assert the observed
  edge set is a subgraph of the committed ``.lock_graph.json`` order
  (its transitive closure — the committed file is a partial order, not
  an adjacency requirement);
- **hold times** — per-node streaming histograms
  (:class:`~esac_tpu.obs.metrics.StreamingHistogram`, the same bounded
  sketch the serving fleet uses), published into an obs registry via
  :meth:`bind_obs` as the ``lock_witness`` collector;
- **blocked-while-held events** — an acquire that had to wait more than
  ``blocked_threshold_s`` while the thread already held another
  witnessed lock: the runtime shadow of an R13 finding.

**Zero overhead when off** is structural, not a fast path: production
code never imports this module and never sees a wrapped lock — the
witness is attached by tests/benches, AFTER construction and BEFORE any
worker thread starts (attaching while a thread waits on the old lock
object would strand it).  ``MicroBatchDispatcher(start_worker=False)``
+ ``attach`` + ``start()`` is the pattern; the tier-1 concurrency
stress legs (tests/test_serve.py, tests/test_obs.py) and ``python
bench.py chaos`` ride it.

The witness's own bookkeeping lock is deliberately NOT witnessed, and
all recording happens without taking any witnessed lock — observing
the fleet must not add edges to it.
"""

from __future__ import annotations

import collections
import threading
import time

from esac_tpu.obs.metrics import StreamingHistogram


class WitnessLock:
    """Proxy around a ``threading.Lock`` that reports to a witness.

    Implements the lock protocol ``threading.Condition`` relies on
    (``acquire``/``release``/context manager; no ``_release_save`` /
    ``_is_owned`` overrides, so Condition falls back to plain
    release/acquire through THIS proxy and the witness sees a
    coalescing wait as release -> reacquire, exactly what happens)."""

    __slots__ = ("_raw", "_witness", "name")

    def __init__(self, raw, name: str, witness: "LockWitness"):
        self._raw = raw
        self.name = name
        self._witness = witness

    def acquire(self, blocking=True, timeout=-1):
        t0 = time.perf_counter()
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            self._witness._acquired(self.name, time.perf_counter() - t0)
        return ok

    def release(self):
        self._witness._released(self.name)
        self._raw.release()

    def locked(self):
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<WitnessLock {self.name} over {self._raw!r}>"


class LockWitness:
    """Records acquisition edges, hold times, and blocked-while-held
    events across every lock wrapped through :meth:`wrap`/:meth:`attach`
    (see the module docstring for the attach-before-start contract)."""

    def __init__(self, blocked_threshold_s: float = 1e-3):
        self._mu = threading.Lock()   # witness-internal; never witnessed
        self._tls = threading.local()
        self._edges: collections.Counter = collections.Counter()
        self._holds: dict[str, StreamingHistogram] = {}
        self._blocked: collections.deque = collections.deque(maxlen=1000)
        self._thresh = blocked_threshold_s

    # ---- wrapping ----

    def wrap(self, raw, name: str) -> WitnessLock:
        if isinstance(raw, WitnessLock):
            return raw
        return WitnessLock(raw, name, self)

    def attach(self, obj, *attrs) -> "LockWitness":
        """Wrap ``obj.<attr>`` in place for each attr, naming the node
        ``type(obj).__name__ + '.' + attr`` — the SAME id the static
        graph uses, instance-collapsed.  Conditions on the instance that
        wrap the raw lock are rebuilt over the proxy, so aliases keep
        aliasing.  Idempotent.  Attach before any thread can hold or
        wait on the lock."""
        for attr in attrs:
            raw = getattr(obj, attr)
            if isinstance(raw, WitnessLock):
                continue
            wrapped = self.wrap(raw, f"{type(obj).__name__}.{attr}")
            setattr(obj, attr, wrapped)
            try:
                items = list(vars(obj).items())
            except TypeError:  # __slots__ classes carry no Conditions here
                items = []
            for other, val in items:
                if isinstance(val, threading.Condition) and \
                        val._lock is raw:
                    setattr(obj, other, threading.Condition(wrapped))
        return self

    def attach_obs(self, metrics) -> "LockWitness":
        """Wrap a :class:`~esac_tpu.obs.MetricsRegistry`'s own lock plus
        every registered instrument's lock, every EXISTING histogram
        child's, and — when attached (ISSUE 15) — the trace store's,
        the timeline's and the rule engine's leaf locks.  Children
        created after attach stay unwrapped — their acquisitions simply
        go unobserved, which only shrinks the observed set (the
        subgraph check is one-sided)."""
        self.attach(metrics, "_lock")
        for inst in list(metrics._metrics.values()):
            self.attach(inst, "_lock")
            for child in list(getattr(inst, "_children", {}).values()):
                self.attach(child, "_lock")
        for attachment in (metrics._trace_store, metrics._timeline,
                           metrics._health_rules):
            if attachment is not None:
                self.attach(attachment, "_lock")
        return self

    def attach_fleet(self, disp=None, registry=None, injector=None,
                     prefetcher=None, router=None,
                     session_router=None) -> "LockWitness":
        """One-call wiring for the shipped fleet shapes: a
        MicroBatchDispatcher (lock + conditions + its obs instruments),
        a SceneRegistry (health/program locks, manifest, weight cache +
        its host tier when attached, its obs registry), a
        WeightPrefetcher, a FleetRouter (ISSUE 14 — its lock, its obs
        registry, and every replica's dispatcher + registry + a tagged
        FaultInjector infer fn; attach BEFORE ``router.start()``, the
        same contract as the dispatcher worker), and optionally a
        FaultInjector.  The
        attach-before-start contract is ENFORCED for the prefetcher: an
        explicitly passed one whose thread is already running raises
        (rebuilding its Condition would strand the live waiter); an
        auto-discovered running one is skipped silently — the subgraph
        check is one-sided, an unwitnessed lock only shrinks the
        observed set."""
        if registry is not None:
            self.attach(registry, "_health_lock", "_fns_lock")
            self.attach(registry.manifest, "_lock")
            self.attach(registry.cache, "_lock")
            if getattr(registry.cache, "tier", None) is not None:
                self.attach(registry.cache.tier, "_lock")
            auto_pf = getattr(registry, "_prefetcher", None)
            if auto_pf is not None and prefetcher is None \
                    and not self._thread_running(auto_pf):
                prefetcher = auto_pf
            self.attach_obs(registry.obs)
        if prefetcher is not None:
            if self._thread_running(prefetcher):
                raise ValueError(
                    "attach the witness BEFORE the prefetcher starts "
                    "(attach_prefetcher(start=False) -> attach_fleet -> "
                    "start()): wrapping a live thread's lock rebuilds "
                    "its Condition under the waiter and strands it"
                )
            self.attach(prefetcher, "_lock")
        if disp is not None:
            self.attach(disp, "_lock")
            self.attach_obs(disp.obs)
        if injector is not None:
            self.attach(injector, "_lock")
        if router is not None:
            self.attach(router, "_lock")
            self.attach_obs(router.obs)
            for rep in router._replicas.values():
                self.attach_fleet(
                    disp=rep.dispatcher,
                    registry=getattr(rep, "registry", None),
                )
                infer = getattr(rep.dispatcher, "_infer", None)
                if infer is not None and hasattr(infer, "_lock") and \
                        hasattr(infer, "stall_once"):
                    self.attach(infer, "_lock")  # a tagged FaultInjector
            front = getattr(router, "_retrieval", None)
            if front is not None:
                # ISSUE 18: the retrieval front + its scene index are
                # LEAF locks (taken sequentially, never nested under
                # each other or the router lock).
                self.attach(front, "_lock")
                idx = getattr(front, "_index", None)
                if idx is not None and hasattr(idx, "_lock"):
                    self.attach(idx, "_lock")
        if session_router is not None:
            # ISSUE 20: the session table is a committed LEAF lock —
            # plan/observe snapshot under it, every dispatch and result
            # wait happens outside (R13), so no edge may ever appear.
            self.attach(session_router.table, "_lock")
        return self

    @staticmethod
    def _thread_running(obj) -> bool:
        t = getattr(obj, "_thread", None)
        return t is not None and t.is_alive()

    # ---- recording (called from WitnessLock; no witnessed lock taken) ----

    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _acquired(self, name: str, waited_s: float) -> None:
        st = self._stack()
        if st:
            held = [h for h, _ in st]
            with self._mu:
                for h in held:
                    self._edges[(h, name)] += 1
                if waited_s >= self._thresh:
                    self._blocked.append({
                        "held": held, "wanted": name,
                        "waited_s": round(waited_s, 6),
                    })
        st.append((name, time.perf_counter()))

    def _released(self, name: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == name:
                _, t0 = st.pop(i)
                hold = time.perf_counter() - t0
                with self._mu:
                    h = self._holds.get(name)
                    if h is None:
                        h = self._holds[name] = StreamingHistogram()
                h.observe(hold)
                return
        # Release with no recorded acquire: the lock was taken before
        # attach. Ignore — bookkeeping starts at the first clean acquire.

    # ---- reading ----

    def edges(self) -> dict[tuple[str, str], int]:
        with self._mu:
            return dict(self._edges)

    def hold_summary(self) -> dict[str, dict]:
        with self._mu:
            holds = dict(self._holds)
        return {name: holds[name].summary() for name in sorted(holds)}

    def blocked_events(self) -> list[dict]:
        with self._mu:
            return [dict(e) for e in self._blocked]

    def snapshot(self) -> dict:
        """The ``lock_witness`` obs collector payload: observed edges,
        per-lock hold-time summaries, blocked-while-held events."""
        return {
            "edges": {f"{s}->{d}": n for (s, d), n in
                      sorted(self.edges().items())},
            "holds": self.hold_summary(),
            "blocked_while_held": self.blocked_events(),
        }

    def bind_obs(self, metrics, name: str = "lock_witness") -> None:
        """Publish hold-time histograms + observed edges into an obs
        registry as a pull collector (DESIGN.md §14 pattern)."""
        metrics.register_collector(name, self.snapshot)

    # ---- the gate ----

    def violations(self, committed_graph: dict) -> list[str]:
        """Observed edges NOT sanctioned by the committed partial order
        (its transitive closure).  Node ids absent from the committed
        graph are violations too — an unmodeled lock in the nest means
        the static graph is stale."""
        from esac_tpu.lint.lockgraph import transitive_closure

        allowed = transitive_closure(committed_graph.get("edges", []))
        nodes = committed_graph.get("nodes", {})
        out = []
        for (src, dst), n in sorted(self.edges().items()):
            if src not in nodes or dst not in nodes:
                out.append(
                    f"{src}->{dst} (x{n}): lock(s) missing from the "
                    "committed graph nodes"
                )
            elif src == dst and nodes[src].get("kind") == "RLock":
                continue  # reentrant re-acquisition: the static pass
                #           sanctions it ('reentrant by design'), so the
                #           runtime check must not call it a violation
            elif (src, dst) not in allowed:
                out.append(
                    f"{src}->{dst} (x{n}): acquisition order not in the "
                    "committed .lock_graph.json partial order"
                )
        return out

    def assert_subgraph(self, committed_graph: dict) -> None:
        v = self.violations(committed_graph)
        if v:
            raise AssertionError(
                "observed lock acquisitions escape the committed order "
                "(regenerate + review .lock_graph.json if intentional):\n"
                + "\n".join(v)
            )


class OutcomeWitness:
    """Runtime outcome witness (graft-audit v5): holds every error type
    and (error type, outcome) pair a drill observes to the committed
    ``.fault_taxonomy.json``.

    The static pass (:mod:`esac_tpu.lint.faultflow`) proves each
    taxonomy error is DISPOSED somewhere — mapped to an accounted
    outcome class via a typed handler, a recorder call, or a broad
    accounting backstop.  This witness checks the same contract on the
    trail a real run leaves behind: ``bench.py chaos`` and the fleet
    drill feed it the loadgen's ``per_request_outcomes`` /
    ``per_request_error_types`` arrays, and :meth:`violations` reports

    - an observed error type that is NOT a committed taxonomy member
      (someone minted outside the closed catalog — the runtime shadow
      of an R16 finding), and
    - an observed (error type, outcome) pair outside the committed
      effective edges (direct + taxonomy-ancestor edges + the wildcard
      backstop: :func:`esac_tpu.lint.faultflow.effective_outcomes`) —
      a disposal path the static map does not know about, or an
      outcome string outside the closed vocabulary.

    Requests that finished without an error (``error_type`` None) only
    have their outcome checked against the vocabulary.  Like the lock
    witness, the check is one-sided: a committed edge no drill happens
    to take is stale-report territory for the static differ, never a
    runtime violation."""

    def __init__(self, taxonomy: dict):
        from esac_tpu.lint.faultflow import effective_outcomes

        self._taxonomy = taxonomy
        self._effective = effective_outcomes(taxonomy)
        self._vocabulary = tuple(taxonomy.get("outcome_classes", ()))
        self._mu = threading.Lock()
        self._pairs: collections.Counter = collections.Counter()
        self._error_free: collections.Counter = collections.Counter()

    @classmethod
    def from_repo(cls, root) -> "OutcomeWitness":
        """Build from the committed artifact at ``root`` (raises if it
        is missing — a drill without a committed taxonomy is exactly
        the gap the gate exists to close)."""
        import pathlib

        from esac_tpu.lint.faultflow import FAULT_TAXONOMY_NAME, load_taxonomy

        taxonomy = load_taxonomy(pathlib.Path(root) / FAULT_TAXONOMY_NAME)
        if taxonomy is None:
            raise FileNotFoundError(
                f"no committed {FAULT_TAXONOMY_NAME} under {root}; run "
                "`python -m esac_tpu.lint --write-fault-taxonomy`"
            )
        return cls(taxonomy)

    # ---- recording ----

    def observe(self, error_type: str | None, outcome: str) -> None:
        with self._mu:
            if error_type:
                self._pairs[(error_type, outcome)] += 1
            else:
                self._error_free[outcome] += 1

    def observe_run(self, result: dict) -> "OutcomeWitness":
        """Consume one loadgen summary dict (``run_open_loop`` /
        ``FleetRouter`` drill shape): zips ``per_request_outcomes``
        against ``per_request_error_types``."""
        outcomes = result.get("per_request_outcomes", ())
        err_types = result.get("per_request_error_types", ())
        for outcome, err in zip(outcomes, err_types):
            self.observe(err, outcome)
        return self

    # ---- reading / the gate ----

    def pairs(self) -> dict[tuple[str, str], int]:
        with self._mu:
            return dict(self._pairs)

    def violations(self) -> list[str]:
        with self._mu:
            pairs = dict(self._pairs)
            error_free = dict(self._error_free)
        out = []
        for (err, outcome), n in sorted(pairs.items()):
            if err not in self._effective:
                out.append(
                    f"{err} (x{n}): observed error type is not a member "
                    "of the committed fault taxonomy"
                )
            elif outcome not in self._effective[err]:
                out.append(
                    f"{err}->{outcome} (x{n}): observed pair rides no "
                    "committed raise->outcome edge (direct, inherited, "
                    "or wildcard)"
                )
        for outcome, n in sorted(error_free.items()):
            if outcome not in self._vocabulary:
                out.append(
                    f"(no error)->{outcome} (x{n}): outcome outside the "
                    "committed vocabulary"
                )
        return out

    def snapshot(self) -> dict:
        """The ``fault_taxonomy`` obs collector / artifact block:
        observed per-(error, outcome) counts, the violation list, and
        the committed catalog size the run was held to."""
        with self._mu:
            pairs = dict(self._pairs)
            error_free = dict(self._error_free)
        return {
            "observed": {f"{e}->{o}": n for (e, o), n in
                         sorted(pairs.items())},
            "error_free_outcomes": {o: n for o, n in
                                    sorted(error_free.items())},
            "violations": self.violations(),
            "committed_errors": len(self._taxonomy.get("errors", {})),
            "committed_edges": len(self._taxonomy.get("edges", [])),
        }

    def bind_obs(self, metrics, name: str = "fault_taxonomy") -> None:
        """Publish the observed error->outcome trail into an obs
        registry as a pull collector (the DESIGN.md §14 pattern the
        lock witness uses)."""
        metrics.register_collector(name, self.snapshot)

    def assert_consistent(self) -> None:
        v = self.violations()
        if v:
            raise AssertionError(
                "observed fault flow escapes the committed taxonomy "
                "(regenerate + review .fault_taxonomy.json if "
                "intentional):\n" + "\n".join(v)
            )
