"""Layer 1c, R12/R13: whole-fleet concurrency analysis (graft-audit v3).

R10 (:mod:`esac_tpu.lint.concurrency`) answers "is guarded state touched
unlocked?" one class at a time.  This module answers the two questions
R10 cannot: **can the fleet's locks deadlock?** (R12) and **does anything
block or take unbounded time while holding one?** (R13).  The fleet now
holds five interacting lock domains — the dispatcher lock (with its
``_work``/``_space`` Condition aliases), the registry health + program
locks, the weight-cache lock, the manifest lock, and the obs instrument
locks — and every concurrency bug shipped so far was found by hand in
review; this pass makes the lock map a committed, diffed artifact
instead.

**The model.**  Pure AST over ``esac_tpu/{serve,registry,obs}/``:

- **Lock nodes**: one node per ``(class, lock attribute)``, where lock
  attributes are ``threading.Lock``/``RLock`` assignments in
  ``__init__`` and ``threading.Condition`` aliases collapse onto the
  lock they wrap (the dispatcher's ``_work``/``_space`` ARE ``_lock`` —
  two names, one node; a bare ``Condition()`` owns its lock).  Nodes are
  per-class, instance-collapsed: every ``CounterVec`` shares one node,
  which is exactly the granularity a lock ORDER lives at.
- **May-held propagation**: for every method, helper, closure and
  module-level function, the set of locks that MAY be held when it runs
  — lexical ``with self.<lock>:`` state unioned, through a fixpoint,
  into every resolvable callee (``self._helper()``, typed-attribute
  calls like ``self.cache.get(...)``, annotation-resolved chains like
  ``self._child(labels).observe(v)``, cross-module function calls).
  Types come from ``__init__`` constructor calls, parameter/return
  annotations, and known-class constructors — unresolvable calls
  under-approximate rather than false-positive (same contract as R3/R8).
  Closures start over as held-∅ (a closure built under the lock runs
  later — the R10 convention).
- **R12 — lock-order graph**: acquiring lock B while (possibly) holding
  A is the edge A→B.  The canonical edge set is committed as
  ``.lock_graph.json``; a cycle, a re-acquisition of a non-reentrant
  lock, or an edge missing from the committed file fails the lint
  (unreviewed new edge → regenerate with ``--write-lock-graph`` +
  review; an edge that DISAPPEARED is reported stale, J4-style).
- **R13 — blocking-under-lock**: a call from the blocking catalog —
  ``Event.wait``/``Condition.wait``, ``Future.result``, ``.join``,
  ``time.sleep``, file IO / checkpoint loads, jax device sync
  (``block_until_ready``, ``np.asarray`` on device trees) — reached
  with any lock held is a finding.  The one allowlisted idiom is the
  coalescing wait: ``Condition.wait`` where the condition aliases the
  ONLY held lock *releases* that lock for the duration, which is the
  whole point of the dispatcher's design; waiting on a condition while
  holding a SECOND lock still flags.  Reviewed exceptions use the
  normal ``# graft-lint: disable=R13(reason)`` inline suppression.

The runtime side is :mod:`esac_tpu.lint.witness`: an opt-in wrapper
around the fleet's lock objects that records the edges ACTUALLY taken
under the tier-1 concurrency stress legs and the chaos drill and asserts
they are a subgraph of the committed order.

Pure stdlib — no jax, no imports of the checked modules.
"""

from __future__ import annotations

import ast
import json
import pathlib

from esac_tpu.lint.ast_rules import _alias_map, _dotted, iter_python_files
from esac_tpu.lint.findings import Finding
from esac_tpu.lint.suppress import is_suppressed, parse_suppressions

LOCK_GRAPH_NAME = ".lock_graph.json"

# The fleet scope the graph covers (ISSUE 14 added the replica-fleet
# scheduler tier, whose router lock nests over the obs instruments;
# ISSUE 18 the retrieval front-end, whose front/index locks are LEAVES)...
FLEET_PREFIXES = ("esac_tpu/serve/", "esac_tpu/registry/", "esac_tpu/obs/",
                  "esac_tpu/fleet/", "esac_tpu/retrieval/")
# ...and what triggers the pass in --changed mode (the analysis itself
# rides in esac_tpu/lint/, so editing it must re-run the gate).
PASS_PREFIXES = FLEET_PREFIXES + ("esac_tpu/lint/",)


def lock_pass_needed(files) -> bool:
    """Mirror of cli._audit_needed for the lock-graph pass: full runs
    always analyze; scoped runs only when a fleet or lint file changed."""
    if files is None:
        return True
    return any(
        f.startswith(PASS_PREFIXES) and f.endswith(".py") for f in files
    )


# --------------------------------------------------------------------------
# the blocking catalog (R13)

# Dotted-name calls that block/sync regardless of receiver type.
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep parks the thread",
    "jax.block_until_ready": "jax device sync waits for in-flight compute",
    "jax.device_get": "jax device transfer waits for in-flight compute",
    "numpy.asarray": "np.asarray on a device tree is an implicit device "
                     "sync",
    "jax.numpy.asarray": "jnp.asarray can devolve to a device transfer",
}
# Bare-name calls (registry/checkpoint IO — the 29ms..seconds cold-load
# class) and plain file IO.
_BLOCKING_NAMES = {
    "load_checkpoint": "checkpoint read (the cold-load IO path)",
    "save_checkpoint": "checkpoint write",
    "load_scene_params": "scene weight load (retrying checkpoint IO)",
    "open": "file IO",
}
_IO_ATTRS = {"read_text", "write_text", "read_bytes", "write_bytes"}
# Receivers whose .join is a path join, not a thread join.
_JOIN_EXEMPT_PREFIXES = ("os.", "posixpath.", "ntpath.", "str.")

_GENERIC_CONTAINERS = {
    "list", "List", "dict", "Dict", "tuple", "Tuple", "set", "Set",
    "frozenset", "deque", "Sequence", "Iterable", "Iterator", "Mapping",
}


# --------------------------------------------------------------------------
# per-class facts

def _self_attr(node) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _Class:
    def __init__(self, rel: str, node: ast.ClassDef, aliases: dict):
        self.rel = rel
        self.name = node.name
        self.node = node
        self.aliases = aliases
        self.methods: dict[str, ast.FunctionDef] = {
            n.name: n for n in node.body if isinstance(n, ast.FunctionDef)
        }
        # lock attr -> root lock attr (Condition aliases collapse);
        # root attr -> kind ("Lock" | "RLock" | "Condition").
        self.lock_roots: dict[str, str] = {}
        self.lock_kinds: dict[str, str] = {}
        self._collect_locks()
        self.attr_types: dict[str, str] = {}       # filled by _Analysis
        self.method_returns: dict[str, str] = {}   # filled by _Analysis

    def _collect_locks(self) -> None:
        init = self.methods.get("__init__")
        if init is None:
            return
        for node in ast.walk(init):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            attr = _self_attr(node.targets[0])
            if attr is None or not isinstance(node.value, ast.Call):
                continue
            dotted = _dotted(node.value.func, self.aliases) or ""
            base = dotted.rpartition(".")[2]
            if dotted in ("threading.Lock", "threading.RLock") or \
                    (dotted == base and base in ("Lock", "RLock")):
                self.lock_roots[attr] = attr
                self.lock_kinds[attr] = base
            elif dotted == "threading.Condition" or \
                    (dotted == base and base == "Condition"):
                arg = node.value.args[0] if node.value.args else None
                wrapped = _self_attr(arg) if arg is not None else None
                if wrapped is not None and wrapped in self.lock_roots:
                    # Condition(self.X) IS lock X: one node, two names.
                    self.lock_roots[attr] = self.lock_roots[wrapped]
                else:
                    self.lock_roots[attr] = attr
                    self.lock_kinds[attr] = "Condition"

    def node_id(self, attr: str) -> str:
        return f"{self.name}.{self.lock_roots[attr]}"


def _ann_class(ann, known: dict) -> str | None:
    """Class name named by an annotation, if exactly one known class.

    ``X``, ``"X"``, ``X | None``, ``Optional[X]`` resolve; container
    annotations (``list[X]``…) deliberately do NOT — a list of X is not
    an X, and typing it as one would fabricate call edges."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.strip().strip("'\"")
        return name if name in known else None
    if isinstance(ann, ast.Name):
        return ann.id if ann.id in known else None
    if isinstance(ann, ast.Attribute):
        return ann.attr if ann.attr in known else None
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        hits = {c for c in (_ann_class(ann.left, known),
                            _ann_class(ann.right, known)) if c}
        return hits.pop() if len(hits) == 1 else None
    if isinstance(ann, ast.Subscript):
        base = ann.value
        base_name = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else None
        )
        if base_name in _GENERIC_CONTAINERS:
            return None
        if base_name in ("Optional", "Union", "Annotated"):
            sl = ann.slice
            elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
            hits = {c for c in (_ann_class(e, known) for e in elts) if c}
            return hits.pop() if len(hits) == 1 else None
        return None
    return None


# --------------------------------------------------------------------------
# the analysis

class _CallableInfo:
    __slots__ = ("key", "rel", "cls", "label", "acquisitions", "blocking",
                 "calls")

    def __init__(self, key, rel, cls, label):
        self.key = key
        self.rel = rel
        self.cls = cls          # _Class or None (module functions)
        self.label = label      # "Class.method" / "module:fn" for provenance
        self.acquisitions = []  # (node_id, frozenset(held_lex), lineno)
        self.blocking = []      # (kind, detail, release_node, held_lex, lineno)
        self.calls = []         # (callee_key, frozenset(held_lex))


class _Analysis:
    def __init__(self, root: pathlib.Path, prefixes=FLEET_PREFIXES):
        self.root = root
        self.prefixes = prefixes
        # Every class in scope, for WALKING (acquisitions/blocking are
        # always analyzed, even under a name collision)...
        self.class_list: list[_Class] = []
        # ...vs the name->class map for TYPED dispatch, where ambiguous
        # names must drop out (sound: unresolved calls under-approximate).
        self.classes: dict[str, _Class] = {}
        self.mod_functions: dict[str, dict[str, ast.FunctionDef]] = {}
        self.mod_of_rel: dict[str, str] = {}
        self.files: dict[str, tuple] = {}  # rel -> (tree, aliases, lines,
        #                                            per_line, per_file)
        self.callables: dict[tuple, _CallableInfo] = {}
        self.entry: dict[tuple, frozenset] = {}
        self.edges: dict[tuple[str, str], set[str]] = {}
        self.findings: list[Finding] = []
        self._load()
        self._type_pass()
        self._walk_all()
        self._fixpoint()
        self._emit()

    # ---- pass 0: parse the fleet scope ----

    def _load(self) -> None:
        for rel in iter_python_files(self.root):
            if not rel.startswith(self.prefixes):
                continue
            try:
                source = (self.root / rel).read_text()
                tree = ast.parse(source, filename=rel)
            except (SyntaxError, UnicodeDecodeError):
                continue  # R0 comes from the main python pass
            aliases = _alias_map(tree)
            per_line, per_file = parse_suppressions(source)
            self.files[rel] = (tree, aliases, source.splitlines(),
                               per_line, per_file)
            dotted_mod = rel[:-3].replace("/", ".")
            self.mod_of_rel[rel] = dotted_mod
            fns = {}
            for node in tree.body:
                if isinstance(node, ast.ClassDef):
                    c = _Class(rel, node, aliases)
                    self.class_list.append(c)
                    # Duplicate class names across files make TYPED
                    # dispatch ambiguous — drop the name from the typing
                    # map only; both classes stay fully walked (their
                    # same-id lock nodes merge, which is the node model's
                    # instance-collapse applied to name collisions).
                    if c.name in self.classes:
                        self.classes[c.name] = None  # type: ignore[assignment]
                    else:
                        self.classes[c.name] = c
                elif isinstance(node, ast.FunctionDef):
                    fns[node.name] = node
            self.mod_functions[dotted_mod] = fns
        self.classes = {k: v for k, v in self.classes.items()
                        if v is not None}

    # ---- pass 1: attribute / return types ----

    def _type_pass(self) -> None:
        known = self.classes
        for cls in known.values():
            for name, m in cls.methods.items():
                ret = _ann_class(m.returns, known)
                if ret is not None:
                    cls.method_returns[name] = ret
        for cls in known.values():
            init = cls.methods.get("__init__")
            if init is None:
                continue
            local = self._param_types(init)
            for stmt in init.body:
                for node in ast.walk(stmt):
                    if not (isinstance(node, ast.Assign)
                            and len(node.targets) == 1):
                        continue
                    t = self._expr_type(node.value, cls, local)
                    target = node.targets[0]
                    attr = _self_attr(target)
                    if attr is not None and t is not None:
                        cls.attr_types[attr] = t
                    elif isinstance(target, ast.Name) and t is not None:
                        local[target.id] = t

    def _param_types(self, fn: ast.FunctionDef) -> dict[str, str]:
        out = {}
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            t = _ann_class(a.annotation, self.classes)
            if t is not None:
                out[a.arg] = t
        return out

    def _expr_type(self, expr, cls: _Class | None,
                   local: dict[str, str]) -> str | None:
        if isinstance(expr, ast.Name):
            if expr.id == "self" and cls is not None:
                return cls.name
            return local.get(expr.id)
        if isinstance(expr, ast.Attribute):
            attr = _self_attr(expr)
            if attr is not None and cls is not None:
                return cls.attr_types.get(attr)
            return None
        if isinstance(expr, ast.IfExp):
            hits = {t for t in (self._expr_type(expr.body, cls, local),
                                self._expr_type(expr.orelse, cls, local))
                    if t}
            return hits.pop() if len(hits) == 1 else None
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Name) and f.id in self.classes:
                return f.id
            aliases = cls.aliases if cls is not None else {}
            dotted = _dotted(f, aliases)
            if dotted is not None:
                base = dotted.rpartition(".")[2]
                if base in self.classes and (dotted == base
                                             or "." in dotted):
                    # Constructor via import alias (dotted resolves to the
                    # class) — but only when it's not a method call on a
                    # typed receiver, which the branch below handles.
                    if not isinstance(f, ast.Attribute) or \
                            self._expr_type(f.value, cls, local) is None:
                        return base
            if isinstance(f, ast.Attribute):
                recv_t = self._expr_type(f.value, cls, local)
                if recv_t is not None:
                    owner = self.classes.get(recv_t)
                    if owner is not None:
                        return owner.method_returns.get(f.attr)
        return None

    # ---- pass 2: walk every callable ----

    def _walk_all(self) -> None:
        for cls in self.class_list:
            # Key on (rel, name) so a name collision cannot alias two
            # classes' callables onto one entry-set.
            for m in cls.methods.values():
                self._walk_callable(("C", cls.rel, cls.name, m.name),
                                    cls.rel, cls, m)
        for rel, (tree, _aliases, _lines, _pl, _pf) in self.files.items():
            mod = self.mod_of_rel[rel]
            for node in tree.body:
                if isinstance(node, ast.FunctionDef):
                    self._walk_callable(("F", mod, node.name), rel, None,
                                        node)

    def _walk_callable(self, key, rel, cls, fn) -> None:
        label = (f"{cls.name}.{fn.name}" if cls is not None
                 else f"{self.mod_of_rel[rel]}.{fn.name}")
        info = _CallableInfo(key, rel, cls, label)
        self.callables[key] = info
        local = self._param_types(fn)
        nested: list = []

        def lock_root_of(expr) -> str | None:
            attr = _self_attr(expr)
            if attr is not None and cls is not None and \
                    attr in cls.lock_roots:
                return attr
            return None

        def visit(node, held: frozenset) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    root = lock_root_of(item.context_expr)
                    if root is not None:
                        nid = cls.node_id(root)
                        info.acquisitions.append(
                            (nid, held, item.context_expr.lineno)
                        )
                        acquired.append(nid)
                    else:
                        visit(item.context_expr, held)
                h2 = held | frozenset(acquired)
                for child in node.body:
                    visit(child, h2)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                # Closures run later, possibly without the lock: analyzed
                # as their own held-∅ callables (R10 convention).
                nested.append(node)
                return
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = self._expr_type(node.value, cls, local)
                if t is not None:
                    local[node.targets[0].id] = t
            if isinstance(node, ast.Call):
                self._classify_call(info, node, held, cls, local)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, frozenset())
        for i, sub in enumerate(nested):
            name = getattr(sub, "name", f"<lambda:{sub.lineno}>")
            self._walk_callable(key + (f"{name}@{sub.lineno}",), rel, cls,
                                _as_fn(sub))

    def _classify_call(self, info, call: ast.Call, held: frozenset,
                       cls, local) -> None:
        f = call.func
        aliases = self.files[info.rel][1]
        dotted = _dotted(f, aliases)

        # ---- blocking catalog ----
        if dotted in _BLOCKING_DOTTED:
            info.blocking.append(
                ("blocking", _BLOCKING_DOTTED[dotted], None, held,
                 call.lineno)
            )
        elif isinstance(f, ast.Name) and f.id in _BLOCKING_NAMES:
            info.blocking.append(
                ("blocking", _BLOCKING_NAMES[f.id], None, held, call.lineno)
            )
        elif isinstance(f, ast.Attribute):
            if f.attr == "wait":
                root = None
                attr = _self_attr(f.value)
                if attr is not None and cls is not None and \
                        attr in cls.lock_roots:
                    root = cls.node_id(attr)
                info.blocking.append((
                    "wait",
                    "Condition.wait releases only its own lock"
                    if root is not None else
                    "Event/Condition wait can block unboundedly",
                    root, held, call.lineno,
                ))
            elif f.attr == "join" and not isinstance(f.value, ast.Constant):
                if not (dotted or "").startswith(_JOIN_EXEMPT_PREFIXES):
                    info.blocking.append(
                        ("blocking", "join blocks until the target "
                         "finishes", None, held, call.lineno)
                    )
            elif f.attr == "result" and isinstance(
                    f.value, (ast.Name, ast.Attribute)):
                info.blocking.append(
                    ("blocking", "Future.result blocks until the future "
                     "resolves", None, held, call.lineno)
                )
            elif f.attr in _IO_ATTRS:
                info.blocking.append(
                    ("blocking", "file IO", None, held, call.lineno)
                )
            elif f.attr in _BLOCKING_NAMES and dotted is None:
                info.blocking.append(
                    ("blocking", _BLOCKING_NAMES[f.attr], None, held,
                     call.lineno)
                )

        # ---- propagation edges ----
        callee = self._resolve_callee(call, info, cls, local)
        if callee is not None:
            info.calls.append((callee, held))

    def _resolve_callee(self, call, info, cls, local):
        f = call.func
        if isinstance(f, ast.Attribute):
            recv_t = self._expr_type(f.value, cls, local)
            if recv_t is not None:
                owner = self.classes.get(recv_t)
                if owner is not None and f.attr in owner.methods:
                    return ("C", owner.rel, recv_t, f.attr)
            dotted = _dotted(f, self.files[info.rel][1])
            if dotted is not None:
                mod, _, name = dotted.rpartition(".")
                fns = self.mod_functions.get(mod)
                if fns is not None and name in fns:
                    return ("F", mod, name)
        elif isinstance(f, ast.Name):
            mod = self.mod_of_rel[info.rel]
            if f.id in self.mod_functions.get(mod, {}):
                return ("F", mod, f.id)
            dotted = _dotted(f, self.files[info.rel][1])
            if dotted is not None and "." in dotted:
                m, _, name = dotted.rpartition(".")
                fns = self.mod_functions.get(m)
                if fns is not None and name in fns:
                    return ("F", m, name)
        return None

    # ---- pass 3: may-held fixpoint ----

    def _fixpoint(self) -> None:
        self.entry = {key: frozenset() for key in self.callables}
        changed = True
        while changed:
            changed = False
            for key, info in self.callables.items():
                base = self.entry[key]
                for callee, held_lex in info.calls:
                    if callee not in self.entry:
                        continue
                    target = base | held_lex
                    if not target <= self.entry[callee]:
                        self.entry[callee] = self.entry[callee] | target
                        changed = True

    # ---- pass 4: edges + findings ----

    def _emit(self) -> None:
        for key, info in self.callables.items():
            base = self.entry[key]
            _tree, _al, lines, per_line, per_file = self.files[info.rel]
            for nid, held_lex, lineno in info.acquisitions:
                held = base | held_lex
                for h in sorted(held):
                    if h == nid:
                        kind = self._node_kind(nid)
                        if kind == "RLock":
                            continue  # reentrant by design
                        f = Finding(
                            "R12", info.rel, lineno, _line(lines, lineno),
                            f"{info.label} re-acquires non-reentrant lock "
                            f"{nid} while it may already be held (callers "
                            "enter with the lock taken): self-deadlock — "
                            "split a '(lock held)' helper or make the "
                            "caller drop the lock first",
                        )
                        if not is_suppressed("R12", lineno, per_line,
                                             per_file, path=info.rel):
                            self.findings.append(f)
                    else:
                        self.edges.setdefault((h, nid), set()).add(
                            info.label
                        )
            for kind, what, release, held_lex, lineno in info.blocking:
                held = base | held_lex
                if kind == "wait" and release is not None:
                    # The coalescing idiom: waiting on a Condition aliasing
                    # a held lock RELEASES it — only OTHER held locks block.
                    held = held - {release}
                if not held:
                    continue
                f = Finding(
                    "R13", info.rel, lineno, _line(lines, lineno),
                    f"{info.label} can block while holding "
                    f"{', '.join(sorted(held))}: {what} — every thread "
                    "needing the lock stalls behind it (the wedge class "
                    "this fleet exists to bound); move the call outside "
                    "the critical section (snapshot under the lock, block "
                    "outside — the _drain_probes/cache-load pattern)",
                )
                if not is_suppressed("R13", lineno, per_line, per_file,
                                     path=info.rel):
                    self.findings.append(f)
        self.findings += self._cycle_findings()
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule))

    def _node_kind(self, nid: str) -> str:
        cls_name, _, attr = nid.partition(".")
        kinds = {
            c.lock_kinds.get(attr, "Lock")
            for c in self.class_list
            if c.name == cls_name and attr in c.lock_kinds
        }
        # Name-collided classes share a node id; a mixed-kind collision
        # is treated as non-reentrant (the conservative verdict).
        return kinds.pop() if len(kinds) == 1 else "Lock"

    def _cycle_findings(self) -> list[Finding]:
        adj: dict[str, list[str]] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, []).append(dst)
        for dsts in adj.values():
            dsts.sort()
        seen: set[str] = set()
        cycles: list[tuple[str, ...]] = []

        def dfs(node, stack, on_stack):
            seen.add(node)
            on_stack[node] = len(stack)
            stack.append(node)
            for nxt in adj.get(node, ()):
                if nxt in on_stack:
                    cyc = tuple(stack[on_stack[nxt]:])
                    # Canonical rotation so the finding id is stable.
                    i = cyc.index(min(cyc))
                    cycles.append(cyc[i:] + cyc[:i])
                elif nxt not in seen:
                    dfs(nxt, stack, on_stack)
            stack.pop()
            del on_stack[node]

        for node in sorted(adj):
            if node not in seen:
                dfs(node, [], {})
        out = []
        for cyc in sorted(set(cycles)):
            sig = "->".join(cyc + (cyc[0],))
            out.append(Finding(
                "R12", LOCK_GRAPH_NAME, 0, f"cycle:{sig}",
                f"lock-order cycle {sig}: two threads taking these locks "
                "in opposite orders deadlock the fleet — break the cycle "
                "(move one acquisition outside the other's critical "
                "section, or merge the domains)",
            ))
        return out

    # ---- the committed artifact ----

    def graph(self) -> dict:
        nodes: dict[str, dict] = {}
        for cls in self.class_list:
            for attr, root in sorted(cls.lock_roots.items()):
                nid = f"{cls.name}.{root}"
                rec = nodes.setdefault(nid, {
                    "file": cls.rel,
                    "kind": cls.lock_kinds.get(root, "Lock"),
                    "aliases": [],
                })
                if attr != root and attr not in rec["aliases"]:
                    rec["aliases"].append(attr)
        for rec in nodes.values():
            rec["aliases"].sort()
        edges = [
            {"src": src, "dst": dst, "via": sorted(via)}
            for (src, dst), via in sorted(self.edges.items())
        ]
        return {"nodes": {k: nodes[k] for k in sorted(nodes)},
                "edges": edges}


def _as_fn(node):
    """Normalize a Lambda into a FunctionDef-shaped object for the walker."""
    if isinstance(node, ast.Lambda):
        fn = ast.FunctionDef(
            name=f"<lambda:{node.lineno}>", args=node.args,
            body=[ast.Expr(value=node.body)], decorator_list=[],
            returns=None,
        )
        ast.copy_location(fn, node)
        ast.fix_missing_locations(fn)
        return fn
    return node


def _line(lines, lineno):
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


# --------------------------------------------------------------------------
# public API

# One full lint run needs the analysis twice (run_layer1's R12/R13 pass +
# the CLI's committed-graph diff); memoize on the scope files' identity so
# the fixpoint runs once per tree state.  Keyed on (path, mtime_ns, size)
# per scope file — fixture trees that rewrite a file re-analyze.
_MEMO: dict = {}
_MEMO_CAP = 8


def analyze(root, prefixes=FLEET_PREFIXES) -> _Analysis:
    root = pathlib.Path(root)
    try:
        fingerprint = tuple(
            (rel, (root / rel).stat().st_mtime_ns, (root / rel).stat().st_size)
            for rel in iter_python_files(root)
            if rel.startswith(prefixes)
        )
    except OSError:
        return _Analysis(root, prefixes)  # racing tree: skip the memo
    key = (str(root.resolve()), prefixes, fingerprint)
    a = _MEMO.get(key)
    if a is None:
        a = _Analysis(root, prefixes)
        if len(_MEMO) >= _MEMO_CAP:
            _MEMO.pop(next(iter(_MEMO)))
        _MEMO[key] = a
    return a


def build_graph(root, prefixes=FLEET_PREFIXES) -> dict:
    return analyze(root, prefixes).graph()


def run_lock_rules(root, files=None, prefixes=FLEET_PREFIXES):
    """R12 (self-deadlock + cycles) and R13 findings over the fleet scope
    of ``root``.  The whole scope is always analyzed — lock order is a
    fleet-global property — but the pass is skipped entirely when a
    scoped run touched no fleet/lint file (``--changed`` fast mode).
    The committed-graph DIFF is the CLI's job (ledger pattern)."""
    if not lock_pass_needed(files):
        return []
    return analyze(root, prefixes).findings


def write_graph(path: pathlib.Path, graph: dict) -> None:
    data = {
        "comment": "graft-audit v3 lock-order graph; see LINT.md.  Nodes "
                   "are (class, lock attribute) — Condition aliases "
                   "collapse onto the lock they wrap — and each edge "
                   "src->dst means dst may be acquired while src is held "
                   "(via: the acquiring method).  The edge set is the "
                   "canonical acquisition partial order: a cycle or an "
                   "uncommitted new edge fails tier-1; regenerate with "
                   "`python -m esac_tpu.lint --write-lock-graph` and "
                   "review the diff.  The runtime witness "
                   "(lint/witness.py) asserts observed edges are a "
                   "subgraph of this order.",
        **graph,
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


def load_graph(path: pathlib.Path) -> dict | None:
    if not path.exists():
        return None
    data = json.loads(path.read_text())
    return {"nodes": data.get("nodes", {}), "edges": data.get("edges", [])}


def _edge_map(graph: dict) -> dict[tuple[str, str], list[str]]:
    return {
        (e["src"], e["dst"]): list(e.get("via", []))
        for e in graph.get("edges", [])
    }


def diff_graph(committed: dict, current: dict):
    """-> (R12 findings, stale notes), J4-style: a CURRENT edge the
    committed order does not sanction fails; committed edges/nodes that
    drifted away are stale (regenerate + review)."""
    findings: list[Finding] = []
    stale: list[str] = []
    want = _edge_map(committed)
    have = _edge_map(current)
    for (src, dst), via in sorted(have.items()):
        old = want.get((src, dst))
        if old is None:
            findings.append(Finding(
                "R12", LOCK_GRAPH_NAME, 0, f"edge:{src}->{dst}",
                f"unreviewed lock-order edge {src} -> {dst} "
                f"(via {', '.join(via)}): not in the committed "
                f"{LOCK_GRAPH_NAME} — if intentional, regenerate with "
                "`python -m esac_tpu.lint --write-lock-graph`, review "
                "the diff (does the new nesting keep the order acyclic "
                "fleet-wide?), and commit",
            ))
        elif sorted(old) != sorted(via):
            stale.append(
                f"lock-graph edge {src} -> {dst} changed provenance "
                f"({', '.join(old)} -> {', '.join(via)}) — regenerate "
                "with --write-lock-graph and review the diff"
            )
    for (src, dst) in sorted(set(want) - set(have)):
        stale.append(
            f"committed lock-graph edge {src} -> {dst} is no longer "
            "taken by any code path — regenerate with --write-lock-graph"
        )
    want_nodes = set(committed.get("nodes", {}))
    have_nodes = set(current.get("nodes", {}))
    for n in sorted(have_nodes - want_nodes):
        stale.append(
            f"lock {n} is new and not in the committed graph — "
            "regenerate with --write-lock-graph and review"
        )
    for n in sorted(want_nodes - have_nodes):
        stale.append(
            f"committed lock-graph node {n} no longer exists — "
            "regenerate with --write-lock-graph"
        )
    return findings, stale


def transitive_closure(edges) -> set[tuple[str, str]]:
    """Closure of an edge iterable ((src, dst) pairs or edge dicts) —
    the PARTIAL-ORDER membership test the runtime witness uses: an
    observed A->C is sanctioned when the committed order says A before
    C, directly or through intermediates."""
    pairs = set()
    for e in edges:
        if isinstance(e, dict):
            pairs.add((e["src"], e["dst"]))
        else:
            pairs.add((e[0], e[1]))
    changed = True
    while changed:
        changed = False
        for (a, b) in list(pairs):
            for (c, d) in list(pairs):
                if b == c and (a, d) not in pairs and a != d:
                    pairs.add((a, d))
                    changed = True
    return pairs
