"""Layer-2 registry: the entry points the jaxpr auditor traces.

Each entry names a real compiled surface of the system — the RANSAC kernel,
the scoring impls, the PnP solve, the sharded train step — and a builder
that returns its ClosedJaxpr, traced at deliberately tiny static shapes
(tracing is abstract evaluation; shapes only change trace time, not what
primitives appear).  ``pinned=True`` marks call graphs whose every
``dot_general`` must run at HIGHEST precision / f32 output (the CLAUDE.md
rotation-math invariant); the CNN-bearing sharded step is audited for
primitives and shapes only, since bf16 conv/dense compute is the *correct*
policy there (models/expert.py).

Everything imports jax lazily and the auditor forces the CPU backend before
any builder runs — the lint must never itself become a TPU relay client.

Gradient traces are used wherever the backward pass is the risk surface
(autodiff-through-IRLS is where NaN/precision bugs actually bite); the
sharded entry is traced forward-only to keep the audit cheap.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable


@dataclasses.dataclass(frozen=True)
class Entry:
    name: str
    pinned: bool          # enforce HIGHEST/f32 on every dot_general
    build: Callable       # () -> jax.core.ClosedJaxpr | None (None = skip)
    note: str = ""
    # grad=True marks a GRAD-REGISTERED entry: its build traces a
    # jax.grad/value_and_grad program, so the traced jaxpr CONTAINS the
    # backward pass (the VJP leg of the shared tracing pass).  These
    # entries get the graft-audit v4 treatment: the J5 backward-jaxpr
    # hazard census in the ledger (lint/ledger.py), the R14/R15 dataflow
    # roots (lint/gradsafety.py parses this file for grad=True builders),
    # and the degenerate-input gradient witness (lint/gradcheck.py).
    grad: bool = False


def _geom_inputs(n_cells: int = 16):
    import jax
    import jax.numpy as jnp

    k = jax.random.key(0)
    coords = jax.random.uniform(k, (n_cells, 3), minval=-1.0, maxval=1.0)
    pixels = jax.random.uniform(jax.random.key(1), (n_cells, 2), maxval=64.0)
    f = jnp.float32(60.0)
    c = jnp.asarray([32.0, 24.0])
    return coords, pixels, f, c


# Inference entries trace at a cell count where the scoring stage (the
# only stage scaling as hyps x cells) carries the peak — at the default 16
# cells the P3P/refine small-tensor chain masks it, and the ledger's
# peak-bytes record would not witness the ISSUE 8 fusion (errmap gone from
# every inference entry).  128 cells keeps tracing fast while putting the
# would-be errmap (n_hyps * 128 * 4 bytes) decisively above the chain.
_INFER_CELLS = 128


def _build_pnp_minimal_grad():
    import jax
    import jax.numpy as jnp

    from esac_tpu.geometry.pnp import solve_pnp_minimal

    coords, pixels, f, c = _geom_inputs()
    X4, x4 = coords[:4], pixels[:4]

    def loss(X4):
        rvec, tvec = solve_pnp_minimal(X4, x4, f, c, polish_iters=1)
        return jnp.sum(rvec) + jnp.sum(tvec)

    return jax.make_jaxpr(jax.grad(loss))(X4)


def _build_refine_grad():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.refine import refine_soft_inliers

    coords, pixels, f, c = _geom_inputs()
    rvec = jnp.asarray([0.1, -0.05, 0.02])
    tvec = jnp.asarray([0.0, 0.0, 2.0])

    def loss(coords):
        rv, tv = refine_soft_inliers(
            rvec, tvec, coords, pixels, f, c, tau=10.0, beta=0.5, iters=2
        )
        return jnp.sum(rv) + jnp.sum(tv)

    return jax.make_jaxpr(jax.grad(loss))(coords)


def _build_dsac_infer():
    import jax

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.kernel import dsac_infer

    coords, pixels, f, c = _geom_inputs(_INFER_CELLS)
    # score_chunk < n_hyps so the streamed inference scoring's real tiled
    # structure is traced (n_tiles > 1), exactly as serve shapes see it.
    cfg = RansacConfig(n_hyps=8, refine_iters=2, polish_iters=1,
                       score_chunk=4)
    key = jax.random.key(2)
    return jax.make_jaxpr(
        lambda k, co: dsac_infer(k, co, pixels, f, c, cfg)
    )(key, coords)


def _build_dsac_train_grad():
    import jax
    import jax.numpy as jnp

    from esac_tpu.geometry.rotations import rodrigues
    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.kernel import dsac_train_loss

    coords, pixels, f, c = _geom_inputs()
    cfg = RansacConfig(n_hyps=4, train_refine_iters=1, polish_iters=1)
    R_gt = rodrigues(jnp.asarray([0.1, 0.0, 0.0]))
    t_gt = jnp.asarray([0.0, 0.0, 2.0])
    key = jax.random.key(3)

    def loss(coords):
        val, _ = dsac_train_loss(key, coords, pixels, f, c, R_gt, t_gt, cfg)
        return val

    return jax.make_jaxpr(jax.grad(loss))(coords)


def _build_scoring(impl: str):
    def build():
        import jax
        import jax.numpy as jnp

        from esac_tpu.ransac.config import RansacConfig
        from esac_tpu.ransac.kernel import _score_hypotheses

        coords, pixels, f, c = _geom_inputs()
        # score_chunk < n_hyps so the "fused_select" training path's real
        # tiled scan (2 tiles) is traced; errmap/fused ignore the knob.
        cfg = RansacConfig(n_hyps=4, scoring_impl=impl, score_chunk=2)
        rvecs = jnp.tile(jnp.asarray([0.1, -0.05, 0.02]), (4, 1))
        tvecs = jnp.tile(jnp.asarray([0.0, 0.0, 2.0]), (4, 1))
        key = jax.random.key(4)

        def loss(coords):
            return jnp.sum(
                _score_hypotheses(key, rvecs, tvecs, coords, pixels, f, c, cfg)
            )

        return jax.make_jaxpr(jax.grad(loss))(coords)

    return build


def _build_scoring_fused_select_grad():
    import jax
    import jax.numpy as jnp

    from esac_tpu.geometry.rotations import rodrigues
    from esac_tpu.ransac.pallas_scoring import soft_inlier_score_select

    coords, pixels, f, c = _geom_inputs()
    rvecs = jnp.asarray([[0.1, -0.05, 0.02], [0.0, 0.1, -0.1],
                         [-0.2, 0.0, 0.05], [0.05, 0.05, 0.0]])
    Rs = jax.vmap(rodrigues)(rvecs)
    ts = jnp.tile(jnp.asarray([0.0, 0.0, 2.0]), (4, 1))

    def loss(coords):
        _, best_score = soft_inlier_score_select(
            Rs, ts, coords, pixels, f, c, 10.0, 0.5,
            use_pallas=False, chunk=2,
        )
        return best_score

    return jax.make_jaxpr(jax.grad(loss))(coords)


def _build_dsac_infer_fused_select():
    import jax

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.kernel import dsac_infer

    coords, pixels, f, c = _geom_inputs(_INFER_CELLS)
    cfg = RansacConfig(n_hyps=8, refine_iters=2, polish_iters=1,
                       score_chunk=4, scoring_impl="fused_select")
    key = jax.random.key(12)
    return jax.make_jaxpr(
        lambda k, co: dsac_infer(k, co, pixels, f, c, cfg)
    )(key, coords)


def _build_esac_train_grad():
    import jax
    import jax.numpy as jnp

    from esac_tpu.geometry.rotations import rodrigues
    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.esac import esac_train_loss

    coords, pixels, f, c = _geom_inputs()
    M = 2
    coords_all = jnp.stack([coords, coords + 0.1])
    cfg = RansacConfig(n_hyps=4, train_refine_iters=1, polish_iters=1)
    logits = jnp.zeros((M,))
    R_gt = rodrigues(jnp.asarray([0.1, 0.0, 0.0]))
    t_gt = jnp.asarray([0.0, 0.0, 2.0])
    key = jax.random.key(5)

    def loss(coords_all, logits):
        val, _ = esac_train_loss(
            key, logits, coords_all, pixels, f, c, R_gt, t_gt, cfg, "dense"
        )
        return val

    return jax.make_jaxpr(jax.grad(loss, argnums=(0, 1)))(coords_all, logits)


def _build_dsac_infer_frames():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.kernel import dsac_infer_frames

    coords, pixels, f, c = _geom_inputs(_INFER_CELLS)
    B = 2
    cfg = RansacConfig(n_hyps=8, refine_iters=2, polish_iters=1,
                       score_chunk=4)
    keys = jax.random.split(jax.random.key(6), B)
    coords_B = jnp.stack([coords, coords + 0.1])
    pixels_B = jnp.stack([pixels, pixels])
    f_B = jnp.stack([f, f])
    return jax.make_jaxpr(
        lambda k, co: dsac_infer_frames(k, co, pixels_B, f_B, c, cfg)
    )(keys, coords_B)


def _build_esac_infer_frames():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.esac import esac_infer_frames

    coords, pixels, f, c = _geom_inputs(_INFER_CELLS)
    B, M = 2, 2
    cfg = RansacConfig(n_hyps=8, refine_iters=2, polish_iters=1,
                       score_chunk=4)
    keys = jax.random.split(jax.random.key(7), B)
    coords_all = jnp.stack([coords, coords + 0.1])          # (M, N, 3)
    coords_B = jnp.stack([coords_all, coords_all + 0.05])   # (B, M, N, 3)
    logits_B = jnp.zeros((B, M))
    pixels_B = jnp.stack([pixels, pixels])
    f_B = jnp.stack([f, f])
    return jax.make_jaxpr(
        lambda k, co: esac_infer_frames(k, logits_B, co, pixels_B, f_B, c, cfg)
    )(keys, coords_B)


def _build_esac_infer_topk_frames():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.esac import esac_infer_topk_frames

    coords, pixels, f, c = _geom_inputs(_INFER_CELLS)
    B, M = 2, 3
    cfg = RansacConfig(n_hyps=8, refine_iters=2, polish_iters=1,
                       score_chunk=4)
    keys = jax.random.split(jax.random.key(9), B)
    coords_all = jnp.stack([coords, coords + 0.1, coords - 0.1])  # (M, N, 3)
    coords_B = jnp.stack([coords_all, coords_all + 0.05])         # (B, M, N, 3)
    logits_B = jnp.zeros((B, M))
    pixels_B = jnp.stack([pixels, pixels])
    f_B = jnp.stack([f, f])
    # k < M so the gather-pruned expert subset path itself is traced, not
    # the dense specialization.
    return jax.make_jaxpr(
        lambda k, co: esac_infer_topk_frames(
            k, logits_B, co, pixels_B, f_B, c, cfg, k=2
        )
    )(keys, coords_B)


def _build_esac_infer_routed_frames():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.esac import esac_infer_routed_frames

    coords, pixels, f, c = _geom_inputs(_INFER_CELLS)
    B, M, K = 2, 4, 2
    cfg = RansacConfig(n_hyps=8, refine_iters=2, polish_iters=1,
                       score_chunk=4)
    keys = jax.random.split(jax.random.key(8), B)
    coords_sel = jnp.stack([
        jnp.stack([coords, coords + 0.1]),
        jnp.stack([coords + 0.05, coords + 0.2]),
    ])  # (B, K, N, 3)
    logits_B = jnp.zeros((B, M))
    selected = jnp.tile(jnp.asarray([1, 3], jnp.int32)[None], (B, 1))
    kept = jnp.asarray([[True, True], [True, False]])
    pixels_B = jnp.stack([pixels, pixels])
    f_B = jnp.stack([f, f])
    return jax.make_jaxpr(
        lambda k, co: esac_infer_routed_frames(
            k, logits_B, co, selected, kept, pixels_B, f_B, c, cfg
        )
    )(keys, coords_sel)


def _build_esac_infer_frames_prior():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.esac import esac_infer_frames_prior

    coords, pixels, f, c = _geom_inputs(_INFER_CELLS)
    B, M, P = 2, 2, 3
    cfg = RansacConfig(n_hyps=8, refine_iters=2, polish_iters=1,
                       score_chunk=4)
    keys = jax.random.split(jax.random.key(7), B)
    coords_all = jnp.stack([coords, coords + 0.1])          # (M, N, 3)
    coords_B = jnp.stack([coords_all, coords_all + 0.05])   # (B, M, N, 3)
    logits_B = jnp.zeros((B, M))
    pixels_B = jnp.stack([pixels, pixels])
    f_B = jnp.stack([f, f])
    # A mixed validity mask so both the masked prior scoring and the
    # strict-> winner replacement are live in the traced program.
    p_rv = jnp.zeros((B, P, 3))
    p_tv = jnp.zeros((B, P, 3))
    p_va = jnp.asarray([[True, True, False], [False, False, False]])
    return jax.make_jaxpr(
        lambda k, co: esac_infer_frames_prior(
            k, logits_B, co, pixels_B, f_B, c, p_rv, p_tv, p_va, cfg
        )
    )(keys, coords_B)


def _build_esac_infer_routed_frames_prior():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.esac import esac_infer_routed_frames_prior

    coords, pixels, f, c = _geom_inputs(_INFER_CELLS)
    B, M, K, P = 2, 4, 2, 3
    cfg = RansacConfig(n_hyps=8, refine_iters=2, polish_iters=1,
                       score_chunk=4)
    keys = jax.random.split(jax.random.key(8), B)
    coords_sel = jnp.stack([
        jnp.stack([coords, coords + 0.1]),
        jnp.stack([coords + 0.05, coords + 0.2]),
    ])  # (B, K, N, 3)
    logits_B = jnp.zeros((B, M))
    selected = jnp.tile(jnp.asarray([1, 3], jnp.int32)[None], (B, 1))
    kept = jnp.asarray([[True, True], [True, False]])
    pixels_B = jnp.stack([pixels, pixels])
    f_B = jnp.stack([f, f])
    p_rv = jnp.zeros((B, P, 3))
    p_tv = jnp.zeros((B, P, 3))
    p_va = jnp.asarray([[True, True, False], [False, False, False]])
    return jax.make_jaxpr(
        lambda k, co: esac_infer_routed_frames_prior(
            k, logits_B, co, selected, kept, pixels_B, f_B, c,
            p_rv, p_tv, p_va, cfg
        )
    )(keys, coords_sel)


def _build_routed_scene_serve():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.registry.manifest import ScenePreset
    from esac_tpu.registry.serving import make_routed_scene_bucket_fn

    H = W = 16
    M, B = 4, 2
    preset = ScenePreset(
        height=H, width=W, num_experts=M,
        stem_channels=(2, 2, 2), head_channels=2, head_depth=1,
        gating_channels=(2,), compute_dtype="float32", gated=True,
    )
    cfg = RansacConfig(n_hyps=4, refine_iters=1, polish_iters=1,
                       frame_buckets=(1, 4), score_chunk=2)
    # k < M so the traced program is the REAL two-phase routed pipeline
    # (gating -> top-k -> capacity blocks -> scatter -> routed esac), not
    # the K=M dense specialization.
    fn = make_routed_scene_bucket_fn(preset, cfg, 2)

    from esac_tpu.models.expert import ExpertNet
    from esac_tpu.models.gating import GatingNet

    expert = ExpertNet(scene_center=(0.0, 0.0, 0.0),
                       stem_channels=preset.stem_channels,
                       head_channels=preset.head_channels,
                       head_depth=preset.head_depth,
                       compute_dtype=jnp.float32)
    gating = GatingNet(num_experts=M, channels=preset.gating_channels,
                       compute_dtype=jnp.float32)
    img = jnp.zeros((1, H, W, 3))
    params = {
        "expert": jax.vmap(lambda k: expert.init(k, img))(
            jax.random.split(jax.random.key(0), M)
        ),
        "gating": gating.init(jax.random.key(1), img),
        "centers": jnp.zeros((M, 3)),
        "c": jnp.asarray([W / 2.0, H / 2.0]),
        "f": jnp.float32(20.0),
    }
    batch = {
        "key": jax.random.split(jax.random.key(2), B),
        "image": jnp.zeros((B, H, W, 3)),
    }
    return jax.make_jaxpr(fn)(params, batch)


def _build_registry_scene_serve():
    import jax
    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.registry.manifest import ScenePreset
    from esac_tpu.registry.serving import make_scene_bucket_fn

    H = W = 16
    M, B = 2, 2
    preset = ScenePreset(
        height=H, width=W, num_experts=M,
        stem_channels=(2, 2, 2), head_channels=2, head_depth=1,
        gating_channels=(2,), compute_dtype="float32", gated=True,
    )
    cfg = RansacConfig(n_hyps=4, refine_iters=1, polish_iters=1,
                       score_chunk=2)
    fn = make_scene_bucket_fn(preset, cfg)

    from esac_tpu.models.expert import ExpertNet
    from esac_tpu.models.gating import GatingNet

    expert = ExpertNet(scene_center=(0.0, 0.0, 0.0),
                       stem_channels=preset.stem_channels,
                       head_channels=preset.head_channels,
                       head_depth=preset.head_depth,
                       compute_dtype=jnp.float32)
    gating = GatingNet(num_experts=M, channels=preset.gating_channels,
                       compute_dtype=jnp.float32)
    img = jnp.zeros((1, H, W, 3))
    params = {
        "expert": jax.vmap(lambda k: expert.init(k, img))(
            jax.random.split(jax.random.key(0), M)
        ),
        "gating": gating.init(jax.random.key(1), img),
        "centers": jnp.zeros((M, 3)),
        "c": jnp.asarray([W / 2.0, H / 2.0]),
        "f": jnp.float32(20.0),
    }
    batch = {
        "key": jax.random.split(jax.random.key(2), B),
        "image": jnp.zeros((B, H, W, 3)),
    }
    return jax.make_jaxpr(fn)(params, batch)


def _build_retrieval_posterior():
    import jax
    import jax.numpy as jnp

    from esac_tpu.retrieval.model import (
        RetrievalConfig,
        build_retriever,
        make_retrieval_fn,
    )

    cfg = RetrievalConfig(height=16, width=16, max_scenes=8, embed_dim=4,
                          channels=(2,))
    fn = make_retrieval_fn(cfg)
    img = jnp.zeros((1, cfg.height, cfg.width, 3))
    params = build_retriever(cfg).init(jax.random.key(0), img)
    prototypes = jnp.zeros((cfg.max_scenes, cfg.embed_dim))
    mask = jnp.zeros((cfg.max_scenes,), bool)
    return jax.make_jaxpr(fn)(params, prototypes, mask, img)


def _build_sharded_train():
    import jax

    if jax.device_count() < 8:
        return None  # no virtual mesh in this process; entry is skipped

    import jax.numpy as jnp

    from esac_tpu.data.synthetic import output_pixel_grid
    from esac_tpu.geometry.rotations import rodrigues
    from esac_tpu.models.expert import ExpertNet
    from esac_tpu.models.gating import GatingNet
    from esac_tpu.parallel.mesh import make_mesh
    from esac_tpu.parallel.train_sharded import make_sharded_esac_loss
    from esac_tpu.ransac.config import RansacConfig

    H = W = 16
    M, B = 4, 2
    mesh = make_mesh(n_data=2, n_expert=4)
    expert = ExpertNet(stem_channels=(2, 2, 2), head_channels=2, head_depth=1)
    gating = GatingNet(num_experts=M, channels=(2,))
    img = jnp.zeros((1, H, W, 3))
    e_params = jax.vmap(lambda k: expert.init(k, img))(
        jax.random.split(jax.random.key(0), M)
    )
    g_params = gating.init(jax.random.key(1), img)
    cfg = RansacConfig(n_hyps=4, train_refine_iters=1, polish_iters=1)
    pixels = output_pixel_grid(H, W, 8)
    f = jnp.float32(20.0)
    c = jnp.asarray([W / 2.0, H / 2.0])
    loss_fn = make_sharded_esac_loss(
        mesh, expert, gating, e_params, g_params, pixels, f, c, cfg
    )
    images = jnp.zeros((B, H, W, 3))
    R_gts = jnp.tile(rodrigues(jnp.asarray([0.1, 0.0, 0.0]))[None], (B, 1, 1))
    t_gts = jnp.tile(jnp.asarray([0.0, 0.0, 2.0]), (B, 1))
    with mesh:
        return jax.make_jaxpr(loss_fn)(
            e_params, g_params, images, R_gts, t_gts, jax.random.key(2)
        )


def _build_sharded_infer_frames_dynamic():
    import jax

    if jax.device_count() < 8:
        return None  # no virtual mesh in this process; entry is skipped

    import jax.numpy as jnp

    from esac_tpu.parallel.esac_sharded import (
        make_esac_infer_sharded_frames_dynamic,
    )
    from esac_tpu.parallel.mesh import make_mesh
    from esac_tpu.ransac.config import RansacConfig

    coords, pixels, f, c = _geom_inputs(_INFER_CELLS)
    B, M = 2, 4
    mesh = make_mesh(n_data=2, n_expert=4)
    cfg = RansacConfig(n_hyps=4, refine_iters=1, polish_iters=1,
                       score_chunk=2)
    infer = make_esac_infer_sharded_frames_dynamic(mesh, cfg)
    coords_all = jnp.stack(
        [coords, coords + 0.1, coords - 0.1, coords + 0.2]
    )  # (M, N, 3)
    batch = {
        "key": jax.random.split(jax.random.key(10), B),
        "coords_all": jnp.stack([coords_all, coords_all + 0.05]),
        "pixels": jnp.stack([pixels, pixels]),
        "f": jnp.stack([f, f]),
    }
    with mesh:
        return jax.make_jaxpr(infer)(batch, c)


ENTRIES: tuple[Entry, ...] = (
    Entry("pnp_minimal_grad", pinned=True, grad=True, build=_build_pnp_minimal_grad,
          note="grad of solve_pnp_minimal wrt the 4 scene points"),
    Entry("refine_soft_inliers_grad", pinned=True, grad=True, build=_build_refine_grad,
          note="autodiff-through-IRLS backward (the reference's "
               "finite-difference replacement)"),
    Entry("dsac_infer", pinned=True, build=_build_dsac_infer,
          note="full single-frame hypothesis pipeline"),
    Entry("dsac_train_loss_grad", pinned=True, grad=True, build=_build_dsac_train_grad,
          note="training expectation + backward"),
    Entry("scoring_errmap_grad", pinned=True, grad=True, build=_build_scoring("errmap"),
          note="reference-parity scoring impl"),
    Entry("scoring_fused_grad", pinned=True, grad=True, build=_build_scoring("fused"),
          note="fused XLA broadcast+reduce scoring impl"),
    Entry("scoring_fused_select_train_grad", pinned=True, grad=True,
          build=_build_scoring("fused_select"),
          note="fused_select TRAINING scoring path: chunked+remat errmap "
               "math (soft_inlier_scores_chunked) — all scores for the "
               "softmax expectation, peak bytes bounded to one "
               "(score_chunk, n_cells) tile in forward and backward"),
    Entry("scoring_fused_select_grad", pinned=True, grad=True,
          build=_build_scoring_fused_select_grad,
          note="streamed score+select forward (chunked XLA sibling) + the "
               "custom_vjp backward that recomputes only the winner's "
               "score path — nothing errmap-shaped in either direction"),
    Entry("dsac_infer_fused_select", pinned=True,
          build=_build_dsac_infer_fused_select,
          note="full single-frame inference under scoring_impl="
               "'fused_select': selection fused into the scoring stream, "
               "no (n_hyps,) score vector in the program at all"),
    Entry("esac_train_loss_dense_grad", pinned=True, grad=True,
          build=_build_esac_train_grad,
          note="multi-expert dense training loss + backward"),
    Entry("dsac_infer_frames", pinned=True, build=_build_dsac_infer_frames,
          note="frames-major serving dispatch (esac_tpu.serve): B frames "
               "per dispatch, the DESIGN.md §9 amortization path"),
    Entry("esac_infer_frames", pinned=True, build=_build_esac_infer_frames,
          note="frames-major multi-expert serving dispatch"),
    Entry("esac_infer_topk_frames", pinned=True,
          build=_build_esac_infer_topk_frames,
          note="gating-pruned frames-major serving dispatch: per-frame "
               "top-k expert subsets gathered by coordinate map (k < M so "
               "the pruned path is traced, not the dense specialization); "
               "pure geometry, so dot precision IS audited"),
    Entry("esac_infer_routed_frames", pinned=True,
          build=_build_esac_infer_routed_frames,
          note="capacity-routed frames-major hypothesis loop (DESIGN.md "
               "§11): gathered expert subsets, drop masking, reallocated "
               "budget — the RANSAC stage of the routed serve programs; "
               "pure geometry, so dot precision IS audited"),
    Entry("esac_infer_frames_prior", pinned=True,
          build=_build_esac_infer_frames_prior,
          note="prior-slot sibling of esac_infer_frames (ISSUE 20): "
               "frames-major dispatch with a static-count motion-prior "
               "hypothesis slot entering as traced (pose, validity-mask) "
               "arguments — tracked/cold/lost frames share ONE program; "
               "pure geometry, so dot precision IS audited"),
    Entry("esac_infer_routed_frames_prior", pinned=True,
          build=_build_esac_infer_routed_frames_prior,
          note="prior-slot sibling of esac_infer_routed_frames (ISSUE "
               "20): capacity-routed hypothesis loop with the session "
               "prior slot scored against every live gathered expert "
               "under the same masked -inf/strict-> tie-break parity "
               "contract; pure geometry, so dot precision IS audited"),
    Entry("routed_scene_serve", pinned=False,
          build=_build_routed_scene_serve,
          note="gating-first routed bucket program (esac_tpu.registry, "
               "k < M so the capacity dispatch itself is traced): gating "
               "CNN -> top-k -> per-expert frame blocks -> scatter -> "
               "routed esac, weights as traced jit arguments; CNN compute "
               "is legitimately bf16 in production presets so dot "
               "precision is not audited, but primitives/static-shapes "
               "are — the sparse hot path must stay scan/while-free and "
               "fixed-shape"),
    Entry("registry_scene_serve", pinned=False,
          build=_build_registry_scene_serve,
          note="multi-scene registry bucket program (esac_tpu.registry): "
               "gating + expert CNNs + frames-major esac over weights "
               "passed as jit ARGUMENTS; CNN compute is legitimately bf16 "
               "in production presets so dot precision is not audited, but "
               "primitives/static-shapes are — the hot-swap path must stay "
               "scan/while-free and fixed-shape"),
    Entry("retrieval_posterior", pinned=False,
          build=_build_retrieval_posterior,
          note="scene-retrieval forward (esac_tpu.retrieval, ISSUE 18): "
               "embedder CNN -> unit embedding -> masked cosine logits "
               "over the static max_scenes prototype axis -> posterior; "
               "prototypes and mask are TRACED arguments so "
               "enroll/remove never recompile; CNN compute follows the "
               "gating-net policy (bf16-eligible) so dot precision is "
               "not audited, but primitives/static-shapes are"),
    Entry("sharded_infer_frames_dynamic", pinned=True,
          build=_build_sharded_infer_frames_dynamic,
          note="registry-backed expert-sharded frames-major inference "
               "(parallel.make_esac_infer_sharded_frames_dynamic): the "
               "principal point rides as a traced replicated argument so "
               "one program serves every scene sharing shapes+cfg; "
               "coords-level pure geometry, so dot precision IS audited"),
    Entry("sharded_train_step", pinned=False, build=_build_sharded_train,
          note="EP+DP shard_map loss, forward only; CNN compute is "
               "legitimately bf16 so dot precision is not audited here"),
)


# --------------------------------------------------------------------------
# R11 waivers: public jitted entry points (discovered package-wide by the
# coverage gate in ast_rules) that are DELIBERATELY not traced as their own
# registry entries.  Every waiver needs a reviewed reason — an entry point
# that is neither named above nor waived here fails `python -m esac_tpu.lint`
# (rule R11).  Prefer registering over waiving; waive only when the entry's
# jaxpr is already covered transitively or is untraceable off-TPU.

R11_WAIVED: dict[str, str] = {
    "refine_pose_gn": (
        "inner Gauss-Newton polisher; traced transitively inside every "
        "pnp/dsac/esac entry via solve_pnp_minimal's polish loop"
    ),
    "esac_infer": (
        "per-frame core of esac_infer_frames (registered): identical "
        "primitives modulo the frame vmap axis"
    ),
    "esac_infer_topk": (
        "per-frame core of esac_infer_topk_frames (registered): identical "
        "primitives modulo the frame vmap axis"
    ),
    "esac_infer_prior": (
        "per-frame core of esac_infer_frames_prior (registered): identical "
        "primitives modulo the frame vmap axis"
    ),
    "sample_correspondence_sets": (
        "hypothesis sampling primitive; traced transitively inside every "
        "dsac/esac entry via generate_hypotheses"
    ),
    "sample_correspondence_sets_exact": (
        "rejection-free sampling sibling; traced transitively wherever "
        "cfg.exact_sampling selects it (same entries as above)"
    ),
    "soft_inlier_scores_pallas": (
        "deliberately unregistered: off-TPU it traces through interpret "
        "mode whose jaxpr is not the shipped kernel; parity is pinned by "
        "tests/test_pallas_scoring.py (see LINT.md)"
    ),
    "make_esac_infer_routed_frames_sharded": (
        "expert-sharded sibling of esac_infer_routed_frames (registered); "
        "shares _routed_frame_winner + route_frames_to_experts verbatim, "
        "bit-agreement pinned by tests/test_serve_routed.py's heavy leg"
    ),
    "make_dsac_serve_fn": (
        "thin jit closure over dsac_infer_frames (registered): adds only "
        "the tree unpack + constant principal point"
    ),
    "make_esac_serve_fn": (
        "thin jit closure over esac_infer_frames (registered): adds only "
        "the tree unpack + constant principal point"
    ),
    "make_dsac_train_step": (
        "single-chip training step: loss core audited via "
        "dsac_train_loss_grad; optimizer update is optax glue"
    ),
    "make_expert_train_step": (
        "single-chip expert CNN pretraining step: bf16 CNN compute is "
        "policy-exempt from pinning and the geometry-free loss has no "
        "audited invariant beyond R1-R9"
    ),
    "make_expert_reproj_train_step": (
        "single-chip reprojection finetune step: geometry core audited "
        "via refine_soft_inliers_grad/dsac_train_loss_grad"
    ),
    "make_gating_train_step": (
        "single-chip gating CNN step: bf16 CNN compute, no geometry core"
    ),
}
