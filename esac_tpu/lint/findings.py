"""Finding record + the rule catalog (ids, one-liners, rationale pointers).

Pure stdlib on purpose: layer 1 must be runnable (and fast) without
initializing anything jax-adjacent.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit.  ``text`` is the stripped offending source line — the
    line-number-independent identity used for baseline matching, so findings
    survive unrelated edits above them."""

    rule: str      # "R1".."R7" or "J1".."J3" (jaxpr auditor)
    path: str      # repo-relative, forward slashes
    line: int      # 1-based; 0 for whole-file findings
    text: str      # stripped source line ("" for whole-file findings)
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


# rule id -> (summary, rationale pointer).  LINT.md carries the full prose.
RULES = {
    "R1": (
        "module-level jnp/jax array constant (import-time backend init)",
        "CLAUDE.md environment hazards: NEVER create module-level jnp.array "
        "constants — they initialize the device backend at import time",
    ),
    "R2": (
        "raw jnp.linalg.norm / bare jnp.sqrt in differentiated geometry",
        "CLAUDE.md code conventions: where does not stop NaNs from the "
        "untaken branch's VJP; use utils.num.safe_norm / safe_sqrt (eps "
        "inside the sqrt) for anything differentiated",
    ),
    "R3": (
        "scalar-looping linalg (svd/solve/inv/...) reachable from a "
        "jit/vmap hot path",
        "CLAUDE.md code conventions / DESIGN.md: jnp.linalg.svd/solve lower "
        "to scalar loops on TPU — use triad alignment / unrolled "
        "elimination as in geometry/pnp.py",
    ),
    "R4": (
        "unpinned matmul/einsum/dot in a precision-pinned module",
        "CLAUDE.md code conventions: pin 3x3/6x6 algebra with "
        "utils.precision.hmm/heinsum — bf16-default MXU corrupts rotation "
        "math",
    ),
    "R5": (
        "config dataclass not frozen=True",
        "CLAUDE.md code conventions: configs are frozen dataclasses used as "
        "static jit args; an unfrozen config is unhashable under jit and "
        "invites silent retraces",
    ),
    "R6": (
        "ad-hoc script imports jax-adjacent modules without the force-CPU "
        "guard",
        "CLAUDE.md environment hazards: a bare interpreter that touches "
        "jax.devices() while the relay is unhealthy becomes a second stuck "
        "process; force CPU with jax.config.update('jax_platforms', 'cpu')",
    ),
    "R7": (
        "shell script timeout/kill around a python invocation "
        "(relay-wedge hazard)",
        "CLAUDE.md environment hazards: the TPU relay wedges permanently if "
        "a jax process holding/awaiting the device is killed; wrap "
        "chip-touching scripts the way bench.py does (detached child, "
        "poll, never kill)",
    ),
    # Layer-2 (jaxpr auditor) finding ids, reported with path = the
    # registry entry name:
    "J1": (
        "disallowed primitive in a registered entry point's jaxpr",
        "CLAUDE.md code conventions: no svd/lu/eig/while-with-dynamic-trip "
        "in compiled hot paths",
    ),
    "J2": (
        "non-static shape in a registered entry point's jaxpr",
        "CLAUDE.md code conventions: static shapes and fixed iteration "
        "counts everywhere under jit",
    ),
    "J3": (
        "dot_general without pinned HIGHEST/f32 precision in a "
        "precision-pinned call graph",
        "CLAUDE.md code conventions: bf16-default MXU corrupts rotation "
        "math; geometry-core contractions go through hmm/heinsum",
    ),
}
