"""Finding record + the rule catalog (ids, one-liners, rationale pointers).

Pure stdlib on purpose: layer 1 must be runnable (and fast) without
initializing anything jax-adjacent.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit.  ``text`` is the stripped offending source line — the
    line-number-independent identity used for baseline matching, so findings
    survive unrelated edits above them."""

    rule: str      # "R1".."R11" or "J1".."J4" (jaxpr auditor / ledger)
    path: str      # repo-relative, forward slashes
    line: int      # 1-based; 0 for whole-file findings
    text: str      # stripped source line ("" for whole-file findings)
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    @property
    def id(self) -> str:
        """Stable finding id for CI/driver consumption (``--format json``):
        keyed on the same line-number-independent identity the baseline
        uses, so the id survives unrelated edits above the finding."""
        digest = hashlib.sha1(
            f"{self.rule}|{self.path}|{self.text}".encode()
        ).hexdigest()[:10]
        return f"{self.rule}-{digest}"

    def to_json(self, ordinal: int = 0) -> str:
        """One-line JSON object (the ``--format json`` record).

        ``ordinal`` disambiguates findings sharing the same (rule, path,
        text) identity within one run — textually identical lines in
        different methods would otherwise collide; the CLI numbers them in
        report order (stable under edits elsewhere in the file), so a
        driver keying on ``id`` never conflates two real findings.
        """
        fid = self.id if ordinal == 0 else f"{self.id}-{ordinal + 1}"
        return json.dumps({
            "id": fid,
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "text": self.text,
            "message": self.message,
        }, sort_keys=True)


# rule id -> (summary, rationale pointer).  LINT.md carries the full prose.
RULES = {
    "R1": (
        "module-level jnp/jax array constant (import-time backend init)",
        "CLAUDE.md environment hazards: NEVER create module-level jnp.array "
        "constants — they initialize the device backend at import time",
    ),
    "R2": (
        "raw jnp.linalg.norm / bare jnp.sqrt in differentiated geometry",
        "CLAUDE.md code conventions: where does not stop NaNs from the "
        "untaken branch's VJP; use utils.num.safe_norm / safe_sqrt (eps "
        "inside the sqrt) for anything differentiated",
    ),
    "R3": (
        "scalar-looping linalg (svd/solve/inv/...) reachable from a "
        "jit/vmap hot path",
        "CLAUDE.md code conventions / DESIGN.md: jnp.linalg.svd/solve lower "
        "to scalar loops on TPU — use triad alignment / unrolled "
        "elimination as in geometry/pnp.py",
    ),
    "R4": (
        "unpinned matmul/einsum/dot in a precision-pinned module",
        "CLAUDE.md code conventions: pin 3x3/6x6 algebra with "
        "utils.precision.hmm/heinsum — bf16-default MXU corrupts rotation "
        "math",
    ),
    "R5": (
        "config dataclass not frozen=True",
        "CLAUDE.md code conventions: configs are frozen dataclasses used as "
        "static jit args; an unfrozen config is unhashable under jit and "
        "invites silent retraces",
    ),
    "R6": (
        "ad-hoc script imports jax-adjacent modules without the force-CPU "
        "guard",
        "CLAUDE.md environment hazards: a bare interpreter that touches "
        "jax.devices() while the relay is unhealthy becomes a second stuck "
        "process; force CPU with jax.config.update('jax_platforms', 'cpu')",
    ),
    "R7": (
        "shell script timeout/kill around a python invocation "
        "(relay-wedge hazard)",
        "CLAUDE.md environment hazards: the TPU relay wedges permanently if "
        "a jax process holding/awaiting the device is killed; wrap "
        "chip-touching scripts the way bench.py does (detached child, "
        "poll, never kill)",
    ),
    "R8": (
        "buffer reused after riding a donated position (donation safety)",
        "registry/serving.py donation policy: a donated buffer is "
        "invalidated at the call — reusing it (across loop iterations, "
        "after the call, or donating a cached/registry-held tree) crashes "
        "on accelerators; restage per-dispatch data, never donate cached "
        "params",
    ),
    "R9": (
        "retrace hazard: jit built in a loop / invoked inline / unhashable "
        "static argument",
        "CLAUDE.md code conventions: compile-exactly-once per bucket is "
        "load-bearing (tests/test_registry.py, tests/test_serve_routed.py) "
        "— a jit wrapper built per iteration or invoked as "
        "jax.jit(f)(x) recompiles every pass, and unhashable static "
        "arguments break jit hashing outright",
    ),
    "R10": (
        "lock-guarded mutable state touched outside the instance lock",
        "serve/dispatcher.py + registry/cache.py concurrency invariant: "
        "rings, pending queues, LRU order and per-lane stats are shared "
        "across the worker and submitter threads — every access must hold "
        "the instance lock the class already uses for the same attribute",
    ),
    "R11": (
        "public jitted entry point missing from the jaxpr-audit registry",
        "LINT.md layer 2: every compiled surface must be registered in "
        "esac_tpu/lint/registry.py (traced + audited + ledgered) or "
        "explicitly waived in R11_WAIVED with a reviewed reason — the "
        "coverage gate that keeps the entry-point matrix inside the audit "
        "(ROADMAP item 5 precondition)",
    ),
    "R12": (
        "lock-order hazard: cycle, self-deadlock, or an edge not in the "
        "committed .lock_graph.json",
        "LINT.md graft-audit v3 / DESIGN.md §15: the fleet's lock "
        "acquisition order (dispatcher -> obs instruments, registry "
        "health -> manifest, …) is a committed partial order — a cycle "
        "deadlocks, a re-acquired non-reentrant lock self-deadlocks, and "
        "a new edge needs review (--write-lock-graph + commit the diff)",
    ),
    "R13": (
        "blocking or unbounded-time call while a lock is held",
        "LINT.md graft-audit v3: Event/Condition waits, joins, sleeps, "
        "file/checkpoint IO and jax device syncs under a lock stall every "
        "thread needing it (the wedge class the SLO layer exists to "
        "bound) — snapshot under the lock, block outside (the "
        "_drain_probes / per-key cache-load-future pattern); the "
        "coalescing Condition.wait that RELEASES the held lock is the "
        "one allowlisted idiom",
    ),
    "R14": (
        "unguarded domain-edge primitive in differentiated scope "
        "(eps-free division, unclamped arccos/arcsin, log/fractional-pow "
        "of maybe-zero)",
        "LINT.md graft-audit v4 / CLAUDE.md code conventions: geometry is "
        "total + grad-safe at EVERY input — a single degenerate sample's "
        "NaN backward value poisons the whole vmapped batch gradient; "
        "guard the operand (eps-add, jnp.maximum floor, select-clamp, "
        "safe_norm/safe_sqrt), never the forward value alone",
    ),
    "R15": (
        "NaN-hazard expression inside a jnp.where/lax.select branch "
        "(the where-VJP trap) in differentiated scope",
        "LINT.md graft-audit v4: where does not stop NaNs from the "
        "untaken branch's VJP (0 * inf = NaN) — the documented trap the "
        "safe_norm/safe_sqrt/select-clamp idioms exist to avoid; guard "
        "the OPERAND (x / where(bad, 1.0, d)), not the result",
    ),
    "R16": (
        "untyped raise / taxonomy-contract violation in fleet scope "
        "(bare builtin exception minted outside __init__, missing "
        "retryable/wire_name, error with no outcome class, or an "
        "unreviewed .fault_taxonomy.json entry)",
        "LINT.md graft-audit v5 / DESIGN.md §20: every fault in the "
        "serving fleet must be a member of the closed "
        "ServeError/ManifestError taxonomy — typed, carrying retryable "
        "and a stable wire_name (ROADMAP item-2 serialization seam), and "
        "mapped to at least one accounted outcome class; "
        "constructor-argument validation confined to "
        "__init__/__post_init__ is the sanctioned near-miss",
    ),
    "R17": (
        "broad except swallows: neither re-raises, converts to a typed "
        "error, resolves a future/_finish, nor records a counter/outcome",
        "LINT.md graft-audit v5 / DESIGN.md §13: a fault must end in "
        "exactly one accounted outcome — the BaseException guards in "
        "registry/cache.py and serve/dispatcher.py that resolve per-key "
        "futures and re-raise are the allowlisted shape (matched "
        "structurally); `except Exception: pass` is the flagged one",
    ),
    "R18": (
        "thread/future lifecycle hazard: non-daemon Thread, bare "
        "join(), or a per-key load future without an all-exit-paths "
        "owner",
        "LINT.md graft-audit v5 / CLAUDE.md environment hazards as a "
        "rule: a thread wedged on the TPU relay can never be killed — "
        "fleet threads must be daemon with a bounded join(timeout)-"
        "then-abandon close path, and a minted load future must be "
        "set() on every exit (an un-set Event strands waiters forever)",
    ),
    # Layer-2 (jaxpr auditor) finding ids, reported with path = the
    # registry entry name:
    "J1": (
        "disallowed primitive in a registered entry point's jaxpr",
        "CLAUDE.md code conventions: no svd/lu/eig/while-with-dynamic-trip "
        "in compiled hot paths",
    ),
    "J2": (
        "non-static shape in a registered entry point's jaxpr",
        "CLAUDE.md code conventions: static shapes and fixed iteration "
        "counts everywhere under jit",
    ),
    "J3": (
        "dot_general without pinned HIGHEST/f32 precision in a "
        "precision-pinned call graph",
        "CLAUDE.md code conventions: bf16-default MXU corrupts rotation "
        "math; geometry-core contractions go through hmm/heinsum",
    ),
    "J4": (
        "jaxpr resource ledger regression vs the committed "
        ".jaxpr_ledger.json",
        "LINT.md ledger workflow: per-entry flops / peak intermediate "
        "bytes / dot-precision census are committed numbers — growth "
        "beyond tolerance, a dropped HIGHEST pin, or an unledgered entry "
        "fails; regenerate with --write-ledger and review the diff",
    ),
    "J5": (
        "backward-jaxpr grad-hazard census regression vs the committed "
        ".jaxpr_ledger.json (new unguarded domain-edge site)",
        "LINT.md graft-audit v4: every grad-registered entry's traced "
        "backward is walked for domain-edge primitives (div, rsqrt, pow, "
        "log, acos, asin, atan2) keyed by whether an eps-add/floor/clamp "
        "dominates the vulnerable operand; the counts are committed — an "
        "unreviewed NEW unguarded site fails, improvements report stale "
        "(--write-ledger + review, the J4 workflow)",
    ),
}
