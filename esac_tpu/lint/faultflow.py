"""Layer 1e, R16/R17/R18: fault-flow analysis (graft-audit v5).

DESIGN.md §13's contract — every way a request or scene can go bad ends
in EXACTLY one typed, accounted outcome — was until now enforced only by
runtime drills: nothing stopped a new ``raise RuntimeError(...)`` in
fleet scope, a broad ``except`` that silently swallowed, or a thread
lifecycle that re-created the relay-wedge hazard CLAUDE.md documents.
This module is the static side of that contract, following the proven
graft-audit shape (v3 lock graph, v4 grad ledger): a pure-AST pass over
``esac_tpu/{serve,registry,fleet,obs}/`` → a committed artifact
(``.fault_taxonomy.json``) → a tier-1 diff gate → a runtime witness
(:class:`esac_tpu.lint.witness.OutcomeWitness`, riding ``bench.py
chaos`` and the fleet drill).

**The taxonomy.**  An error class is a taxonomy member when it derives
(transitively, within fleet scope) from ``ServeError`` (serve/slo.py)
or ``ManifestError`` (registry/manifest.py).  Every member must carry
an EXPLICIT literal ``retryable`` flag and a stable literal
``wire_name`` — the ROADMAP item-2 serialization seam: a typed error
crossing an RPC wire is identified by ``wire_name``, never by a Python
qualname — and wire names must be unique fleet-wide.

**R16 — untyped raise.**  Every raise site in fleet scope that MINTS an
exception (``raise SomeClass(...)``) must mint a taxonomy member.  A
bare ``ValueError``/``RuntimeError``/``AssertionError``/... escaping to
callers flags.  The one sanctioned near-miss class is
constructor-argument validation that cannot outlive construction: a
builtin raise whose innermost enclosing function is ``__init__`` or
``__post_init__`` passes (the frozen-dataclass policy objects and the
dispatcher/router constructors all validate there).  Raises that only
PROPAGATE an existing exception object — bare ``raise``, ``raise e``,
``raise fut["error"]``, ``raise req.error`` — are the handler's job,
not a minting site, and never flag here (R17 owns the handlers).

**R17 — exception swallowing.**  A broad handler (bare ``except``,
``except Exception``, ``except BaseException``, or a tuple containing
either) must visibly dispose of the fault.  Disposal is matched
STRUCTURALLY, not by name — the handler body must contain at least one
of: a ``raise`` (re-raise or typed conversion); a counter record
(any augmented assignment — ``self.load_failures += 1``,
``failures += 1``); a store into non-local state (attribute or
subscript assignment — ``out[name] = {"error": repr(e)}``); or a call
into the resolve/record surface (``.set()`` on a future's event,
``.inc``/``.observe``/``.add``/``.append`` on an instrument, or any
``_finish*``/``_record*``/``_abandon``/``_on_worker*``/``_note*``
method — the dispatcher/cache idiom that resolves waiters).  The
``except BaseException`` guards in ``registry/cache.py`` and
``serve/dispatcher.py`` that resolve per-key futures and re-raise are
exactly the allowlisted shape; ``except Exception: pass`` is exactly
the flagged one.

**R18 — thread/future lifecycle.**  The CLAUDE.md relay hazard as a
rule: (1) every ``threading.Thread(...)`` constructed in fleet scope
must be created ``daemon=True`` (a non-daemon thread wedged on the TPU
relay pins the process forever); (2) a bare ``.join()`` — no timeout
argument — flags: the close path must be ``join(timeout)`` then
abandon, never an unbounded wait, never a kill; (3) every per-key load
future (a dict literal carrying an ``"event"`` key stored under a
subscript — the ``self._loading[key] = {...}`` idiom) must have an
owner that resolves it on ALL exit paths: the owning function needs an
``except BaseException`` handler that both stores the ``"error"`` slot
and ``.set()``s the event, plus a success-path ``.set()``.

**The artifact.**  :func:`build_taxonomy` emits the closed catalog:
per error class its module, bases, ``retryable``, ``wire_name``, mint
(raise/construction) sites and handler sites as line-number-independent
``file::Class.method`` ids, and the raise→outcome edges — which of the
outcome classes (:data:`OUTCOME_CLASSES`) each error lands in, as
extracted from the recorder calls (``_finish``/``_finish_locked``/
``_count_outcome`` with a literal outcome), typed-handler bodies,
raise-context recording, and the broad accounting backstops (recorded
as the wildcard error ``"*"``).  A class's EFFECTIVE outcomes are its
direct edges plus its taxonomy ancestors' (a handler naming
``ShedError`` disposes of ``LaneQuarantinedError`` too); the witness
additionally accepts the wildcard backstop edges.  A minted error with
no effective outcome and no backstop anywhere fails (R16): a raise site
mapping to NO outcome class is exactly the leak DESIGN.md §13 bans.
:func:`diff_taxonomy` applies the v3/v4 gate: a NEW error class, a NEW
raise→outcome edge, or a drifted ``retryable``/``wire_name`` contract
needs a reviewed ``--write-fault-taxonomy`` diff; vanished entries
report stale.

Pure stdlib — no jax, no imports of the checked modules.
"""

from __future__ import annotations

import ast
import json
import pathlib

from esac_tpu.lint.ast_rules import _alias_map, _dotted, iter_python_files
from esac_tpu.lint.findings import Finding
from esac_tpu.lint.lockgraph import FLEET_PREFIXES, PASS_PREFIXES
from esac_tpu.lint.suppress import is_suppressed, parse_suppressions

FAULT_TAXONOMY_NAME = ".fault_taxonomy.json"

# The taxonomy roots: deriving from either (transitively, inside fleet
# scope) makes a class a member.
TAXONOMY_ROOTS = ("ServeError", "ManifestError")

# The closed outcome vocabulary a typed error may land in (DESIGN.md
# §13/§20).  "quarantined" is the scene/replica-level terminal class —
# carried by breaker and fleet bookkeeping, not per-request counters.
OUTCOME_CLASSES = ("served", "shed", "expired", "degraded", "failed",
                   "quarantined")

# Builtin exception classes whose MINTING in fleet scope flags R16.
_BUILTIN_RAISES = frozenset({
    "Exception", "BaseException", "ValueError", "TypeError",
    "RuntimeError", "AssertionError", "KeyError", "IndexError",
    "LookupError", "AttributeError", "OSError", "IOError",
    "NotImplementedError", "ArithmeticError", "ZeroDivisionError",
    "StopIteration", "FileNotFoundError", "PermissionError",
    "TimeoutError", "InterruptedError", "BufferError", "EOFError",
})

# The sanctioned R16 near-miss scope: constructor-argument validation
# that cannot outlive construction (__post_init__ is the frozen-
# dataclass spelling of the same thing).
_INIT_SCOPES = ("__init__", "__post_init__")

_BROAD_EXCEPTS = ("Exception", "BaseException")

# Attribute-call names that count as R17 disposal (resolve/record).
_RESOLVE_ATTRS = frozenset({"set", "inc", "observe", "add", "append",
                            "notify", "notify_all"})
_RESOLVE_PREFIXES = ("_finish", "_record", "_abandon", "_on_worker",
                     "_note")


def fault_pass_needed(files) -> bool:
    """Mirror of lockgraph.lock_pass_needed for the fault-flow pass:
    full runs always analyze; scoped runs only when a fleet or lint
    file changed."""
    if files is None:
        return True
    return any(
        f.startswith(PASS_PREFIXES) and f.endswith(".py") for f in files
    )


# --------------------------------------------------------------------------
# small AST helpers

def _class_name_of(node, aliases) -> str | None:
    """The bare class name a raise/except/base expression refers to
    (``ShedError``, ``slo.ShedError`` -> ``ShedError``), or None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        dotted = _dotted(node, aliases)
        if dotted:
            return dotted.rsplit(".", 1)[-1]
        return node.attr
    return None


def _handler_names(handler: ast.ExceptHandler, aliases) -> list[str | None]:
    """Exception class names an except clause catches; [None] for bare."""
    t = handler.type
    if t is None:
        return [None]
    if isinstance(t, ast.Tuple):
        return [_class_name_of(e, aliases) for e in t.elts]
    return [_class_name_of(t, aliases)]


def _outcome_literals(call: ast.Call) -> list[str]:
    """Literal outcome-class strings among a call's args/kwargs."""
    out = []
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(a, ast.Constant) and a.value in OUTCOME_CLASSES:
            out.append(a.value)
    return out


def _refs_name(node, name: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(node)
    )


def _line(lines, lineno):
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


# --------------------------------------------------------------------------
# the analysis

class _ErrorClass:
    """One taxonomy member's statically collected facts."""

    def __init__(self, name: str, rel: str, bases: list[str]):
        self.name = name
        self.rel = rel
        self.bases = bases
        self.retryable = None       # literal bool, or None if not explicit
        self.wire_name = None       # literal str, or None if not explicit
        self.lineno = 0


class _Analysis:
    def __init__(self, root: pathlib.Path, prefixes=FLEET_PREFIXES):
        self.root = pathlib.Path(root)
        self.prefixes = prefixes
        # rel -> (tree, aliases, lines, per_line, per_file)
        self.files: dict[str, tuple] = {}
        self.errors: dict[str, _ErrorClass] = {}
        # (error name | "*", outcome) -> set of provenance fn ids
        self.edges: dict[tuple[str, str], set] = {}
        self.raise_sites: dict[str, set] = {}
        self.handler_sites: dict[str, set] = {}
        self.findings: list[Finding] = []
        # (rel, class name | None, fn name) -> set of taxonomy classes
        # the function returns constructed (the `return ShedError(...)`
        # admission idiom — `raise why` resolves through this).
        self._fn_returns: dict[tuple, set] = {}
        self._load()
        self._collect_classes()
        self._collect_returns()
        for rel in sorted(self.files):
            self._walk_file(rel)
        self._taxonomy_checks()
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.text))

    # ---- loading ----

    def _load(self) -> None:
        for rel in iter_python_files(self.root):
            if not rel.startswith(self.prefixes):
                continue
            try:
                source = (self.root / rel).read_text()
                tree = ast.parse(source)
            except (OSError, SyntaxError):
                continue  # R1's problem, not ours
            per_line, per_file = parse_suppressions(source)
            self.files[rel] = (tree, _alias_map(tree), source.splitlines(),
                               per_line, per_file)

    def _emit(self, rule: str, rel: str, node, text: str, message: str):
        _, _, lines, per_line, per_file = self.files[rel]
        lineno = getattr(node, "lineno", 0)
        if is_suppressed(rule, lineno, per_line, per_file, path=rel):
            return
        self.findings.append(Finding(rule, rel, lineno, text, message))

    # ---- pass 1: the error-class table ----

    def _collect_classes(self) -> None:
        raw: dict[str, tuple] = {}
        for rel, (tree, aliases, *_rest) in self.files.items():
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases = [b for b in
                         (_class_name_of(x, aliases) for x in node.bases)
                         if b is not None]
                if node.name not in raw:
                    raw[node.name] = (rel, node, bases)
        members = set(n for n in TAXONOMY_ROOTS if n in raw)
        changed = True
        while changed:
            changed = False
            for name, (_rel, _node, bases) in raw.items():
                if name not in members and any(b in members for b in bases):
                    members.add(name)
                    changed = True
        for name in members:
            rel, node, bases = raw[name]
            ec = _ErrorClass(name, rel, bases)
            ec.lineno = node.lineno
            for item in node.body:
                tgt = None
                if isinstance(item, ast.Assign) and len(item.targets) == 1 \
                        and isinstance(item.targets[0], ast.Name):
                    tgt = item.targets[0].id
                    val = item.value
                elif isinstance(item, ast.AnnAssign) \
                        and isinstance(item.target, ast.Name) \
                        and item.value is not None:
                    tgt = item.target.id
                    val = item.value
                if tgt == "retryable" and isinstance(val, ast.Constant) \
                        and isinstance(val.value, bool):
                    ec.retryable = val.value
                elif tgt == "wire_name" and isinstance(val, ast.Constant) \
                        and isinstance(val.value, str):
                    ec.wire_name = val.value
            self.errors[name] = ec

    # ---- pass 2: admission-idiom return classes ----

    def _collect_returns(self) -> None:
        for rel, (tree, aliases, *_rest) in self.files.items():
            for node in ast.walk(tree):
                if not isinstance(node, ast.FunctionDef):
                    continue
                cls = self._owner_class(tree, node)
                returned = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) and \
                            isinstance(sub.value, ast.Call):
                        name = _class_name_of(sub.value.func, aliases)
                        if name in self.errors:
                            returned.add(name)
                if returned:
                    self._fn_returns[(rel, cls, node.name)] = returned

    @staticmethod
    def _owner_class(tree, fn) -> str | None:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and fn in node.body:
                return node.name
        return None

    # ---- pass 3: per-function fault-flow walk ----

    def _walk_file(self, rel: str) -> None:
        tree, aliases, *_rest = self.files[rel]
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        self._walk_fn(rel, node.name, item, [item.name])
            elif isinstance(node, ast.FunctionDef):
                self._walk_fn(rel, None, node, [node.name])

    def _fnid(self, rel: str, cls: str | None, stack: list) -> str:
        qual = ".".join(([cls] if cls else []) + stack)
        return f"{rel}::{qual}"

    def _walk_fn(self, rel, cls, fn, stack) -> None:
        _tree, aliases, lines, *_rest = self.files[rel]
        fnid = self._fnid(rel, cls, stack)
        in_init = len(stack) == 1 and stack[0] in _INIT_SCOPES
        # local name -> set of taxonomy classes it may hold (assigned
        # from a constructor or an admission-idiom helper call).
        local_err: dict[str, set] = {}

        def resolve_call_classes(call: ast.Call) -> set:
            """Taxonomy classes a call expression may produce."""
            name = _class_name_of(call.func, aliases)
            if name in self.errors:
                return {name}
            # self._helper(...) / module_fn(...) admission idiom
            if isinstance(call.func, ast.Attribute) and \
                    isinstance(call.func.value, ast.Name) and \
                    call.func.value.id == "self":
                return set(self._fn_returns.get(
                    (rel, cls, call.func.attr), ()))
            if isinstance(call.func, ast.Name):
                return set(self._fn_returns.get(
                    (rel, None, call.func.id), ()))
            return set()

        def minted_in_expr(node) -> set:
            """Taxonomy classes constructed anywhere inside ``node``
            (direct calls, lambdas, locals with known error type)."""
            out = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = _class_name_of(sub.func, aliases)
                    if name in self.errors:
                        out.add(name)
                elif isinstance(sub, ast.Name) and sub.id in local_err:
                    out |= local_err[sub.id]
            return out

        def add_edge(err: str, outcome: str) -> None:
            self.edges.setdefault((err, outcome), set()).add(fnid)

        def scan_call(call: ast.Call) -> None:
            """Rule (a): recorder call carrying BOTH a minted taxonomy
            error and a literal outcome; plus R18 thread/join checks
            and mint-site bookkeeping."""
            outcomes = _outcome_literals(call)
            minted = set()
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                minted |= minted_in_expr(a)
            for c in sorted(minted):
                self.raise_sites.setdefault(c, set()).add(fnid)
                for o in outcomes:
                    add_edge(c, o)
            # R18: thread creation must be daemon=True.
            dotted = _dotted(call.func, aliases)
            if dotted in ("threading.Thread", "Thread"):
                daemon = next(
                    (kw.value for kw in call.keywords
                     if kw.arg == "daemon"), None)
                if not (isinstance(daemon, ast.Constant)
                        and daemon.value is True):
                    self._emit(
                        "R18", rel, call, f"thread:{fnid}",
                        f"{_line(lines, call.lineno)!r}: Thread created "
                        "without daemon=True in fleet scope — a non-daemon "
                        "thread wedged on the TPU relay pins the process "
                        "forever (CLAUDE.md hazard); create it daemon and "
                        "give close() a bounded join",
                    )
            # R18: bare .join() (no timeout) is an unbounded wait.
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "join" \
                    and not call.args and not call.keywords:
                self._emit(
                    "R18", rel, call, f"join:{fnid}",
                    f"{_line(lines, call.lineno)!r}: bare join() in fleet "
                    "scope — a thread wedged on the TPU relay makes this "
                    "wait forever; use join(timeout) then abandon the "
                    "daemon thread (the dispatcher-watchdog idiom)",
                )

        def scan_raise(node: ast.Raise) -> None:
            """R16 + mint-site bookkeeping for raise statements."""
            exc = node.exc
            if exc is None:
                return  # bare re-raise: propagation
            call = exc if isinstance(exc, ast.Call) else None
            target = call.func if call is not None else exc
            name = _class_name_of(target, aliases)
            if name in self.errors:
                self.raise_sites.setdefault(name, set()).add(fnid)
                return
            if isinstance(target, ast.Name) and call is None:
                # ``raise e`` / ``raise why``: propagation of an object
                # minted elsewhere; the admission idiom resolves below
                # through local_err (raise-context edges), never R16.
                return
            if name in _BUILTIN_RAISES and not in_init:
                self._emit(
                    "R16", rel, node, f"raise:{name}@{fnid}",
                    f"{_line(lines, node.lineno)!r}: mints untyped "
                    f"{name} in fleet scope — callers cannot classify it "
                    "into an outcome; raise a ServeError/ManifestError "
                    "taxonomy member (or validate in __init__/"
                    "__post_init__, the sanctioned near-miss)",
                )

        def raise_classes(node: ast.Raise) -> set:
            exc = node.exc
            if exc is None:
                return set()
            if isinstance(exc, ast.Call):
                return resolve_call_classes(exc)
            if isinstance(exc, ast.Name):
                return set(local_err.get(exc.id, ()))
            return set()

        def handler_is_broad(handler: ast.ExceptHandler) -> bool:
            names = _handler_names(handler, aliases)
            return any(n is None or n in _BROAD_EXCEPTS for n in names)

        def handler_disposes(handler: ast.ExceptHandler) -> bool:
            for sub in ast.walk(handler):
                if isinstance(sub, ast.Raise):
                    return True
                if isinstance(sub, ast.AugAssign):
                    return True
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    if any(isinstance(t, (ast.Subscript, ast.Attribute))
                           for t in targets):
                        return True
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute):
                    attr = sub.func.attr
                    if attr in _RESOLVE_ATTRS or \
                            attr.startswith(_RESOLVE_PREFIXES):
                        return True
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name) and \
                        sub.func.id.startswith(_RESOLVE_PREFIXES):
                    return True
            return False

        def scan_handler(handler: ast.ExceptHandler) -> None:
            names = [n for n in _handler_names(handler, aliases)
                     if n in self.errors]
            for n in names:
                self.handler_sites.setdefault(n, set()).add(fnid)
            # Typed-handler edges: an outcome literal anywhere in the
            # body (recorder arg or stored assignment value) maps every
            # named taxonomy class onto it.
            outcomes = set()
            for sub in ast.walk(handler):
                if isinstance(sub, ast.Call):
                    outcomes.update(_outcome_literals(sub))
                elif isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Constant) and \
                        sub.value.value in OUTCOME_CLASSES:
                    outcomes.add(sub.value.value)
            for n in names:
                for o in sorted(outcomes):
                    add_edge(n, o)
            if handler_is_broad(handler):
                # Wildcard backstop edges: a recorder call that carries
                # the caught object AND a literal outcome accounts for
                # ANY error reaching this handler.
                caught = handler.name
                if caught:
                    for sub in ast.walk(handler):
                        if isinstance(sub, ast.Call) and \
                                _refs_name(sub, caught):
                            for o in _outcome_literals(sub):
                                add_edge("*", o)
                if not handler_disposes(handler):
                    shape = "bare except" if handler.type is None else \
                        f"except {_class_name_of(handler.type, aliases)}" \
                        if not isinstance(handler.type, ast.Tuple) else \
                        "except (...broad...)"
                    self._emit(
                        "R17", rel, handler, f"swallow:{fnid}",
                        f"{shape} at line {handler.lineno} swallows: the "
                        "handler neither re-raises, converts to a typed "
                        "taxonomy error, resolves a future/_finish, nor "
                        "records a counter/outcome — a fault must end in "
                        "exactly one accounted outcome (DESIGN.md §13); "
                        "the cache.py BaseException guard is the "
                        "allowlisted shape",
                    )

        def track_assign(stmt) -> None:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                classes = resolve_call_classes(stmt.value)
                if classes:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            local_err[t.id] = set(classes)

        def walk_block(body: list) -> None:
            """One statement list: sequential raise-context tracking
            (a recorder call with a literal outcome followed by a raise
            in the same block binds the minted classes to it), plus
            recursion into nested blocks.  No per-node scans here —
            those run exactly once in the ``scan`` pass below."""
            pending: list[str] = []
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested defs get their own _walk_fn pass
                track_assign(stmt)
                if isinstance(stmt, ast.Expr) and \
                        isinstance(stmt.value, ast.Call):
                    outcomes = _outcome_literals(stmt.value)
                    if outcomes:
                        pending = outcomes
                if isinstance(stmt, ast.Raise):
                    for c in sorted(raise_classes(stmt)):
                        self.raise_sites.setdefault(c, set()).add(fnid)
                        for o in pending:
                            add_edge(c, o)
                for field in ("body", "orelse", "finalbody"):
                    nested = getattr(stmt, field, None)
                    if nested:
                        walk_block(nested)
                for handler in getattr(stmt, "handlers", []) or []:
                    walk_block(handler.body)

        def scan(node) -> None:
            """Generic per-node scan (rule-a edges, R16-R18, handler
            edges): visits every node of this function EXACTLY once,
            pruning nested defs (their own walk contexts)."""
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                if isinstance(child, ast.Call):
                    scan_call(child)
                elif isinstance(child, ast.Raise):
                    scan_raise(child)
                elif isinstance(child, ast.ExceptHandler):
                    scan_handler(child)
                scan(child)

        # Sequential pass first: it fills local_err for the whole
        # function, which the generic scan's minted_in_expr reads.
        walk_block(fn.body)
        scan(fn)
        self._check_future_owner(rel, cls, fn, fnid, aliases, lines)
        # Nested defs are their own (non-init) walk contexts.
        for sub in ast.walk(fn):
            if isinstance(sub, ast.FunctionDef) and sub is not fn and \
                    self._direct_parent_is(fn, sub):
                self._walk_fn(rel, cls, sub, stack + [sub.name])

    @staticmethod
    def _direct_parent_is(parent, child) -> bool:
        """True when ``child`` is nested in ``parent`` with no other
        FunctionDef in between (each nesting level walks its own)."""
        for node in ast.walk(parent):
            if isinstance(node, ast.FunctionDef) and node is not parent \
                    and node is not child:
                if any(n is child for n in ast.walk(node)):
                    return False
        return any(n is child for n in ast.walk(parent))

    def _check_future_owner(self, rel, cls, fn, fnid, aliases, lines):
        """R18 future-lifecycle: a function that mints a per-key load
        future must resolve it on all exit paths (see module docstring)."""
        mints = False
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Dict):
                keys = {k.value for k in sub.value.keys
                        if isinstance(k, ast.Constant)}
                if "event" in keys and any(
                        isinstance(t, ast.Subscript) for t in sub.targets):
                    mints = True
        if not mints:
            return
        guarded = False
        set_calls = 0
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "set" and not sub.args:
                set_calls += 1
            if isinstance(sub, ast.ExceptHandler) and \
                    _class_name_of(sub.type, aliases) == "BaseException":
                stores_error = any(
                    isinstance(n, ast.Assign) and any(
                        isinstance(t, ast.Subscript) and
                        isinstance(t.slice, ast.Constant) and
                        t.slice.value == "error" for t in n.targets)
                    for n in ast.walk(sub)
                )
                sets_event = any(
                    isinstance(n, ast.Call) and
                    isinstance(n.func, ast.Attribute) and
                    n.func.attr == "set" for n in ast.walk(sub)
                )
                if stores_error and sets_event:
                    guarded = True
        if not guarded or set_calls < 2:
            self._emit(
                "R18", rel, fn, f"future:{fnid}",
                f"{fnid} mints a per-key load future but does not resolve "
                "it on every exit path: the owner needs an `except "
                "BaseException` that stores the \"error\" slot and sets "
                "the event, plus the success-path set() — an un-set Event "
                "strands every waiter forever (the cache.get idiom)",
            )

    # ---- pass 4: taxonomy-contract checks ----

    def _effective_outcomes(self, name: str) -> set:
        """Direct edges plus taxonomy ancestors' (a ShedError handler
        disposes of every ShedError subclass)."""
        out = set()
        seen = set()
        stack = [name]
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            out |= {o for (e, o) in self.edges if e == n}
            ec = self.errors.get(n)
            if ec is not None:
                stack.extend(b for b in ec.bases if b in self.errors)
        return out

    def _taxonomy_checks(self) -> None:
        wildcard = any(e == "*" for (e, _o) in self.edges)
        wires: dict[str, str] = {}
        for name in sorted(self.errors):
            ec = self.errors[name]
            node_stub = type("L", (), {"lineno": ec.lineno})()
            if ec.retryable is None:
                self._emit(
                    "R16", ec.rel, node_stub, f"error:{name}:retryable",
                    f"taxonomy error {name} lacks an explicit literal "
                    "`retryable` bool — every member carries its own "
                    "flag (the breaker/failover contract reads it)",
                )
            if ec.wire_name is None:
                self._emit(
                    "R16", ec.rel, node_stub, f"error:{name}:wire_name",
                    f"taxonomy error {name} lacks an explicit literal "
                    "`wire_name` str — the stable cross-wire identity "
                    "(ROADMAP item 2 serialization seam)",
                )
            elif ec.wire_name in wires:
                self._emit(
                    "R16", ec.rel, node_stub, f"error:{name}:wire_dup",
                    f"taxonomy error {name} reuses wire_name "
                    f"{ec.wire_name!r} (also {wires[ec.wire_name]}) — "
                    "wire names identify classes and must be unique",
                )
            else:
                wires[ec.wire_name] = name
            if self.raise_sites.get(name) and \
                    not self._effective_outcomes(name) and not wildcard:
                self._emit(
                    "R16", ec.rel, node_stub, f"error:{name}:no-outcome",
                    f"taxonomy error {name} is minted but maps to NO "
                    "outcome class: no typed handler, recorder call or "
                    "accounting backstop disposes of it — exactly the "
                    "leak DESIGN.md §13 bans",
                )

    # ---- the artifact ----

    def taxonomy(self) -> dict:
        errors = {}
        for name in sorted(self.errors):
            ec = self.errors[name]
            errors[name] = {
                "module": ec.rel,
                "bases": sorted(ec.bases),
                "retryable": ec.retryable,
                "wire_name": ec.wire_name,
                "raise_sites": sorted(self.raise_sites.get(name, ())),
                "handler_sites": sorted(self.handler_sites.get(name, ())),
                "outcomes": sorted(self._effective_outcomes(name)),
            }
        edges = [
            {"error": e, "outcome": o, "via": sorted(via)}
            for (e, o), via in sorted(self.edges.items())
        ]
        return {"errors": errors, "edges": edges,
                "outcome_classes": list(OUTCOME_CLASSES)}


# --------------------------------------------------------------------------
# public API

# Same memo contract as lockgraph: one full lint run needs the analysis
# twice (run_layer1's R16-R18 pass + the CLI's committed-taxonomy diff).
_MEMO: dict = {}
_MEMO_CAP = 8


def analyze(root, prefixes=FLEET_PREFIXES) -> _Analysis:
    root = pathlib.Path(root)
    try:
        fingerprint = tuple(
            (rel, (root / rel).stat().st_mtime_ns, (root / rel).stat().st_size)
            for rel in iter_python_files(root)
            if rel.startswith(prefixes)
        )
    except OSError:
        return _Analysis(root, prefixes)  # racing tree: skip the memo
    key = (str(root.resolve()), prefixes, fingerprint)
    a = _MEMO.get(key)
    if a is None:
        a = _Analysis(root, prefixes)
        if len(_MEMO) >= _MEMO_CAP:
            _MEMO.pop(next(iter(_MEMO)))
        _MEMO[key] = a
    return a


def build_taxonomy(root, prefixes=FLEET_PREFIXES) -> dict:
    return analyze(root, prefixes).taxonomy()


def run_faultflow_rules(root, files=None, prefixes=FLEET_PREFIXES):
    """R16/R17/R18 findings over the fleet scope of ``root``.  The whole
    scope is always analyzed — the taxonomy is a fleet-global property —
    but the pass is skipped entirely when a scoped run touched no
    fleet/lint file (``--changed`` fast mode).  The committed-taxonomy
    DIFF is the CLI's job (ledger pattern)."""
    if not fault_pass_needed(files):
        return []
    return analyze(root, prefixes).findings


def write_taxonomy(path: pathlib.Path, taxonomy: dict) -> None:
    data = {
        "comment": "graft-audit v5 fault taxonomy; see LINT.md.  The "
                   "closed typed-error catalog of the serving fleet: "
                   "per error its module, retryable flag, stable "
                   "wire_name (the serialization identity), mint and "
                   "handler sites (file::Class.method, line-number-"
                   "independent), and the raise->outcome edges — which "
                   "accounted outcome class each error lands in "
                   "(\"*\" is the broad accounting backstop).  A NEW "
                   "error class or raise->outcome edge fails tier-1 "
                   "until regenerated with `python -m esac_tpu.lint "
                   "--write-fault-taxonomy` and reviewed; the runtime "
                   "witness (lint/witness.py OutcomeWitness) asserts "
                   "every error type observed in the chaos/fleet drills "
                   "is a member and lands inside these edges.",
        **taxonomy,
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


def load_taxonomy(path: pathlib.Path) -> dict | None:
    if not path.exists():
        return None
    data = json.loads(path.read_text())
    return {
        "errors": data.get("errors", {}),
        "edges": data.get("edges", []),
        "outcome_classes": data.get("outcome_classes",
                                    list(OUTCOME_CLASSES)),
    }


def _edge_map(taxonomy: dict) -> dict[tuple[str, str], list[str]]:
    return {
        (e["error"], e["outcome"]): list(e.get("via", []))
        for e in taxonomy.get("edges", [])
    }


def diff_taxonomy(committed: dict, current: dict):
    """-> (R16 findings, stale notes), the v3/v4 gate contract: a NEW
    error class, a NEW raise->outcome edge, or a drifted
    retryable/wire_name contract fails until reviewed; vanished or
    drifted-provenance entries are stale (regenerate + review)."""
    findings: list[Finding] = []
    stale: list[str] = []
    want_err = committed.get("errors", {})
    have_err = current.get("errors", {})
    for name in sorted(set(have_err) - set(want_err)):
        findings.append(Finding(
            "R16", FAULT_TAXONOMY_NAME, 0, f"error:{name}",
            f"unreviewed new taxonomy error {name} "
            f"({have_err[name].get('module')}): not in the committed "
            f"{FAULT_TAXONOMY_NAME} — if intentional, regenerate with "
            "`python -m esac_tpu.lint --write-fault-taxonomy`, review "
            "the diff (is retryable right? is the wire name stable and "
            "unique? which outcomes dispose of it?), and commit",
        ))
    for name in sorted(set(want_err) - set(have_err)):
        stale.append(
            f"committed taxonomy error {name} no longer exists — "
            "regenerate with --write-fault-taxonomy"
        )
    for name in sorted(set(want_err) & set(have_err)):
        w, h = want_err[name], have_err[name]
        for field in ("retryable", "wire_name"):
            if w.get(field) != h.get(field):
                findings.append(Finding(
                    "R16", FAULT_TAXONOMY_NAME, 0,
                    f"contract:{name}:{field}",
                    f"taxonomy error {name} changed {field}: "
                    f"{w.get(field)!r} -> {h.get(field)!r} — the wire "
                    "contract is load-bearing (item-2 serialization); "
                    "if intentional, regenerate with "
                    "--write-fault-taxonomy and review",
                ))
        for field in ("raise_sites", "handler_sites", "outcomes"):
            if w.get(field) != h.get(field):
                stale.append(
                    f"taxonomy error {name} {field} drifted "
                    f"({w.get(field)} -> {h.get(field)}) — regenerate "
                    "with --write-fault-taxonomy and review the diff"
                )
    want = _edge_map(committed)
    have = _edge_map(current)
    for (err, outcome), via in sorted(have.items()):
        old = want.get((err, outcome))
        if old is None:
            findings.append(Finding(
                "R16", FAULT_TAXONOMY_NAME, 0, f"edge:{err}->{outcome}",
                f"unreviewed raise->outcome edge {err} -> {outcome} "
                f"(via {', '.join(via)}): not in the committed "
                f"{FAULT_TAXONOMY_NAME} — if intentional, regenerate "
                "with `python -m esac_tpu.lint --write-fault-taxonomy` "
                "and review (does the new disposal keep the accounting "
                "exact?)",
            ))
        elif sorted(old) != sorted(via):
            stale.append(
                f"taxonomy edge {err} -> {outcome} changed provenance "
                f"({', '.join(old)} -> {', '.join(via)}) — regenerate "
                "with --write-fault-taxonomy"
            )
    for (err, outcome) in sorted(set(want) - set(have)):
        stale.append(
            f"committed taxonomy edge {err} -> {outcome} is no longer "
            "taken by any code path — regenerate with "
            "--write-fault-taxonomy"
        )
    return findings, stale


def effective_outcomes(taxonomy: dict) -> dict[str, set]:
    """Per-error effective outcome sets from a (committed) taxonomy
    dict: direct edges + taxonomy ancestors' + the wildcard backstop —
    the membership test the runtime OutcomeWitness applies to every
    observed (error type, outcome) pair."""
    errors = taxonomy.get("errors", {})
    direct: dict[str, set] = {}
    wildcard: set = set()
    for e in taxonomy.get("edges", []):
        if e["error"] == "*":
            wildcard.add(e["outcome"])
        else:
            direct.setdefault(e["error"], set()).add(e["outcome"])
    out: dict[str, set] = {}
    for name in errors:
        acc = set(wildcard)
        seen: set = set()
        stack = [name]
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            acc |= direct.get(n, set())
            stack.extend(b for b in errors.get(n, {}).get("bases", ())
                         if b in errors)
        out[name] = acc
    return out
