"""Retriever model: image -> scene embedding -> posterior over scenes.

ESAC's gating CNN one level up (ISSUE 18, DESIGN.md §22): where
``models/gating.py`` distributes hypotheses over the experts *within*
a scene, the retriever distributes an image-only request over the
*scenes of the whole fleet*.  Same conv trunk shape, but the head emits
an L2-normalized embedding instead of fixed-arity logits: scene
identities live in a per-scene PROTOTYPE table (``index.SceneIndex``)
that is a TRACED argument of the one jitted forward — padded to a
static ``max_scenes`` axis and masked, so scenes can be enrolled and
removed without ever recompiling (the registry's no-recompile hot-swap
contract, applied to retrieval).

The forward is registered in ``lint/registry.py`` (R11,
``retrieval_posterior``) and its resource profile is pinned in
``.jaxpr_ledger.json``.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from esac_tpu.utils.num import safe_norm


@dataclasses.dataclass(frozen=True)
class RetrievalConfig:
    """Static-shape config of the retrieval front (a frozen dataclass,
    usable as a static jit argument like every other config).

    ``max_scenes`` is the padded prototype axis — the fleet can enroll
    at most this many scenes without a recompile; raising it is a new
    program (a deliberate, observable compile at attach time, never on
    the request path).  ``temperature`` scales the cosine logits before
    the softmax (lower = sharper posterior)."""

    height: int = 64
    width: int = 64
    max_scenes: int = 64
    embed_dim: int = 32
    channels: tuple[int, ...] = (16, 32, 64)
    compute_dtype: str = "float32"
    temperature: float = 0.1

    def __post_init__(self):
        if self.height < 1 or self.width < 1:
            raise ValueError(f"bad retrieval input {self.height}x{self.width}")
        if self.max_scenes < 1:
            raise ValueError(f"max_scenes {self.max_scenes} < 1")
        if self.embed_dim < 1:
            raise ValueError(f"embed_dim {self.embed_dim} < 1")
        if not self.channels:
            raise ValueError("channels must be non-empty")
        if not self.temperature > 0.0:
            raise ValueError(f"temperature {self.temperature} must be > 0")


class RetrieverNet(nn.Module):
    """CNN embedder: RGB (..., H, W, 3) -> unit embedding (..., D).

    The ``models/gating.py`` trunk (strided convs + global average
    pool, configurable compute dtype / f32 params) with an embedding
    head; the output is L2-normalized with the eps-inside-sqrt idiom so
    a degenerate all-zero activation stays finite (CLAUDE.md grad
    safety — prototypes are built from this output during enrollment).
    """

    embed_dim: int
    channels: Sequence[int] = (16, 32, 64)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.compute_dtype)
        for ch in self.channels:
            x = nn.Conv(ch, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                        dtype=self.compute_dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(ch, (3, 3), dtype=self.compute_dtype)(x)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(-3, -2))  # global average pool
        x = x.astype(jnp.float32)
        x = nn.Dense(max(self.embed_dim * 2, 64), dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.Dense(self.embed_dim, dtype=jnp.float32)(x)
        return x / safe_norm(x, axis=-1)[..., None]


# Large-negative logit for masked prototype slots: softmax weight
# underflows to exactly 0.0 in f32 without producing inf-inf NaNs the
# way -inf logits would.
_MASKED_LOGIT = -1e30


def build_retriever(config: RetrievalConfig) -> RetrieverNet:
    return RetrieverNet(
        embed_dim=config.embed_dim,
        channels=tuple(config.channels),
        compute_dtype=jnp.dtype(config.compute_dtype),
    )


def make_retrieval_fn(config: RetrievalConfig):
    """ONE jitted forward for the whole retrieval front:

    ``fn(params, prototypes, mask, images) -> {"embedding", "posterior"}``

    - ``prototypes`` (max_scenes, D) and ``mask`` (max_scenes,) are
      TRACED arguments — enrolling/removing a scene re-dispatches the
      SAME compiled program (the no-recompile contract; pinned by the
      city drill's jit cache-miss counter).
    - ``images`` is (B, H, W, 3); static shapes throughout, no
      data-dependent control flow.

    The returned fn exposes ``_cache_size()`` (the registry
    ``infer_fn`` convention) so benches can pin zero hot-path
    recompiles across index mutations.
    """
    model = build_retriever(config)

    def _forward(params, prototypes, mask, images):
        emb = model.apply(params, images)                    # (B, D) unit
        logits = jnp.einsum("bd,md->bm", emb, prototypes)
        logits = logits / jnp.float32(config.temperature)
        logits = jnp.where(mask[None, :], logits, _MASKED_LOGIT)
        return {
            "embedding": emb,
            "posterior": jax.nn.softmax(logits, axis=-1),
        }

    fn = jax.jit(_forward)
    return fn
