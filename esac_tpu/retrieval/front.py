"""Retrieval front-end: posterior -> candidate policy -> accounting.

The host side of ISSUE 18 (DESIGN.md §22): :class:`RetrievalFront` owns
the jitted retriever dispatch, the candidate policy (top-K over the
posterior, each candidate gated by the per-scene breaker state), the
posterior-prefetch feed, and the image-request outcome books the
``retrieval`` obs collector publishes.  The router's ``infer_image``
is a thin orchestration over this class — retrieval POLICY lives here,
fleet SCHEDULING stays in fleet/router.py (which must keep importing
neither jax nor numpy; the winner scoring that needs numpy therefore
lives here too, see :meth:`RetrievalFront.select_winner`).

Accounting contract (DESIGN.md §13 lifted to the image tier): every
offered image request books EXACTLY one terminal outcome —
``offered == served + shed + expired + failed + degraded + pending`` at
every instant — via the first-wins :class:`_Booking` token minted by
:meth:`RetrievalFront.offer`.  Typed faults ride the
:class:`~esac_tpu.retrieval.errors.RetrievalMissError` family; the
raise→outcome edges are committed in ``.fault_taxonomy.json`` and the
city drill's ``OutcomeWitness`` observes each pair.

Concurrency (R10/R12/R13): all mutable front state lives under the one
instance lock — a LEAF of the committed ``.lock_graph.json``.  The
jitted forward, the index snapshot, the health callable (which takes
registry locks) and the prefetch sinks all run with the front lock
RELEASED; only counter folds happen under it.
"""

from __future__ import annotations

import collections
import dataclasses
import math

import threading

import numpy as np

from esac_tpu.retrieval.errors import RetrievalMissError

# The image-tier outcome vocabulary: the fleet's classes (fleet.router
# OUTCOMES) — the booking token only ever receives these.
_OUTCOMES = ("served", "shed", "expired", "degraded", "failed")


@dataclasses.dataclass(frozen=True)
class RetrievalPolicy:
    """Host-side candidate-policy knobs (frozen — pure scheduler state,
    never a jit argument; the static-shape knobs live in
    :class:`~esac_tpu.retrieval.model.RetrievalConfig`)."""

    # Candidate fan-out: how many healthy top-posterior scenes one
    # image request dispatches to (the recall@K / latency dial the
    # city drill sweeps).
    top_k: int = 2
    # Admission floor on the posterior's top-1 mass: below it the query
    # matches NO enrolled scene well enough to spend expert dispatches
    # on, and the request sheds typed (RetrievalMissError) instead of
    # burning fleet capacity on a guaranteed-garbage pose.
    min_confidence: float = 0.35
    # Posterior mass floor for the prefetch feed: scenes under it are
    # noise, not staging signal.
    prefetch_min_p: float = 0.05

    def __post_init__(self):
        if self.top_k < 1:
            raise ValueError(f"top_k {self.top_k} < 1")
        if not 0.0 <= self.min_confidence <= 1.0:
            raise ValueError(
                f"min_confidence {self.min_confidence} outside [0, 1]"
            )
        if not 0.0 <= self.prefetch_min_p <= 1.0:
            raise ValueError(
                f"prefetch_min_p {self.prefetch_min_p} outside [0, 1]"
            )


@dataclasses.dataclass(frozen=True)
class RetrievalDecision:
    """One image request's retrieval verdict: the dispatchable candidate
    scenes (posterior-ranked, breaker-gated, length <= top_k) plus the
    evidence the books and traces record."""

    candidates: tuple          # healthy scenes to dispatch, ranked
    posterior: dict            # scene_id -> posterior mass (enrolled only)
    ranked: tuple              # ALL enrolled scenes by posterior, no gate
    entropy: float             # posterior entropy, nats
    top1: str                  # ranked[0] — the health-agnostic best
    top1_p: float              # its posterior mass
    tripped_skipped: int       # candidates skipped by the breaker gate


class _Booking:
    """First-wins outcome token for one offered image request: however
    many error paths race to classify it, exactly one outcome lands in
    the front's books (the fleet ``_finish_locked`` contract, token-
    shaped because the image path has no request object of its own)."""

    __slots__ = ("_front", "outcome")

    def __init__(self, front):
        self._front = front
        self.outcome = None

    def book(self, outcome: str, error=None) -> bool:
        """Record the terminal outcome (idempotent: the first call
        wins, later calls are no-ops returning False)."""
        front = self._front
        with front._lock:
            if self.outcome is not None:
                return False
            self.outcome = outcome
            front._outcomes[outcome] += 1
            if error is not None:
                front._error_types[type(error).__name__] += 1
            return True


class RetrievalFront:
    """The "which scene am I in?" front-end over one jitted retriever.

    ``fn`` is :func:`~esac_tpu.retrieval.model.make_retrieval_fn`'s
    jitted forward, ``params`` its weights, ``index`` the
    :class:`~esac_tpu.retrieval.index.SceneIndex` whose snapshot rides
    every dispatch as traced arguments.  ``healthy`` is an optional
    ``scene_id -> bool`` breaker gate (the router wires it to
    ``SceneRegistry.prefetch_targets`` truthiness across its replicas);
    ``prefetch_sinks`` are ``[(scene, p), ...] -> None`` callables fed
    after every decision (the posterior-prefetch seam)."""

    def __init__(self, fn, params, index,
                 policy: RetrievalPolicy = RetrievalPolicy(),
                 healthy=None, prefetch_sinks=()):
        if index.capacity < policy.top_k:
            raise ValueError(
                f"top_k {policy.top_k} > index capacity {index.capacity}"
            )
        self._fn = fn
        self._params = params
        self._index = index
        self._policy = policy
        self._healthy = healthy
        self._sinks = list(prefetch_sinks)
        self._lock = threading.Lock()
        # Image-tier books (all under self._lock).
        self._offered = 0
        self._outcomes: collections.Counter = collections.Counter()
        self._error_types: collections.Counter = collections.Counter()
        self._decided = 0
        self._missed_low_confidence = 0
        self._missed_no_candidate = 0
        self._missed_tripped = 0
        self._tripped_skipped = 0
        self._entropy_sum = 0.0
        self._fanout_sum = 0
        self._winners_noted = 0
        self._top1_hits = 0
        self._winner_in_topk = 0
        self._prefetch_feeds = 0
        self._feed_errors = 0

    # ---------------- wiring ----------------

    @property
    def policy(self) -> RetrievalPolicy:
        return self._policy

    @property
    def index(self):
        return self._index

    def attach_health(self, healthy) -> None:
        """Install the breaker gate (``scene_id -> bool``); the callable
        runs with NO front lock held — it may take registry locks."""
        with self._lock:
            self._healthy = healthy

    def has_health(self) -> bool:
        with self._lock:
            return self._healthy is not None

    def add_prefetch_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    # ---------------- the decision ----------------

    def decide(self, frame) -> RetrievalDecision:
        """One retrieval pass: index snapshot -> jitted posterior ->
        confidence gate -> breaker-gated top-K candidates.  Raises
        :class:`RetrievalMissError` (typed, accounted by the caller's
        booking token) when no dispatchable candidate exists; never
        dispatches anything itself."""
        protos, mask, ids = self._index.snapshot()
        enrolled = [(slot, sid) for slot, sid in enumerate(ids)
                    if sid is not None]
        if not enrolled:
            with self._lock:
                self._missed_no_candidate += 1
            raise RetrievalMissError(
                "retrieval index has no enrolled scene — image-only "
                "requests need at least one prototype"
            )
        # The serve tier's frames are leaf-named dicts ({"image": ...,
        # "coords": ...}); the retriever only reads the image leaf, and
        # the FULL frame goes on to the expert dispatch untouched.
        images = frame["image"] if isinstance(frame, dict) else frame
        images = np.asarray(images, np.float32)
        if images.ndim == 3:
            images = images[None]
        # The ONE jitted dispatch — outside every lock (R13); prototypes
        # and mask are traced, so index mutations never recompile this.
        out = self._fn(self._params, protos, mask, images)
        post = np.asarray(out["posterior"][0], np.float32)
        posterior = {sid: float(post[slot]) for slot, sid in enrolled}
        ranked = tuple(sorted(posterior, key=lambda s: (-posterior[s], s)))
        top1 = ranked[0]
        top1_p = posterior[top1]
        entropy = -math.fsum(
            p * math.log(p) for p in posterior.values() if p > 0.0
        )
        pol = self._policy
        if top1_p < pol.min_confidence:
            with self._lock:
                self._missed_low_confidence += 1
            raise RetrievalMissError(
                f"posterior top-1 {top1!r} at {top1_p:.3f} < "
                f"min_confidence {pol.min_confidence} — the query matches "
                "no enrolled scene well enough to dispatch"
            )
        # Breaker gate: a tripped candidate is SKIPPED (never
        # dispatched) and the next-ranked healthy scene backfills, so
        # the fan-out stays top_k-wide when the index allows.  The
        # callable is snapshotted under the lock (attach_health mutates
        # it there) and CALLED outside it — it takes registry locks.
        with self._lock:
            healthy = self._healthy
        candidates = []
        tripped = 0
        for sid in ranked:
            if len(candidates) >= pol.top_k:
                break
            if healthy is not None and not healthy(sid):
                tripped += 1
                continue
            candidates.append(sid)
        if not candidates:
            with self._lock:
                self._missed_tripped += 1
                self._tripped_skipped += tripped
            raise RetrievalMissError(
                f"every ranked candidate of {len(ranked)} enrolled "
                "scene(s) is breaker-tripped — release_scene() after "
                "recovery"
            )
        with self._lock:
            self._decided += 1
            self._entropy_sum += entropy
            self._fanout_sum += len(candidates)
            self._tripped_skipped += tripped
        return RetrievalDecision(
            candidates=tuple(candidates), posterior=posterior,
            ranked=ranked, entropy=entropy, top1=top1, top1_p=top1_p,
            tripped_skipped=tripped,
        )

    # ---------------- accounting ----------------

    def offer(self) -> _Booking:
        """Book one offered image request; the returned token records
        its single terminal outcome (first caller wins)."""
        with self._lock:
            self._offered += 1
        return _Booking(self)

    def note_result(self, winner_scene, decision: RetrievalDecision) -> None:
        """Fold one served request's winner into the recall proxies."""
        with self._lock:
            self._winners_noted += 1
            if winner_scene == decision.top1:
                self._top1_hits += 1
            if winner_scene in decision.candidates:
                self._winner_in_topk += 1

    # ---------------- the prefetch seam ----------------

    def feed_prefetch(self, decision: RetrievalDecision) -> None:
        """Feed the posterior into the staged-weights seam: every sink
        gets ``[(scene, p), ...]`` over the scenes carrying at least
        ``prefetch_min_p`` mass — ambiguous queries stage their
        runner-up scenes AHEAD of the fault.  Never raises (the
        arrival-feed contract): a broken sink is counted, not served."""
        weights = [(sid, p) for sid, p in decision.posterior.items()
                   if p >= self._policy.prefetch_min_p]
        if not weights:
            return
        with self._lock:
            sinks = list(self._sinks)
            self._prefetch_feeds += 1
        for sink in sinks:
            try:
                sink(weights)
            except Exception:  # noqa: BLE001 — the feed must never hurt serving
                with self._lock:
                    self._feed_errors += 1

    # ---------------- winner scoring ----------------

    @staticmethod
    def select_winner(results):
        """Pick the winning (scene, result) from per-candidate expert
        results by soft-inlier score — the max over each result's
        ``scores`` vector (the ESAC hypothesis-score semantics: the
        best-supported hypothesis of the best-matching scene wins).
        Lives here, not in fleet/router.py, so the router keeps its
        no-numpy discipline; the winning result dict is returned
        UNTOUCHED (the bit-identity contract reads rvec/tvec/scores/
        expert straight from the replica's answer)."""
        best = None
        best_score = -np.inf
        for scene, res in results:
            score = float(np.max(np.asarray(res["scores"])))
            if score > best_score:
                best_score = score
                best = (scene, res)
        return best

    # ---------------- observability ----------------

    def stats(self) -> dict:
        """The ``retrieval`` obs collector (KNOWN_COLLECTORS-pinned):
        image-tier accounting (sums exactly to offered with pending),
        miss counts by class, posterior-entropy / fan-out means, and
        the recall proxies."""
        with self._lock:
            outcomes = {o: int(self._outcomes.get(o, 0)) for o in _OUTCOMES}
            done = sum(outcomes.values())
            decided = self._decided
            winners = self._winners_noted
            snap = {
                "offered": self._offered,
                **outcomes,
                "pending": self._offered - done,
                "decided": decided,
                "missed_low_confidence": self._missed_low_confidence,
                "missed_no_candidate": self._missed_no_candidate,
                "missed_tripped": self._missed_tripped,
                "tripped_skipped": self._tripped_skipped,
                "posterior_entropy_mean": (
                    self._entropy_sum / decided if decided else float("nan")
                ),
                "candidate_fanout_mean": (
                    self._fanout_sum / decided if decided else float("nan")
                ),
                "winners_noted": winners,
                "top1_hits": self._top1_hits,
                "winner_in_topk": self._winner_in_topk,
                "recall_proxy_top1": (
                    self._top1_hits / winners if winners else float("nan")
                ),
                "prefetch_feeds": self._prefetch_feeds,
                "feed_errors": self._feed_errors,
                "error_types": dict(self._error_types),
            }
        # Index stats OUTSIDE the front lock: front._lock and
        # index._lock are both lock-graph LEAVES — nesting them would
        # be a new committed edge for no benefit.
        snap["enrolled"] = len(self._index)
        return snap
