"""Scene prototype index: the retrieval analogue of the manifest.

A fixed-capacity slot table of per-scene prototype embeddings, padded
to ``RetrievalConfig.max_scenes`` and masked — the table rides the one
jitted retrieval forward as TRACED arguments, so ``enroll``/``remove``
never recompile anything (ISSUE 18, DESIGN.md §22).

Concurrency (R10/R12/R13): all mutable state lives under the one
instance lock, which is a LEAF of the committed ``.lock_graph.json`` —
``snapshot`` copies the arrays under the lock and the jitted dispatch
happens entirely outside it; prototype math (means, norms) runs before
the lock is taken.
"""

from __future__ import annotations

import threading

import numpy as np

from esac_tpu.registry.manifest import ManifestError


class SceneIndex:
    """Slot table: scene_id -> (prototype row, mask bit).

    ``capacity`` is the static prototype axis; enrolling past it raises
    :class:`ManifestError` (a deterministic config fault, exactly like
    registering past a manifest's shape contract).  Re-enrolling an
    existing scene updates its prototype in place, keeping its slot.
    """

    def __init__(self, capacity: int, embed_dim: int):
        if capacity < 1:
            raise ValueError(f"capacity {capacity} < 1")
        if embed_dim < 1:
            raise ValueError(f"embed_dim {embed_dim} < 1")
        self.capacity = int(capacity)
        self.embed_dim = int(embed_dim)
        self._lock = threading.Lock()
        self._slots: dict[str, int] = {}           # scene_id -> slot
        self._ids: list[str | None] = [None] * self.capacity
        self._prototypes = np.zeros((self.capacity, self.embed_dim),
                                    np.float32)
        self._mask = np.zeros((self.capacity,), np.bool_)
        self._enrollments = 0
        self._removals = 0

    @staticmethod
    def _prototype_of(embeddings) -> np.ndarray:
        """Mean-then-renormalize prototype from one scene's view
        embeddings ((n, D) or (D,)) — pure host math, run BEFORE the
        lock."""
        emb = np.asarray(embeddings, np.float32)
        if emb.ndim == 1:
            emb = emb[None, :]
        proto = emb.mean(axis=0)
        norm = float(np.sqrt(float(proto @ proto) + 1e-12))
        return proto / norm

    def enroll(self, scene_id: str, embeddings) -> int:
        """Install (or refresh) ``scene_id``'s prototype; returns its
        slot.  Raises :class:`ManifestError` when the padded axis is
        full — growing ``max_scenes`` is a config change, never an
        implicit recompile."""
        proto = self._prototype_of(embeddings)
        if proto.shape != (self.embed_dim,):
            raise ManifestError(
                f"embedding dim {proto.shape} != ({self.embed_dim},) for "
                f"scene {scene_id!r}"
            )
        with self._lock:
            slot = self._slots.get(scene_id)
            if slot is None:
                free = next(
                    (i for i, sid in enumerate(self._ids) if sid is None),
                    None,
                )
                if free is None:
                    raise ManifestError(
                        f"scene index full ({self.capacity} slots) "
                        f"enrolling {scene_id!r}; raise "
                        "RetrievalConfig.max_scenes (a reviewed recompile)"
                    )
                slot = free
                self._slots[scene_id] = slot
                self._ids[slot] = scene_id
            self._prototypes[slot] = proto
            self._mask[slot] = True
            self._enrollments += 1
            return slot

    def remove(self, scene_id: str) -> bool:
        """Mask ``scene_id`` out of the table (frees its slot).
        Idempotent; returns whether anything was removed."""
        with self._lock:
            slot = self._slots.pop(scene_id, None)
            if slot is None:
                return False
            self._ids[slot] = None
            self._mask[slot] = False
            self._prototypes[slot] = 0.0
            self._removals += 1
            return True

    def snapshot(self) -> tuple[np.ndarray, np.ndarray, tuple]:
        """(prototypes copy, mask copy, slot ids tuple) — the traced
        arguments of one retrieval dispatch, consistent under the
        lock; the dispatch itself happens outside it."""
        with self._lock:
            return (self._prototypes.copy(), self._mask.copy(),
                    tuple(self._ids))

    def scene_ids(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(sid for sid in self._ids if sid is not None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "embed_dim": self.embed_dim,
                "enrolled": len(self._slots),
                "enrollments": self._enrollments,
                "removals": self._removals,
            }
