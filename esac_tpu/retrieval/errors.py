"""Typed faults of the retrieval front-end (ISSUE 18, DESIGN.md §22).

Both classes are graft-audit v5 taxonomy members (LINT.md R16): they
derive from the :class:`~esac_tpu.serve.slo.ServeError` root, declare
``retryable`` + ``wire_name`` as literals, and every raise→outcome edge
they ride is committed in ``.fault_taxonomy.json``.
"""

from __future__ import annotations

from esac_tpu.serve.slo import ShedError


class RetrievalMissError(ShedError):
    """The retrieval front could not produce a dispatchable candidate
    set for an image-only request: the posterior's top-1 confidence sat
    below ``RetrievalPolicy.min_confidence``, the index had no enrolled
    scene, or every candidate inside the fan-out was breaker-tripped.
    The request is rejected BEFORE any expert dispatch — a shed at the
    retrieval admission tier, so callers that only distinguish
    *admitted vs not* can keep catching :class:`ShedError`."""

    # Deterministic for the same frame against the same index/breaker
    # state: re-submitting the identical image cannot clear a
    # low-confidence posterior.
    retryable = False
    wire_name = "retrieval_miss"


class RetrievalCandidatesExhaustedError(RetrievalMissError):
    """Retrieval produced a healthy candidate set but every candidate's
    expert dispatch failed (typed, per-candidate) before any winner
    could be scored.  Unlike its parent this happens AFTER admission —
    the image request lands in the ``failed`` outcome class, and the
    per-candidate fleet requests carry their own books."""

    # Retryable: the candidates failed for serving reasons (fault
    # injection, transient replica faults) — a re-submit can route to
    # recovered candidates.
    retryable = True
    wire_name = "retrieval_candidates_exhausted"
