"""Scene retrieval front-end (ISSUE 18, DESIGN.md §22): image-only
requests resolve "which scene am I in?" through a coarse retriever
posterior before the fleet's routed expert dispatch decides which
experts.  See model.py (the jitted forward), index.py (the no-recompile
prototype table), front.py (candidate policy + accounting) and
errors.py (the typed miss family); fleet/router.py's ``infer_image``
is the request path over them.

model.py imports jax/flax, so its exports resolve LAZILY (PEP 562):
the jax-free host modules (fleet/router.py, the lint passes) import the
errors and the front through this package without ever initializing a
device backend — the obs-tier discipline."""

from esac_tpu.retrieval.errors import (
    RetrievalCandidatesExhaustedError,
    RetrievalMissError,
)
from esac_tpu.retrieval.front import (
    RetrievalDecision,
    RetrievalFront,
    RetrievalPolicy,
)
from esac_tpu.retrieval.index import SceneIndex

_MODEL_EXPORTS = (
    "RetrievalConfig",
    "RetrieverNet",
    "build_retriever",
    "make_retrieval_fn",
)

__all__ = [
    "RetrievalCandidatesExhaustedError",
    "RetrievalDecision",
    "RetrievalFront",
    "RetrievalMissError",
    "RetrievalPolicy",
    "SceneIndex",
    *_MODEL_EXPORTS,
]


def __getattr__(name: str):
    if name in _MODEL_EXPORTS:
        from esac_tpu.retrieval import model

        return getattr(model, name)
    raise AttributeError(  # graft-lint: disable=R16(PEP 562 module __getattr__ must raise AttributeError; import-time, never a request fault)
        f"module {__name__!r} has no attribute {name!r}"
    )
