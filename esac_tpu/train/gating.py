"""Stage-2 gating training: expert classification.

Reference counterpart: ``train_gating.py`` (SURVEY.md §3.2) — cross-entropy
against the GT scene/cluster label.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax

from esac_tpu.models.gating import gating_cross_entropy


def make_gating_train_step(
    net,
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Returns jitted ``step(params, opt_state, images, labels)``."""

    @jax.jit
    def step(params, opt_state, images, labels):
        def loss_fn(p):
            logits = net.apply(p, images)
            return gating_cross_entropy(logits, labels)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
