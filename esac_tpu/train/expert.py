"""Stage-1 expert training: scene-coordinate regression.

Reference counterpart: ``train_expert.py`` hot loop (SURVEY.md §3.1):
image -> expert forward -> masked L1 against GT coordinates (or clamped
reprojection error when no depth GT exists) -> Adam step.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from esac_tpu.models.expert import coordinate_loss


def make_expert_train_step(
    net,
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Returns jitted ``step(params, opt_state, images, targets, masks)``.

    images: (B, H, W, 3); targets: (B, H/8, W/8, 3); masks: (B, H/8, W/8)
    or None-shaped ones.  Returns (params, opt_state, loss).
    """

    @jax.jit
    def step(params, opt_state, images, targets, masks):
        def loss_fn(p):
            pred = net.apply(p, images)
            return coordinate_loss(pred, targets, masks)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
