"""Stage-1 expert training: scene-coordinate regression.

Reference counterpart: ``train_expert.py`` hot loop (SURVEY.md §3.1):
image -> expert forward -> masked L1 against GT coordinates, or — for
scenes without depth GT (the outdoor/Aachen path, SURVEY.md §0 stage 1) —
clamped reprojection error against the GT pose, bootstrapped from
heuristic constant-depth targets (``geometry.backproject_at_depth``).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from esac_tpu.geometry.camera import reprojection_errors
from esac_tpu.geometry.rotations import rodrigues
from esac_tpu.models.expert import coordinate_loss


def make_expert_train_step(
    net,
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Returns jitted ``step(params, opt_state, images, targets, masks)``.

    images: (B, H, W, 3); targets: (B, H/8, W/8, 3); masks: (B, H/8, W/8)
    or None-shaped ones.  Returns (params, opt_state, loss).
    """

    @jax.jit
    def step(params, opt_state, images, targets, masks):
        def loss_fn(p):
            pred = net.apply(p, images)
            return coordinate_loss(pred, targets, masks)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def reprojection_loss(
    pred: jnp.ndarray,
    rvecs: jnp.ndarray,
    tvecs: jnp.ndarray,
    pixels: jnp.ndarray,
    fs: jnp.ndarray,
    c: jnp.ndarray,
    clamp_px: float = 100.0,
) -> jnp.ndarray:
    """Mean clamped reprojection error of predicted scene coordinates.

    The stage-1 loss when no depth GT exists (SURVEY.md §3.1): every output
    cell's predicted 3D point is projected through the GT pose and penalized
    by its pixel distance to the cell center, clamped so gross outliers
    (inevitable early in outdoor training) cannot dominate the gradient.

    pred: (B, h, w, 3) or (B, N, 3); rvecs/tvecs: (B, 3); pixels: (N, 2);
    fs: scalar or (B,) focal lengths — outdoor datasets carry per-frame
    intrinsics, so the focal is batched alongside the poses.

    The clamp is LOGARITHMIC, not a hard min: ``clamp * log1p(err/clamp)``
    tracks the raw error below ``clamp_px`` (slope 1 at 0) but damps large
    errors with a 1/(1 + err/clamp) slope that never reaches zero — a hard
    ``min`` would hand every >clamp cell (including behind-camera cells,
    which carry err+1000 by design) exactly zero gradient and stall
    training whenever most cells start far from their pixels (e.g.
    ``--init-iters 0``).  Grad-safety per CLAUDE.md: degenerate inputs keep
    a penalty that still drives gradients.
    """
    B = pred.shape[0]
    coords = pred.reshape(B, -1, 3)
    Rs = jax.vmap(rodrigues)(rvecs)
    fs = jnp.broadcast_to(jnp.asarray(fs, coords.dtype), (B,))
    errs = jax.vmap(
        lambda R, t, co, f: reprojection_errors(R, t, co, pixels, f, c)
    )(Rs, tvecs, coords, fs)
    return jnp.mean(clamp_px * jnp.log1p(errs / clamp_px))


def make_expert_reproj_train_step(
    net,
    optimizer: optax.GradientTransformation,
    pixels: jnp.ndarray,
    c: jnp.ndarray,
    clamp_px: float = 100.0,
) -> Callable:
    """Returns jitted ``step(params, opt_state, images, rvecs, tvecs, fs)``
    minimizing ``reprojection_loss`` — the no-depth-GT stage-1 mode.
    ``fs``: (B,) per-frame focal lengths."""

    @jax.jit
    def step(params, opt_state, images, rvecs, tvecs, fs):
        def loss_fn(p):
            pred = net.apply(p, images)
            return reprojection_loss(pred, rvecs, tvecs, pixels, fs, c, clamp_px)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
