"""Three-stage training, mirroring the reference's pipeline (SURVEY.md §0):

1. ``expert``  — per-expert scene-coordinate init (coordinate / reprojection
   loss), the reference's ``train_expert.py``.
2. ``gating``  — gating classifier init (cross-entropy), ``train_gating.py``.
3. ``e2e``     — end-to-end expected-pose-loss training through the
   hypothesis kernel, ``train_esac.py``.

All steps are pure jitted functions over (params, opt_state, batch); entry
scripts at the repo root provide the reference-compatible CLI.
"""

from esac_tpu.train.expert import (
    make_expert_reproj_train_step, make_expert_train_step, reprojection_loss,
)
from esac_tpu.train.gating import make_gating_train_step
from esac_tpu.train.e2e import make_dsac_train_step

__all__ = [
    "make_expert_reproj_train_step",
    "make_expert_train_step",
    "reprojection_loss",
    "make_gating_train_step",
    "make_dsac_train_step",
]
