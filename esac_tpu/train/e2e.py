"""Stage-3 end-to-end training: expected pose loss through the kernel.

Reference counterpart: ``train_esac.py`` (SURVEY.md §3.3).  The single-expert
(DSAC, config #1) step trains the expert through the whole hypothesis loop:
image -> expert -> coords -> sample/solve/score/select/refine -> expected
pose loss; ``jax.grad`` delivers the full backward pass that the reference
assembles from analytic C++ gradients + central finite differences.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from esac_tpu.ransac.config import RansacConfig
from esac_tpu.ransac.kernel import dsac_train_loss


def make_dsac_train_step(
    net,
    optimizer: optax.GradientTransformation,
    cfg: RansacConfig,
    f: float,
    c: tuple[float, float],
) -> Callable:
    """Single-expert end-to-end step (driver config #1).

    Returns jitted ``step(params, opt_state, key, images, pixels, R_gts,
    t_gts)`` over a batch of frames -> (params, opt_state, loss, aux).
    """
    fx = jnp.float32(f)
    cx = jnp.asarray(c, dtype=jnp.float32)

    @jax.jit
    def step(params, opt_state, key, images, pixels, R_gts, t_gts):
        def loss_fn(p):
            coords = net.apply(p, images)  # (B, h, w, 3)
            B = coords.shape[0]
            flat = coords.reshape(B, -1, 3)
            keys = jax.random.split(key, B)
            losses, aux = jax.vmap(
                lambda k, co, px, Rg, tg: dsac_train_loss(
                    k, co, px, fx, cx, Rg, tg, cfg
                )
            )(keys, flat, pixels, R_gts, t_gts)
            return jnp.mean(losses), aux

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, aux

    return step
