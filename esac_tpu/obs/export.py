"""Export surface: JSON sanitation, Prometheus text, artifact provenance.

``MetricsRegistry.snapshot()`` delegates here for :func:`jsonable` (the
fleet snapshot contract is ``json.dumps(snapshot)`` NEVER raises — lane
tuples, numpy scalars and deque-shaped collector output all sanitize);
:func:`render_prometheus` turns a snapshot into the text exposition
format scrapers expect; :func:`provenance` is the block ``bench.py``'s
``_driver_main`` scaffold embeds in EVERY committed artifact so each one
records which obs schema produced it (and, for modes that ran a fleet,
the full snapshot).

Pure host code, no jax import (CLAUDE.md: observability must never
become a TPU relay client).
"""

from __future__ import annotations

import math

from esac_tpu.obs.metrics import OBS_SCHEMA


def jsonable(obj):
    """Recursively convert ``obj`` into something ``json.dumps`` accepts:
    non-string dict keys stringify, tuples/sets/deques become lists,
    numpy scalars unwrap via ``.item()``, and anything else falls back to
    ``repr`` — a snapshot must never raise on one odd leaf."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj  # json emits NaN/Infinity tokens, matching bench.py
    if isinstance(obj, dict):
        return {
            (k if isinstance(k, str) else str(k)): jsonable(v)
            for k, v in obj.items()
        }
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [jsonable(v) for v in obj]
    item = getattr(obj, "item", None)
    if callable(item) and getattr(obj, "shape", None) in ((), None):
        try:
            return jsonable(item())
        except Exception:  # noqa: BLE001 — fall through to repr
            pass
    if hasattr(obj, "__iter__"):
        try:
            return [jsonable(v) for v in obj]
        except Exception:  # noqa: BLE001 — fall through to repr
            pass
    return repr(obj)


def _prom_escape(v) -> str:
    s = str(v)
    return s.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_prom_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _prom_value(v) -> str:
    if v is None:
        return "NaN"
    if isinstance(v, float) and not math.isfinite(v):
        return "NaN" if math.isnan(v) else ("+Inf" if v > 0 else "-Inf")
    return repr(float(v))


def render_prometheus(snapshot: dict) -> str:
    """Prometheus text exposition of a :meth:`MetricsRegistry.snapshot`
    dict.  Counters/gauges render directly; histograms render as
    summaries (quantile-labeled samples + ``_count``/``_sum``).
    Structured collector blocks are not flattenable into samples and are
    listed as comments so the page still names every surface."""
    lines = [f"# esac_tpu obs schema {snapshot.get('obs_schema')}"]
    for name, m in sorted(snapshot.get("metrics", {}).items()):
        kind = m.get("kind", "untyped")
        if m.get("help"):
            lines.append(f"# HELP {name} {m['help']}")
        lines.append(
            f"# TYPE {name} {'summary' if kind == 'histogram' else kind}"
        )
        for s in m.get("samples", []):
            labels = s.get("labels", {})
            if kind == "histogram":
                for k, v in s.items():
                    if k.startswith("p") and k[1:].isdigit():
                        q = int(k[1:]) / 100.0
                        lines.append(
                            f"{name}{_prom_labels({**labels, 'quantile': q})}"
                            f" {_prom_value(v)}"
                        )
                lines.append(
                    f"{name}_count{_prom_labels(labels)} "
                    f"{_prom_value(s.get('count', 0))}"
                )
                lines.append(
                    f"{name}_sum{_prom_labels(labels)} "
                    f"{_prom_value(s.get('sum', 0.0))}"
                )
            else:
                lines.append(
                    f"{name}{_prom_labels(labels)} "
                    f"{_prom_value(s.get('value'))}"
                )
    for cname in sorted(snapshot.get("collectors", {})):
        lines.append(f"# COLLECTOR {cname} (structured; see JSON snapshot)")
    return "\n".join(lines) + "\n"


def provenance(fleet_snapshot: dict | None = None) -> dict:
    """The obs provenance block every bench artifact embeds: the schema
    version that produced it plus, when the measured mode ran a fleet,
    its full ``obs.snapshot()``."""
    out = {
        "obs_schema": OBS_SCHEMA,
        "has_fleet_snapshot": fleet_snapshot is not None,
    }
    if fleet_snapshot is not None:
        out["fleet"] = jsonable(fleet_snapshot)
    return out
