"""Export surface: JSON sanitation, Prometheus text, artifact provenance.

``MetricsRegistry.snapshot()`` delegates here for :func:`jsonable` (the
fleet snapshot contract is ``json.dumps(snapshot)`` NEVER raises — lane
tuples, numpy scalars and deque-shaped collector output all sanitize);
:func:`render_prometheus` turns a snapshot into the text exposition
format scrapers expect; :func:`provenance` is the block ``bench.py``'s
``_driver_main`` scaffold embeds in EVERY committed artifact so each one
records which obs schema produced it (and, for modes that ran a fleet,
the full snapshot).

Pure host code, no jax import (CLAUDE.md: observability must never
become a TPU relay client).
"""

from __future__ import annotations

import math

from esac_tpu.obs.metrics import OBS_SCHEMA

# Every collector the shipped fleet registers, with the key fields its
# rendered block must carry — the SCHEMA PIN (ISSUE 15 satellite): the
# audit test (tests/test_obs.py) builds a full fleet and asserts the
# registered collector set is covered here, so the NEXT collector
# cannot land unrendered — adding it to a surface forces adding it (and
# its load-bearing fields) to this table, and the renderer below
# flattens every entry's numeric leaves into real Prometheus samples.
KNOWN_COLLECTORS = {
    # dispatcher (PR 10)
    "serve_slo_totals": ("offered", "served", "pending"),
    "serve_dispatch_totals": (),          # lane -> count (dynamic keys)
    "serve_quarantined_lanes": (),        # lane -> reason (non-numeric)
    # scene registry / health (PR 9/10)
    "scene_health": (),                   # scenes/canaries/events
    "weight_cache": ("hits", "misses", "host_hits", "disk_loads",
                     "demotions", "resident", "bytes_in_use"),
    # tier hierarchy + prefetcher (ISSUE 13)
    "host_tier": ("hits", "misses", "admissions", "resident",
                  "bytes_in_use"),
    "prefetch": ("issued_device", "issued_host", "hits", "wasted",
                 "failures", "posterior_feeds", "cycles"),
    # replica fleet (ISSUE 14)
    "fleet": (),                          # per-replica merge (dynamic)
    # retrieval front-end (ISSUE 18): image-tier accounting + recall
    # proxies + posterior evidence
    "retrieval": ("offered", "served", "shed", "expired", "failed",
                  "pending", "decided", "missed_low_confidence",
                  "missed_no_candidate", "missed_tripped",
                  "tripped_skipped", "posterior_entropy_mean",
                  "candidate_fanout_mean", "winners_noted", "top1_hits",
                  "winner_in_topk", "recall_proxy_top1",
                  "prefetch_feeds", "enrolled"),
    # temporal sessions (ISSUE 20): warm-start lane accounting
    "session": ("sessions", "opened", "closed", "evicted", "frames",
                "tracked_frames", "full_frames", "tracked_frac",
                "track_losses", "track_entries", "budget_saved_hyps",
                "dispatch_errors"),
    # runtime lock witness (graft-audit v3; test/bench attach only)
    "lock_witness": (),
    # runtime outcome witness (graft-audit v5; test/bench attach only)
    "fault_taxonomy": ("committed_errors", "committed_edges"),
    # ISSUE 15: causal traces, time axis, health rules
    "traces": ("added", "retained"),
    "timeline": ("ticks", "windows_retained", "window_s"),
    "health_alerts": (),
}


def jsonable(obj):
    """Recursively convert ``obj`` into something ``json.dumps`` accepts:
    non-string dict keys stringify, tuples/sets/deques become lists,
    numpy scalars unwrap via ``.item()``, and anything else falls back to
    ``repr`` — a snapshot must never raise on one odd leaf."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj  # json emits NaN/Infinity tokens, matching bench.py
    if isinstance(obj, dict):
        return {
            (k if isinstance(k, str) else str(k)): jsonable(v)
            for k, v in obj.items()
        }
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [jsonable(v) for v in obj]
    item = getattr(obj, "item", None)
    if callable(item) and getattr(obj, "shape", None) in ((), None):
        try:
            return jsonable(item())
        except Exception:  # graft-lint: disable=R17(the repr fall-through after the try IS the disposal — outside the handler, invisible to the structural pass)
            pass  # noqa: BLE001 — fall through to repr
    if hasattr(obj, "__iter__"):
        try:
            return [jsonable(v) for v in obj]
        except Exception:  # graft-lint: disable=R17(the repr fall-through after the try IS the disposal — outside the handler, invisible to the structural pass)
            pass  # noqa: BLE001 — fall through to repr
    return repr(obj)


def _prom_escape(v) -> str:
    s = str(v)
    return s.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_prom_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _prom_value(v) -> str:
    if v is None:
        return "NaN"
    if isinstance(v, float) and not math.isfinite(v):
        return "NaN" if math.isnan(v) else ("+Inf" if v > 0 else "-Inf")
    return repr(float(v))


def render_prometheus(snapshot: dict) -> str:
    """Prometheus text exposition of a :meth:`MetricsRegistry.snapshot`
    dict.  Counters/gauges render directly; histograms render as
    summaries (quantile-labeled samples + ``_count``/``_sum``);
    EVERY collector block's numeric leaves render as
    ``esac_collector_value{collector=...,path=...}`` samples (the
    ISSUE 15 satellite: prefetch / host_tier / weight_cache / fleet /
    lock_witness stats are scrapeable numbers, not comments — and the
    generic flattener means the next collector renders by
    construction, with :data:`KNOWN_COLLECTORS` as the reviewed pin);
    a collector with no numeric leaf still appears as a comment so the
    page names every surface."""
    lines = [f"# esac_tpu obs schema {snapshot.get('obs_schema')}"]
    for name, m in sorted(snapshot.get("metrics", {}).items()):
        kind = m.get("kind", "untyped")
        if m.get("help"):
            lines.append(f"# HELP {name} {m['help']}")
        lines.append(
            f"# TYPE {name} {'summary' if kind == 'histogram' else kind}"
        )
        for s in m.get("samples", []):
            labels = s.get("labels", {})
            if kind == "histogram":
                for k, v in s.items():
                    if k.startswith("p") and k[1:].isdigit():
                        q = int(k[1:]) / 100.0
                        lines.append(
                            f"{name}{_prom_labels({**labels, 'quantile': q})}"
                            f" {_prom_value(v)}"
                        )
                lines.append(
                    f"{name}_count{_prom_labels(labels)} "
                    f"{_prom_value(s.get('count', 0))}"
                )
                lines.append(
                    f"{name}_sum{_prom_labels(labels)} "
                    f"{_prom_value(s.get('sum', 0.0))}"
                )
            else:
                lines.append(
                    f"{name}{_prom_labels(labels)} "
                    f"{_prom_value(s.get('value'))}"
                )
    collectors = snapshot.get("collectors", {})
    if collectors:
        from esac_tpu.obs.timeline import flatten_numeric

        lines.append("# TYPE esac_collector_value untyped")
    for cname in sorted(collectors):
        flat = flatten_numeric(collectors[cname]) \
            if isinstance(collectors[cname], dict) else {}
        lines.append(
            f"# COLLECTOR {cname} ({len(flat)} numeric leaves; full "
            "structure in the JSON snapshot)"
        )
        for path in sorted(flat):
            labels = _prom_labels({"collector": cname, "path": path})
            lines.append(f"esac_collector_value{labels} "
                         f"{_prom_value(flat[path])}")
    return "\n".join(lines) + "\n"


def render_traces(snapshot: dict, k: int = 5) -> str:
    """Human rendering of the K slowest sampled traces carried by a
    snapshot's ``traces`` collector (``python -m esac_tpu.obs
    --traces``): per trace the root stage walk (the fleet telescoping
    partition) and the child span tree with per-stage durations."""
    block = snapshot.get("collectors", {}).get("traces")
    if not isinstance(block, dict) or not block.get("slowest"):
        return ("no sampled traces in this snapshot (enable "
                "FleetPolicy.trace_sample / MicroBatchDispatcher("
                "trace=True) and re-capture)\n")
    out = [f"{min(k, len(block['slowest']))} slowest sampled traces "
           f"({block.get('retained', '?')} retained, "
           f"{block.get('added', '?')} recorded):"]

    def ms(v):
        return f"{v * 1e3:.2f}ms" if isinstance(v, (int, float)) else "?"

    for t in block["slowest"][:k]:
        out.append(
            f"\ntrace {t.get('trace_id')}  scene={t.get('scene')} "
            f"outcome={t.get('outcome')}  total={ms(t.get('total_s'))}  "
            f"(1-in-{t.get('sampled_1_in', 1)} sampled, "
            f"residual {t.get('residual_s', 0):.2e}s)"
        )
        for stage, dt in t.get("root_stages", []):
            out.append(f"  |- {stage:<18} {ms(dt)}")
        spans = t.get("spans", [])
        by_parent: dict = {}
        for s in spans:
            by_parent.setdefault(s.get("parent_id"), []).append(s)

        def walk(parent, depth):
            for s in by_parent.get(parent, []):
                ann = s.get("annotations", {})
                ann_s = " ".join(f"{a}={ann[a]}" for a in sorted(ann))
                dur = (ms(s.get("duration_s"))
                       if s.get("kind") != "event" else "event")
                out.append(f"  {'   ' * depth}+- [{s.get('kind')}] "
                           f"{s.get('name')}  {dur}  {ann_s}".rstrip())
                for stage, dt in s.get("stages", []) or []:
                    out.append(f"  {'   ' * (depth + 1)}.  "
                               f"{stage:<16} {ms(dt)}")
                walk(s.get("span_id"), depth + 1)

        walk(None, 0)
    return "\n".join(out) + "\n"


def provenance(fleet_snapshot: dict | None = None) -> dict:
    """The obs provenance block every bench artifact embeds: the schema
    version that produced it plus, when the measured mode ran a fleet,
    its full ``obs.snapshot()``."""
    out = {
        "obs_schema": OBS_SCHEMA,
        "has_fleet_snapshot": fleet_snapshot is not None,
    }
    if fleet_snapshot is not None:
        out["fleet"] = jsonable(fleet_snapshot)
    return out
