"""Request-scoped span chains (DESIGN.md §14): where a request's time went.

A traced request carries ONE :class:`SpanChain`: an append-only list of
(stage, monotonic-timestamp) stamps written at the dispatcher's existing
choke points — no new threads, no device syncs, no allocation beyond the
stamp tuples.  The canonical stage sequence of a served request:

  ``admitted``   — ``submit()`` accepted the request (its ``t_submit``)
  ``coalesced``  — the worker popped it into a dispatch batch
  ``staged``     — the batch is padded, stacked and handed to device_put
  ``dispatched`` — the async device call was issued
  ``device``     — ``block_until_ready`` returned (the sync the dispatch
                   path ALREADY performs — tracing adds zero host syncs,
                   the PR-9 deferred-probe discipline applied to timing)
  ``sliced``     — per-request host result trees were cut from the batch
  ``<outcome>``  — terminal stamp at ``t_done`` (served / degraded /
                   expired / failed), written by ``_finish``

Each consecutive stamp pair defines one duration, attributed to the LATER
stage ("time spent reaching it"), so a request that never dispatched
(expired in queue, failed by the watchdog) still yields a well-formed
chain — admitted straight to its terminal stage.  Durations telescope:
their sum is EXACTLY last-stamp minus first-stamp, i.e. the request's
measured end-to-end latency (``t_done - t_submit``), which is the span
integrity invariant ``python bench.py obs`` and tests/test_obs.py pin.

A dispatch retry re-stamps staged/dispatched/device for each attempt;
:meth:`durations` aggregates by stage name, and the telescoping-sum
property survives because aggregation only regroups the same diffs.
Chains are written by one thread at a time (the submitter, then the
worker that owns the batch, then whoever resolves the request under the
dispatcher lock), so they carry no lock of their own — with ONE
documented exception: a request abandoned mid-dispatch (caller timeout,
watchdog) is resolved by its terminal stamp while the wedged worker may
still be walking the batch, and when that worker unsticks its late
stage stamps can land AFTER the terminal one.  The read side is
therefore what owns the invariant: every accessor truncates the chain
at the FIRST terminal stamp, so late post-terminal writes are inert and
``fsum(durations) == total == t_done - t_submit`` holds for every
resolved request, abandoned or not (regression-pinned in
tests/test_obs.py).
"""

from __future__ import annotations

import math

# The non-terminal stages, in dispatch order.
STAGES = ("admitted", "coalesced", "staged", "dispatched", "device",
          "sliced")
# Terminal stamps reuse the outcome-class names of the SLO accounting.
TERMINAL_STAGES = ("served", "degraded", "shed", "expired", "failed")


class SpanChain:
    """Append-only (stage, t) stamps for one request; see module doc."""

    __slots__ = ("stamps",)

    def __init__(self, stage: str, t: float):
        self.stamps: list[tuple[str, float]] = [(stage, t)]

    def stamp(self, stage: str, t: float) -> None:
        self.stamps.append((stage, t))

    def _effective(self) -> list[tuple[str, float]]:
        """The chain up to (and including) its FIRST terminal stamp —
        the truncation that makes late post-terminal writes from an
        abandoned dispatch's worker inert (see module docstring)."""
        for i, (stage, _) in enumerate(self.stamps):
            if stage in TERMINAL_STAGES:
                return self.stamps[:i + 1]
        return self.stamps

    def total(self) -> float:
        """First terminal stamp (or last stamp, unresolved) minus first:
        the chain's end-to-end span."""
        eff = self._effective()
        return eff[-1][1] - eff[0][1]

    def segments(self) -> list[tuple[str, float]]:
        """(stage, dt) per consecutive stamp pair, attributed to the
        later stage, in stamp order (retries appear as repeats);
        truncated at the first terminal stamp."""
        eff = self._effective()
        out = []
        for (_, t0), (stage, t1) in zip(eff, eff[1:]):
            out.append((stage, t1 - t0))
        return out

    def durations(self) -> dict[str, float]:
        """Per-stage durations aggregated by stage name.  Their
        ``math.fsum`` equals :meth:`total` (telescoping — the span
        integrity pin)."""
        agg: dict[str, float] = {}
        for stage, dt in self.segments():
            agg[stage] = agg.get(stage, 0.0) + dt
        return agg

    def residual(self) -> float:
        """|fsum(durations) - total| — 0 up to float summation noise;
        exported by the bench so the artifact carries the evidence."""
        return abs(math.fsum(self.durations().values()) - self.total())
