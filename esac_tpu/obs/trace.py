"""Request-scoped span chains + fleet-wide causal traces (DESIGN.md §14/§19).

A traced request carries ONE :class:`SpanChain`: an append-only list of
(stage, monotonic-timestamp) stamps written at the dispatcher's existing
choke points — no new threads, no device syncs, no allocation beyond the
stamp tuples.  The canonical stage sequence of a served request:

  ``admitted``   — ``submit()`` accepted the request (its ``t_submit``)
  ``coalesced``  — the worker popped it into a dispatch batch
  ``staged``     — the batch is padded, stacked and handed to device_put
  ``dispatched`` — the async device call was issued
  ``device``     — ``block_until_ready`` returned (the sync the dispatch
                   path ALREADY performs — tracing adds zero host syncs,
                   the PR-9 deferred-probe discipline applied to timing)
  ``sliced``     — per-request host result trees were cut from the batch
  ``<outcome>``  — terminal stamp at ``t_done`` (served / degraded /
                   expired / failed), written by ``_finish``

Each consecutive stamp pair defines one duration, attributed to the LATER
stage ("time spent reaching it"), so a request that never dispatched
(expired in queue, failed by the watchdog) still yields a well-formed
chain — admitted straight to its terminal stage.  Durations telescope:
their sum is EXACTLY last-stamp minus first-stamp, i.e. the request's
measured end-to-end latency (``t_done - t_submit``), which is the span
integrity invariant ``python bench.py obs`` and tests/test_obs.py pin.

A dispatch retry re-stamps staged/dispatched/device for each attempt;
:meth:`durations` aggregates by stage name, and the telescoping-sum
property survives because aggregation only regroups the same diffs.
Chains are written by one thread at a time (the submitter, then the
worker that owns the batch, then whoever resolves the request under the
dispatcher lock), so they carry no lock of their own — with ONE
documented exception: a request abandoned mid-dispatch (caller timeout,
watchdog) is resolved by its terminal stamp while the wedged worker may
still be walking the batch, and when that worker unsticks its late
stage stamps can land AFTER the terminal one.  The read side is
therefore what owns the invariant: every accessor truncates the chain
at the FIRST terminal stamp, so late post-terminal writes are inert and
``fsum(durations) == total == t_done - t_submit`` holds for every
resolved request, abandoned or not (regression-pinned in
tests/test_obs.py).

Causal traces (ISSUE 15, DESIGN.md §19): a :class:`SpanChain` sees ONE
dispatcher.  A request today crosses the FleetRouter (affinity / spill /
failover, §18), a replica dispatcher, and — on a cache fault — the host
tier, prefetcher and disk (§17).  :class:`Trace` is the container that
ties those tiers together under one trace id:

- the ROOT of a trace is a SpanChain in the minting tier's clock domain
  (the FleetRouter's for fleet traces: submitted -> routing ->
  replica [-> failover_routing -> replica ...] -> outcome; the
  dispatcher's own admitted -> ... -> outcome chain for traces minted by
  a standalone traced dispatcher).  The root chain IS the telescoping
  contract at fleet scope: router overhead + replica span(s) (+ failover
  siblings) partition [t_submit, t_done] exactly, because every segment
  is a consecutive-stamp diff in ONE clock — fsum(durations) == total,
  the §14 invariant lifted a tier;
- child :class:`Span` records nest under it — the replica dispatch (the
  underlying request's admitted->...->outcome stage chain, measured in
  the DISPATCHER's clock and telescoping on its own), the registry fault
  path (cache miss -> host-tier hit or disk load -> decompress -> stage,
  with prefetch-coalesced demand faults annotated), and
  breaker/quarantine events as zero-duration event spans.  A failover
  re-dispatch span carries ``retry_of`` linking it to the sibling it
  replaced;
- writes are LOCKLESS: ``spans`` is an append-only list (GIL-atomic
  appends, same contract as SpanChain stamps — the writer at any instant
  is the single thread owning that phase of the request, and the one
  documented exception, a late span from an abandoned dispatch's wedged
  worker, appends after ``finish()`` and stays out of any snapshot that
  already rendered).  The read side copies.

Trace CONTEXT flows to the registry tiers through a contextvar, not an
argument: the dispatcher wraps each dispatch attempt in
:func:`trace_scope` with the batch's traced requests' traces, and the
weight cache / host tier / scene-health machinery record spans into
:func:`active_traces` when (and only when) the running dispatch carries
one — zero plumbing through jitted-adjacent signatures, zero cost when
no trace is active (one contextvar read on the fault path, which is
already a multi-ms path).  :func:`issuer_scope` marks the prefetcher's
thread so a demand fault coalescing onto an in-flight prefetch is
annotated as exactly that.

:class:`TraceStore` is the ring-bounded home of completed traces (the
``traces`` obs collector; ``python -m esac_tpu.obs --traces`` renders
the K slowest).  Its lock is a LEAF of the committed lock graph:
``add`` is a deque append, nothing is ever acquired under it.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import math
import os
import threading

# The non-terminal stages, in dispatch order.
STAGES = ("admitted", "coalesced", "staged", "dispatched", "device",
          "sliced")
# Terminal stamps reuse the outcome-class names of the SLO accounting.
TERMINAL_STAGES = ("served", "degraded", "shed", "expired", "failed")


class SpanChain:
    """Append-only (stage, t) stamps for one request; see module doc."""

    __slots__ = ("stamps",)

    def __init__(self, stage: str, t: float):
        self.stamps: list[tuple[str, float]] = [(stage, t)]

    def stamp(self, stage: str, t: float) -> None:
        self.stamps.append((stage, t))

    def _effective(self) -> list[tuple[str, float]]:
        """The chain up to (and including) its FIRST terminal stamp —
        the truncation that makes late post-terminal writes from an
        abandoned dispatch's worker inert (see module docstring)."""
        for i, (stage, _) in enumerate(self.stamps):
            if stage in TERMINAL_STAGES:
                return self.stamps[:i + 1]
        return self.stamps

    def total(self) -> float:
        """First terminal stamp (or last stamp, unresolved) minus first:
        the chain's end-to-end span."""
        eff = self._effective()
        return eff[-1][1] - eff[0][1]

    def segments(self) -> list[tuple[str, float]]:
        """(stage, dt) per consecutive stamp pair, attributed to the
        later stage, in stamp order (retries appear as repeats);
        truncated at the first terminal stamp."""
        eff = self._effective()
        out = []
        for (_, t0), (stage, t1) in zip(eff, eff[1:]):
            out.append((stage, t1 - t0))
        return out

    def durations(self) -> dict[str, float]:
        """Per-stage durations aggregated by stage name.  Their
        ``math.fsum`` equals :meth:`total` (telescoping — the span
        integrity pin)."""
        agg: dict[str, float] = {}
        for stage, dt in self.segments():
            agg[stage] = agg.get(stage, 0.0) + dt
        return agg

    def residual(self) -> float:
        """|fsum(durations) - total| — 0 up to float summation noise;
        exported by the bench so the artifact carries the evidence."""
        return abs(math.fsum(self.durations().values()) - self.total())


# ---------------------------------------------------------------------------
# Causal traces (ISSUE 15): trace ids, child spans, context propagation.
# ---------------------------------------------------------------------------

_TRACE_SEQ = itertools.count(1)  # .__next__ is GIL-atomic


def new_trace_id() -> str:
    """Process-unique, cheap trace id (no uuid import on the hot path)."""
    return f"t{os.getpid():x}-{next(_TRACE_SEQ):x}"


class Span:
    """One child record of a :class:`Trace`: a named [t0, t1] interval
    (``kind`` in dispatch / weight_fault / event) with optional per-stage
    segments (a dispatch span carries the underlying request's chain
    segments) and free-form annotations.  Immutable after construction
    except ``parent_id``, which :meth:`Trace.finish` may assign by
    interval containment (a weight-fault span recorded mid-dispatch is
    adopted by the dispatch span that covers it)."""

    __slots__ = ("span_id", "parent_id", "name", "kind", "t0", "t1",
                 "stages", "annotations")

    def __init__(self, span_id, name, kind, t0, t1, stages=None,
                 parent_id=None, annotations=None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.t0 = t0
        self.t1 = t1
        self.stages = stages  # [(stage, dt)] or None
        self.annotations = annotations or {}

    def to_dict(self) -> dict:
        out = {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "t0": self.t0,
            "duration_s": (self.t1 - self.t0
                           if self.t1 is not None else None),
        }
        if self.stages:
            out["stages"] = [[s, dt] for s, dt in self.stages]
        if self.annotations:
            out["annotations"] = dict(self.annotations)
        return out


class Trace:
    """One sampled request's causal trace: a root :class:`SpanChain` in
    the minting tier's clock plus lockless child spans (module
    docstring).  ``root`` is stamped by the tier that minted the trace —
    a standalone traced dispatcher hands the root chain to the request
    itself (``req.spans is trace.root``), a FleetRouter keeps the root
    and gives each underlying request a fresh child chain."""

    __slots__ = ("trace_id", "scene", "root", "spans", "outcome", "done",
                 "sampled_1_in", "_span_seq")

    def __init__(self, t_submit: float, scene=None, trace_id: str = None,
                 sampled_1_in: int = 1, root_stage: str = "submitted"):
        self.trace_id = trace_id if trace_id is not None else new_trace_id()
        self.scene = scene
        self.root = SpanChain(root_stage, t_submit)
        self.spans: list[Span] = []  # append-only; GIL-atomic appends
        self.outcome = None
        self.done = False
        self.sampled_1_in = sampled_1_in
        self._span_seq = itertools.count(1)

    # -- write side (lockless; see module docstring) --

    def stamp(self, stage: str, t: float) -> None:
        """Stamp the ROOT chain (router overhead boundaries).  Inert
        after the terminal stamp — the SpanChain read-side truncation."""
        self.root.stamp(stage, t)

    def add_span(self, name: str, kind: str, t0: float, t1: float,
                 stages=None, parent_id=None, **annotations) -> Span:
        sp = Span(next(self._span_seq), name, kind, t0, t1, stages,
                  parent_id, annotations)
        self.spans.append(sp)
        return sp

    def add_event(self, name: str, t: float, **annotations) -> Span:
        """Zero-duration event span (breaker trips, quarantines,
        prefetch coalescing)."""
        return self.add_span(name, "event", t, t, **annotations)

    def finish(self, outcome: str, t_done: float) -> bool:
        """Terminal root stamp + adopt orphan spans into the dispatch
        span whose interval contains them.  Idempotent (first caller
        wins), mirroring the dispatcher's exactly-once ``_finish``."""
        if self.done:
            return False
        self.stamp(outcome, t_done)
        self.outcome = outcome
        dispatches = [s for s in list(self.spans) if s.kind == "dispatch"]
        for sp in list(self.spans):
            if sp.parent_id is None and sp.kind != "dispatch":
                for d in dispatches:
                    if d.t0 is not None and sp.t0 is not None \
                            and d.t0 <= sp.t0 and (d.t1 is None
                                                   or sp.t0 <= d.t1):
                        sp.parent_id = d.span_id
                        break
        self.done = True
        return True

    # -- read side --

    def total(self) -> float:
        return self.root.total()

    def durations(self) -> dict[str, float]:
        return self.root.durations()

    def residual(self) -> float:
        """The FLEET telescoping check: |fsum(root durations) - total|.
        Router overhead + replica span(s) + failover siblings partition
        the end-to-end span exactly (``python bench.py obs`` fleet leg
        pins this at < 1e-6 s)."""
        return self.root.residual()

    def to_dict(self) -> dict:
        eff = self.root._effective()
        return {
            "trace_id": self.trace_id,
            "scene": self.scene,
            "outcome": self.outcome,
            "sampled_1_in": self.sampled_1_in,
            "t_submit": eff[0][1],
            "total_s": self.total(),
            "root_stages": [[stage, dt] for stage, dt
                            in self.root.segments()],
            "residual_s": self.residual(),
            "spans": [s.to_dict() for s in list(self.spans)],
        }


class TraceStore:
    """Ring-bounded home of completed traces — the ``traces`` obs
    collector.  The lock is a LEAF of the committed lock graph
    (``add``/readers only touch the deque and counters; nothing is
    acquired under it), so publishing a trace from inside a dispatcher
    or router critical section is a sanctioned owner -> leaf nesting,
    exactly like the obs instrument locks."""

    def __init__(self, maxlen: int = 256):
        if maxlen < 1:
            raise ValueError(f"maxlen {maxlen} < 1")
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=maxlen)
        self.added = 0

    def add(self, trace: Trace) -> None:
        with self._lock:
            self._ring.append(trace)
            self.added += 1

    def traces(self) -> list[Trace]:
        with self._lock:
            return list(self._ring)

    def slowest(self, k: int = 5) -> list[dict]:
        """The K slowest COMPLETED retained traces, slowest first —
        rendered (to_dict) outside the lock."""
        done = [t for t in self.traces() if t.done]
        done.sort(key=lambda t: t.total(), reverse=True)
        return [t.to_dict() for t in done[:k]]

    def snapshot(self) -> dict:
        """The ``traces`` collector payload: counts + the 5 slowest."""
        with self._lock:
            retained = len(self._ring)
            added = self.added
        return {
            "added": added,
            "retained": retained,
            "slowest": self.slowest(5),
        }


# -- context propagation (dispatcher -> registry tiers) --

_ACTIVE_TRACES: contextvars.ContextVar = contextvars.ContextVar(
    "esac_obs_active_traces", default=()
)
_ISSUER: contextvars.ContextVar = contextvars.ContextVar(
    "esac_obs_issuer", default="demand"
)


def active_traces() -> tuple:
    """The traces carried by the dispatch currently running in this
    thread (empty when untraced — the common case, one contextvar
    read)."""
    return _ACTIVE_TRACES.get()


@contextlib.contextmanager
def trace_scope(traces):
    """Run a dispatch attempt with ``traces`` visible to the registry
    fault path (weight cache, host tier, scene health)."""
    token = _ACTIVE_TRACES.set(tuple(traces))
    try:
        yield
    finally:
        _ACTIVE_TRACES.reset(token)


def current_issuer() -> str:
    """Who is driving this thread's cache/tier loads: "demand" (a
    dispatch) or "prefetch" (the predictive prefetcher's cycle)."""
    return _ISSUER.get()


@contextlib.contextmanager
def issuer_scope(name: str):
    token = _ISSUER.set(name)
    try:
        yield
    finally:
        _ISSUER.reset(token)
