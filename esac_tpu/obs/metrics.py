"""Bounded metric instruments + the unified fleet registry (DESIGN.md §14).

One substrate for every number the serving fleet publishes: the
dispatcher's request/outcome accounting, the SLO layer's sheds and
quarantines, the scene-health breaker, the weight cache and the
per-request trace spans all land in ONE :class:`MetricsRegistry`, which
renders to a locked, ``json.dumps``-able snapshot and a Prometheus-style
text page.  Three instrument families:

- :class:`CounterVec` — monotone labeled counters (``inc``), plus
  ``reset``/``rebase`` window hooks (the dispatcher's ``reset_stats``
  subtracts its own contribution via negative ``inc`` so a SHARED
  registry's other publishers survive a local reset; a counter that
  could only grow would break the accounting invariant across resets).
- :class:`GaugeVec` — labeled last-value-wins gauges.
- :class:`HistogramVec` — labeled :class:`StreamingHistogram` children:
  fixed-memory log-bucketed quantile sketches.  This is what replaces the
  dispatcher's sort-the-whole-deque ``latency_quantiles()``: a snapshot
  reads quantiles in O(buckets), not O(n log n) over ``10*stats_window``
  samples under the dispatch lock, and the relative error is bounded by
  the bucket growth factor (sqrt(growth)-1, ~3.4% at the default 1.07 —
  pinned against exact nearest-rank in tests/test_obs.py).

Windowing: a histogram with ``window=N`` keeps ``epochs`` fixed-size
bucket arrays and rotates them by sample count, so quantiles cover the
most recent ~N observations with memory that never grows — the same
recent-window semantics as the stat rings it replaces.

Concurrency (graft-lint R10 applies to this package): every instrument
guards its mutable state with its own instance lock, and the registry
lock covers only the name->instrument / collector tables.  Lock order is
registry -> collector-owner (e.g. the dispatcher) -> instrument; writers
go owner -> instrument.  Nothing here ever calls back into an owner
while holding an instrument lock, so the order is acyclic — and
``snapshot()`` runs collectors OUTSIDE the registry lock, so a slow
collector cannot block concurrent instrument writes behind the registry.
Since graft-audit v3 this order is MACHINE-CHECKED, not prose: the
owner->instrument edges are committed in ``.lock_graph.json`` (R12,
DESIGN.md §15), a new nesting fails the lint until reviewed, and the
runtime witness (lint/witness.py) asserts the edges actually taken
under the concurrency stress legs stay inside that order.

Pure host code: no jax import anywhere in this package (observability
must never become a TPU relay client, CLAUDE.md hazards).
"""

from __future__ import annotations

import math
import threading

OBS_SCHEMA = 1

# Default histogram resolution: log-spaced buckets over 0.1us..10000s with
# 7% growth — 374 buckets, worst-case relative quantile error
# sqrt(1.07)-1 ~= 3.4% (the tolerance tests/test_obs.py pins at 5%).
_HIST_LO = 1e-7
_HIST_HI = 1e4
_HIST_GROWTH = 1.07


def _labelkey(labels: dict) -> tuple:
    """Canonical hashable key for a label set (sorted by label name;
    values may be None/int/str — they are stringified only at export)."""
    return tuple(sorted(labels.items()))


def _matches(key: tuple, sub: dict) -> bool:
    """True iff the child labeled ``key`` carries every (k, v) in ``sub``
    — the subset-match used to merge histogram children per label."""
    have = dict(key)
    return all(have.get(k, _MISSING) == v for k, v in sub.items())


_MISSING = object()


class StreamingHistogram:
    """Fixed-memory log-bucketed quantile sketch over positive samples.

    ``window`` bounds the number of retained observations (None =
    lifetime): internally ``epochs`` bucket arrays rotate by count, so
    between window*(epochs-1)/epochs and window samples are live at any
    time.  Non-positive/non-finite samples clamp into the underflow
    bucket (they exist — a clock can step backwards across threads — and
    must never corrupt the sketch or raise on the serving path).
    """

    __slots__ = ("_lo", "_log_lo", "_log_growth", "_n_buckets", "_lock",
                 "_epochs", "_epoch_cap", "_counts", "_stats",
                 "_life_counts", "_life_n", "_life_sum")

    def __init__(self, lo: float = _HIST_LO, hi: float = _HIST_HI,
                 growth: float = _HIST_GROWTH,
                 window: int | None = None, epochs: int = 8):
        if not (0 < lo < hi) or growth <= 1.0:
            raise ValueError(f"bad histogram bounds lo={lo} hi={hi} "
                             f"growth={growth}")
        if window is not None and window < 1:
            raise ValueError(f"window {window} < 1")
        if epochs < 1:
            raise ValueError(f"epochs {epochs} < 1")
        self._lo = lo
        self._log_lo = math.log(lo)
        self._log_growth = math.log(growth)
        self._n_buckets = int(math.ceil(math.log(hi / lo) / self._log_growth))
        self._lock = threading.Lock()
        self._epochs = 1 if window is None else epochs
        self._epoch_cap = (None if window is None
                           else max(1, window // self._epochs))
        # Ring of epochs, newest last; each epoch is (counts, stats) with
        # stats = [count, sum, min, max].
        self._counts: list[list[int]] = [self._new_counts()]
        self._stats: list[list[float]] = [[0, 0.0, math.inf, -math.inf]]
        # LIFETIME (never-rotated) bucket counts: the timeline layer
        # (obs/timeline.py) diffs these between ticks to build exact
        # per-window histograms — windowed epoch counts rotate, so their
        # diffs can go negative and cannot anchor a delta.  One extra
        # fixed-size array + two scalars: the fixed-memory bound holds.
        self._life_counts: list[int] = self._new_counts()
        self._life_n = 0
        self._life_sum = 0.0

    def _new_counts(self) -> list[int]:
        return [0] * (self._n_buckets + 2)  # + underflow/overflow slots

    def _index(self, v: float) -> int:
        if not (v > self._lo) or not math.isfinite(v):
            return 0
        i = int((math.log(v) - self._log_lo) / self._log_growth) + 1
        return min(i, self._n_buckets + 1)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            counts, stats = self._counts[-1], self._stats[-1]
            i = self._index(v)
            counts[i] += 1
            self._life_counts[i] += 1
            self._life_n += 1
            stats[0] += 1
            if math.isfinite(v):
                stats[1] += v
                stats[2] = min(stats[2], v)
                stats[3] = max(stats[3], v)
                self._life_sum += v
            if self._epoch_cap is not None and stats[0] >= self._epoch_cap:
                self._counts.append(self._new_counts())
                self._stats.append([0, 0.0, math.inf, -math.inf])
                if len(self._counts) > self._epochs:
                    del self._counts[0]
                    del self._stats[0]

    def observe_many(self, values) -> None:
        """Bulk :meth:`observe`: ONE lock acquisition for a whole
        dispatch's samples (the serving hot path publishes per-dispatch,
        not per-request — the host-path overhaul's obs batching).
        Sample-for-sample identical to a loop of scalar ``observe``
        calls: same bucket increments, same lifetime stream, and the
        epoch-rotation check runs after EVERY sample exactly as the
        scalar path does, so windowed quantiles cannot tell the two
        apart."""
        vs = [float(v) for v in values]
        if not vs:
            return
        with self._lock:
            for v in vs:
                counts, stats = self._counts[-1], self._stats[-1]
                i = self._index(v)
                counts[i] += 1
                self._life_counts[i] += 1
                self._life_n += 1
                stats[0] += 1
                if math.isfinite(v):
                    stats[1] += v
                    stats[2] = min(stats[2], v)
                    stats[3] = max(stats[3], v)
                    self._life_sum += v
                if self._epoch_cap is not None \
                        and stats[0] >= self._epoch_cap:
                    self._counts.append(self._new_counts())
                    self._stats.append([0, 0.0, math.inf, -math.inf])
                    if len(self._counts) > self._epochs:
                        del self._counts[0]
                        del self._stats[0]

    def _merged_locked(self):
        """(counts, count, sum, min, max) over the retained window
        (lock held by the caller)."""
        counts = self._new_counts()
        n, s, lo, hi = 0, 0.0, math.inf, -math.inf
        for epoch, stats in zip(self._counts, self._stats):
            for i, c in enumerate(epoch):
                counts[i] += c
            n += stats[0]
            s += stats[1]
            lo = min(lo, stats[2])
            hi = max(hi, stats[3])
        return counts, n, s, lo, hi

    def merged(self):
        with self._lock:
            return self._merged_locked()

    @staticmethod
    def _quantile_from(counts, n, lo_seen, hi_seen, q: float,
                       log_lo: float, log_growth: float) -> float:
        """Nearest-rank quantile from merged bucket counts, with the
        bucket's geometric midpoint as the representative value, clamped
        to the observed [min, max]."""
        if n == 0:
            return float("nan")
        rank = min(n - 1, round(q * (n - 1)))
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen > rank:
                if i == 0:
                    v = lo_seen
                else:
                    # bucket i covers [lo*g^(i-1), lo*g^i): geometric mid.
                    v = math.exp(log_lo + (i - 0.5) * log_growth)
                if math.isfinite(lo_seen):
                    v = min(max(v, lo_seen), hi_seen)
                return float(v)
        return float(hi_seen)  # unreachable (counts sum to n)

    def quantile(self, q: float) -> float:
        counts, n, _, lo, hi = self.merged()
        return self._quantile_from(counts, n, lo, hi, q,
                                   self._log_lo, self._log_growth)

    def reset(self) -> None:
        """Clear the WINDOW.  The lifetime stream (:meth:`lifetime`) is
        deliberately untouched: it is a monotone accounting stream like
        a counter, so timeline deltas survive a stats reset instead of
        going negative."""
        with self._lock:
            self._counts = [self._new_counts()]
            self._stats = [[0, 0.0, math.inf, -math.inf]]

    def lifetime(self):
        """(bucket counts copy, n, sum) over the histogram's LIFETIME —
        the monotone stream the timeline layer diffs per window."""
        with self._lock:
            return list(self._life_counts), self._life_n, self._life_sum

    def quantile_from_counts(self, counts, n, q: float) -> float:
        """Nearest-rank quantile over caller-supplied bucket counts in
        THIS histogram's bucket geometry (the timeline's per-window
        delta histograms) — bucket midpoints; a rank landing in the
        underflow bucket reports the bucket floor ``lo`` (per-window
        extrema are not retained, and +inf here would leak
        non-JSON-standard tokens into window records — review
        finding)."""
        return self._quantile_from(counts, n, self._lo, math.inf, q,
                                   self._log_lo, self._log_growth)

    def summary(self, quantiles=(0.5, 0.9, 0.99)) -> dict:
        counts, n, s, lo, hi = self.merged()
        out = {
            "count": int(n),
            "sum": float(s),
            "min": (float(lo) if n and math.isfinite(lo) else None),
            "max": (float(hi) if n and math.isfinite(hi) else None),
        }
        for q in quantiles:
            out[f"p{round(q * 100):d}"] = self._quantile_from(
                counts, n, lo, hi, q, self._log_lo, self._log_growth
            )
        return out


class CounterVec:
    """Labeled monotone counter family (plus the documented
    reset/rebase/negative-inc window hooks ``reset_stats``-style
    re-basing requires — see the module docstring)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.kind = "counter"
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _labelkey(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, **labels) -> float:
        with self._lock:
            return self._values.get(_labelkey(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def items(self) -> list[tuple[dict, float]]:
        with self._lock:
            return [(dict(k), v) for k, v in sorted(
                self._values.items(), key=lambda kv: repr(kv[0])
            )]

    def rebase(self, value: float, **labels) -> None:
        """Set one child to an absolute value — a window hook for
        external monitors that re-anchor a counter wholesale; never for
        normal accounting.  (The dispatcher's ``reset_stats`` does NOT
        use this: it subtracts its own contribution via negative
        :meth:`inc` so shared-registry peers survive.)"""
        with self._lock:
            self._values[_labelkey(labels)] = float(value)

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def export(self) -> dict:
        return {
            "kind": self.kind, "help": self.help,
            "samples": [{"labels": labels, "value": v}
                        for labels, v in self.items()],
        }


class GaugeVec:
    """Labeled last-value-wins gauge family."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.kind = "gauge"
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_labelkey(labels)] = float(value)

    def get(self, **labels) -> float:
        with self._lock:
            return self._values.get(_labelkey(labels), float("nan"))

    def items(self) -> list[tuple[dict, float]]:
        with self._lock:
            return [(dict(k), v) for k, v in sorted(
                self._values.items(), key=lambda kv: repr(kv[0])
            )]

    def reset(self) -> None:
        with self._lock:
            self._values.clear()

    def export(self) -> dict:
        return {
            "kind": self.kind, "help": self.help,
            "samples": [{"labels": labels, "value": v}
                        for labels, v in self.items()],
        }


class HistogramVec:
    """Labeled family of :class:`StreamingHistogram` children.

    ``quantile``/``count``/``summary`` accept a PARTIAL label set and
    merge every child that matches it — the accessor the per-scene /
    per-route_k latency views use (merge over the other label).  Label
    cardinality is the caller's responsibility, exactly like the
    dispatcher's per-lane counters: keyed by fleet, not by traffic.
    """

    def __init__(self, name: str, help: str = "", lo: float = _HIST_LO,
                 hi: float = _HIST_HI, growth: float = _HIST_GROWTH,
                 window: int | None = None, epochs: int = 8):
        self.name = name
        self.help = help
        self.kind = "histogram"
        self._hist_kw = dict(lo=lo, hi=hi, growth=growth, window=window,
                             epochs=epochs)
        self._lock = threading.Lock()
        self._children: dict[tuple, StreamingHistogram] = {}

    def _child(self, labels: dict) -> StreamingHistogram:
        key = _labelkey(labels)
        with self._lock:
            h = self._children.get(key)
            if h is None:
                h = self._children[key] = StreamingHistogram(**self._hist_kw)
            return h

    def observe(self, v: float, **labels) -> None:
        self._child(labels).observe(v)

    def observe_many(self, values, **labels) -> None:
        """Bulk observe into one child: a single family-lock lookup and
        a single child-lock acquisition for the whole batch (vs one of
        each per sample on the scalar path)."""
        self._child(labels).observe_many(values)

    def labelsets(self) -> list[dict]:
        with self._lock:
            return [dict(k) for k in self._children]

    def children(self) -> list[tuple[dict, "StreamingHistogram"]]:
        """(labels, child) snapshot — the timeline layer's iteration
        surface (children are internally locked; the list is a copy)."""
        with self._lock:
            return [(dict(k), h) for k, h in self._children.items()]

    def _select(self, sub: dict) -> list[StreamingHistogram]:
        with self._lock:
            return [h for k, h in self._children.items() if _matches(k, sub)]

    def _merged(self, sub: dict):
        counts = None
        n, s, lo, hi = 0, 0.0, math.inf, -math.inf
        ref = None
        for h in self._select(sub):
            c, cn, cs, clo, chi = h.merged()
            if counts is None:
                counts = list(c)
                ref = h
            else:
                for i, x in enumerate(c):
                    counts[i] += x
            n += cn
            s += cs
            lo = min(lo, clo)
            hi = max(hi, chi)
        return ref, counts, n, s, lo, hi

    def quantile(self, q: float, **labels) -> float:
        ref, counts, n, _, lo, hi = self._merged(labels)
        if ref is None or n == 0:
            return float("nan")
        return StreamingHistogram._quantile_from(
            counts, n, lo, hi, q, ref._log_lo, ref._log_growth
        )

    def count(self, **labels) -> int:
        return int(self._merged(labels)[2])

    def summary(self, quantiles=(0.5, 0.9, 0.99), **labels) -> dict:
        ref, counts, n, s, lo, hi = self._merged(labels)
        out = {
            "count": int(n), "sum": float(s),
            "min": (float(lo) if n and math.isfinite(lo) else None),
            "max": (float(hi) if n and math.isfinite(hi) else None),
        }
        for q in quantiles:
            out[f"p{round(q * 100):d}"] = (
                float("nan") if ref is None or n == 0
                else StreamingHistogram._quantile_from(
                    counts, n, lo, hi, q, ref._log_lo, ref._log_growth
                )
            )
        return out

    def reset(self) -> None:
        with self._lock:
            children = list(self._children.values())
        for h in children:
            h.reset()

    def export(self) -> dict:
        with self._lock:
            items = sorted(self._children.items(),
                           key=lambda kv: repr(kv[0]))
        return {
            "kind": self.kind, "help": self.help,
            "samples": [{"labels": dict(k), **h.summary()}
                        for k, h in items],
        }


class MetricsRegistry:
    """The unified fleet registry: named instruments + pull collectors.

    ``counter``/``gauge``/``histogram`` are idempotent per name (the
    existing instrument is returned; a kind mismatch raises — two
    components silently sharing a name across kinds is a bug).
    ``register_collector`` attaches a zero-argument callable whose
    locked snapshot dict rides ``snapshot()`` under ``collectors`` — the
    pull side of the registry, used by surfaces that already own a
    consistent snapshot method (``slo_totals``, ``SceneRegistry.health``,
    ``DeviceWeightCache.stats``).  Collectors run OUTSIDE the registry
    lock (see module docstring for the lock order).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}
        self._collectors: dict[str, object] = {}
        # ISSUE 15 attachments (created on demand, idempotent): the
        # ring-bounded trace store, the windowed-aggregate timeline and
        # the health-rule engine.  Each owns a LEAF lock of the
        # committed lock graph; the registry lock only guards the
        # attachment slots themselves.
        self._trace_store = None
        self._timeline = None
        self._health_rules = None

    def _instrument(self, name: str, factory, kind: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif m.kind != kind:
                raise ValueError(  # graft-lint: disable=R16(obs stays import-free of serve — no taxonomy available here; registration misuse is a programming error at wiring time, never a servable fault)
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> CounterVec:
        return self._instrument(
            name, lambda: CounterVec(name, help), "counter"
        )

    def gauge(self, name: str, help: str = "") -> GaugeVec:
        return self._instrument(name, lambda: GaugeVec(name, help), "gauge")

    def histogram(self, name: str, help: str = "", **hist_kw) -> HistogramVec:
        return self._instrument(
            name, lambda: HistogramVec(name, help, **hist_kw), "histogram"
        )

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def register(self, instrument) -> None:
        """Adopt an EXISTING instrument object under its own name — the
        cross-registry sharing hook: a component that owns instruments
        (e.g. the SceneRegistry's health counters) registers the same
        objects into a dispatcher's registry so one fleet snapshot sees
        them.  Re-adopting the same object is a no-op; a different
        instrument under a taken name raises (silent shadowing would
        split the truth)."""
        with self._lock:
            have = self._metrics.get(instrument.name)
            if have is None:
                self._metrics[instrument.name] = instrument
            elif have is not instrument:
                raise ValueError(  # graft-lint: disable=R16(obs stays import-free of serve — no taxonomy available here; registration misuse is a programming error at wiring time, never a servable fault)
                    f"metric {instrument.name!r} already registered with a "
                    "different instrument object"
                )

    # ---- ISSUE 15 attachments ----

    def trace_store(self, maxlen: int = 256) -> "TraceStore":
        """The registry's ring-bounded :class:`~esac_tpu.obs.trace.\
TraceStore`, created on first call (idempotent; ``maxlen`` binds only
        at creation) and published as the ``traces`` collector.  Every
        tracing surface (dispatcher, FleetRouter) that mints traces
        calls this once at construction."""
        from esac_tpu.obs.trace import TraceStore

        with self._lock:
            ts = self._trace_store
            if ts is None:
                ts = self._trace_store = TraceStore(maxlen)
        self.register_collector("traces", ts.snapshot)
        return ts

    def get_trace_store(self) -> "TraceStore | None":
        """The attached trace store, or None (never creates)."""
        with self._lock:
            return self._trace_store

    def tables(self) -> tuple[dict, dict]:
        """Locked copy of (instruments, collectors) — the iteration
        surface ``snapshot()`` and the timeline's aggregation share
        (the registry lock is released before any instrument lock is
        taken; the committed lock order stays acyclic)."""
        with self._lock:
            return dict(self._metrics), dict(self._collectors)

    def attach_timeline(self, window_s: float = 1.0,
                        max_windows: int = 120,
                        collectors: bool = True):
        """Attach (or return the existing) :class:`~esac_tpu.obs.\
timeline.Timeline` over this registry, published as the ``timeline``
        collector.  Idempotent: sizing binds at first attach."""
        from esac_tpu.obs.timeline import Timeline

        with self._lock:
            tl = self._timeline
            if tl is None:
                tl = self._timeline = Timeline(
                    self, window_s=window_s, max_windows=max_windows,
                    collectors=collectors,
                )
        self.register_collector("timeline", tl.snapshot)
        return tl

    def timeline(self):
        """The attached timeline, or None (never creates)."""
        with self._lock:
            return self._timeline

    def attach_health_rules(self, rules=None, max_alerts: int = 256,
                            **timeline_kw):
        """Attach (or return the existing) :class:`~esac_tpu.obs.rules.\
RuleEngine` over this registry's timeline (attached too when missing),
        published as the ``health_alerts`` collector plus the
        ``health_alerts_total`` counter / ``health_alert_active`` gauge.
        ``rules=None`` takes the default catalog (DESIGN.md §19)."""
        from esac_tpu.obs.rules import RuleEngine, default_rules

        tl = self.attach_timeline(**timeline_kw)
        with self._lock:
            eng = self._health_rules
            if eng is None:
                eng = self._health_rules = RuleEngine(
                    tl, default_rules() if rules is None else rules,
                    registry=None, max_alerts=max_alerts,
                )
        eng.bind_obs(self)
        return eng

    def health_rules(self):
        """The attached rule engine, or None (never creates)."""
        with self._lock:
            return self._health_rules

    def register_collector(self, name: str, fn) -> None:
        """Attach a named pull collector: a zero-argument callable
        returning a snapshot-consistent dict.  Registration is
        LAST-WINS by design: ``SceneRegistry.bind_obs`` re-registers an
        equivalent ``scene_health`` collector into each dispatcher's
        registry it adopts.  Corollary for the shared-registry
        aggregation mode (see the dispatcher docstring's NOTE): two
        components of the same kind sharing one registry aggregate
        their COUNTERS but only the most recent registrant's collector
        block rides the snapshot — per-instance views want per-instance
        registries."""
        with self._lock:
            self._collectors[name] = fn

    def snapshot(self) -> dict:
        """One locked, ``json.dumps``-able fleet snapshot: every
        instrument's exported samples plus every collector's dict (tuple
        keys and numpy scalars sanitized).  Collector failures are
        recorded in place, never raised — a snapshot must not die on one
        sick surface."""
        from esac_tpu.obs.export import jsonable

        import time

        with self._lock:
            metrics = dict(self._metrics)
            collectors = dict(self._collectors)
        out = {
            "obs_schema": OBS_SCHEMA,
            "recorded_at_unix": time.time(),
            "metrics": {name: m.export() for name, m in metrics.items()},
            "collectors": {},
        }
        for name, fn in collectors.items():
            try:
                out["collectors"][name] = fn()
            except Exception as e:  # noqa: BLE001 — recorded, never raised
                out["collectors"][name] = {"error": repr(e)}
        return jsonable(out)

    def render_prometheus(self) -> str:
        from esac_tpu.obs.export import render_prometheus

        return render_prometheus(self.snapshot())
