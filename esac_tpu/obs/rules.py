"""Declarative health rules over the timeline (ISSUE 15, DESIGN.md §19).

The timeline (obs/timeline.py) gives every fleet number a time axis;
this module is the judgment layer on top: a small catalog of DECLARATIVE
rules — each a frozen parameter set with one ``evaluate(windows)``
method — producing typed, ring-bounded :class:`Alert` events that ride
``obs.snapshot()`` (the ``health_alerts`` collector), the Prometheus
page (``health_alerts_total`` counter + ``health_alert_active`` gauge,
labeled per rule) and ``python -m esac_tpu.obs``.

The shipped catalog (thresholds argued in DESIGN.md §19):

- :class:`BurnRateRule` — SLO error-budget burn over a FAST/SLOW window
  pair: bad outcomes / offered must exceed the fast threshold (it is
  happening now, not an old average) AND the slow threshold (enough
  budget actually burned to matter) before firing — the standard
  multi-window burn-rate shape, immune to both a single bad window and
  a slow leak hiding inside a long average.
- :class:`BadFracSlopeRule` — per-scene ``bad_frac`` SLOPE from the
  ``scene_health`` collector series: the ROADMAP item 5 trigger ("bad
  frac drifting up WITHOUT tripping") is a derivative, invisible to any
  threshold on the value itself until too late.
- :class:`PrefetchWasteRule` — wasted / issued prefetches over the
  recent windows: a predictor issuing staging work the demand stream
  never collects is burning PCIe/host bandwidth the serve path needs.
- :class:`AffinitySagRule` — affinity hit rate (affinity / scene-routed
  routes) sagging below a floor: the 10x cold/warm gap of
  ``.registry_swap.json`` is only collected while affinity holds.
- :class:`QueueKneeRule` — queue occupancy (pending / depth) nearing
  the loadtest knee: occupancy is the leading indicator of the
  goodput cliff (DESIGN.md §12), and shedding starts AT the cliff —
  the alert is the margin warning before it.

Evaluation discipline (R13, the committed lock-graph leaf contract):
``RuleEngine.evaluate`` snapshots windows via the timeline's locked
accessor, evaluates EVERY rule with no lock held, publishes instrument
updates (instrument locks only), and only then appends alert events
under its own leaf lock.  Alerts are EDGE-TRIGGERED: an event is
recorded when a rule transitions inactive -> active (and one on
recovery), so a persistent condition cannot flood the ring; the
current state rides the ``health_alert_active`` gauge.

Pure host code: no jax import (the obs package contract).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time


@dataclasses.dataclass(frozen=True)
class Alert:
    """One typed alert event (json-dumpable via :meth:`to_dict`)."""

    rule: str
    severity: str          # "warn" | "page"
    value: float           # the statistic that fired
    threshold: float       # the limit it crossed
    message: str
    labels: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "severity": self.severity,
            "value": self.value, "threshold": self.threshold,
            "message": self.message, "labels": dict(self.labels),
        }


def _counter_sum(windows, name: str, label_sub: str | None = None):
    """Sum of a counter's per-window deltas over ``windows`` (all label
    children, or only keys containing ``label_sub``)."""
    total = 0.0
    for w in windows:
        for key, d in w.get("counters", {}).get(name, {}).items():
            if label_sub is None or label_sub in key:
                total += d
    return total


def _collector_series(windows, collector: str, path_suffix: str):
    """Per-path series of a collector leaf across windows: {full_path:
    [values]} for every path ending in ``path_suffix`` (the per-scene
    fan-out — one series per scene)."""
    series: dict[str, list[float]] = collections.defaultdict(list)
    for w in windows:
        block = w.get("collectors", {}).get(collector, {})
        for path, v in block.items():
            if path.endswith(path_suffix):
                series[path].append(v)
    return dict(series)


def _slope(ys) -> float:
    """Least-squares slope per window of ``ys`` (0.0 under 2 points)."""
    n = len(ys)
    if n < 2:
        return 0.0
    xbar = (n - 1) / 2.0
    ybar = sum(ys) / n
    num = sum((i - xbar) * (y - ybar) for i, y in enumerate(ys))
    den = sum((i - xbar) ** 2 for i in range(n))
    return num / den if den else 0.0


@dataclasses.dataclass(frozen=True)
class BurnRateRule:
    """Multi-window SLO burn rate over an outcomes counter (module
    docstring).  ``bad`` outcome labels burn budget; the denominator is
    the offered counter."""

    name: str = "slo_burn_rate"
    severity: str = "page"
    outcomes_counter: str = "serve_outcomes_total"
    offered_counter: str = "serve_offered_total"
    bad_outcomes: tuple = ("shed", "expired", "failed")
    fast_windows: int = 3
    slow_windows: int = 30
    fast_bad_frac: float = 0.10
    slow_bad_frac: float = 0.02
    min_offered: int = 20  # evidence floor: no verdicts on a whisper

    def evaluate(self, windows) -> list[Alert]:
        if not windows:
            return []
        out = []
        fast = windows[-self.fast_windows:]
        slow = windows[-self.slow_windows:]

        def frac(ws):
            offered = _counter_sum(ws, self.offered_counter)
            bad = sum(_counter_sum(ws, self.outcomes_counter,
                                   f"outcome={o}")
                      for o in self.bad_outcomes)
            return bad / offered if offered else 0.0, offered

        fast_frac, fast_n = frac(fast)
        slow_frac, slow_n = frac(slow)
        if (fast_n >= self.min_offered
                and fast_frac >= self.fast_bad_frac
                and slow_frac >= self.slow_bad_frac):
            out.append(Alert(
                self.name, self.severity, round(fast_frac, 4),
                self.fast_bad_frac,
                f"error budget burning: bad-frac {fast_frac:.3f} over "
                f"last {len(fast)} window(s) (slow {slow_frac:.3f} over "
                f"{len(slow)}; offered {int(fast_n)})",
                {"slow_bad_frac": round(slow_frac, 4)},
            ))
        return out


@dataclasses.dataclass(frozen=True)
class BadFracSlopeRule:
    """Per-scene bad-frac drift (ROADMAP item 5's trigger): the slope of
    a scene's ``bad_frac`` series over the recent windows exceeds
    ``min_slope`` per window AND the latest value is already past a
    noise floor — a flat-but-noisy breaker window cannot fire it, a
    steady drift toward the trip threshold does, BEFORE the trip."""

    name: str = "scene_bad_frac_slope"
    severity: str = "warn"
    collector: str = "scene_health"
    path_suffix: str = ".bad_frac"
    windows: int = 10
    min_slope: float = 0.02
    min_latest: float = 0.05

    def evaluate(self, windows) -> list[Alert]:
        out = []
        recent = windows[-self.windows:]
        for path, ys in _collector_series(recent, self.collector,
                                          self.path_suffix).items():
            if len(ys) < 3:
                continue
            slope = _slope(ys)
            if slope >= self.min_slope and ys[-1] >= self.min_latest:
                out.append(Alert(
                    self.name, self.severity, round(slope, 4),
                    self.min_slope,
                    f"{path} drifting up: slope {slope:.3f}/window over "
                    f"{len(ys)} windows, latest {ys[-1]:.3f}",
                    {"path": path, "latest": round(ys[-1], 4)},
                ))
        return out


@dataclasses.dataclass(frozen=True)
class PrefetchWasteRule:
    """Wasted / issued prefetch ratio over the recent windows (reads the
    ``prefetch`` collector's cumulative counters, diffing first->last):
    a predictor whose issues stopped converting is staging for nobody."""

    name: str = "prefetch_waste"
    severity: str = "warn"
    collector: str = "prefetch"
    windows: int = 10
    max_waste_ratio: float = 0.5
    min_issued: int = 8

    def evaluate(self, windows) -> list[Alert]:
        recent = windows[-self.windows:]
        if not recent:
            return []

        def series(path):
            ys = [w.get("collectors", {}).get(self.collector, {}).get(path)
                  for w in recent]
            ys = [y for y in ys if y is not None]
            return (ys[-1] - ys[0]) if len(ys) >= 2 else 0.0

        issued = series("issued_device") + series("issued_host")
        wasted = series("wasted")
        if issued >= self.min_issued:
            ratio = wasted / issued
            if ratio >= self.max_waste_ratio:
                return [Alert(
                    self.name, self.severity, round(ratio, 4),
                    self.max_waste_ratio,
                    f"prefetch waste {ratio:.2f} ({int(wasted)} wasted / "
                    f"{int(issued)} issued over {len(recent)} windows)",
                )]
        return []


@dataclasses.dataclass(frozen=True)
class AffinitySagRule:
    """Affinity hit rate over the recent windows' route deltas sagging
    below the floor (scene-routed routes only — the §18 denominator)."""

    name: str = "affinity_sag"
    severity: str = "warn"
    routes_counter: str = "fleet_routes_total"
    windows: int = 10
    min_hit_rate: float = 0.5
    min_routed: int = 16

    def evaluate(self, windows) -> list[Alert]:
        recent = windows[-self.windows:]
        if not recent:
            return []
        aff = _counter_sum(recent, self.routes_counter, "kind=affinity")
        spill = _counter_sum(recent, self.routes_counter, "kind=spill")
        cold = _counter_sum(recent, self.routes_counter, "kind=cold")
        routed = aff + spill + cold
        if routed >= self.min_routed:
            rate = aff / routed
            if rate < self.min_hit_rate:
                return [Alert(
                    self.name, self.severity, round(rate, 4),
                    self.min_hit_rate,
                    f"affinity hit rate {rate:.2f} over {len(recent)} "
                    f"windows ({int(aff)}/{int(routed)} scene-routed)",
                )]
        return []


@dataclasses.dataclass(frozen=True)
class QueueKneeRule:
    """Queue occupancy (``serve_slo_totals.pending`` / ``queue_depth``)
    near the knee: mean occupancy over the fast windows at/above the
    fraction where the loadtest curve bends (DESIGN.md §12 measured the
    knee at ~0.8x capacity; occupancy is its leading indicator)."""

    name: str = "queue_knee"
    severity: str = "warn"
    collector: str = "serve_slo_totals"
    queue_depth: int = 64
    windows: int = 3
    max_occupancy_frac: float = 0.7

    def evaluate(self, windows) -> list[Alert]:
        recent = windows[-self.windows:]
        ys = [w.get("collectors", {}).get(self.collector, {}).get("pending")
              for w in recent]
        ys = [y for y in ys if y is not None]
        if not ys:
            return []
        occ = (sum(ys) / len(ys)) / max(self.queue_depth, 1)
        if occ >= self.max_occupancy_frac:
            return [Alert(
                self.name, self.severity, round(occ, 4),
                self.max_occupancy_frac,
                f"queue occupancy {occ:.2f} of depth {self.queue_depth} "
                f"over {len(ys)} windows — approaching the goodput knee",
            )]
        return []


def default_rules(queue_depth: int = 64) -> tuple:
    """The shipped catalog (DESIGN.md §19 argues each threshold)."""
    return (
        BurnRateRule(),
        BadFracSlopeRule(),
        PrefetchWasteRule(),
        AffinitySagRule(),
        QueueKneeRule(queue_depth=queue_depth),
    )


class RuleEngine:
    """Evaluate a rule catalog over a timeline; typed, ring-bounded,
    edge-triggered alert events (module docstring)."""

    def __init__(self, timeline, rules, registry=None,
                 max_alerts: int = 256, clock=time.time):
        self._timeline = timeline
        self._rules = tuple(rules)
        self._clock = clock
        self._lock = threading.Lock()  # LEAF: ring + active/edge state
        self._alerts: collections.deque = collections.deque(
            maxlen=max_alerts
        )
        self._active: dict[str, Alert] = {}
        self._eval_errors = 0
        self._last_ticks = -1
        self._m_alerts = None
        self._g_active = None
        if registry is not None:
            self.bind_obs(registry)

    def bind_obs(self, registry) -> None:
        """Create/adopt the engine's instruments in ``registry`` and
        register the ``health_alerts`` collector (idempotent)."""
        self._m_alerts = registry.counter(
            "health_alerts_total",
            "edge-triggered health-rule alerts by (rule, edge)",
        )
        self._g_active = registry.gauge(
            "health_alert_active",
            "1 while a health rule's condition holds, else 0",
        )
        registry.register_collector("health_alerts", self.snapshot)

    def rules(self) -> tuple:
        return self._rules

    # ---- evaluation ----

    def evaluate(self) -> list[Alert]:
        """One pass: snapshot windows (timeline's lock), run every rule
        (NO lock held), publish instruments, then record edges under
        the engine's leaf lock.  Returns the alerts currently FIRING
        (not just the edges)."""
        windows = self._timeline.windows()
        firing: list[Alert] = []
        eval_errors = 0
        for rule in self._rules:
            try:
                firing.extend(rule.evaluate(windows))
            except Exception:  # noqa: BLE001 — one sick rule must not
                eval_errors += 1  # silence the rest; counted, not hidden
        now = self._clock()
        by_key = {(a.rule, a.labels.get("path", "")): a for a in firing}
        with self._lock:
            self._eval_errors += eval_errors
            rising = [a for k, a in by_key.items()
                      if k not in self._active]
            falling = [k for k in self._active if k not in by_key]
            for a in rising:
                self._alerts.append({"t_unix": now, "edge": "raise",
                                     **a.to_dict()})
            for k in falling:
                prev = self._active[k]
                self._alerts.append({
                    "t_unix": now, "edge": "clear", "rule": prev.rule,
                    "labels": dict(prev.labels),
                })
            self._active = dict(by_key)
            rule_active = {r.name: 0.0 for r in self._rules}
            for a in by_key.values():
                rule_active[a.rule] = 1.0
        # Instrument publishes OUTSIDE the engine lock (leaf contract).
        if self._m_alerts is not None:
            for a in rising:
                self._m_alerts.inc(rule=a.rule, edge="raise")
            for k in falling:
                self._m_alerts.inc(rule=k[0], edge="clear")
        if self._g_active is not None:
            for name, v in rule_active.items():
                self._g_active.set(v, rule=name)
        return firing

    def maybe_evaluate(self) -> list[Alert] | None:
        """Evaluate once per NEW timeline window (the piggyback hook a
        polling loop calls every iteration)."""
        ticks = self._timeline.ticks
        with self._lock:
            if ticks == self._last_ticks:
                return None
            self._last_ticks = ticks
        return self.evaluate()

    # ---- read side ----

    def active(self) -> dict:
        with self._lock:
            return {f"{r}|{p}" if p else r: a.to_dict()
                    for (r, p), a in self._active.items()}

    def alerts(self) -> list[dict]:
        with self._lock:
            return [dict(a) for a in self._alerts]

    def snapshot(self) -> dict:
        """The ``health_alerts`` collector payload."""
        with self._lock:
            events = [dict(a) for a in self._alerts]
            active = {f"{r}|{p}" if p else r: a.to_dict()
                      for (r, p), a in self._active.items()}
            eval_errors = self._eval_errors
        return {
            "rules": [r.name for r in self._rules],
            "active": active,
            "events": events,
            "eval_errors": eval_errors,
        }
