"""Fleet observability (DESIGN.md §14/§19): tracing, metrics, export.

Observability as a LAYER, not another ring buffer: one
:class:`MetricsRegistry` that the dispatcher, SLO layer, scene registry,
health breakers and weight cache all publish into; request-scoped
:class:`SpanChain` tracing stamped at the dispatcher's existing choke
points (gated — the hot path with tracing off is unchanged, and with it
on gains zero host syncs and zero jit interactions); fleet-wide causal
:class:`Trace` records tying the FleetRouter, replica dispatchers and
the registry's weight-fault path together under one sampled trace id
(ring-bounded :class:`TraceStore`, the ``traces`` collector); a
ring-bounded windowed :class:`~esac_tpu.obs.timeline.Timeline` giving
every collector a time axis; a declarative health
:class:`~esac_tpu.obs.rules.RuleEngine` over it; and one export surface
— a locked ``json.dumps``-able ``snapshot()``, a Prometheus-style text
page (every collector's numeric leaves included), the ``python -m
esac_tpu.obs`` dump CLI (``--traces`` renders the K slowest sampled
traces) and the ``python bench.py obs`` overhead gate behind
``.obs_overhead.json``.

Pure host package: importing it never touches jax or the TPU relay.
"""

from esac_tpu.obs.export import jsonable, provenance, render_prometheus
from esac_tpu.obs.metrics import (
    OBS_SCHEMA,
    CounterVec,
    GaugeVec,
    HistogramVec,
    MetricsRegistry,
    StreamingHistogram,
)
from esac_tpu.obs.rules import Alert, RuleEngine, default_rules
from esac_tpu.obs.timeline import Timeline
from esac_tpu.obs.trace import (
    STAGES,
    Span,
    SpanChain,
    TERMINAL_STAGES,
    Trace,
    TraceStore,
    active_traces,
    current_issuer,
    issuer_scope,
    new_trace_id,
    trace_scope,
)

__all__ = [
    "OBS_SCHEMA",
    "Alert",
    "CounterVec",
    "GaugeVec",
    "HistogramVec",
    "MetricsRegistry",
    "RuleEngine",
    "Span",
    "SpanChain",
    "STAGES",
    "StreamingHistogram",
    "TERMINAL_STAGES",
    "Timeline",
    "Trace",
    "TraceStore",
    "active_traces",
    "current_issuer",
    "default_rules",
    "issuer_scope",
    "jsonable",
    "new_trace_id",
    "provenance",
    "render_prometheus",
    "trace_scope",
]
