"""Fleet observability (DESIGN.md §14): tracing, metrics, export.

Observability as a LAYER, not another ring buffer: one
:class:`MetricsRegistry` that the dispatcher, SLO layer, scene registry,
health breakers and weight cache all publish into; request-scoped
:class:`SpanChain` tracing stamped at the dispatcher's existing choke
points (gated — the hot path with tracing off is unchanged, and with it
on gains zero host syncs and zero jit interactions); and one export
surface — a locked ``json.dumps``-able ``snapshot()``, a
Prometheus-style text page, the ``python -m esac_tpu.obs`` dump CLI and
the ``python bench.py obs`` overhead gate behind ``.obs_overhead.json``.

Pure host package: importing it never touches jax or the TPU relay.
"""

from esac_tpu.obs.export import jsonable, provenance, render_prometheus
from esac_tpu.obs.metrics import (
    OBS_SCHEMA,
    CounterVec,
    GaugeVec,
    HistogramVec,
    MetricsRegistry,
    StreamingHistogram,
)
from esac_tpu.obs.trace import SpanChain, STAGES, TERMINAL_STAGES

__all__ = [
    "OBS_SCHEMA",
    "CounterVec",
    "GaugeVec",
    "HistogramVec",
    "MetricsRegistry",
    "SpanChain",
    "STAGES",
    "StreamingHistogram",
    "TERMINAL_STAGES",
    "jsonable",
    "provenance",
    "render_prometheus",
]
