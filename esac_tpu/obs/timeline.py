"""Time-series layer over the MetricsRegistry (ISSUE 15, DESIGN.md §19).

Every obs surface before this module answered "what is the fleet doing
NOW": counters are lifetime totals, histograms cover a recent sample
window, collectors are point-in-time snapshots.  ROADMAP items 3
(quality-weighted degradation) and 5 (closed-loop retraining, triggered
by "bad-frac drifting up without tripping") both need a TREND — a value
moving across windows — which no point snapshot can produce.  The
:class:`Timeline` is that axis:

- :meth:`tick` closes one WINDOW: for every counter, the per-label
  delta (and rate) since the previous tick; for every histogram child,
  an exact per-window histogram (lifetime bucket counts diffed between
  ticks — see ``StreamingHistogram.lifetime``) reduced to count /
  p50 / p99; every gauge's last value; and, optionally, every numeric
  leaf of every pull collector (the per-scene ``bad_frac``s, prefetch
  issue/waste counters, queue occupancy — the exact inputs the rule
  engine reads), flattened to dotted paths with a hard per-collector
  cap so a hostile collector cannot grow a window without bound.
- windows land in a ring (``deque(maxlen=max_windows)``): memory is
  pinned by (max_windows x instrument cardinality), both fleet-bounded
  — a week-long server's timeline is as flat as its stat rings
  (regression-pinned in tests/test_obs.py under a 10k-request stream).

Locking (graft-lint R10/R12/R13; the committed ``.lock_graph.json``):
``Timeline._lock`` is a LEAF.  :meth:`tick` aggregates with NO timeline
lock held — instrument and collector-owner locks are taken one at a
time, exactly as ``snapshot()`` does — and only the ring append + the
previous-tick baseline swap happen under the lock.  Nothing blocks
under it, nothing is acquired under it.

Driving: the timeline is PULL-driven, no thread of its own.
:meth:`maybe_tick` is the cheap piggyback hook (one clock read + one
compare when the window has not elapsed): the FleetRouter's completion
loop calls it between polls, benches/tests call :meth:`tick` directly.

Pure host code: no jax import (the obs package contract).
"""

from __future__ import annotations

import collections
import threading
import time

# Hard cap on numeric leaves recorded per collector per window: the
# flattener must bound a window's size even against a collector that
# returns unbounded structure (the ring pins window COUNT; this pins
# window WIDTH).
COLLECTOR_LEAF_CAP = 512


def _labels_key(labels: dict) -> str:
    """Canonical string key for a label set ("" for unlabeled) — window
    records must be json-dumpable as-is (artifact material)."""
    if not labels:
        return ""
    return ",".join(f"{k}={labels[k]}" for k in sorted(labels))


def flatten_numeric(obj, prefix: str = "", out=None, cap=COLLECTOR_LEAF_CAP):
    """Dotted-path -> scalar map of ``obj``'s numeric leaves (bools
    excluded; lists/events skipped — trend inputs are scalars), capped
    at ``cap`` entries in deterministic (sorted-key) order."""
    if out is None:
        out = {}
    if len(out) >= cap:
        return out
    if isinstance(obj, bool):
        return out
    if isinstance(obj, (int, float)):
        out[prefix] = float(obj)
        return out
    if isinstance(obj, dict):
        for k in sorted(obj, key=str):
            if len(out) >= cap:
                break
            key = str(k) if not prefix else f"{prefix}.{k}"
            flatten_numeric(obj[k], key, out, cap)
    return out


class Timeline:
    """Ring-bounded windowed aggregates over one
    :class:`~esac_tpu.obs.metrics.MetricsRegistry` (module docstring)."""

    def __init__(self, registry, window_s: float = 1.0,
                 max_windows: int = 120, collectors: bool = True,
                 clock=time.perf_counter):
        if window_s <= 0:
            raise ValueError(f"window_s {window_s} <= 0")
        if max_windows < 1:
            raise ValueError(f"max_windows {max_windows} < 1")
        self._registry = registry
        self.window_s = window_s
        self.max_windows = max_windows
        self._collectors = bool(collectors)
        self._clock = clock
        self._lock = threading.Lock()  # LEAF: ring + baseline only
        self._ring = collections.deque(maxlen=max_windows)
        self._baseline = None   # previous tick's raw aggregate
        self._t_baseline = None
        self.ticks = 0

    # ---- aggregation (NO timeline lock held) ----

    def _collect(self) -> dict:
        """Raw monotone/point aggregate of every instrument (and,
        optionally, collector numeric leaves).  Takes instrument /
        collector-owner locks one at a time; never the timeline lock."""
        metrics, collectors = self._registry.tables()
        counters, gauges, hists = {}, {}, {}
        for name, m in metrics.items():
            kind = getattr(m, "kind", None)
            if kind == "counter":
                counters[name] = {
                    _labels_key(labels): v for labels, v in m.items()
                }
            elif kind == "gauge":
                gauges[name] = {
                    _labels_key(labels): v for labels, v in m.items()
                }
            elif kind == "histogram":
                per = {}
                for labels, child in m.children():
                    counts, n, s = child.lifetime()
                    per[_labels_key(labels)] = (counts, n, s, child)
                hists[name] = per
        coll = {}
        if self._collectors:
            for name, fn in collectors.items():
                if name in ("timeline", "traces", "health_alerts"):
                    # Never aggregate ourselves, and skip the obs
                    # layer's own list-heavy collectors: TraceStore.
                    # snapshot sorts + serializes the 5 slowest traces
                    # per call, which at a 50ms window cadence is pure
                    # wasted work on the serving control thread for two
                    # scalars no rule reads (review finding).
                    continue
                try:
                    coll[name] = flatten_numeric(fn())
                except Exception:  # noqa: BLE001 — a sick collector must
                    coll[name] = {}  # not kill the tick (snapshot contract)
        return {"counters": counters, "gauges": gauges, "hists": hists,
                "collectors": coll}

    @staticmethod
    def _window(prev, cur, t0, t1) -> dict:
        dt = max(t1 - t0, 1e-9)
        counters, rates = {}, {}
        for name, vals in cur["counters"].items():
            pvals = (prev or {}).get("counters", {}).get(name, {})
            # Counter-reset convention (the Prometheus rate() rule): a
            # value BELOW the baseline means the counter was re-based
            # (reset_stats subtracts the dispatcher's own contribution),
            # and the honest window delta is the value itself — a raw
            # diff would record a huge negative delta/rate and poison
            # the burn-rate denominator for a whole slow horizon
            # (review finding).
            deltas = {}
            for k, v in vals.items():
                d = v - pvals.get(k, 0.0)
                deltas[k] = v if d < 0 else d
            counters[name] = deltas
            rates[name] = {k: d / dt for k, d in deltas.items()}
        gauges = {name: dict(vals) for name, vals in cur["gauges"].items()}
        hist = {}
        for name, per in cur["hists"].items():
            pper = (prev or {}).get("hists", {}).get(name, {})
            out = {}
            for key, (counts, n, s, child) in per.items():
                pcounts, pn, ps, _ = pper.get(key, (None, 0, 0.0, None))
                if pcounts is None:
                    dcounts = list(counts)
                else:
                    dcounts = [a - b for a, b in zip(counts, pcounts)]
                dn = n - pn
                rec = {"count": int(dn)}
                if dn > 0:
                    rec["mean"] = (s - ps) / dn
                    rec["p50"] = child.quantile_from_counts(
                        dcounts, dn, 0.5)
                    rec["p99"] = child.quantile_from_counts(
                        dcounts, dn, 0.99)
                out[key] = rec
            hist[name] = out
        return {
            "t0": t0, "t1": t1, "dt_s": dt,
            "counters": counters, "rates": rates, "gauges": gauges,
            "hist": hist, "collectors": dict(cur["collectors"]),
        }

    # ---- ticking ----

    def tick(self, now: float | None = None) -> dict | None:
        """Close one window against the previous tick's baseline and
        append it to the ring; the FIRST tick only establishes the
        baseline (there is no previous edge to diff against) and
        returns None.  The window DIFF is computed with no lock held —
        only the baseline swap and the ring append ride the leaf lock
        (review finding: building the full diff under it made every
        concurrent ``snapshot()``/``windows()`` reader wait out the
        aggregation).  Concurrent tickers are not a supported driver
        pattern (one loop owns the cadence); a racing pair costs at
        most one out-of-order append, never corruption."""
        if now is None:
            now = self._clock()
        cur = self._collect()
        with self._lock:
            prev, t_prev = self._baseline, self._t_baseline
            self._baseline, self._t_baseline = cur, now
            self.ticks += 1
        if prev is None:
            return None
        win = self._window(prev, cur, t_prev, now)
        with self._lock:
            self._ring.append(win)
        return win

    def maybe_tick(self, now: float | None = None) -> dict | None:
        """Tick iff a full window elapsed since the last tick — the
        piggyback hook for an existing loop (one clock read + one
        compare when not due)."""
        if now is None:
            now = self._clock()
        with self._lock:
            due = (self._t_baseline is None
                   or now - self._t_baseline >= self.window_s)
        return self.tick(now) if due else None

    # ---- read side ----

    def windows(self) -> list[dict]:
        """Locked snapshot of the ring, oldest first (window dicts are
        immutable once appended — the copy is the list, not the
        records)."""
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> dict:
        """The ``timeline`` collector payload: sizing, tick count, and
        the LAST window (the full ring is pull-read via
        :meth:`windows` — a fleet snapshot must stay proportional to
        the fleet, not to the ring)."""
        with self._lock:
            last = self._ring[-1] if self._ring else None
            return {
                "window_s": self.window_s,
                "max_windows": self.max_windows,
                "ticks": self.ticks,
                "windows_retained": len(self._ring),
                "last_window": last,
            }
