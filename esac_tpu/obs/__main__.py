"""``python -m esac_tpu.obs`` — dump a fleet snapshot.

Reads an obs snapshot and renders it as Prometheus text (default,
every collector's numeric leaves included as samples), pretty JSON, or
— with ``--traces [K]`` — the K slowest sampled causal traces (span
tree + per-stage durations, ISSUE 15).  Sources, in order:

- ``--file PATH``: a JSON file that is either a bare ``snapshot()`` dict
  (has a ``metrics`` key) or a bench artifact carrying one (the
  ``obs_provenance.fleet`` block every ``_driver_main`` artifact embeds,
  or the obs mode's ``obs.obs_snapshot`` payload field);
- no flag: the committed ``.obs_overhead.json`` next to the repo's
  ``bench.py`` (the zero-setup "what does the fleet look like" answer);
- ``--demo``: run a tiny in-process echo fleet (forcing the CPU backend
  FIRST — CLAUDE.md: an ad-hoc interpreter touching jax while the relay
  is unhealthy becomes a second stuck process) and dump its live
  snapshot, tracing on.

Exit status 2 when no snapshot can be located.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys


def _extract_snapshot(doc: dict) -> dict | None:
    """Find a snapshot dict inside a bare snapshot or a bench artifact."""
    if not isinstance(doc, dict):
        return None
    if "metrics" in doc and "obs_schema" in doc:
        return doc
    prov = doc.get("obs_provenance")
    if isinstance(prov, dict) and isinstance(prov.get("fleet"), dict):
        return prov["fleet"]
    obs = doc.get("obs")
    if isinstance(obs, dict) and isinstance(obs.get("obs_snapshot"), dict):
        return obs["obs_snapshot"]
    return None


def _demo_snapshot() -> dict:
    """A tiny live fleet on the CPU backend: echo infer fn, traced
    dispatcher, a few mixed-scene requests — enough to exercise every
    instrument the dispatcher publishes."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.serve.dispatcher import MicroBatchDispatcher

    def echo(tree, scene=None, route_k=None):
        return {"echo": tree["x"]}

    cfg = RansacConfig(frame_buckets=(1, 4), serve_max_wait_ms=1.0)
    disp = MicroBatchDispatcher(echo, cfg, trace=True)
    try:
        reqs = [
            disp.submit({"x": np.full(2, i, np.float32)},
                        scene=f"s{i % 2}")
            for i in range(8)
        ]
        for r in reqs:
            r.get(30.0)
    finally:
        disp.close()
    return disp.obs.snapshot()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m esac_tpu.obs",
        description="dump an esac_tpu fleet observability snapshot",
    )
    ap.add_argument("--file", type=pathlib.Path, default=None,
                    help="snapshot JSON or bench artifact carrying one")
    ap.add_argument("--format", choices=("prom", "json"), default="prom")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny in-process CPU fleet and dump it")
    ap.add_argument("--traces", type=int, nargs="?", const=5, default=None,
                    metavar="K",
                    help="render the K slowest sampled traces (default 5) "
                         "instead of the metrics page")
    args = ap.parse_args(argv)

    if args.demo:
        snap = _demo_snapshot()
    else:
        path = args.file
        if path is None:
            path = (pathlib.Path(__file__).resolve().parents[2]
                    / ".obs_overhead.json")
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError) as e:
            print(f"no readable snapshot at {path}: {e}", file=sys.stderr)
            return 2
        snap = _extract_snapshot(doc)
        if snap is None:
            print(f"{path} carries no obs snapshot "
                  "(expected a snapshot dict, obs_provenance.fleet, or "
                  "obs.obs_snapshot)", file=sys.stderr)
            return 2

    if args.traces is not None:
        from esac_tpu.obs.export import render_traces

        sys.stdout.write(render_traces(snap, args.traces))
    elif args.format == "json":
        print(json.dumps(snap, indent=1, sort_keys=True))
    else:
        from esac_tpu.obs.export import render_prometheus

        sys.stdout.write(render_prometheus(snap))
    return 0


if __name__ == "__main__":
    sys.exit(main())
