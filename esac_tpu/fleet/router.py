"""Scene-affinity replica fleet: the scheduler tier above the dispatchers.

Everything below this module is ONE dispatcher in front of ONE device
program.  The ROADMAP's "millions of users" claim needs N serving
replicas — each a :class:`~esac_tpu.serve.MicroBatchDispatcher` over its
own :class:`~esac_tpu.registry.SceneRegistry` + weight cache (CPU-viable
in-process here; the replica boundary is exactly the per-host process
boundary PARALLELISM.md draws, so the shapes transfer) — and a router
that survives a replica going bad: the observed relay-stall failure
mode, one level up.  This module is that router (DESIGN.md §18):

- **Scene-affinity routing.**  The 10x cold/warm gap in
  ``.registry_swap.json`` is the routing prize: a request goes to a
  replica already holding its scene warm (its *home*), spilling to the
  least-loaded healthy replica only on overload (the home shed it) or
  cold (no healthy home yet — the chosen replica becomes one).  Route
  kinds — affinity / spill / cold / dense — are counted per replica
  (``fleet_routes_total``) and summarized by :meth:`FleetRouter.\
affinity_stats`.
- **Per-replica health breakers**, composing with PR 9's per-scene ones
  one level down: a wedge-class fault (``DispatchStalledError`` /
  ``WorkerDiedError`` / ``DispatcherClosedError``) quarantines the
  replica immediately, a streak of other replica-INDICTING faults after
  ``FleetPolicy.replica_quarantine_after`` — while a per-scene LANE
  quarantine drain only fails over, never indicts the replica (a
  scene-scoped fault must not cascade into quarantining the fleet;
  see ``_REPLICA_INDICTING``); quarantined replicas shed
  typed (:class:`ReplicaQuarantinedError`, a
  :class:`~esac_tpu.serve.slo.ShedError` — admission semantics) and
  :meth:`FleetRouter.release_replica` is the operator hook mirroring
  ``release_lane``/``release_scene``.
- **Failover within the deadline.**  A request whose replica faults is
  re-dispatched to a surviving replica with its REMAINING deadline, up
  to ``failover_max`` times; the faulted attempt's underlying request
  is abandoned first (its late result is discarded by the dispatcher's
  exactly-once ``_finish``), so a drained request is never
  double-counted — fleet books record exactly ONE outcome per offered
  request, whatever happened underneath.  Because every replica's
  programs are compiled from the same (preset, cfg) and weights load
  from the same manifest, a failed-over result is bit-identical to
  dispatching the surviving replica directly (pinned in
  tests/test_fleet.py and measured by ``python bench.py fleet``).
- **Hot-scene replication + obs-driven rebalancing.**  The completion
  thread periodically replicates a scene to a second home when its
  share of the recent arrival window crosses
  ``FleetPolicy.replicate_share`` (optionally gated on the home
  replica's per-scene p99 from the obs lane histogram —
  ``replicate_p99_ms``); the new home is warmed OFF the request path.
  Per-scene p50/p99 and cache hit rates ride the ``fleet`` collector
  for the operator's view of the same decision inputs.
- **Fleet-level outcome accounting** that still sums exactly to offered
  at every instant: ``offered == served + degraded + shed + expired +
  failed + pending`` (:meth:`FleetRouter.fleet_totals`; the
  tests/test_fleet.py invariant, concurrent-stress pinned).

Pure host code: this module never imports jax (the obs discipline —
the scheduler tier must never become a second TPU relay client).
Concurrency: all mutable router state lives under ONE instance lock
(graft-lint R10); routing decisions snapshot under it and every
blocking call — dispatcher submits, underlying-request abandons, scene
warms, the poll sleep — happens OUTSIDE it (R13).  The router's lock
nests only over the obs instrument locks, the same committed
``.lock_graph.json`` order the dispatcher takes (R12; DESIGN.md §15),
and the runtime witness rides the fleet stress leg
(``LockWitness.attach_fleet(router=...)``).

The completion loop is a single poll thread (``FleetPolicy.poll_ms``):
underlying requests expose no callback, so the router polls their
events, settles finished ones, and runs the rebalancer between polls —
bounded work, no per-request threads, and failover latency is measured
honestly through it (``fleet_failover_seconds``).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

from esac_tpu.obs import MetricsRegistry, Trace
from esac_tpu.retrieval.errors import (
    RetrievalCandidatesExhaustedError,
    RetrievalMissError,
)
from esac_tpu.serve.slo import (
    ConfigError,
    DeadlineExceededError,
    DispatcherClosedError,
    DispatchStalledError,
    LaneQuarantinedError,
    ServeError,
    ShedError,
    WorkerDiedError,
)


class ReplicaQuarantinedError(ShedError):
    """The request's replica (or every healthy candidate) is quarantined
    after a wedge or fault streak; an operator must ``release_replica``
    it.  A quarantine rejection is a shed (admission semantics), so
    callers that only distinguish *admitted vs not* catch
    :class:`~esac_tpu.serve.slo.ShedError` — the exact contract
    ``LaneQuarantinedError`` set one level down."""

    # NOT retryable, unlike LaneQuarantinedError: this is only raised
    # once routing found NO healthy replica — there is nowhere else to
    # retry until an operator releases one.
    retryable = False
    wire_name = "replica_quarantined"


# FAILOVER-ELIGIBLE fault classes — another replica may well serve the
# request: the dispatch wedged (the relay-stall mode), the worker died,
# the dispatcher was closed under us, or a lane/replica quarantine
# drained the queue.  Anything else (a scene's checksum mismatch, a
# breaker shed) would fault identically on every replica and fails the
# request typed instead of re-paying the fault.
_REPLICA_FAULTS = (
    DispatchStalledError,
    WorkerDiedError,
    DispatcherClosedError,
    LaneQuarantinedError,
    ReplicaQuarantinedError,
)
# The subset that INDICTS THE REPLICA and feeds its breaker.  Lane- and
# replica-quarantine drains deliberately do NOT: a lane quarantine is
# the dispatcher's verdict on ONE (scene, route_k) — typically a
# scene-scoped fault — and a hot scene's drained backlog counting
# per-victim toward the replica streak would cascade a single corrupt
# scene into quarantining every replica in turn, fleet-wide (review
# finding); the drained requests simply fail over, and if the scene is
# truly broken everywhere they die typed on the scene's own error
# there.  (ReplicaQuarantinedError drains are the router's OWN trip —
# re-counting them would be circular.)
_REPLICA_INDICTING = (
    DispatchStalledError,
    WorkerDiedError,
    DispatcherClosedError,
)

OUTCOMES = ("served", "shed", "expired", "degraded", "failed")

# close() drain budget for the completion/poll thread, seconds.  Orders
# of magnitude above poll_ms, so a healthy loop always beats it; bounded
# so a wedged relay cannot hang close() forever (graft-lint R18).
_CLOSE_JOIN_S = 5.0


@dataclasses.dataclass(frozen=True)
class Replica:
    """One serving replica: a name, its dispatcher, and (optionally) the
    SceneRegistry behind it — the registry is only needed for warm-on-
    replicate and the cache-stats block of the fleet view; a bare
    dispatcher replica routes fine without one."""

    name: str
    dispatcher: object
    registry: object = None


@dataclasses.dataclass(frozen=True)
class FleetPolicy:
    """Host-side fleet scheduling knobs (frozen, like SLOPolicy — pure
    scheduler state, never a jit argument)."""

    # Completion-loop poll interval: bounds failover detection latency
    # (the dispatcher's own watchdog_poll_ms is the same order).
    poll_ms: float = 5.0
    # Max re-dispatches per request after replica faults; exhausted ->
    # the request fails typed with the replica fault it last saw.
    failover_max: int = 2
    # Consecutive non-wedge replica-INDICTING faults before quarantine.
    # Wedge-class faults (stall / dead worker / closed dispatcher) trip
    # instantly; in the in-process transport those are the only
    # indicting classes, so this knob is the seam for the multi-host
    # transport's softer fault classes (RPC timeouts, connection
    # resets).  Lane-quarantine drains never count (see
    # _REPLICA_INDICTING).
    replica_quarantine_after: int = 3
    # Scene-affinity table: how many home replicas one scene may hold.
    max_homes_per_scene: int = 2
    # Hot-scene replication: a scene whose share of the recent arrival
    # window reaches this fraction gets a second home (up to the cap).
    replicate_share: float = 0.4
    # ...but only once the window carries enough evidence.
    replicate_min_requests: int = 32
    # Optional obs gate: additionally require the scene's p99 on its
    # first home (obs lane histogram) at/above this before replicating.
    # None = share alone decides.
    replicate_p99_ms: float | None = None
    # Rebalancer cadence, and the arrival-window length it judges over.
    rebalance_every_s: float = 0.25
    arrivals_window: int = 512
    # Causal-trace sampling (ISSUE 15, DESIGN.md §19): 0 = tracing off;
    # N >= 1 mints a fleet Trace for every Nth submission (1 = every
    # request).  Sampling is what makes ALWAYS-ON tracing viable: the
    # per-request cost is gated at <= 3% by `python bench.py obs` at
    # N=1, and 1-in-N divides it.  Sampled traces land in the obs
    # registry's ring-bounded TraceStore (`traces` collector).
    trace_sample: int = 0

    def __post_init__(self):
        if self.trace_sample < 0:
            raise ValueError(f"trace_sample {self.trace_sample} < 0")
        if self.poll_ms <= 0:
            raise ValueError(f"poll_ms {self.poll_ms} <= 0")
        if self.failover_max < 0:
            raise ValueError(f"failover_max {self.failover_max} < 0")
        if self.replica_quarantine_after < 1:
            raise ValueError("replica_quarantine_after must be >= 1")
        if self.max_homes_per_scene < 1:
            raise ValueError("max_homes_per_scene must be >= 1")
        if not 0.0 < self.replicate_share <= 1.0:
            raise ValueError(
                f"replicate_share {self.replicate_share} outside (0, 1]"
            )
        if self.replicate_min_requests < 1 or self.arrivals_window < 1:
            raise ValueError("replicate_min_requests / arrivals_window "
                             "must be >= 1")
        if self.rebalance_every_s <= 0:
            raise ValueError("rebalance_every_s must be > 0")


class FleetRequest:
    """One fleet-level request.  Duck-compatible with the dispatcher's
    ``_Request`` where the open-loop harness reads it (``event``,
    ``outcome``, ``error``, ``deadline``, ``t_submit``, ``t_done``), so
    ``serve.loadgen.run_open_loop`` drives a :class:`FleetRouter`
    unchanged.  The underlying per-replica request (``ureq``) changes
    across failovers; the fleet outcome is recorded exactly once."""

    __slots__ = ("frame", "scene", "route_k", "n_hyps", "deadline",
                 "t_submit", "event", "result", "error", "outcome",
                 "t_done", "done", "replica", "ureq", "attempts",
                 "failover_from", "t_faulted", "owner", "_key", "trace",
                 "_last_span")

    def __init__(self, frame, scene, route_k, deadline, t_submit, owner,
                 n_hyps=None):
        self.frame = frame
        self.scene = scene
        self.route_k = route_k
        self.n_hyps = n_hyps       # per-dispatch budget override (ISSUE 20)
        self.deadline = deadline   # absolute clock() time, or None
        self.t_submit = t_submit
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.outcome = None        # one of OUTCOMES, exactly once
        self.t_done = None
        self.done = False
        self.replica = None        # current replica name
        self.ureq = None           # current underlying dispatcher request
        self.attempts = 0          # failover re-dispatches so far
        self.failover_from = []    # replicas that faulted this request
        self.t_faulted = None      # first replica-fault instant
        self.owner = owner
        self._key = None           # router _pending key (set at submit)
        self.trace = None          # sampled obs.Trace, or None
        self._last_span = None     # last dispatch child span (failover
        #                            siblings link through it: retry_of)

    def get(self, timeout: float | None = None):
        """Wait up to ``timeout`` seconds; raises the request's typed
        error, or :class:`~esac_tpu.serve.slo.DeadlineExceededError` on
        timeout — the timeout ABANDONS the request (fleet outcome
        expired, any late result discarded), mirroring the dispatcher's
        ``_Request.get`` contract."""
        if not self.event.wait(timeout):
            err = DeadlineExceededError(
                f"no fleet result within {timeout}s — request abandoned"
            )
            self.owner._abandon(self, err)
            if self.error is not None:
                raise self.error
            return self.result
        if self.error is not None:
            raise self.error
        return self.result


class FleetRouter:
    """Scene-affinity scheduler over N dispatcher replicas (module
    docstring has the full story).  ``replicas`` is a list of
    :class:`Replica`; give each dispatcher an
    :class:`~esac_tpu.serve.slo.SLOPolicy` — the router's spill and
    failover semantics need typed sheds and the watchdog, not the
    legacy block-for-space contract.  ``start=False`` skips the
    completion thread (attach a lock witness, then :meth:`start`)."""

    def __init__(
        self,
        replicas,
        policy: FleetPolicy = FleetPolicy(),
        clock=time.perf_counter,
        obs: MetricsRegistry | None = None,
        start: bool = True,
    ):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {sorted(names)}")
        self._replicas = {r.name: r for r in replicas}
        self._policy = policy
        self._clock = clock
        self._lock = threading.Lock()
        # Fleet books (all under self._lock): pending fleet requests by
        # submission sequence, per-replica quarantine + fault streaks,
        # the scene -> home-replicas affinity table, per-replica
        # in-flight load, the recent-arrival window the rebalancer
        # judges, and the outcome accounting.
        self._seq = 0
        self._pending: dict[int, FleetRequest] = {}
        self._quarantined: dict[str, str] = {}
        self._fail_streak: collections.Counter = collections.Counter()
        self._scene_home: dict = {}
        # Incremental mirror of "homes held per replica" (the tie-break
        # _route_locked orders by): maintained by _claim_home_locked so
        # the per-request routing pass stops rebuilding a Counter over
        # the whole affinity table (the host-path overhaul).
        self._homes_held: collections.Counter = collections.Counter()
        self._load: collections.Counter = collections.Counter()
        self._recent_scenes: collections.deque = collections.deque(
            maxlen=policy.arrivals_window
        )
        self._route_counts: collections.Counter = collections.Counter()
        self.offered = 0
        self.outcome_counts: collections.Counter = collections.Counter()
        self._closed = False
        # Observability (DESIGN.md §14): the dispatcher's convention —
        # instruments created once, counted in the same critical
        # sections as the legacy attributes, one truth.
        self.obs = obs if obs is not None else MetricsRegistry()
        self._m_offered = self.obs.counter(
            "fleet_offered_total", "requests ever offered to the fleet",
        )
        self._m_outcomes = self.obs.counter(
            "fleet_outcomes_total",
            "terminal fleet outcome classes; with pending they sum to "
            "offered",
        )
        self._m_routes = self.obs.counter(
            "fleet_routes_total",
            "route decisions per (replica, kind: affinity|spill|cold|"
            "dense|failover)",
        )
        self._m_failovers = self.obs.counter(
            "fleet_failovers_total",
            "re-dispatches after a replica fault, by (from, to) replica",
        )
        self._m_events = self.obs.counter(
            "fleet_events_total",
            "breaker/rebalance events by kind (replica_quarantined, "
            "replica_released, scene_replicated)",
        )
        self._m_latency = self.obs.histogram(
            "fleet_request_latency_seconds",
            "fleet end-to-end latency of served+degraded requests",
            window=100_000,
        )
        self._m_failover_s = self.obs.histogram(
            "fleet_failover_seconds",
            "replica-fault -> served latency of failed-over requests",
            window=100_000,
        )
        self.obs.register_collector("fleet", self.fleet_view)
        # Sampled causal traces (ISSUE 15): the ring-bounded store is
        # created only when sampling is on, so an untraced fleet's
        # snapshot schema is unchanged.
        self._trace_store = (self.obs.trace_store()
                             if policy.trace_sample else None)
        # Retrieval front-end (ISSUE 18): attach_retrieval installs it;
        # image-only requests (infer_image) carry no scene id and are
        # book-kept by the front, not the fleet books — each candidate
        # dispatch below them is an ordinary fleet request.
        self._retrieval = None
        self._image_seq = 0
        self._thread = None
        if start:
            self.start()

    # ---------------- lifecycle ----------------

    def start(self):
        """Start the completion/rebalance thread (idempotent)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="esac-fleet-router",
            )
            self._thread.start()

    def close(self, close_replicas: bool = True):
        """Stop routing, drain the books, optionally close the replica
        dispatchers.  Every pending fleet request resolves typed —
        nobody strands on a closed fleet (the dispatcher contract,
        lifted a level)."""
        with self._lock:
            self._closed = True
        if close_replicas:
            for rep in self._replicas.values():
                rep.dispatcher.close()
        thread = self._thread
        own = thread is not None and thread is threading.current_thread()
        if thread is not None and not own:
            # BOUNDED grace join: let already-resolved underlying
            # requests settle to their real outcomes.  Unbounded would
            # hang when a replica never resolves (close_replicas=False
            # over a watchdog-less dispatcher — review finding): the
            # loop only exits once pending drains, and it is the typed
            # cleanup BELOW that drains the stragglers.
            thread.join(max(0.05, 10 * self._policy.poll_ms / 1e3))
        # Whatever the loop could not settle (no thread ever started, a
        # replica that never resolved its requests) fails typed here.
        with self._lock:
            leftovers = [r for r in self._pending.values() if not r.done]
            for r in leftovers:
                if r.replica is not None and r.ureq is not None:
                    self._load[r.replica] -= 1
                    r.ureq = None
                self._finish_locked(
                    r,
                    error=DispatcherClosedError(
                        "fleet router closed with the request still pending"
                    ),
                    outcome="failed",
                )
        if thread is not None and not own:
            # Pending is drained and submit() rejects closed, so the
            # poll loop exits on its next tick; the join is bounded
            # anyway (R18) — if the poll body itself is wedged on the
            # relay, the daemon thread is abandoned, never waited on
            # forever and never killed.
            thread.join(_CLOSE_JOIN_S)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---------------- request path ----------------

    def submit(self, frame, scene=None, route_k=None,
               deadline_ms: float | None = None,
               n_hyps: int | None = None) -> FleetRequest:
        """Route one request into the fleet; returns a
        :class:`FleetRequest` whose event fires at its (single) fleet
        outcome.  Raises typed at admission: a
        :class:`~esac_tpu.serve.slo.ShedError` subclass when every
        healthy replica rejected it (or none is healthy —
        :class:`ReplicaQuarantinedError`), both counted shed;
        :class:`~esac_tpu.serve.slo.DeadlineExceededError` when the
        deadline died during admission (counted expired).  ``n_hyps``
        rides the per-dispatch hypothesis-budget override through to the
        chosen replica's dispatcher (the session lane, ISSUE 20); scene
        affinity is unchanged, so a session's shrunken-budget frames
        land on the replica already holding its scene warm."""
        t_submit = self._clock()
        deadline = (t_submit + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = FleetRequest(frame, scene, route_k, deadline, t_submit, self,
                           n_hyps=n_hyps)
        route = None
        route_err = None
        with self._lock:
            if self._closed:
                raise DispatcherClosedError("fleet router is closed")
            # Offered and pending move together: the accounting
            # invariant (offered == outcomes + pending) holds at every
            # instant, including while this request is being routed.
            self.offered += 1
            self._m_offered.inc()
            self._recent_scenes.append(scene)
            self._seq += 1
            req._key = self._seq
            self._pending[req._key] = req
            n = self._policy.trace_sample
            if n and self._seq % n == 0:
                # Mint the fleet trace (1-in-N deterministic sampling).
                # The root chain lives in THIS router's clock: its
                # consecutive stamps partition [t_submit, t_done] into
                # routing / replica / failover_routing segments whose
                # fsum equals the end-to-end span EXACTLY — the §14
                # telescoping invariant at fleet scope (bench-pinned).
                req.trace = Trace(t_submit, scene=scene, sampled_1_in=n)
            # First route decision in the SAME critical section as the
            # books (the host-path overhaul: one lock pass per request
            # on the happy path, not one for books plus one to route).
            # A dead-on-arrival deadline skips it — _dispatch_to_replica
            # expires the request before any placement side effect (a
            # cold route claims a home), exactly as the two-pass path
            # did.  A routing shed is classified here, not re-raised
            # through the handlers below, because the lock must be
            # released between the decision and the finish.
            if deadline is None or deadline > t_submit:
                try:
                    route = self._route_locked(scene, set(), None)
                except ShedError as e:  # incl. ReplicaQuarantinedError
                    route_err = e
                    self._finish_locked(req, error=e, outcome="shed")
        if route_err is not None:
            raise route_err
        try:
            self._dispatch_to_replica(req, exclude=set(), route=route)
        except DeadlineExceededError as e:
            with self._lock:
                self._finish_locked(req, error=e, outcome="expired")
            raise
        except ShedError as e:  # incl. ReplicaQuarantinedError
            with self._lock:
                self._finish_locked(req, error=e, outcome="shed")
            raise
        except BaseException as e:  # noqa: BLE001 — accounting backstop
            # An unexpected routing fault must not leak a forever-
            # pending request (the invariant holds at every instant,
            # bugs included); classify failed, re-raise unchanged.
            with self._lock:
                self._finish_locked(req, error=e, outcome="failed")
            raise
        return req

    def infer_one(self, frame, scene=None, route_k=None,
                  timeout: float | None = None,
                  deadline_ms: float | None = None,
                  n_hyps: int | None = None):
        """Blocking single-request inference through the fleet.  The
        bound is end-to-end: on timeout/deadline the request is
        abandoned (fleet outcome expired, late results discarded) and a
        typed error raised — no caller blocks past its bound even when
        a replica is wedged."""
        if deadline_ms is None and timeout is not None:
            deadline_ms = timeout * 1e3
        req = self.submit(frame, scene, route_k, deadline_ms,
                          n_hyps=n_hyps)
        limit = timeout
        if req.deadline is not None:
            # Remaining deadline + settle grace: the terminal event
            # fires from the completion loop one poll after the
            # underlying request resolves, so the grace covers loop
            # scheduling, never correctness (abandonment below is the
            # hard bound).
            remaining = max(0.0, req.deadline - self._clock())
            grace = remaining + 4 * self._policy.poll_ms / 1e3 + 0.25
            limit = grace if limit is None else min(limit, grace)
        return req.get(limit)

    # ---------------- image-only request path (ISSUE 18) ----------------

    def attach_retrieval(self, front) -> None:
        """Install the retrieval front-end: wires the default per-scene
        breaker gate (a candidate is healthy when ANY replica registry
        still has prefetchable targets for it — i.e. it is not
        breaker-tripped everywhere), feeds every replica prefetcher from
        the posterior (the ``observe_candidates`` seam), and registers
        the ``retrieval`` obs collector.  One front per router."""
        with self._lock:
            if self._retrieval is not None:
                raise ConfigError(
                    "a retrieval front is already attached to this router"
                )
            self._retrieval = front
        if not front.has_health():
            front.attach_health(self._candidate_healthy)
        for rep in self._replicas.values():
            pf = getattr(rep.registry, "_prefetcher", None)
            if pf is not None and hasattr(pf, "observe_candidates"):
                front.add_prefetch_sink(pf.observe_candidates)
        self.obs.register_collector("retrieval", front.stats)

    def _candidate_healthy(self, scene) -> bool:
        """Default retrieval breaker gate: ``prefetch_targets`` is the
        registries' health-aware resolution (active + canary minus
        tripped), so "no targets anywhere" == "tripped/unknown
        everywhere" — exactly the candidates that must be SKIPPED, not
        dispatched.  Runs with NO router lock held (registry locks
        inside)."""
        regs = [rep.registry for rep in self._replicas.values()
                if rep.registry is not None]
        if not regs:
            return True  # bare-dispatcher fleet: no breaker state exists
        return any(reg.prefetch_targets(scene) for reg in regs)

    def infer_image(self, frame, route_k=None,
                    timeout: float | None = None,
                    deadline_ms: float | None = None):
        """Blocking IMAGE-ONLY inference: no scene id — the retrieval
        front decides the top-K candidate scenes (each breaker-gated),
        every candidate is dispatched through the ordinary fleet path,
        and the winner is chosen by soft-inlier score.  Typed faults:
        :class:`~esac_tpu.retrieval.errors.RetrievalMissError` (shed —
        low confidence / empty index / all candidates tripped) and
        :class:`~esac_tpu.retrieval.errors.\
RetrievalCandidatesExhaustedError` (failed — every candidate dispatch
        died).  The image request books EXACTLY one outcome in the
        front's accounting; the per-candidate fleet requests carry
        their own books underneath.  A sampled trace gets a
        ``retrieval`` root segment + per-candidate dispatch child spans
        (the §14 telescoping invariant at image scope)."""
        with self._lock:
            front = self._retrieval
        if front is None:
            raise ConfigError(
                "no retrieval front attached — attach_retrieval() first"
            )
        if deadline_ms is None and timeout is not None:
            deadline_ms = timeout * 1e3
        t0 = self._clock()
        deadline = (t0 + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        trace = None
        with self._lock:
            if self._closed:
                raise DispatcherClosedError("fleet router is closed")
            self._image_seq += 1
            n = self._policy.trace_sample
            if n and self._image_seq % n == 0:
                trace = Trace(t0, scene=None, sampled_1_in=n)
        tok = front.offer()
        try:
            try:
                decision = front.decide(frame)
            except RetrievalMissError as e:
                # Typed retrieval shed: no candidate was dispatchable.
                tok.book("shed", e)
                raise
            t_dec = self._clock()
            if trace is not None:
                # Root boundary: everything up to here is the retrieval
                # decision (index snapshot + jitted posterior + gates).
                trace.stamp("retrieval", t_dec)
                trace.add_event(
                    "retrieval_decision", t_dec,
                    candidates=list(decision.candidates),
                    top1=decision.top1, top1_p=decision.top1_p,
                    entropy=decision.entropy,
                    tripped_skipped=decision.tripped_skipped,
                )
            # Posterior-driven prefetch: runner-up scenes stage ahead
            # of their fault, whatever candidate wins below.
            front.feed_prefetch(decision)
            # Fan out: submit every candidate first (admission only),
            # then collect — candidates overlap in flight instead of
            # paying each other's latency.
            submitted = []
            last_err = None
            for cand in decision.candidates:
                now = self._clock()
                if deadline is not None and now >= deadline:
                    break
                remaining_ms = (None if deadline is None
                                else (deadline - now) * 1e3)
                try:
                    submitted.append((cand, self.submit(
                        frame, scene=cand, route_k=route_k,
                        deadline_ms=remaining_ms,
                    )))
                except ServeError as e:
                    # Per-candidate admission fault (shed/quarantine/
                    # dead deadline): counted in the fleet books by
                    # submit itself; the image request survives on the
                    # remaining candidates.
                    last_err = e
            results = []
            for cand, req in submitted:
                limit = None
                if req.deadline is not None:
                    remaining = max(0.0, req.deadline - self._clock())
                    limit = (remaining
                             + 4 * self._policy.poll_ms / 1e3 + 0.25)
                try:
                    results.append((cand, req.get(limit)))
                except ServeError as e:
                    last_err = e
                if trace is not None:
                    trace.add_span(
                        f"candidate:{cand}", "dispatch",
                        req.t_submit, req.t_done or self._clock(),
                        scene=cand, outcome=req.outcome,
                    )
            if trace is not None:
                trace.stamp("candidates", self._clock())
            if results:
                winner_scene, wres = front.select_winner(results)
                # The winning replica answer is returned UNTOUCHED
                # under its own keys (the confident-query bit-identity
                # contract); retrieval evidence rides alongside.
                out = dict(wres)
                out["retrieval"] = {
                    "scene": winner_scene,
                    "candidates": list(decision.candidates),
                    "top1": decision.top1,
                    "top1_p": decision.top1_p,
                    "entropy": decision.entropy,
                }
                front.note_result(winner_scene, decision)
                tok.book("served")
                self._finish_image_trace(trace, "served")
                return out
            if deadline is not None and self._clock() >= deadline:
                expired_err = DeadlineExceededError(
                    "image request deadline died across "
                    f"{len(decision.candidates)} candidate dispatch(es)"
                )
                tok.book("expired", expired_err)
                raise expired_err
            exhausted_err = RetrievalCandidatesExhaustedError(
                f"all {len(decision.candidates)} candidate dispatch(es) "
                f"failed (last: {last_err!r})"
            )
            tok.book("failed", exhausted_err)
            raise exhausted_err
        except BaseException as e:  # noqa: BLE001 — accounting backstop
            # Every error path lands exactly one outcome (the booking
            # token is first-wins, so typed paths above keep theirs);
            # the trace finishes with whatever was booked.
            tok.book("failed", e)
            self._finish_image_trace(trace, tok.outcome or "failed")
            raise

    def _finish_image_trace(self, trace, outcome: str) -> None:
        """Terminal root stamp + store publication for one image-request
        trace (idempotent through Trace.finish: racing error paths store
        it exactly once; the append is a leaf-lock deque op, R13-clean)."""
        if trace is None:
            return
        with self._lock:
            store = self._trace_store
        if trace.finish(outcome, self._clock()) and store is not None:
            store.add(trace)

    def _dispatch_to_replica(self, req: FleetRequest, exclude: set,
                             route=None) -> None:
        """Admit ``req`` to a replica chosen by the affinity table
        (NO router lock held across the dispatcher submit — R13).
        Spills walk the healthy set; a replica whose dispatcher is
        closed/dead is noted as a replica fault and skipped.  Raises
        the last typed rejection when nobody could take it.  ``route``
        is an optional pre-made first (name, kind) decision — submit
        routes inside its books critical section — consumed on the
        first attempt only; every retry re-decides under the lock."""
        exclude = set(exclude)
        last_shed = None
        while True:
            now = self._clock()
            if req.deadline is not None and now >= req.deadline:
                raise DeadlineExceededError(
                    "deadline expired while routing "
                    f"(scene {req.scene!r}, "
                    f"{len(exclude)} replica(s) already tried)"
                )
            if route is not None:
                name, kind = route
                route = None
            else:
                with self._lock:
                    name, kind = self._route_locked(req.scene, exclude,
                                                    last_shed)
            rep = self._replicas[name]
            remaining_ms = (None if req.deadline is None
                            else (req.deadline - now) * 1e3)
            try:
                kw = {}
                if req.trace is not None:
                    # Trace context rides into the replica: its request
                    # gets a child chain + the registry fault path sees
                    # the trace, whatever the dispatcher's own flag.
                    kw["trace_ctx"] = req.trace
                ureq = rep.dispatcher.submit(
                    req.frame, scene=req.scene, route_k=req.route_k,
                    deadline_ms=remaining_ms, n_hyps=req.n_hyps, **kw,
                )
            except (DispatcherClosedError, WorkerDiedError) as e:
                # The replica itself is unroutable: breaker bookkeeping,
                # then try the next one.
                self._note_replica_fault(name, e)
                exclude.add(name)
                last_shed = ReplicaQuarantinedError(
                    f"replica {name!r} is unservable ({e!r})"
                )
                continue
            except ShedError as e:  # overload / lane quarantine: spill
                with self._lock:
                    self._m_routes.inc(replica=name, kind="rejected")
                exclude.add(name)
                last_shed = e
                continue
            with self._lock:
                if req.done:
                    # A caller-side abandon resolved the request while
                    # this (failover) routing was in flight: do not
                    # register the fresh dispatch — hand it back below
                    # so its late result is discarded and the load
                    # books never skew.
                    stale_err = req.error
                else:
                    if req.failover_from:
                        kind = "failover"
                        self._m_failovers.inc(**{
                            "from": req.failover_from[-1], "to": name,
                        })
                    req.replica = name
                    req.ureq = ureq
                    self._load[name] += 1
                    self._route_counts[kind] += 1
                    self._m_routes.inc(replica=name, kind=kind)
                    if req.trace is not None:
                        # Root boundary: time up to here is router
                        # overhead (routing / failover_routing); the
                        # routing DECISION rides as an event span.
                        t = self._clock()
                        req.trace.stamp(
                            "failover_routing" if req.failover_from
                            else "routing", t,
                        )
                        req.trace.add_event("route_decision", t,
                                            replica=name, route_kind=kind)
                    return
            rep.dispatcher._abandon(ureq, stale_err or
                                    DeadlineExceededError(
                                        "request abandoned during routing"
                                    ))
            return

    def _route_locked(self, scene, exclude: set, last_shed):
        """Pick (replica name, route kind) for ``scene`` (lock held).
        Affinity first (least-loaded healthy home), else least-loaded
        healthy replica — ``cold`` claims a home slot for the scene,
        ``spill`` (healthy homes exist but all rejected/excluded) does
        not.  Raises typed when no candidate remains: the last shed if
        replicas rejected, :class:`ReplicaQuarantinedError` otherwise."""
        healthy = [n for n in self._replicas if n not in self._quarantined]
        if not healthy:
            raise ReplicaQuarantinedError(
                f"all {len(self._replicas)} replicas are quarantined "
                f"({sorted(self._quarantined)}); release_replica() after "
                "recovery"
            )
        avail = [n for n in healthy if n not in exclude]
        if not avail:
            if last_shed is not None:
                raise last_shed
            raise ReplicaQuarantinedError(
                f"no replica left for scene {scene!r}: every healthy "
                "replica already failed this request"
            )
        # Least-loaded ordering with a placement tie-break: equal
        # in-flight load falls back to fewest homes held, so cold
        # scenes SPREAD across an idle fleet instead of all claiming
        # the first replica — the scene-sharded placement the affinity
        # table then preserves.  (_homes_held is the incrementally
        # maintained count — this used to be a full rebuild over the
        # affinity table on EVERY route decision.)
        homes_held = self._homes_held
        order = {n: (self._load[n], homes_held[n], n) for n in avail}
        if scene is None:
            return min(avail, key=order.__getitem__), "dense"
        homes = self._scene_home.get(scene, [])
        homes_avail = [n for n in homes if n in avail]
        if homes_avail:
            name = min(homes_avail, key=order.__getitem__)
            return name, "affinity"
        name = min(avail, key=order.__getitem__)
        homes_healthy = [n for n in homes if n in healthy]
        if homes_healthy:
            # Healthy homes exist but shed/failed this request: serve
            # elsewhere without moving the scene's home (one overloaded
            # burst must not thrash the affinity table).
            return name, "spill"
        self._claim_home_locked(scene, name)
        return name, "cold"

    def _claim_home_locked(self, scene, name) -> None:
        """Record ``name`` as a home for ``scene`` (lock held), pruning
        quarantined homes first and capping at ``max_homes_per_scene``
        (oldest out)."""
        homes = self._scene_home.setdefault(scene, [])
        if name in homes:
            return
        homes.append(name)
        self._homes_held[name] += 1
        while len(homes) > self._policy.max_homes_per_scene:
            dead = next((h for h in homes if h in self._quarantined),
                        homes[0])
            homes.remove(dead)
            self._homes_held[dead] -= 1

    def _abandon(self, req: FleetRequest, err) -> None:
        """Caller-side timeout (FleetRequest.get): record the fleet
        outcome expired and abandon the underlying request so a late
        result is discarded — the books agree with the error the caller
        saw.  No-op if already resolved."""
        with self._lock:
            if req.done:
                return
            ureq = req.ureq
            if req.replica is not None and ureq is not None:
                self._load[req.replica] -= 1
                req.ureq = None
            self._finish_locked(req, error=err, outcome="expired")
        if ureq is not None and ureq.owner is not None:
            ureq.owner._abandon(ureq, err)

    def _finish_locked(self, req: FleetRequest, result=None, error=None,
                       outcome: str = "served",
                       publish: bool = True) -> None:
        """Resolve one fleet request exactly once (lock held): outcome
        books + latency/failover histograms + event, one choke point.
        ``publish=False`` defers the obs counter/histogram publishes to
        the caller — the batched completion pass — which MUST publish
        the aggregates for every such finish before releasing the lock;
        the legacy books, pending pop, trace finish and event always
        happen here."""
        if req.done:
            return
        req.done = True
        req.result = result
        req.error = error
        req.outcome = outcome
        req.t_done = self._clock()
        self.outcome_counts[outcome] += 1
        if publish:
            self._m_outcomes.inc(outcome=outcome)
        if req._key is not None:
            self._pending.pop(req._key, None)
        if publish and outcome in ("served", "degraded"):
            self._m_latency.observe(req.t_done - req.t_submit)
            if req.t_faulted is not None:
                self._m_failover_s.observe(req.t_done - req.t_faulted)
        if req.trace is not None:
            # Terminal root stamp in the SAME clock and with the SAME
            # instant as the fleet accounting, so the trace's total is
            # bit-equal to the measured end-to-end latency; publication
            # into the store is a leaf-lock deque append (R13-clean).
            req.trace.finish(outcome, req.t_done)
            if self._trace_store is not None:
                self._trace_store.add(req.trace)
        req.event.set()

    # ---------------- completion loop ----------------

    def _loop(self):
        poll = self._policy.poll_ms / 1e3
        next_rebalance = self._clock() + self._policy.rebalance_every_s
        while True:
            if self._settle():
                return
            now = self._clock()
            if now >= next_rebalance:
                self._rebalance()
                next_rebalance = now + self._policy.rebalance_every_s
            # Drive the time-series + rule layers between polls (ISSUE
            # 15): both are piggyback hooks — one clock compare when not
            # due — and both run with NO router lock held (timeline
            # aggregation takes instrument locks one at a time, rule
            # evaluation reads the timeline's locked window snapshot).
            tl = self.obs.timeline()
            if tl is not None:
                tl.maybe_tick()
                eng = self.obs.health_rules()
                if eng is not None:
                    eng.maybe_evaluate()
            time.sleep(poll)

    def _settle(self) -> bool:
        """One BATCHED completion pass: scan for resolved underlying
        requests and consume every one of them — fulfill, classify, or
        queue for failover — in a SINGLE critical section (the host-path
        overhaul: one lock acquisition per poll tick, not one for the
        scan plus one per ready request), with the obs publishes
        aggregated per outcome class at the end of the section.  Each
        ureq is detached under the lock, so a racing abandon can never
        settle it twice.  Fault follow-up — breaker bookkeeping and the
        failover re-dispatch, both potentially blocking — runs OUTSIDE
        the lock (R13), exactly as the per-request path did.  Returns
        True when the router is closed and fully drained (the poll
        loop's exit test, folded into the same acquisition)."""
        n_by_outcome: collections.Counter = collections.Counter()
        lats: list[float] = []
        fo_lats: list[float] = []
        faults = []
        with self._lock:
            if self._closed and not self._pending:
                return True
            ready = [r for r in self._pending.values()
                     if not r.done and r.ureq is not None
                     and r.ureq.event.is_set()]
            for req in ready:
                ureq = req.ureq
                req.ureq = None
                self._load[req.replica] -= 1
                if req.trace is not None:
                    # Child dispatch span: the underlying request's chain
                    # (ITS clock domain — it telescopes on its own) under
                    # the fleet root; failover siblings link via retry_of.
                    sp = req.trace.add_span(
                        f"replica:{req.replica}", "dispatch",
                        ureq.t_submit, ureq.t_done,
                        stages=(ureq.spans.segments()
                                if ureq.spans is not None else None),
                        replica=req.replica, outcome=ureq.outcome,
                        retry_of=(req._last_span.span_id
                                  if req._last_span is not None else None),
                    )
                    req._last_span = sp
                    # Root boundary (router clock): the replica segment
                    # ends when the completion loop CONSUMED it — poll
                    # latency is router overhead charged to the replica
                    # segment honestly, not hidden.
                    req.trace.stamp("replica", self._clock())
                err = ureq.error
                if err is None:
                    self._fail_streak.pop(req.replica, None)
                    self._finish_locked(req, result=ureq.result,
                                        outcome=ureq.outcome,
                                        publish=False)
                    n_by_outcome[req.outcome] += 1
                    lats.append(req.t_done - req.t_submit)
                    if req.t_faulted is not None:
                        fo_lats.append(req.t_done - req.t_faulted)
                elif not isinstance(err, _REPLICA_FAULTS):
                    if isinstance(err, DeadlineExceededError):
                        self._finish_locked(req, error=err,
                                            outcome="expired",
                                            publish=False)
                    else:
                        # Scene-/request-level typed fault: every replica
                        # would re-pay it — fail fast, don't fail over.
                        self._finish_locked(req, error=err,
                                            outcome="failed",
                                            publish=False)
                    n_by_outcome[req.outcome] += 1
                else:
                    faults.append((req, req.replica, err))
            # Aggregated obs publish — still inside the critical
            # section, so the counters and the done-flags/pending books
            # move together (one truth), but with ONE instrument-lock
            # acquisition per outcome class / histogram instead of one
            # per request.
            for o, n in n_by_outcome.items():
                self._m_outcomes.inc(n, outcome=o)
            if lats:
                self._m_latency.observe_many(lats)
            if fo_lats:
                self._m_failover_s.observe_many(fo_lats)
        # Failover path, outside the lock: replica-INDICTING faults feed
        # the breaker first (it may quarantine and abandon the replica's
        # other in-flight work); lane/replica-quarantine drains skip it
        # (see _REPLICA_INDICTING) and only re-route.
        for req, faulted, err in faults:
            if isinstance(err, _REPLICA_INDICTING):
                self._note_replica_fault(faulted, err)
            self._failover(req, faulted, err)
        return False

    def _failover(self, req: FleetRequest, from_name: str, err) -> None:
        """Re-dispatch ``req`` to a surviving replica inside its
        remaining deadline (no lock held).  Exhausted budget or no
        survivor -> the request fails typed with the replica fault; a
        dead deadline -> expired."""
        now = self._clock()
        if req.t_faulted is None:
            req.t_faulted = now
        req.attempts += 1
        req.failover_from.append(from_name)
        if req.trace is not None:
            req.trace.add_event("replica_fault", now, replica=from_name,
                                error=type(err).__name__,
                                attempt=req.attempts)
        if req.deadline is not None and now >= req.deadline:
            with self._lock:
                self._finish_locked(req, error=DeadlineExceededError(
                    f"replica {from_name!r} fault ({err!r}) left no "
                    "deadline for failover"
                ), outcome="expired")
            return
        if req.attempts > self._policy.failover_max:
            with self._lock:
                self._finish_locked(req, error=err, outcome="failed")
            return
        try:
            self._dispatch_to_replica(req, exclude=set(req.failover_from))
        except DeadlineExceededError as e:
            with self._lock:
                self._finish_locked(req, error=e, outcome="expired")
        except ShedError:
            # No survivor could admit it: the request was already
            # admitted to the fleet once, so this is a failure of the
            # original fault's making, not a shed.
            with self._lock:
                self._finish_locked(req, error=err, outcome="failed")

    # ---------------- replica breaker ----------------

    def _note_replica_fault(self, name: str, err) -> None:
        """Breaker bookkeeping for one observed replica fault (no lock
        held on entry).  A trip abandons every in-flight underlying
        request on the replica OUTSIDE the lock — their events fire
        with :class:`ReplicaQuarantinedError` and the completion loop
        fails each over exactly once (drained, never double-counted)."""
        wedge = isinstance(err, _REPLICA_INDICTING)
        victims = []
        reason = None
        with self._lock:
            self._fail_streak[name] += 1
            if name not in self._quarantined and (
                    wedge or self._fail_streak[name]
                    >= self._policy.replica_quarantine_after):
                what = ("wedge-class fault" if wedge else
                        f"{self._fail_streak[name]} consecutive "
                        "replica faults")
                reason = f"{what} (last: {err!r})"
                self._quarantined[name] = reason
                self._m_events.inc(event="replica_quarantined")
                # Snapshot the (request, underlying) PAIRS under the
                # lock: a concurrent settle may swap req.ureq to a
                # fresh dispatch on a HEALTHY replica, and abandoning
                # that would kill good work — the snapshotted ureq is
                # pinned to this replica (replica and ureq only change
                # together, under the lock), and abandoning one that
                # already resolved is a no-op.
                victims = [(r, r.ureq) for r in self._pending.values()
                           if r.replica == name and not r.done
                           and r.ureq is not None]
        if reason is None:
            return
        disp = self._replicas[name].dispatcher
        t_quar = self._clock()
        for r, ureq in victims:
            if r.trace is not None:
                r.trace.add_event("replica_quarantined", t_quar,
                                  replica=name)
            disp._abandon(ureq, ReplicaQuarantinedError(
                f"replica {name!r} quarantined ({reason}); request "
                "failed over"
            ))

    def release_replica(self, name: str) -> bool:
        """Operator hook mirroring ``release_lane``/``release_scene``:
        clear a replica's quarantine + fault streak after the fault
        (relay recovery, a restarted worker) is fixed.  Idempotent;
        True when a quarantine was actually cleared."""
        if name not in self._replicas:
            raise ConfigError(f"unknown replica {name!r} "
                              f"(fleet: {sorted(self._replicas)})")
        with self._lock:
            was = self._quarantined.pop(name, None)
            self._fail_streak.pop(name, None)
            if was is not None:
                self._m_events.inc(event="replica_released")
        return was is not None

    def quarantined_replicas(self) -> dict:
        """Locked snapshot: replica name -> quarantine reason."""
        with self._lock:
            return dict(self._quarantined)

    # ---------------- rebalancer ----------------

    def _rebalance(self) -> None:
        """Hot-scene replication (completion thread, between polls):
        judge the recent arrival window under the lock, warm the new
        home OUTSIDE it, then commit the affinity-table change."""
        with self._lock:
            window = [s for s in self._recent_scenes if s is not None]
            if len(window) < self._policy.replicate_min_requests:
                return
            counts = collections.Counter(window)
            quarantined = set(self._quarantined)
            plans = []
            for scene, c in counts.items():
                # Share of the SCENE-CARRYING window: mixed-in dense
                # (scene=None) traffic must not dilute every scene's
                # share below the threshold and suppress replication
                # (review finding) — hot is relative to scene-routed
                # demand, which is what the homes serve.
                share = c / len(window)
                if share < self._policy.replicate_share:
                    continue
                homes = [h for h in self._scene_home.get(scene, [])
                         if h not in quarantined]
                if not homes or len(homes) >= self._policy.max_homes_per_scene:
                    continue
                candidates = [n for n in self._replicas
                              if n not in quarantined and n not in homes]
                if not candidates:
                    continue
                load = {n: self._load[n] for n in candidates}
                target = min(candidates, key=load.__getitem__)
                plans.append((scene, homes[0], target))
        for scene, first_home, target in plans:
            if not self._replication_due(scene, first_home):
                continue
            rep = self._replicas[target]
            if rep.registry is not None:
                try:
                    rep.registry.warm(scene)
                except Exception:  # noqa: BLE001 — a failed warm skips,
                    # the demand path will retry typed; counted, not hidden
                    self._m_events.inc(event="warm_failed")
                    continue
            with self._lock:
                if target not in self._quarantined:
                    self._claim_home_locked(scene, target)
                    self._m_events.inc(event="scene_replicated")

    def _replication_due(self, scene, first_home) -> bool:
        """The optional obs gate (no lock held): when the policy pins a
        p99 threshold, the scene's latency on its first home (the obs
        lane histogram both the operator and this decision read) must
        be measurable and at/above it."""
        if self._policy.replicate_p99_ms is None:
            return True
        hist = self._replicas[first_home].dispatcher.obs.get(
            "serve_lane_latency_seconds"
        )
        if hist is None:
            return False
        p99 = hist.quantile(0.99, scene=scene)
        return p99 == p99 and p99 * 1e3 >= self._policy.replicate_p99_ms

    # ---------------- views ----------------

    def fleet_totals(self) -> dict:
        """Locked snapshot of the fleet accounting.  The invariant —
        served + shed + expired + degraded + failed + pending ==
        offered — holds at every instant (tests/test_fleet.py)."""
        with self._lock:
            return self._totals_locked()

    def _totals_locked(self) -> dict:
        out = {"offered": int(self._m_offered.total())}
        for o in OUTCOMES:
            out[o] = int(self._m_outcomes.get(outcome=o))
        out["pending"] = sum(1 for r in self._pending.values()
                             if not r.done)
        return out

    def affinity_stats(self) -> dict:
        """Locked snapshot of the routing mix.  ``hit_rate`` is
        affinity / (affinity + spill + cold) — scene-carrying routes
        only; dense and failover re-dispatches are reported but not
        part of the affinity denominator."""
        with self._lock:
            counts = {k: int(self._route_counts.get(k, 0))
                      for k in ("affinity", "spill", "cold", "dense",
                                "failover")}
        routed = counts["affinity"] + counts["spill"] + counts["cold"]
        counts["hit_rate"] = (counts["affinity"] / routed) if routed \
            else float("nan")
        return counts

    def scene_homes(self) -> dict:
        """Locked snapshot: scene -> home replica names (routing order)."""
        with self._lock:
            return {s: list(h) for s, h in self._scene_home.items()}

    def fleet_view(self) -> dict:
        """The ``fleet`` obs collector: one per-replica-labelled merge —
        each replica's serve accounting (its own ``slo_totals``),
        quarantine state, in-flight load and weight-cache stats — plus
        the affinity table and the fleet accounting.  Replica snapshots
        are taken OUTSIDE the router lock (each surface owns its own
        locked snapshot; nesting router -> dispatcher would be a new
        lock-graph edge for no benefit)."""
        with self._lock:
            quarantined = dict(self._quarantined)
            load = {n: int(self._load.get(n, 0)) for n in self._replicas}
            homes = {s: list(h) for s, h in self._scene_home.items()}
            totals = self._totals_locked()
            routes = {k: int(v) for k, v in self._route_counts.items()}
        replicas = {}
        for name, rep in self._replicas.items():
            block = {
                "slo": rep.dispatcher.slo_totals(),
                "quarantined": quarantined.get(name),
                "inflight": load.get(name, 0),
            }
            if rep.registry is not None:
                block["cache"] = rep.registry.cache.stats()
            replicas[name] = block
        return {
            "replicas": replicas,
            "scene_homes": homes,
            "route_counts": routes,
            "accounting": totals,
        }
