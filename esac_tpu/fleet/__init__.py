"""Scene-affinity replica fleet: a fault-tolerant scheduler tier above
the dispatchers (DESIGN.md §18).

A :class:`FleetRouter` routes requests over N in-process
:class:`~esac_tpu.serve.MicroBatchDispatcher` replicas — each with its
own :class:`~esac_tpu.registry.SceneRegistry` and weight cache — with
scene-affinity routing (the warm replica serves; spill to least-loaded
on overload), per-replica health breakers composing with the per-scene
ones (:class:`ReplicaQuarantinedError`, ``release_replica``), failover
of a faulted replica's requests within their deadlines, obs-driven
hot-scene replication, and fleet-level outcome accounting that sums
exactly to offered.  Pure host package: importing it never touches jax.
"""

from esac_tpu.fleet.router import (
    OUTCOMES,
    FleetPolicy,
    FleetRequest,
    FleetRouter,
    Replica,
    ReplicaQuarantinedError,
)

__all__ = [
    "OUTCOMES",
    "FleetPolicy",
    "FleetRequest",
    "FleetRouter",
    "Replica",
    "ReplicaQuarantinedError",
]
