"""Gating network: image -> distribution over experts.

Reference counterpart: the CNN classifier in the reference (SURVEY.md §2 #2)
trained with cross-entropy against the GT scene/cluster label (stage 2) and
with a score-function estimator end-to-end (stage 3).
"""

from __future__ import annotations

from collections.abc import Sequence

import flax.linen as nn
import jax.numpy as jnp


class GatingNet(nn.Module):
    """CNN classifier over M experts.

    RGB (..., H, W, 3) -> logits (..., M).  Strided convs + global average
    pool, bf16 compute / f32 params like the expert.
    """

    num_experts: int
    channels: Sequence[int] = (32, 64, 128, 256)
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.compute_dtype)
        for ch in self.channels:
            x = nn.Conv(ch, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                        dtype=self.compute_dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(ch, (3, 3), dtype=self.compute_dtype)(x)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(-3, -2))  # global average pool
        x = x.astype(jnp.float32)
        x = nn.Dense(max(self.num_experts * 4, 64), dtype=jnp.float32)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_experts, dtype=jnp.float32)(x)


def gating_cross_entropy(logits: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    """Stage-2 loss: cross-entropy against the GT expert label."""
    logp = nn.log_softmax(logits, axis=-1)
    onehot = jnp.eye(logits.shape[-1], dtype=logits.dtype)[label]
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))
