"""Expert scene-coordinate regression network.

Reference counterpart: the VGG-style FCN in the reference's ``network.py``
(SURVEY.md §2 #1; expected path, mount was empty): RGB (H, W, 3) -> scene
coordinates (H/8, W/8, 3), one network per scene/cluster.

TPU-first choices:
- bfloat16 activations/compute, float32 parameters (MXU-native mixed
  precision); the coordinate head upcasts to float32 before the residual
  add so centimeter precision survives.
- channel widths are multiples of 128 at the deep end (MXU lane width).
- output = predicted offset + scene center: the net regresses deviations
  around a per-scene mean, as the reference does with its scene-translation
  initialization.
"""

from __future__ import annotations

from collections.abc import Sequence

import flax.linen as nn
import jax.numpy as jnp


class ExpertNet(nn.Module):
    """Fully-convolutional scene-coordinate regressor, stride-8 output.

    Attributes:
      scene_center: (3,) added to the predicted offsets (meters).
      stem_channels: channels of the three stride-2 stages.
      head_channels: channels of the stride-1 trunk after downsampling.
      head_depth: number of 3x3 stride-1 conv blocks in the trunk.
      compute_dtype: activation dtype (bfloat16 on TPU).
    """

    scene_center: tuple[float, float, float] = (0.0, 0.0, 0.0)
    stem_channels: Sequence[int] = (64, 128, 256)
    head_channels: int = 512
    head_depth: int = 4
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        """(..., H, W, 3) RGB in [0,1] -> (..., H/8, W/8, 3) scene coords."""
        x = x.astype(self.compute_dtype)
        x = nn.Conv(self.stem_channels[0] // 2, (3, 3), dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        for ch in self.stem_channels:
            x = nn.Conv(ch, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                    dtype=self.compute_dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(ch, (3, 3), dtype=self.compute_dtype)(x)
            x = nn.relu(x)
        for _ in range(self.head_depth):
            # Residual 3x3 blocks at stride 1 keep the receptive field growing
            # without more downsampling (output must stay H/8).
            h = nn.Conv(self.head_channels, (3, 3), dtype=self.compute_dtype)(x)
            h = nn.relu(h)
            h = nn.Conv(self.head_channels, (1, 1), dtype=self.compute_dtype)(h)
            if x.shape[-1] != self.head_channels:
                x = nn.Conv(self.head_channels, (1, 1), dtype=self.compute_dtype)(x)
            x = nn.relu(x + h)
        # Coordinate head in float32: bf16 has ~3 decimal digits, not enough
        # for centimeter targets at meter scale.
        x = nn.Conv(3, (1, 1), dtype=jnp.float32, param_dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        return x + jnp.asarray(self.scene_center, dtype=jnp.float32)


def coordinate_loss(
    pred: jnp.ndarray,
    target: jnp.ndarray,
    mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Masked mean L1 distance between predicted and GT scene coordinates.

    The reference's stage-1 "coordinate" loss (SURVEY.md §3.1).  pred/target:
    (..., 3); mask: (...) with 1 = valid GT (invalid depth pixels are masked).
    """
    dist = jnp.sum(jnp.abs(pred - target), axis=-1)
    if mask is None:
        return jnp.mean(dist)
    return jnp.sum(dist * mask) / (jnp.sum(mask) + 1e-9)


def reprojection_loss(
    pred: jnp.ndarray,
    pixels: jnp.ndarray,
    R_gt: jnp.ndarray,
    t_gt: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    clamp_px: float = 100.0,
) -> jnp.ndarray:
    """Mean clamped reprojection error under the GT pose.

    The reference's depth-free init objective for outdoor scenes
    (SURVEY.md §0 stage 1).  pred: (N, 3) coords, pixels: (N, 2).
    """
    from esac_tpu.geometry.camera import reprojection_errors  # local: avoids cycle

    errs = reprojection_errors(R_gt, t_gt, pred, pixels, f, c)
    return jnp.mean(jnp.minimum(errs, clamp_px))
