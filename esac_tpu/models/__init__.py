"""Flax models: per-scene expert FCNs + the gating network.

The reference implements both as PyTorch ``nn.Module``s (SURVEY.md §2 #1-2:
a VGG-style fully-convolutional scene-coordinate regressor with stride-8
output, ~10^7 params, and a CNN classifier over M experts).  Here they are
Flax modules designed TPU-first: bfloat16 compute / float32 params, channel
counts sized for the MXU's 128-lane tiling, and a static config so the same
module scales from test-size to reference-size.
"""

from esac_tpu.models.expert import ExpertNet, coordinate_loss, reprojection_loss
from esac_tpu.models.gating import GatingNet
from esac_tpu.models.convert import torch_conv_to_flax, torch_state_dict_to_flax

__all__ = [
    "ExpertNet",
    "GatingNet",
    "coordinate_loss",
    "reprojection_loss",
    "torch_conv_to_flax",
    "torch_state_dict_to_flax",
]
