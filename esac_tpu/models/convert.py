"""Torch <-> Flax checkpoint conversion.

The reference stores ``torch.save(net.state_dict())`` checkpoints
(SURVEY.md §5 "Checkpoint / resume"); interchanging them with the jax
backend requires the layout conversion below.  Torch Conv2d weights are
(out, in, kH, kW) = OIHW; Flax ``nn.Conv`` kernels are (kH, kW, in, out) =
HWIO.  Torch Linear weights are (out, in); Flax Dense kernels are (in, out).

``torch_state_dict_to_flax`` maps a state dict whose layer ORDER matches the
Flax module's parameter order (the reference nets are plain sequential
stacks, so ordinal matching is exact); names need not match.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np


def torch_conv_to_flax(weight: np.ndarray, bias: np.ndarray | None = None) -> dict:
    """OIHW torch conv weight (+bias) -> flax {'kernel': HWIO, 'bias': ...}."""
    out = {"kernel": jnp.asarray(np.transpose(weight, (2, 3, 1, 0)))}
    if bias is not None:
        out["bias"] = jnp.asarray(bias)
    return out


def torch_linear_to_flax(weight: np.ndarray, bias: np.ndarray | None = None) -> dict:
    """(out, in) torch linear weight (+bias) -> flax {'kernel': (in, out), ...}."""
    out = {"kernel": jnp.asarray(np.transpose(weight, (1, 0)))}
    if bias is not None:
        out["bias"] = jnp.asarray(bias)
    return out


def torch_state_dict_to_flax(
    state_dict: Mapping[str, Any],
    flax_params: Mapping[str, Any],
) -> dict:
    """Fill a Flax param pytree from a torch state dict by layer order.

    state_dict: torch name -> tensor/ndarray (CPU).  flax_params: the target
    module's initialized ``params`` tree (gives names and expected shapes).
    Returns a new params tree.  Raises ValueError on a shape mismatch, which
    catches architecture drift early.
    """
    # Group torch entries into (weight, bias) pairs in order of appearance.
    pairs: list[tuple[np.ndarray, np.ndarray | None]] = []
    pending_w: np.ndarray | None = None
    pending_name = ""
    for name, value in state_dict.items():
        arr = np.asarray(value.detach().cpu() if hasattr(value, "detach") else value)
        if name.endswith("weight"):
            if pending_w is not None:
                pairs.append((pending_w, None))
            pending_w, pending_name = arr, name
        elif name.endswith("bias"):
            if pending_w is None or name[: -len("bias")] != pending_name[: -len("weight")]:
                raise ValueError(f"bias {name} does not follow its weight")
            pairs.append((pending_w, arr))
            pending_w = None
        else:
            raise ValueError(f"unsupported torch entry: {name}")
    if pending_w is not None:
        pairs.append((pending_w, None))

    # Walk the flax tree in definition order (flax dict insertion order is
    # module declaration order for nn.compact modules).
    leaves: list[tuple[str, dict]] = []

    def walk(tree, prefix=""):
        if "kernel" in tree:
            leaves.append((prefix, tree))
            return
        for k in tree:
            walk(tree[k], f"{prefix}/{k}")

    import copy

    new_params = copy.deepcopy({k: v for k, v in flax_params.items()})
    walk(new_params)
    if len(leaves) != len(pairs):
        raise ValueError(
            f"layer count mismatch: torch has {len(pairs)}, flax has {len(leaves)}"
        )
    for (name, leaf), (w, b) in zip(leaves, pairs):
        conv = torch_conv_to_flax(w, b) if w.ndim == 4 else torch_linear_to_flax(w, b)
        if conv["kernel"].shape != leaf["kernel"].shape:
            raise ValueError(
                f"shape mismatch at {name}: torch {conv['kernel'].shape} "
                f"vs flax {leaf['kernel'].shape}"
            )
        leaf["kernel"] = conv["kernel"]
        if b is not None:
            leaf["bias"] = conv["bias"]
    return new_params
