"""Profiling: stage timers + the hypotheses/sec/chip counter.

The reference prints ad-hoc wall-clock stage times from a C++ StopWatch
(SURVEY.md §2 #6, §5).  Under XLA, wall-clock around an async dispatch
measures nothing — every timer here fences with ``block_until_ready``.
``jax.profiler`` traces (TensorBoard) can be layered on via ``trace``.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax


class StageTimer:
    """Accumulates fenced wall-clock per named stage.

    >>> t = StageTimer()
    >>> with t("solve"):
    ...     out = kernel(...)        # timer fences on exit
    >>> t.summary()
    """

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str, fence=None):
        t0 = time.perf_counter()
        holder = []
        try:
            yield holder
        finally:
            target = holder[0] if holder else fence
            if target is not None:
                jax.block_until_ready(target)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def summary(self) -> str:
        lines = []
        for name, total in sorted(self.totals.items(), key=lambda kv: -kv[1]):
            n = self.counts[name]
            lines.append(f"{name:24s} {1e3 * total:10.1f} ms total "
                         f"{1e3 * total / n:8.2f} ms/call x{n}")
        return "\n".join(lines)


def hypotheses_per_sec(
    fn,
    args: tuple,
    n_hyps_per_call: int,
    repeats: int = 20,
) -> float:
    """The north-star counter (BASELINE.md): fenced throughput of a jitted
    hypothesis-kernel callable."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(*args)
    jax.block_until_ready(out)
    return repeats * n_hyps_per_call / (time.perf_counter() - t0)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/esac_tpu_trace"):
    """jax.profiler trace for TensorBoard, as a context manager."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


# --- FLOP model of the hypothesis pipeline (VERDICT r2 #4) ---------------
#
# Analytic per-stage counts for the inference pipeline that bench.py times
# (sample -> P3P -> soft-inlier score -> argmax -> IRLS refine).  These are
# *model* numbers — counted from the op structure of ransac/kernel.py and
# geometry/, not measured by the compiler — so they answer "what fraction of
# the chip does this throughput correspond to", which raw hyps/s cannot.
#
# Per-stage accounting (f32 flops, counting mul/add/div/exp as 1 each):
#
#   score (per hypothesis x per cell), the dominant term:
#     rodrigues rvec->R is amortized over cells (once per hypothesis);
#     R@X + t            3x3 matvec + add        = 21
#     perspective divide + focal/principal scale  =  8
#     residual vs pixel + squared norm            =  6
#     sqrt + sigmoid(beta*(tau-r)) + reduce-add   ~ 10
#                                     ------------------
#                                     ~45 flops/cell/hyp
#
#   minimal P3P solve (per hypothesis): branchless Ferrari quartic +
#     triad alignment + `polish_iters` Gauss-Newton polish rounds on 4
#     points — ~1.5k + polish_iters * ~600 flops.
#
#   IRLS refine (per refined pose per iteration): residuals + weights over
#     all cells (~50/cell) + unrolled 6x6 normal-equation solve (~2.5k).
#     Inference refines only the winner; training refines every hypothesis.

SCORE_FLOPS_PER_CELL = 45.0
P3P_FLOPS_BASE = 1500.0
P3P_FLOPS_PER_POLISH = 600.0
REFINE_FLOPS_PER_CELL_ITER = 50.0
REFINE_FLOPS_SOLVE = 2500.0

# bf16 MXU peak by device kind (flops/s).  The scoring stage is elementwise
# f32 on the VPU, not matmul on the MXU, so %-of-MXU-peak is a deliberately
# conservative utilization figure — it says how far from "the chip's
# headline number" the pipeline runs, which is the honest denominator for
# the north-star claim.  (v5e: 197 TFLOP/s bf16 per chip.)
DEVICE_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
}

# VPU f32 peak ESTIMATE (flops/s): the TPU vector unit is an (8, 128) lane
# grid with 4 ALUs per lane (scaling-book TPU chapter), so
# 8*128*4*clock ~= 3.9e12 at the v5e's ~0.94 GHz.  This — not MXU bf16 —
# is the compute ceiling for the elementwise-f32 scoring stage, and the
# denominator that answers "how fast COULD this pipeline go" (VERDICT r3
# weak #2).  Estimates, labeled so in the artifact.
DEVICE_VPU_F32_FLOPS_EST = {
    "TPU v5 lite": 3.9e12,
    "TPU v5e": 3.9e12,
    "TPU v4": 4.3e12,   # same lane grid at ~1.05 GHz
}

# HBM bandwidth by device kind (bytes/s, public figures).
DEVICE_HBM_BYTES_PER_S = {
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v4": 1228e9,
}

# Scoring-stage HBM traffic model, bytes per (hypothesis x cell):
#   errmap  — materializes the (n_hyps, cells) f32 error map: 4B write +
#             4B read-back for sigmoid/reduce = 8.  The coordinate map
#             (4800 cells x 12B = 57.6 KB) and pixel grid (38.4 KB) fit in
#             VMEM and are amortized across all hypotheses: ~0 per-hyp HBM.
#   fused / pallas — transform+project+error+sigmoid+reduce in one kernel:
#             no error map ever touches HBM; per-(hyp x cell) HBM ~ 0 and
#             the binding resource is the VPU.
SCORE_HBM_BYTES_PER_CELL = {"errmap": 8.0, "fused": 0.0, "pallas": 0.0}


def flops_per_hypothesis(
    n_cells: int,
    polish_iters: int = 3,
    refine_iters: int = 8,
    refined_frac: float = 0.0,
) -> float:
    """Model flops for one hypothesis through sample->solve->score, plus
    ``refined_frac`` of an IRLS refinement (1/n_hyps at inference where only
    the argmax winner is refined; 1.0 in training expectations)."""
    solve = P3P_FLOPS_BASE + polish_iters * P3P_FLOPS_PER_POLISH
    score = n_cells * SCORE_FLOPS_PER_CELL
    refine = refined_frac * refine_iters * (
        n_cells * REFINE_FLOPS_PER_CELL_ITER + REFINE_FLOPS_SOLVE
    )
    return solve + score + refine


def pipeline_flop_summary(
    hyps_per_sec: float,
    device_kind: str | None,
    basis: str = "live",
    n_cells: int = 4800,
    n_hyps: int = 256,
    scoring_impl: str = "errmap",
) -> dict:
    """Effective GFLOP/s (model flops x measured rate) and %-of-peak for the
    bench artifact.  ``basis`` labels where the rate came from ("live" or a
    committed-artifact tag) so a reader always knows which measurement the
    utilization figure describes."""
    fph = flops_per_hypothesis(n_cells, refined_frac=1.0 / n_hyps)
    out = {
        "flops_per_hypothesis_model": round(fph),
        "assumptions": f"{n_cells} cells scored/hyp at "
                       f"{SCORE_FLOPS_PER_CELL:.0f} flops/cell; winner-only "
                       f"IRLS refine amortized 1/{n_hyps}",
    }
    eff = hyps_per_sec * fph
    out["effective_gflops"] = round(eff / 1e9, 1)
    out["basis"] = basis
    peak = DEVICE_PEAK_FLOPS.get(device_kind or "")
    if peak:
        out["pct_of_bf16_peak"] = round(100.0 * eff / peak, 3)
        out["device_kind"] = device_kind
        out["peak_note"] = (
            "scoring is elementwise f32 on the VPU, not MXU matmul; "
            "%-of-MXU-bf16-peak is the conservative denominator for the "
            "north-star claim"
        )
    roofline = scoring_roofline(hyps_per_sec, device_kind, n_cells,
                                scoring_impl)
    if roofline:
        out["roofline"] = roofline
    return out


def scoring_roofline(
    hyps_per_sec: float,
    device_kind: str | None,
    n_cells: int = 4800,
    scoring_impl: str = "errmap",
) -> dict | None:
    """Which resource binds the scoring stage, and how far from it we run.

    The MXU-bf16 denominator above answers "how slow vs the headline";
    this answers the actionable question (VERDICT r3 weak #2): given the
    scoring stage's VPU-f32 flops and HBM bytes per (hyp x cell), what is
    the model's max hyps/s on this chip, which resource sets it, and what
    % of that ceiling the measured rate reaches — the number that says
    whether chasing a faster scoring kernel can pay.
    """
    vpu = DEVICE_VPU_F32_FLOPS_EST.get(device_kind or "")
    hbm = DEVICE_HBM_BYTES_PER_S.get(device_kind or "")
    if not (vpu and hbm):
        return None
    bytes_cell = SCORE_HBM_BYTES_PER_CELL.get(scoring_impl, 0.0)
    t_vpu = SCORE_FLOPS_PER_CELL / vpu      # s per (hyp x cell), compute
    t_hbm = bytes_cell / hbm                # s per (hyp x cell), memory
    binding = "VPU-f32" if t_vpu >= t_hbm else "HBM"
    max_rate = 1.0 / (max(t_vpu, t_hbm) * n_cells)
    return {
        "scoring_impl": scoring_impl,
        "binding_resource": binding,
        "max_hyps_per_sec_model": round(max_rate),
        "pct_of_binding_resource": round(100.0 * hyps_per_sec / max_rate, 2),
        "vpu_f32_peak_est_tflops": round(vpu / 1e12, 1),
        "hbm_gbps": round(hbm / 1e9),
        "hbm_bytes_per_cell_model": bytes_cell,
        "note": "scoring-stage-only roofline: solve/select/refine and "
                "dispatch latency are outside the model, so the ceiling is "
                "optimistic; a measured rate far below it means the "
                "pipeline is bound elsewhere (serial stages, dispatch), "
                "not that the VPU is busy",
    }


def xla_score_flops_per_cell(n_cells: int = 1200, n_hyps: int = 64) -> float:
    """Cross-check SCORE_FLOPS_PER_CELL against XLA's own cost model.

    Lowers the real ``_score_hypotheses`` (errmap impl) through
    ``jit(...).lower(...).compile().cost_analysis()`` — which works on the
    CPU backend — and returns the compiler-counted flops per (hyp x cell).
    The hand count (45) treats mul/add/div/exp/sqrt as 1 flop each; XLA's
    accounting differs in transcendental weighting, so agreement within ~2x
    validates the order of magnitude (pinned in tests/test_profiling.py).
    """
    # Force the CPU backend before any jit/lower: this helper is attractive
    # to call from an ad-hoc interpreter, and per CLAUDE.md a bare backend
    # init while the TPU relay is unhealthy hangs forever (ADVICE r4).
    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from esac_tpu.ransac.config import RansacConfig
    from esac_tpu.ransac.kernel import _score_hypotheses

    cfg = RansacConfig(n_hyps=n_hyps)
    key = jax.random.key(0)
    rvecs = jnp.zeros((n_hyps, 3)) + 0.1
    tvecs = jnp.ones((n_hyps, 3))
    coords = jnp.linspace(0.0, 1.0, n_cells * 3).reshape(n_cells, 3)
    pixels = jnp.linspace(0.0, 100.0, n_cells * 2).reshape(n_cells, 2)
    f = jnp.float32(100.0)
    c = jnp.asarray([50.0, 50.0])

    fn = jax.jit(
        lambda rv, tv, co, px: _score_hypotheses(key, rv, tv, co, px, f, c, cfg)
    )
    compiled = fn.lower(rvecs, tvecs, coords, pixels).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0]
    return float(ca["flops"]) / (n_cells * n_hyps)
