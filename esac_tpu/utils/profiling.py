"""Profiling: stage timers + the hypotheses/sec/chip counter.

The reference prints ad-hoc wall-clock stage times from a C++ StopWatch
(SURVEY.md §2 #6, §5).  Under XLA, wall-clock around an async dispatch
measures nothing — every timer here fences with ``block_until_ready``.
``jax.profiler`` traces (TensorBoard) can be layered on via ``trace``.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax


class StageTimer:
    """Accumulates fenced wall-clock per named stage.

    >>> t = StageTimer()
    >>> with t("solve"):
    ...     out = kernel(...)        # timer fences on exit
    >>> t.summary()
    """

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str, fence=None):
        t0 = time.perf_counter()
        holder = []
        try:
            yield holder
        finally:
            target = holder[0] if holder else fence
            if target is not None:
                jax.block_until_ready(target)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def summary(self) -> str:
        lines = []
        for name, total in sorted(self.totals.items(), key=lambda kv: -kv[1]):
            n = self.counts[name]
            lines.append(f"{name:24s} {1e3 * total:10.1f} ms total "
                         f"{1e3 * total / n:8.2f} ms/call x{n}")
        return "\n".join(lines)


def hypotheses_per_sec(
    fn,
    args: tuple,
    n_hyps_per_call: int,
    repeats: int = 20,
) -> float:
    """The north-star counter (BASELINE.md): fenced throughput of a jitted
    hypothesis-kernel callable."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(*args)
    jax.block_until_ready(out)
    return repeats * n_hyps_per_call / (time.perf_counter() - t0)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/esac_tpu_trace"):
    """jax.profiler trace for TensorBoard, as a context manager."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
