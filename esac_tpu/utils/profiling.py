"""Profiling: stage timers + the hypotheses/sec/chip counter.

The reference prints ad-hoc wall-clock stage times from a C++ StopWatch
(SURVEY.md §2 #6, §5).  Under XLA, wall-clock around an async dispatch
measures nothing — every timer here fences with ``block_until_ready``.
``jax.profiler`` traces (TensorBoard) can be layered on via ``trace``.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax


class StageTimer:
    """Accumulates fenced wall-clock per named stage.

    >>> t = StageTimer()
    >>> with t("solve"):
    ...     out = kernel(...)        # timer fences on exit
    >>> t.summary()
    """

    def __init__(self):
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str, fence=None):
        t0 = time.perf_counter()
        holder = []
        try:
            yield holder
        finally:
            target = holder[0] if holder else fence
            if target is not None:
                jax.block_until_ready(target)
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def summary(self) -> str:
        lines = []
        for name, total in sorted(self.totals.items(), key=lambda kv: -kv[1]):
            n = self.counts[name]
            lines.append(f"{name:24s} {1e3 * total:10.1f} ms total "
                         f"{1e3 * total / n:8.2f} ms/call x{n}")
        return "\n".join(lines)


def hypotheses_per_sec(
    fn,
    args: tuple,
    n_hyps_per_call: int,
    repeats: int = 20,
) -> float:
    """The north-star counter (BASELINE.md): fenced throughput of a jitted
    hypothesis-kernel callable."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(*args)
    jax.block_until_ready(out)
    return repeats * n_hyps_per_call / (time.perf_counter() - t0)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/esac_tpu_trace"):
    """jax.profiler trace for TensorBoard, as a context manager."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


# --- FLOP model of the hypothesis pipeline (VERDICT r2 #4) ---------------
#
# Analytic per-stage counts for the inference pipeline that bench.py times
# (sample -> P3P -> soft-inlier score -> argmax -> IRLS refine).  These are
# *model* numbers — counted from the op structure of ransac/kernel.py and
# geometry/, not measured by the compiler — so they answer "what fraction of
# the chip does this throughput correspond to", which raw hyps/s cannot.
#
# Per-stage accounting (f32 flops, counting mul/add/div/exp as 1 each):
#
#   score (per hypothesis x per cell), the dominant term:
#     rodrigues rvec->R is amortized over cells (once per hypothesis);
#     R@X + t            3x3 matvec + add        = 21
#     perspective divide + focal/principal scale  =  8
#     residual vs pixel + squared norm            =  6
#     sqrt + sigmoid(beta*(tau-r)) + reduce-add   ~ 10
#                                     ------------------
#                                     ~45 flops/cell/hyp
#
#   minimal P3P solve (per hypothesis): branchless Ferrari quartic +
#     triad alignment + `polish_iters` Gauss-Newton polish rounds on 4
#     points — ~1.5k + polish_iters * ~600 flops.
#
#   IRLS refine (per refined pose per iteration): residuals + weights over
#     all cells (~50/cell) + unrolled 6x6 normal-equation solve (~2.5k).
#     Inference refines only the winner; training refines every hypothesis.

SCORE_FLOPS_PER_CELL = 45.0
P3P_FLOPS_BASE = 1500.0
P3P_FLOPS_PER_POLISH = 600.0
REFINE_FLOPS_PER_CELL_ITER = 50.0
REFINE_FLOPS_SOLVE = 2500.0

# bf16 MXU peak by device kind (flops/s).  The scoring stage is elementwise
# f32 on the VPU, not matmul on the MXU, so %-of-MXU-peak is a deliberately
# conservative utilization figure — it says how far from "the chip's
# headline number" the pipeline runs, which is the honest denominator for
# the north-star claim.  (v5e: 197 TFLOP/s bf16 per chip.)
DEVICE_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
}


def flops_per_hypothesis(
    n_cells: int,
    polish_iters: int = 3,
    refine_iters: int = 8,
    refined_frac: float = 0.0,
) -> float:
    """Model flops for one hypothesis through sample->solve->score, plus
    ``refined_frac`` of an IRLS refinement (1/n_hyps at inference where only
    the argmax winner is refined; 1.0 in training expectations)."""
    solve = P3P_FLOPS_BASE + polish_iters * P3P_FLOPS_PER_POLISH
    score = n_cells * SCORE_FLOPS_PER_CELL
    refine = refined_frac * refine_iters * (
        n_cells * REFINE_FLOPS_PER_CELL_ITER + REFINE_FLOPS_SOLVE
    )
    return solve + score + refine


def pipeline_flop_summary(
    hyps_per_sec: float,
    device_kind: str | None,
    basis: str = "live",
    n_cells: int = 4800,
    n_hyps: int = 256,
) -> dict:
    """Effective GFLOP/s (model flops x measured rate) and %-of-peak for the
    bench artifact.  ``basis`` labels where the rate came from ("live" or a
    committed-artifact tag) so a reader always knows which measurement the
    utilization figure describes."""
    fph = flops_per_hypothesis(n_cells, refined_frac=1.0 / n_hyps)
    out = {
        "flops_per_hypothesis_model": round(fph),
        "assumptions": f"{n_cells} cells scored/hyp at "
                       f"{SCORE_FLOPS_PER_CELL:.0f} flops/cell; winner-only "
                       f"IRLS refine amortized 1/{n_hyps}",
    }
    eff = hyps_per_sec * fph
    out["effective_gflops"] = round(eff / 1e9, 1)
    out["basis"] = basis
    peak = DEVICE_PEAK_FLOPS.get(device_kind or "")
    if peak:
        out["pct_of_bf16_peak"] = round(100.0 * eff / peak, 3)
        out["device_kind"] = device_kind
        out["peak_note"] = (
            "scoring is elementwise f32 on the VPU, not MXU matmul; "
            "%-of-MXU-bf16-peak is the conservative denominator for the "
            "north-star claim"
        )
    return out
