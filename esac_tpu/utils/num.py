"""Gradient-safe numerical primitives.

Everything in the geometry core sits under ``jax.grad`` inside a ``vmap``
over thousands of random minimal samples; a single degenerate sample with an
exact zero (norm at 0, sqrt at 0, repeated singular values) produces an
inf/NaN *backward* value that poisons the entire batch gradient, even when
the forward value is masked by ``where`` (0 * inf = NaN).  These helpers put
the epsilon *inside* the sqrt so both forward and backward stay finite.

Epsilon policy: 1e-12 under a sqrt gives a 1e-6 floor — far below any
physically meaningful pixel/meter/radian quantity here, far above float32
underflow.  Use ``eps`` overrides only with a comment justifying the scale.
"""

from __future__ import annotations

import jax.numpy as jnp

DEFAULT_EPS = 1e-12


def safe_norm(x: jnp.ndarray, axis: int = -1, eps: float = DEFAULT_EPS) -> jnp.ndarray:
    """L2 norm with finite gradient at ``x = 0`` (eps inside the sqrt)."""
    return jnp.sqrt(jnp.sum(x * x, axis=axis) + eps)


def safe_sqrt(x: jnp.ndarray, eps: float = DEFAULT_EPS) -> jnp.ndarray:
    """sqrt with finite gradient at 0 (works for real and complex inputs)."""
    return jnp.sqrt(x + eps)
