"""Precision-pinned matmul helpers for the geometry core.

TPU MXU matmuls default to bfloat16 inputs, which is right for the big CNN
convolutions but catastrophically wrong for 3x3 rotation algebra (1e-3 entry
error -> degrees of rotation error).  All geometry-core contractions go
through these helpers, which pin ``Precision.HIGHEST`` (full fp32 on TPU).
The tensors involved are tiny, so the cost is nil.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_HIGH = jax.lax.Precision.HIGHEST


def hmm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """matmul at HIGHEST precision."""
    return jnp.matmul(a, b, precision=_HIGH)


def heinsum(spec: str, *args: jnp.ndarray) -> jnp.ndarray:
    """einsum at HIGHEST precision."""
    return jnp.einsum(spec, *args, precision=_HIGH)
