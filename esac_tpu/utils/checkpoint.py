"""Checkpoint save/restore: Orbax param trees + JSON config sidecar.

Reference counterpart: periodic ``torch.save(state_dict)`` (SURVEY.md §5
"Checkpoint / resume").  Here a checkpoint is a directory:

    <path>/params/   Orbax PyTree checkpoint (params, optionally opt state)
    <path>/config.json   net architecture + scene metadata

so any entry script can reconstruct the exact module without re-specifying
flags, and torch checkpoints can be converted in via
``esac_tpu.models.convert`` then saved through this module.
"""

from __future__ import annotations

import json
import os
import pathlib
import shutil
import warnings
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


def _tree_metadata(ckptr: "ocp.PyTreeCheckpointer", path: pathlib.Path) -> Any:
    """Structure-only metadata of a saved PyTree checkpoint.

    Orbax moved this surface across the version drift window: older
    releases wrap it as ``CheckpointMetadata.item_metadata.tree``; the
    shipping one returns the metadata tree from ``metadata()`` directly.
    Accept both so checkpoints read on either side of the drift.
    """
    meta = ckptr.metadata(path)
    item = getattr(meta, "item_metadata", meta)
    return getattr(item, "tree", item)


def save_checkpoint(path: str | pathlib.Path, params: Any, config: dict) -> None:
    path = pathlib.Path(path).absolute()
    path.mkdir(parents=True, exist_ok=True)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path / "params", params, force=True)
    (path / "config.json").write_text(json.dumps(config, indent=2))


def save_train_state(path: str | pathlib.Path, params: Any, config: dict,
                     opt_state: Any, iteration: int) -> None:
    """Resume-capable checkpoint: params + optimizer state + iteration.

    SURVEY.md §5 build target ("Orbax checkpointing of Flax params +
    optimizer state").  Layout extends ``save_checkpoint`` — eval scripts
    keep reading ``params``/``config.json``; trainers additionally get
    ``opt_state/`` and ``config["iteration"]`` for exact resume.

    Crash-atomic: the composite (params, opt_state, config) is written into
    a ``.staging`` sibling and swapped in by two renames, so a process death
    mid-save (relay stall, preemption, SIGKILL — observed in round 2) can
    never leave a half-written checkpoint at ``path``.  The only vulnerable
    instant is between the renames, where the previous state survives at
    ``<path>.old`` and ``load_train_state`` falls back to it.
    """
    path = pathlib.Path(path).absolute()
    old = path.with_name(path.name + ".old")
    if not path.exists() and old.exists():
        # Repair a previous crash-between-renames BEFORE deleting anything:
        # .old is the only surviving state and must never be removed while
        # no checkpoint exists at path.
        os.rename(old, path)
    staging = path.with_name(path.name + ".staging")
    if staging.exists():
        shutil.rmtree(staging)
    staging.mkdir(parents=True)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(staging / "params", params, force=True)
        ckptr.save(staging / "opt_state", opt_state, force=True)
    (staging / "config.json").write_text(
        json.dumps({**config, "iteration": int(iteration)}, indent=2)
    )
    if old.exists():
        shutil.rmtree(old)  # safe: a complete checkpoint exists at path
    if path.exists():
        os.rename(path, old)
    os.rename(staging, path)
    shutil.rmtree(old, ignore_errors=True)


def load_train_state(path: str | pathlib.Path, opt_state_template: Any
                     ) -> tuple[Any, Any, dict, int]:
    """Restore (params, opt_state, config, iteration).

    ``opt_state_template`` (e.g. ``opt.init(params)``) supplies the pytree
    structure — optax states are namedtuples, which Orbax round-trips as
    plain containers; leaves are restored in traversal order and re-hung on
    the template's treedef.  Raises FileNotFoundError when the checkpoint
    has no optimizer state (written by plain ``save_checkpoint``).
    """
    path = _with_old_fallback(path)
    params, config = load_checkpoint(path)
    opt_dir = path / "opt_state"
    if not opt_dir.exists():
        raise FileNotFoundError(f"{opt_dir} (not a resume-capable checkpoint)")
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = _tree_metadata(ckptr, opt_dir)
        restore_args = jax.tree.map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
        )
        raw = ckptr.restore(opt_dir, restore_args=restore_args)
    leaves = jax.tree.leaves(raw)
    treedef = jax.tree.structure(opt_state_template)
    if len(leaves) != treedef.num_leaves:
        raise ValueError(
            f"opt_state leaf count {len(leaves)} != template {treedef.num_leaves} "
            "(optimizer config changed since the checkpoint was written?)"
        )
    opt_state = jax.tree.unflatten(treedef, leaves)
    return params, opt_state, config, int(config.get("iteration", 0))


def _with_old_fallback(path: str | pathlib.Path) -> pathlib.Path:
    """Death between save_train_state's two renames leaves the previous
    state intact at <path>.old; every reader falls back to it."""
    path = pathlib.Path(path).absolute()
    old = path.with_name(path.name + ".old")
    if not path.exists() and old.exists():
        warnings.warn(f"{path} missing; reading {old.name} (crash between "
                      "checkpoint renames)")
        return old
    return path


def checkpoint_nbytes(path: str | pathlib.Path) -> int:
    """Total parameter bytes of ``<path>/params`` from Orbax METADATA alone
    — no array data is read.

    The operator-side sizing tool for the registry's device weight cache
    (esac_tpu.registry): budget a fleet's ``budget_bytes`` against its
    checkpoints without restoring any of them.  (The cache itself measures
    actual staged bytes post-``device_put`` — authoritative, but only
    after a load; this is the plan-ahead view, equal to the staged size
    for numpy-restored trees, pinned in tests/test_registry.py.)  Falls
    back to a full host restore when a metadata leaf carries no
    shape/dtype (older Orbax layouts).
    """
    path = _with_old_fallback(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = _tree_metadata(ckptr, path / "params")
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            params, _ = load_checkpoint(path)
            return sum(x.nbytes for x in jax.tree.leaves(params))
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def load_checkpoint(path: str | pathlib.Path) -> tuple[Any, dict]:
    """Restore as HOST numpy arrays: checkpoints written on one topology
    (e.g. the TPU) must load on any other (e.g. the CPU test mesh) — the
    saved device shardings are a property of the writer, not the data.
    Callers hand the tree to jit, which places it."""
    path = _with_old_fallback(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = _tree_metadata(ckptr, path / "params")
        restore_args = jax.tree.map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
        )
        params = ckptr.restore(path / "params", restore_args=restore_args)
    config = json.loads((path / "config.json").read_text())
    return params, config
