"""Checkpoint save/restore: Orbax param trees + JSON config sidecar.

Reference counterpart: periodic ``torch.save(state_dict)`` (SURVEY.md §5
"Checkpoint / resume").  Here a checkpoint is a directory:

    <path>/params/   Orbax PyTree checkpoint (params, optionally opt state)
    <path>/config.json   net architecture + scene metadata

so any entry script can reconstruct the exact module without re-specifying
flags, and torch checkpoints can be converted in via
``esac_tpu.models.convert`` then saved through this module.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


def save_checkpoint(path: str | pathlib.Path, params: Any, config: dict) -> None:
    path = pathlib.Path(path).absolute()
    path.mkdir(parents=True, exist_ok=True)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path / "params", params, force=True)
    (path / "config.json").write_text(json.dumps(config, indent=2))


def load_checkpoint(path: str | pathlib.Path) -> tuple[Any, dict]:
    """Restore as HOST numpy arrays: checkpoints written on one topology
    (e.g. the TPU) must load on any other (e.g. the CPU test mesh) — the
    saved device shardings are a property of the writer, not the data.
    Callers hand the tree to jit, which places it."""
    path = pathlib.Path(path).absolute()
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.metadata(path / "params").item_metadata.tree
        restore_args = jax.tree.map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree
        )
        params = ckptr.restore(path / "params", restore_args=restore_args)
    config = json.loads((path / "config.json").read_text())
    return params, config
