from esac_tpu.utils.precision import hmm, heinsum

__all__ = ["hmm", "heinsum"]
