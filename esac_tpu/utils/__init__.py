from esac_tpu.utils.num import safe_norm, safe_sqrt
from esac_tpu.utils.precision import hmm, heinsum

__all__ = ["hmm", "heinsum", "safe_norm", "safe_sqrt"]
