"""Dataset loaders: on-disk re-localization layouts + synthetic scenes.

The reference ships per-benchmark setup scripts (7-Scenes / 12-Scenes /
Aachen, SURVEY.md §2 #13-15) that convert each dataset into a common on-disk
layout consumed by a torch ``Dataset``.  This module reads that common
layout:

    <root>/<scene>/{training,test}/
        rgb/*.png                 RGB frames
        poses/*.txt               4x4 camera-to-scene pose matrices
        calibration/*.txt         focal length (one float per frame)
        init/*.npy  (optional)    (h, w, 3) GT scene coordinates
        depth/*.png (optional)    16-bit depth in mm, used to render GT
                                  scene coordinates when init/ is absent

and also provides ``SyntheticScene`` — the self-contained procedural room
(one distinct texture per scene id) used by tests, CLI smoke runs and
benchmarks in environments where the real datasets cannot be downloaded.

Pose convention note: on-disk poses are camera-to-scene (the inverse of the
(R, t) scene->camera transform used throughout esac_tpu.geometry); loading
inverts them once.
"""

from __future__ import annotations

import pathlib
import warnings
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from esac_tpu.data.synthetic import (
    CAMERA_C,
    CAMERA_F,
    output_pixel_grid,
    random_poses_in_box,
    render_box_scene,
    trajectory_poses_in_box,
)
from esac_tpu.geometry.rotations import so3_log


@dataclass
class Frame:
    image: np.ndarray        # (H, W, 3) float32 in [0, 1]
    rvec: np.ndarray         # (3,) scene->camera
    tvec: np.ndarray         # (3,)
    focal: float
    coords_gt: np.ndarray | None = None  # (h, w, 3) or None
    expert: int = 0          # GT expert/scene label


def _invert_pose(T: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """4x4 camera-to-scene matrix -> (rvec, tvec) scene->camera."""
    R_cs = T[:3, :3]
    t_cs = T[:3, 3]
    R = R_cs.T
    t = -R @ t_cs
    rvec = np.asarray(so3_log(jnp.asarray(R, dtype=jnp.float32)))
    return rvec, t.astype(np.float32)


class SceneDataset:
    """One scene of an on-disk dataset in the common layout."""

    def __init__(self, root: str | pathlib.Path, scene: str, split: str = "training",
                 expert: int = 0, coord_stride: int = 8):
        self.dir = pathlib.Path(root) / scene / split
        self.expert = expert
        self.stride = coord_stride
        rgb = self.dir / "rgb"
        if not rgb.is_dir():
            raise FileNotFoundError(f"no rgb/ under {self.dir}")
        self.names = sorted(p.stem for p in rgb.iterdir())
        if not self.names:
            raise FileNotFoundError(f"empty scene {self.dir}")

    def __len__(self) -> int:
        return len(self.names)

    def _find(self, sub: str, stem: str, exts: tuple[str, ...]):
        for ext in exts:
            p = self.dir / sub / f"{stem}{ext}"
            if p.exists():
                return p
        return None

    def __getitem__(self, i: int) -> Frame:
        stem = self.names[i]
        img_path = self._find("rgb", stem, (".png", ".jpg", ".jpeg"))
        from PIL import Image  # deferred: pillow ships with the baked torch stack

        image = np.asarray(Image.open(img_path).convert("RGB"), dtype=np.float32) / 255.0
        T = np.loadtxt(self._find("poses", stem, (".txt",)))
        rvec, tvec = _invert_pose(T.reshape(4, 4))
        calib = self._find("calibration", stem, (".txt",))
        focal = float(np.loadtxt(calib)) if calib else CAMERA_F
        if abs(focal - 525.0) < 1e-6 and not getattr(self, "_warned_525", False):
            # Trees converted before setup_7scenes' 525->585 focal change
            # keep 525 calibration files; the two conventions produce
            # accuracy numbers that are NOT directly comparable.  Loud
            # once-per-dataset warning rather than silent mixing.
            self._warned_525 = True
            import warnings

            warnings.warn(
                f"{self.dir}: calibration reads f=525 (pre-585-default "
                "conversion, or deliberate --focal 525). Regenerate the "
                "tree with datasets/setup_7scenes.py for the current "
                "convention, or keep 525 consistently — do not compare "
                "accuracy across the two.", stacklevel=2,
            )

        coords = None
        init = self._find("init", stem, (".npy",))
        if init is not None:
            coords = np.load(init).astype(np.float32)
        else:
            depth_path = self._find("depth", stem, (".png",))
            if depth_path is not None:
                from PIL import Image as PImage

                depth = np.asarray(PImage.open(depth_path), dtype=np.float32)
                # Kinect invalid-depth sentinel (7-Scenes: 65535) -> 0, the
                # loader's no-measurement value, BEFORE mm->m conversion.
                depth[depth >= 65535.0] = 0.0
                depth /= 1000.0
                coords = self._coords_from_depth(depth, T.reshape(4, 4), focal, image.shape)
        return Frame(image, rvec, tvec, focal, coords, self.expert)

    def _coords_from_depth(self, depth, T_cs, focal, img_shape):
        """Back-project subsampled depth through the camera-to-scene pose."""
        H, W = img_shape[:2]
        s = self.stride
        d = depth[s // 2::s, s // 2::s][: H // s, : W // s]
        grid = np.asarray(output_pixel_grid(H, W, s)).reshape(H // s, W // s, 2)
        cx, cy = W / 2.0, H / 2.0
        x = (grid[..., 0] - cx) / focal * d
        y = (grid[..., 1] - cy) / focal * d
        cam = np.stack([x, y, d], axis=-1)
        coords = cam @ T_cs[:3, :3].T + T_cs[:3, 3]
        # Invalid depth (0) -> NaN-free sentinel mask handled by callers via
        # the depth==0 test.
        coords[d == 0] = 0.0
        return coords.astype(np.float32)


class SyntheticScene:
    """Procedural box-room scene ``synthN`` with per-scene texture.

    Splits: ``training`` / ``test`` draw i.i.d. poses; ``trajectory``
    (ISSUE 20) draws ONE smooth continuous camera path
    (:func:`~esac_tpu.data.synthetic.trajectory_poses_in_box`) so
    frame ``i+1`` is within a constant-velocity motion model of frame
    ``i`` — the sequence substrate of the session-serving benches,
    with per-frame ground truth and the same pre-staged-batch pattern.
    """

    def __init__(self, scene: str = "synth0", split: str = "training",
                 n_frames: int = 64, height: int = 96, width: int = 128,
                 coord_stride: int = 8, expert: int | None = None):
        sid = int(scene.replace("synth", "") or 0)
        self.sid = sid
        # Expert label is the caller's position in its scene list, NOT the
        # scene-name suffix: 'synth2 synth5' with M=2 must label frames 0/1,
        # or gating cross-entropy trains on out-of-range classes.
        self.expert = sid if expert is None else expert
        self.height, self.width, self.stride = height, width, coord_stride
        self.focal = CAMERA_F * width / 640.0
        seed = sid * 1000 + {"training": 0, "trajectory": 2}.get(split, 1)
        sampler = trajectory_poses_in_box if split == "trajectory" \
            else random_poses_in_box
        self.rvecs, self.tvecs = sampler(jax.random.key(seed), n_frames)
        # Pre-render EVERYTHING once, vmapped, and keep host copies: a jitted
        # render per __getitem__ costs a device dispatch each — through the
        # remote-TPU tunnel of this environment that is ~100ms per frame and
        # dominates training time.
        render = jax.jit(
            jax.vmap(
                lambda rv, tv: render_box_scene(
                    rv, tv, height, width, self.focal,
                    (width / 2.0, height / 2.0), coord_stride,
                    texture_phase=1.7 * sid,
                )
            )
        )
        # Chunked: one all-frames vmap spikes device memory at
        # reference-scale --frames (the render's per-frame intermediates are
        # materialized batch-wide); 64-frame chunks keep the peak flat.
        imgs, crds = [], []
        for i in range(0, n_frames, 64):
            out = render(self.rvecs[i:i + 64], self.tvecs[i:i + 64])
            imgs.append(np.asarray(out["image"], dtype=np.float32))
            crds.append(np.asarray(out["coords_gt"], dtype=np.float32))
        h, w = height // coord_stride, width // coord_stride
        self._images = np.concatenate(imgs)
        self._coords = np.concatenate(crds).reshape(n_frames, h, w, 3)
        self._rvecs = np.asarray(self.rvecs)
        self._tvecs = np.asarray(self.tvecs)

    def __len__(self) -> int:
        return self._images.shape[0]

    def __getitem__(self, i: int) -> Frame:
        return Frame(
            self._images[i],
            self._rvecs[i],
            self._tvecs[i],
            self.focal,
            self._coords[i],
            self.expert,
        )


def open_scene(root: str, scene: str, split: str, expert: int | None = None, **kw):
    """Dispatch: ``synthN`` -> SyntheticScene, else on-disk SceneDataset.

    ``expert=None`` keeps each class's own default label (sid for synthetic
    scenes, 0 on disk), matching direct construction.  Synthetic-scale
    kwargs (n_frames/height/width, from the CLI --frames/--res flags) are
    meaningless for on-disk scenes — fixed frame counts and stored
    resolutions — and are dropped with a warning there.
    """
    if scene.startswith("synth"):
        return SyntheticScene(scene, split, expert=expert, **kw)
    dropped = [k for k in ("n_frames", "height", "width")
               if kw.pop(k, None) is not None]
    if dropped:
        warnings.warn(
            f"synthetic-scale kwargs {dropped} ignored for on-disk scene {scene!r}"
        )
    return SceneDataset(root, scene, split, expert=expert or 0, **kw)


def batch_frames(ds, idx: np.ndarray) -> dict:
    """Stack frames into jnp arrays for a training step."""
    frames = [ds[int(i)] for i in idx]
    out = {
        "images": jnp.stack([jnp.asarray(f.image) for f in frames]),
        "rvecs": jnp.stack([jnp.asarray(f.rvec) for f in frames]),
        "tvecs": jnp.stack([jnp.asarray(f.tvec) for f in frames]),
        "labels": jnp.asarray([f.expert for f in frames]),
        "focal": frames[0].focal,
        # Per-frame intrinsics: outdoor datasets mix cameras, so consumers
        # that project (the reproj stage-1 loss) must not assume frame 0's
        # focal for the whole batch.
        "focals": jnp.asarray([f.focal for f in frames], jnp.float32),
    }
    if frames[0].coords_gt is not None:
        out["coords_gt"] = jnp.stack([jnp.asarray(f.coords_gt) for f in frames])
    return out
