"""Data: synthetic scenes for tests/benchmarks + dataset loaders.

The reference ships one-shot setup scripts for 7-Scenes / 12-Scenes / Aachen
(SURVEY.md §2 #13-15); those datasets cannot be downloaded in this
environment, so the loaders accept the standard on-disk layouts while the
synthetic box-scene provides a fully self-contained renderer for unit tests,
end-to-end training tests and benchmarks.
"""

from esac_tpu.data.synthetic import (
    CAMERA_F,
    CAMERA_C,
    make_correspondence_frame,
    output_pixel_grid,
    render_box_scene,
    random_poses_in_box,
    trajectory_poses_in_box,
)

__all__ = [
    "CAMERA_F",
    "CAMERA_C",
    "make_correspondence_frame",
    "output_pixel_grid",
    "render_box_scene",
    "random_poses_in_box",
    "trajectory_poses_in_box",
]
