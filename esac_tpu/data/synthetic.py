"""Synthetic box-room scene: exact geometry for kernel tests and a
procedurally-textured renderer for end-to-end training tests.

The reference's integration "tests" are benchmark runs on real datasets
(SURVEY.md §4: it has no test suite); our substitute is a closed-form scene —
an axis-aligned room seen by a pinhole camera — where ground-truth scene
coordinates, poses and images are all computable exactly, so an expert can be
trained to convergence in minutes and the full pipeline evaluated at 5cm/5deg
without any dataset download.

Conventions match esac_tpu.geometry: pose (R, t) maps scene -> camera.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from esac_tpu.geometry.rotations import rodrigues

# Default pinhole intrinsics (7-Scenes-like: 640x480 @ f=525).
CAMERA_F = 525.0
CAMERA_C = (320.0, 240.0)

# The room: axis-aligned box [0, ROOM_SIZE]^3 (meters).  numpy, not jnp:
# module-level jnp arrays initialize the device backend at import time.
ROOM_SIZE = np.array([6.0, 4.0, 3.0], dtype=np.float32)


def output_pixel_grid(
    height: int = 480,
    width: int = 640,
    stride: int = 8,
) -> jnp.ndarray:
    """Centers of the expert's output cells in input-pixel coordinates.

    The expert subsamples by ``stride`` (80x60 cells for 640x480 @ 8,
    SURVEY.md §0), each cell center at (stride*j + stride/2).
    Returns (n_cells, 2) float32, row-major (y outer, x inner).
    """
    ys = jnp.arange(height // stride) * stride + stride / 2.0
    xs = jnp.arange(width // stride) * stride + stride / 2.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    return jnp.stack([gx.reshape(-1), gy.reshape(-1)], axis=-1).astype(jnp.float32)


def random_poses_in_box(key: jax.Array, n: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sample plausible camera poses inside the room, looking inward.

    Returns (rvecs (n, 3), tvecs (n, 3)) in scene->camera convention.
    Cameras sit in the middle of the room with modest rotations, so most
    rays hit a wall at reasonable depth.
    """
    k1, k2 = jax.random.split(key)
    rvecs = jax.random.uniform(k1, (n, 3), minval=-0.35, maxval=0.35)
    centers = ROOM_SIZE * (0.5 + jax.random.uniform(k2, (n, 3), minval=-0.2, maxval=0.2))
    Rs = rodrigues(rvecs)
    # t = -R @ center  (camera center -> translation).
    tvecs = -jnp.einsum("nij,nj->ni", Rs, centers)
    return rvecs, tvecs


def trajectory_poses_in_box(
    key: jax.Array,
    n: int,
    dt: float = 1.0 / 30.0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """A smooth continuous camera trajectory through the room.

    The temporal sibling of :func:`random_poses_in_box` (DESIGN.md §23):
    the camera center and rotation each follow a sum of low-frequency
    sinusoids with random per-axis phases, so consecutive frames at
    ``dt`` spacing are within a constant-velocity motion model of each
    other (the warm-start serving assumption) while the path still
    covers the pose box over a long sequence.  Frame-to-frame deltas are
    a few cm / a fraction of a degree at 30 Hz — real handheld-video
    coherence, not i.i.d. redraws.

    Returns (rvecs (n, 3), tvecs (n, 3)) in scene->camera convention,
    same ranges as ``random_poses_in_box`` (centers inside
    ``ROOM_SIZE * (0.5 +- 0.2)``, rotations within +-0.35 rad).
    """
    k1, k2 = jax.random.split(key)
    t = jnp.arange(n, dtype=jnp.float32)[:, None] * dt  # (n, 1) seconds
    # Two incommensurate frequencies per channel; amplitudes sum to the
    # i.i.d. sampler's bounds so the path stays inside its pose box.
    f1, f2 = 0.11, 0.047  # Hz — periods ~9s and ~21s
    ph_c = jax.random.uniform(k1, (2, 3), maxval=2.0 * jnp.pi)
    ph_r = jax.random.uniform(k2, (2, 3), maxval=2.0 * jnp.pi)
    two_pi = 2.0 * jnp.pi
    wiggle_c = 0.13 * jnp.sin(two_pi * f1 * t + ph_c[0]) \
        + 0.07 * jnp.sin(two_pi * f2 * t + ph_c[1])      # (n, 3) in ±0.2
    centers = ROOM_SIZE * (0.5 + wiggle_c)
    rvecs = 0.23 * jnp.sin(two_pi * f1 * t + ph_r[0]) \
        + 0.12 * jnp.sin(two_pi * f2 * t + ph_r[1])      # (n, 3) in ±0.35
    Rs = rodrigues(rvecs)
    tvecs = -jnp.einsum("nij,nj->ni", Rs, centers)
    return rvecs, tvecs


def _ray_box_depth(origin: jnp.ndarray, dirs: jnp.ndarray) -> jnp.ndarray:
    """Depth along each ray to the first box wall hit from inside.

    origin: (3,) camera center in scene frame; dirs: (N, 3) ray directions in
    scene frame (unnormalized ok).  Returns (N,) parameter s with
    hit = origin + s * dirs.  Branchless slab method specialized for a camera
    inside the box: for each axis, the positive-s wall is the exit; take the
    min over axes.
    """
    safe = jnp.where(jnp.abs(dirs) < 1e-9, 1e-9, dirs)
    s_low = (0.0 - origin) / safe
    s_high = (ROOM_SIZE - origin) / safe
    s_exit = jnp.maximum(s_low, s_high)  # per-axis positive crossing
    return jnp.min(s_exit, axis=-1)


def _wall_texture(X: jnp.ndarray, texture_phase: float = 0.0) -> jnp.ndarray:
    """Procedural RGB texture of a scene point (N, 3) -> (N, 3) in [0, 1].

    Smooth, position-unique multi-frequency pattern: gives the expert enough
    visual signal to invert position from appearance.  ``texture_phase``
    shifts the pattern so different synthetic "scenes" look different (each
    ESAC expert owns one scene; the gating net must tell them apart).
    """
    freqs = jnp.array([1.3, 2.9, 0.7])
    phases = jnp.array([0.0, 1.1, 2.3]) + texture_phase
    r = 0.5 + 0.5 * jnp.sin(X @ jnp.array([1.7, 0.9, 2.3]) * freqs[0] + phases[0])
    g = 0.5 + 0.5 * jnp.sin(X @ jnp.array([0.6, 2.2, 1.1]) * freqs[1] + phases[1])
    b = 0.5 + 0.5 * jnp.sin(
        X @ jnp.array([2.9, 1.4, 0.5]) * (freqs[2] + 0.13 * texture_phase) + phases[2]
    )
    return jnp.stack([r, g, b], axis=-1)


def render_box_scene(
    rvec: jnp.ndarray,
    tvec: jnp.ndarray,
    height: int = 480,
    width: int = 640,
    f: float = CAMERA_F,
    c: tuple[float, float] = CAMERA_C,
    coord_stride: int = 8,
    texture_phase: float = 0.0,
) -> dict:
    """Render one frame of the box room.

    Returns dict with:
      'image'      (height, width, 3) RGB in [0,1],
      'coords_gt'  (n_cells, 3) scene coordinates at the output cell centers,
      'pixels'     (n_cells, 2) the cell centers,
      'rvec','tvec' the pose.
    """
    R = rodrigues(rvec)
    center = -R.T @ tvec  # camera center in scene frame

    def scene_points(px: jnp.ndarray) -> jnp.ndarray:
        cx = jnp.asarray(c)
        rays_cam = jnp.concatenate(
            [(px - cx) / f, jnp.ones_like(px[..., :1])], axis=-1
        )
        rays_scene = rays_cam @ R  # R^T applied to rows
        s = _ray_box_depth(center, rays_scene)
        return center + s[..., None] * rays_scene

    # Full-resolution image.
    ys = jnp.arange(height) + 0.5
    xs = jnp.arange(width) + 0.5
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    px_full = jnp.stack([gx, gy], axis=-1).reshape(-1, 2)
    img = _wall_texture(scene_points(px_full), texture_phase).reshape(height, width, 3)

    # Subsampled ground-truth coordinate map.
    pixels = output_pixel_grid(height, width, coord_stride)
    coords = scene_points(pixels)
    return {
        "image": img,
        "coords_gt": coords,
        "pixels": pixels,
        "rvec": rvec,
        "tvec": tvec,
    }


def make_correspondence_frame(
    key: jax.Array,
    height: int = 480,
    width: int = 640,
    stride: int = 8,
    noise: float = 0.0,
    outlier_frac: float = 0.0,
    f: float = CAMERA_F,
    c: tuple[float, float] = CAMERA_C,
) -> dict:
    """Geometry-only frame: GT pose + (noisy, partially corrupted) coords.

    Models what an imperfect expert would predict, without running a network:
    Gaussian noise of ``noise`` meters on all coordinates and a
    ``outlier_frac`` fraction replaced by uniform random room points.
    Returns dict with 'coords', 'coords_gt', 'pixels', 'rvec', 'tvec'.
    """
    k_pose, k_noise, k_out, k_pts = jax.random.split(key, 4)
    rvec, tvec = jax.tree.map(lambda a: a[0], random_poses_in_box(k_pose, 1))
    frame = render_box_scene(rvec, tvec, height, width, f, c, stride)
    coords = frame["coords_gt"]
    n = coords.shape[0]
    coords = coords + noise * jax.random.normal(k_noise, coords.shape)
    if outlier_frac > 0:
        outliers = ROOM_SIZE * jax.random.uniform(k_pts, (n, 3))
        is_out = jax.random.uniform(k_out, (n,)) < outlier_frac
        coords = jnp.where(is_out[:, None], outliers, coords)
    return {
        "coords": coords,
        "coords_gt": frame["coords_gt"],
        "pixels": frame["pixels"],
        "rvec": rvec,
        "tvec": tvec,
    }
