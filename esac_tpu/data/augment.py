"""Training-time augmentation: in-plane rotation, scale, brightness.

The reference augments stage-1 training with scale/rotation/brightness
jitter (SURVEY.md §2 #8, [P-med]).  Geometric augmentations must stay
consistent with the supervision:

- **in-plane rotation** by angle a: the image rotates; the ground-truth pose
  becomes ``Rz(a) @ (R, t)`` (camera rotates about its optical axis), and GT
  scene coordinates are resampled from the rotated coordinate map.  Here we
  rotate the *camera*, not the pixels: both image and coordinate map are
  resampled with the same inverse-rotation warp about the principal point.
- **scale** by s: resampling the image by s is equivalent to multiplying the
  focal length by s; the pose and scene coordinates are unchanged.
- **brightness/contrast**: photometric only.

All warps are bilinear ``jax.scipy.ndimage.map_coordinates`` on fixed grids
— static shapes, jit/vmap-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.ndimage import map_coordinates

from esac_tpu.geometry.rotations import rodrigues, so3_log
from esac_tpu.utils.precision import hmm


def _warp_resample(
    img: jnp.ndarray, angle: jnp.ndarray, scale: jnp.ndarray, order: int = 1
) -> jnp.ndarray:
    """Rotate by `angle` and zoom by `scale` about the center of (H, W, C)."""
    H, W = img.shape[:2]
    cy, cx = (H - 1) / 2.0, (W - 1) / 2.0
    ys = jnp.arange(H) - cy
    xs = jnp.arange(W) - cx
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ca, sa = jnp.cos(angle) / scale, jnp.sin(angle) / scale
    # Inverse warp: output pixel samples input at rotation by -angle, zoom 1/s.
    src_x = ca * gx - sa * gy + cx
    src_y = sa * gx + ca * gy + cy
    coords = jnp.stack([src_y.reshape(-1), src_x.reshape(-1)])

    def chan(c):
        return map_coordinates(img[..., c], coords, order=order, mode="nearest").reshape(H, W)

    return jnp.stack([chan(c) for c in range(img.shape[-1])], axis=-1)


def augment_frame(
    key: jax.Array,
    image: jnp.ndarray,
    coords_gt: jnp.ndarray,
    rvec: jnp.ndarray,
    tvec: jnp.ndarray,
    focal: jnp.ndarray,
    max_rotation_deg: float = 30.0,
    scale_range: tuple[float, float] = (0.8, 1.2),
    brightness: float = 0.15,
) -> dict:
    """Jointly augment (image, GT coords, pose, focal); returns a dict.

    image: (H, W, 3); coords_gt: (h, w, 3).  The returned pose/focal/coords
    remain geometrically consistent: reprojecting the new coords through the
    new pose/focal matches the new image.
    """
    k_rot, k_scale, k_bright = jax.random.split(key, 3)
    angle = jnp.radians(
        jax.random.uniform(k_rot, (), minval=-max_rotation_deg, maxval=max_rotation_deg)
    )
    scale = jax.random.uniform(k_scale, (), minval=scale_range[0], maxval=scale_range[1])
    gain = 1.0 + jax.random.uniform(k_bright, (), minval=-brightness, maxval=brightness)

    # One combined inverse warp, applied identically to image and coord map
    # (their continuous centers coincide for stride-aligned grids):
    # - rotation: with the warp new(q) = old(R(angle) q), the new camera is
    #   the old one rotated by -angle about its optical axis, so the
    #   scene->camera pose picks up Rz(-angle) on the left (projection
    #   commutes with in-plane rotation: proj(Rz(b) Y) = R(b) proj(Y));
    # - zoom about the principal point: exactly equivalent to focal *= scale,
    #   pose unchanged.
    image_aug = _warp_resample(image, angle, scale)
    coords_aug = _warp_resample(coords_gt, angle, scale)
    Rz = rodrigues(jnp.array([0.0, 0.0, -1.0]) * angle)
    R_new = hmm(Rz, rodrigues(rvec))
    t_new = hmm(Rz, tvec[:, None])[:, 0]

    image_aug = jnp.clip(image_aug * gain, 0.0, 1.0)
    return {
        "image": image_aug,
        "coords_gt": coords_aug,
        "rvec": so3_log(R_new),
        "tvec": t_new,
        "focal": focal * scale,
        "scale": scale,
    }
