"""Expert clustering: partition a large scene into expert regions.

Reference counterpart: the Aachen setup's k-means over ground-truth camera
positions, whose ~50 clusters define the experts (SURVEY.md §2 #15, §0).
The cluster assignment supplies (a) the GT expert label for gating training
and (b) each expert's ``scene_center``.  Deterministic k-means++ in numpy —
this runs once at dataset-setup time, not in the training hot path.
"""

from __future__ import annotations

import numpy as np


def kmeans_cluster_cameras(
    positions: np.ndarray,
    n_clusters: int,
    seed: int = 0,
    iters: int = 100,
) -> tuple[np.ndarray, np.ndarray]:
    """k-means over camera positions. positions: (N, 3).

    Returns (labels (N,), centers (n_clusters, 3)).  k-means++ init for
    stability, empty clusters re-seeded from the farthest point.
    """
    positions = np.asarray(positions, dtype=np.float64)
    n = positions.shape[0]
    if n_clusters > n:
        raise ValueError(f"{n_clusters} clusters for {n} cameras")
    rng = np.random.default_rng(seed)

    # k-means++ seeding.
    centers = [positions[rng.integers(n)]]
    for _ in range(1, n_clusters):
        d2 = np.min(
            ((positions[:, None] - np.stack(centers)[None]) ** 2).sum(-1), axis=1
        )
        prob = d2 / (d2.sum() + 1e-12)
        centers.append(positions[rng.choice(n, p=prob)])
    centers = np.stack(centers)

    labels = np.zeros(n, dtype=np.int64)
    for _ in range(iters):
        d2 = ((positions[:, None] - centers[None]) ** 2).sum(-1)
        new_labels = d2.argmin(axis=1)
        if np.array_equal(new_labels, labels) and _ > 0:
            break
        labels = new_labels
        for k in range(n_clusters):
            mask = labels == k
            if mask.any():
                centers[k] = positions[mask].mean(axis=0)
            else:
                # Re-seed an empty cluster at the point farthest from its center.
                far = d2.min(axis=1).argmax()
                centers[k] = positions[far]
    return labels.astype(np.int64), centers.astype(np.float32)


def cluster_scene(dataset, n_clusters: int, seed: int = 0):
    """Cluster a SceneDataset's frames into expert regions.

    Returns (labels, centers) using each frame's camera center -R^T t.
    """
    from esac_tpu.geometry.rotations import rodrigues
    import jax.numpy as jnp

    centers_cam = []
    for i in range(len(dataset)):
        f = dataset[i]
        R = np.asarray(rodrigues(jnp.asarray(f.rvec)))
        centers_cam.append(-R.T @ f.tvec)
    return kmeans_cluster_cameras(np.stack(centers_cam), n_clusters, seed)
