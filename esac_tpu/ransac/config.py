"""Hyperparameters of the hypothesis loop.

Defaults mirror the reference's standard configuration (SURVEY.md §0: n=256
hypotheses, tau ~ 10 px soft-inlier threshold, sigmoid sharpness beta,
selection temperature alpha; exact constants are [P-med] since the reference
mount was empty).  Everything is a static field so the config can be a
``static_argnum`` under jit.
"""

from __future__ import annotations

import dataclasses

# The valid RansacConfig.scoring_impl values — the single source the
# config validator and the kernel dispatches share.
SCORING_IMPLS = ("errmap", "fused", "pallas", "fused_select")


@dataclasses.dataclass(frozen=True)
class RansacConfig:
    # Number of pose hypotheses drawn per frame.
    n_hyps: int = 256
    # Soft-inlier reprojection threshold, pixels.
    tau: float = 10.0
    # Sigmoid sharpness of the soft-inlier count: sigmoid(beta * (tau - r)).
    beta: float = 0.5
    # Softmax temperature over scores for hypothesis selection in training.
    # 0.5 per the round-1 alpha sweep (experiments/generalization.py): sharp
    # selection trains best; 0.05 actively hurts.
    alpha: float = 0.5
    # IRLS (re-weighted Gauss-Newton) rounds when refining the winning pose.
    # The reference refines to convergence capped ~100 (SURVEY.md §3.5); 8
    # SATURATES here — measured at ref scale on the committed R3
    # checkpoints (.refine_sweep_{8,16,32,64}.json: 21.53% 5cm/5deg and
    # 3.07deg/8.44cm medians identical through 64, the 16+ legs moving
    # the trans median by 0.001 cm).  Soft-inlier IRLS from the argmax
    # hypothesis converges in single-digit rounds; extra rounds are pure
    # cost.
    refine_iters: int = 8
    # Light per-hypothesis refinement rounds inside the training expectation.
    train_refine_iters: int = 2
    # Gauss-Newton polish iterations inside the minimal solver.
    polish_iters: int = 3
    # Pose-loss translation weight: loss = max(rot_deg, trans_m * trans_scale).
    # 100.0 puts 1 cm == 1 degree, aligning with the 5cm/5deg metric.
    trans_scale: float = 100.0
    # Clamp on the per-hypothesis pose loss (degrees-equivalent units) so a
    # few wild hypotheses cannot dominate the training expectation.
    loss_clamp: float = 100.0
    # Score hypotheses on a random subset of this many cells (0 = all).
    # Selection is a statistical argmax over soft inlier counts; a 25%
    # subsample retains ample SNR to pick the winner while cutting the
    # dominant (scoring) stage's compute ~4x.  Refinement always uses every
    # cell, so final pose quality is unaffected.  The reference scores all
    # cells; keep 0 for strict parity.
    score_cells: int = 0
    # Scoring implementation:
    #   "errmap"       — reprojection_error_map (hmm matmul) + sigmoid-sum;
    #                    the reference-parity formulation, materializes
    #                    (H, N, 3) transformed points through the dot.
    #   "fused"        — one fused XLA broadcast+reduce program, f32
    #                    (pallas_scoring.soft_inlier_scores_fused): no
    #                    intermediate map in HBM, plain autodiff.
    #   "pallas"       — the hand-written Pallas VMEM kernel (custom_vjp).
    #   "fused_select" — fused score+SELECT: inference entry points stream
    #                    hypotheses through selection and never materialize
    #                    even the (H,) score vector (outputs carry the
    #                    winner's 'score' instead of 'scores').  On TPU the
    #                    Pallas VMEM select kernel runs; elsewhere the
    #                    chunked XLA sibling, whose winner is bit-identical
    #                    to the errmap argmax (ties included).  The TRAINING
    #                    path still needs every score for the softmax
    #                    expectation, so it runs the chunked+remat errmap
    #                    math (soft_inlier_scores_chunked): same numbers,
    #                    peak bytes bounded to one score_chunk tile.
    # NOTE: whatever the impl, inference-path scoring is CHUNKED over
    # hypothesis tiles (score_chunk) since ISSUE 8 — the full errmap never
    # materializes on any inference entry point; "errmap"/"fused" keep
    # their bit-identical (H,) scores output, materialized tile by tile.
    # A bf16 variant of "fused" was tried and REJECTED: bf16 ULP on rotation
    # entries (~4e-3) shifts every projected cell of a hypothesis by ~2 px
    # systematically, and the correlated sigmoid shifts summed over thousands
    # of cells measured a 10% score deviation at full resolution — enough to
    # flip argmax winners.  Scoring precision stays f32.
    # Default is decided by the hardware A/B (tools/pallas_ab.py); "errmap"
    # until a measured win is recorded in .pallas_ab.json.
    scoring_impl: str = "errmap"
    # DEPRECATED back-compat alias: True is resolved to
    # scoring_impl="pallas" (and the flag reset to False) in __post_init__ —
    # the ONE normalization point, so kernels read only scoring_impl and the
    # two spellings hash to the same static-arg config.  Prefer
    # scoring_impl="pallas"; this field will eventually go away.
    use_pallas_scoring: bool = False
    # Hypothesis-tile size for chunked/streamed scoring+selection: the
    # largest live scoring intermediate on inference entries (and the
    # fused_select training path) is (score_chunk, n_cells) instead of
    # (n_hyps, n_cells).  Per-hypothesis numbers are tile-size-invariant
    # (independent reductions), so this knob trades scan trip count against
    # peak bytes without touching results.  Clamped to n_hyps.
    score_chunk: int = 64
    # Differentiate the training expectation through the per-hypothesis
    # refined pose losses (autodiff-through-IRLS — the jax replacement for
    # the reference's central-difference machinery).  False restricts the
    # coords gradient to the score/selection path — a cheaper-backward
    # ablation.  NOTE: the cpp training backward includes the loss path too
    # (finite differences through the solve), so the jax-vs-cpp gradient
    # parity recipe is grad_through_refine=True with train_refine_iters=0
    # (see tests/test_backend_equivalence.py), NOT this flag.
    grad_through_refine: bool = True
    # Rematerialize the per-hypothesis refinement in the backward pass
    # (jax.checkpoint): trades ~2x refine FLOPs for O(n_hyps * n_cells)
    # activation memory — needed for config-#5-scale training
    # (4096 hypotheses x 4800 cells) on one chip's HBM.
    remat: bool = False
    # ---- Frame-axis serving knobs (esac_tpu.serve; DESIGN.md §9) ----
    # NOTE: like every field here, these participate in the config's
    # static-arg hash — a config with different serve knobs is a new
    # compiled-program family even for kernels that never read them.  Pick
    # the knobs once per serving process (build the serve fn, keep it);
    # don't tune queue knobs against a live jit cache.
    # Allowed frame-batch sizes for the micro-batching dispatcher.  Every
    # dispatch is padded up to one of these, so jit compiles exactly one
    # program per bucket (static shapes) no matter how requests arrive.
    frame_buckets: tuple[int, ...] = (1, 4, 16, 64)
    # How long the dispatcher's worker holds the FIRST queued request while
    # waiting for more frames to fill a bucket.  0 disables coalescing
    # (every request dispatches alone — per-frame-call semantics).
    serve_max_wait_ms: float = 2.0
    # Backpressure bound on queued-but-undispatched requests; submitters
    # block (never drop) once the queue is full.
    serve_queue_depth: int = 256
    # ---- Gating-first routed serving knobs (DESIGN.md §11) ----
    # Default top-K experts evaluated per frame by the routed serve programs
    # (registry.make_routed_scene_bucket_fn).  0 = dense serving (all M
    # experts); K = M routes identically to dense (pinned bit-identical).
    # The hypothesis budget is reallocated so total hypotheses per frame
    # stay fixed: each evaluated expert runs n_hyps * M // K hypotheses.
    serve_topk: int = 0
    # Frame capacity of each expert's CNN block in the routed serve
    # programs: at most this many frames run through one expert per
    # dispatch; overflow (frame-index priority, latest frames drop first)
    # is recorded in `experts_evaluated`.  0 = auto:
    # ceil(2 * K * max_bucket / M), i.e. 2x the balanced per-expert load
    # at the LARGEST frame bucket — deliberately bucket-independent, since
    # a capacity that varied with the frame bucket would let padding
    # change which (frame, expert) pairs survive and break the
    # bucket-invariance contract (see ransac.esac.routed_serve_capacity).
    serve_capacity: int = 0

    def __post_init__(self):
        # The ONE resolution point for the deprecated use_pallas_scoring
        # alias: fold it into scoring_impl so no call site re-derives the
        # dispatch (and both spellings hash identically as static args).
        if self.use_pallas_scoring:
            object.__setattr__(self, "scoring_impl", "pallas")
            object.__setattr__(self, "use_pallas_scoring", False)
        if self.scoring_impl not in SCORING_IMPLS:
            raise ValueError(
                f"unknown RansacConfig.scoring_impl: {self.scoring_impl!r} "
                f"(valid: {SCORING_IMPLS})"
            )
