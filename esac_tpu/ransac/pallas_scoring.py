"""Fused Pallas TPU kernel for soft-inlier scoring.

The scoring stage — transform every cell by every hypothesis pose, project,
take the pixel error, sigmoid, reduce — is the FLOP- and bandwidth-dominant
stage of the pipeline once the minimal solves are optimized.  The XLA
version materializes the (n_hyps, n_cells) error map in HBM between fusions;
this kernel keeps everything in VMEM and writes only the (n_hyps,) scores.

Layout (see /opt/skills/guides/pallas_guide.md):
- hypotheses ride the sublane axis in blocks of 8 (f32 native tile height),
  poses packed as 12 floats (row-major R | t) per hypothesis;
- cells ride the lane axis in blocks of 512 (multiples of 128), coordinates
  and pixels pre-transposed to (3, N) / (2, N);
- the cell-block grid dimension is innermost and accumulates into the same
  (8, 1) output block (TPU grids are sequential, so revisiting is safe);
- the transform is done as broadcast outer products on the VPU — a (8, 512)
  tile of Y per axis from (8, 1) pose columns x (1, 512) coordinate rows —
  deliberately NOT an MXU matmul: K=3 contraction wastes the systolic array.

Gated behind ``RansacConfig.use_pallas_scoring`` (default off) until
validated on hardware; ``interpret=True`` runs the same kernel on CPU for
the equivalence tests.

Differentiable: a ``jax.custom_vjp`` pairs the fused forward with an
analytic XLA backward that recomputes the kernel's math op-for-op in f32
broadcast products (``_scores_xla_mirror``) and differentiates it — the
scoring backward is itself one fused elementwise+reduce XLA program, so a
hand-written backward kernel would save only the recompute, not a second
HBM round trip.  Training paths may therefore enable the kernel too.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from esac_tpu.geometry.camera import MIN_DEPTH

HYP_BLOCK = 8
CELL_BLOCK = 512


def _score_kernel(scal_ref, pose_ref, coords_ref, pixels_ref, out_ref):
    """One (hyp-block, cell-block) tile of fused transform+project+score.

    scal_ref: (5, 1) SMEM — f, cx, cy, tau, beta.
    pose_ref: (HYP_BLOCK, 12) VMEM — rows [R00..R22, t0, t1, t2].
    coords_ref: (3, CELL_BLOCK) VMEM;  pixels_ref: (2, CELL_BLOCK) VMEM.
    out_ref: (HYP_BLOCK, 1) VMEM — accumulated over the cell grid dim.
    """
    f = scal_ref[0, 0]
    cx = scal_ref[1, 0]
    cy = scal_ref[2, 0]
    tau = scal_ref[3, 0]
    beta = scal_ref[4, 0]

    X0 = coords_ref[0, :][None, :]  # (1, C)
    X1 = coords_ref[1, :][None, :]
    X2 = coords_ref[2, :][None, :]
    px = pixels_ref[0, :][None, :]
    py = pixels_ref[1, :][None, :]

    def col(k):  # (H, 1) pose column
        return pose_ref[:, k][:, None]

    # Y = R X + t, broadcast (H,1) x (1,C) -> (H,C) per axis on the VPU.
    Yx = col(0) * X0 + col(1) * X1 + col(2) * X2 + col(9)
    Yy = col(3) * X0 + col(4) * X1 + col(5) * X2 + col(10)
    Yz = col(6) * X0 + col(7) * X1 + col(8) * X2 + col(11)

    z = jnp.maximum(Yz, MIN_DEPTH)
    du = f * Yx / z + cx - px
    dv = f * Yy / z + cy - py
    err = jnp.sqrt(du * du + dv * dv + 1e-12)
    err = jnp.where(Yz < MIN_DEPTH, err + 1000.0, err)
    partial_scores = jnp.sum(
        jax.nn.sigmoid(beta * (tau - err)), axis=1, keepdims=True
    )  # (H, 1)

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = partial_scores

    @pl.when(j > 0)
    def _acc():
        out_ref[:] = out_ref[:] + partial_scores


def _pad_to(x: jnp.ndarray, axis: int, multiple: int, value: float) -> jnp.ndarray:
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad, constant_values=value)


def _scores_pallas_raw(
    Rs: jnp.ndarray,
    ts: jnp.ndarray,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    tau: float,
    beta: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused soft-inlier scores. Rs: (H, 3, 3), ts: (H, 3), coords: (N, 3),
    pixels: (N, 2).  Returns (H,) float32 scores.

    Padding cells are placed far behind the camera (err ~ 2000 px), so their
    sigmoid contribution underflows to exactly 0 and no correction is needed;
    padded hypotheses are sliced off the result.
    """
    H = Rs.shape[0]
    poses = jnp.concatenate(
        [Rs.reshape(H, 9), ts.reshape(H, 3)], axis=1
    ).astype(jnp.float32)
    poses = _pad_to(poses, 0, HYP_BLOCK, 0.0)

    coords_t = coords.T.astype(jnp.float32)  # (3, N)
    pixels_t = pixels.T.astype(jnp.float32)  # (2, N)
    # Pad coordinates with a point far behind any camera: Y = R*X + t with
    # X = 0 and identity-ish padding poses gives z = 0 < MIN_DEPTH -> the
    # +1000 px branch -> sigmoid(beta*(tau - ~1000)) == 0 in f32.
    coords_t = _pad_to(coords_t, 1, CELL_BLOCK, 0.0)
    pixels_t = _pad_to(pixels_t, 1, CELL_BLOCK, 1e6)
    Hp = poses.shape[0]
    Np = coords_t.shape[1]

    scalars = jnp.stack(
        [jnp.float32(f), c[0].astype(jnp.float32), c[1].astype(jnp.float32),
         jnp.float32(tau), jnp.float32(beta)]
    ).reshape(5, 1)

    grid = (Hp // HYP_BLOCK, Np // CELL_BLOCK)
    out = pl.pallas_call(
        _score_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((5, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((HYP_BLOCK, 12), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, CELL_BLOCK), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, CELL_BLOCK), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((HYP_BLOCK, 1), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Hp, 1), jnp.float32),
        interpret=interpret,
    )(scalars, poses, coords_t, pixels_t)
    return out[:H, 0]


def soft_inlier_scores_fused(Rs, ts, coords, pixels, f, c, tau, beta):
    """Fused soft-inlier scores as ONE XLA elementwise+reduce program.

    The kernel's math, op-for-op, as plain XLA: same broadcast-product
    transform, same MIN_DEPTH clamp, eps and behind-camera penalty as
    ``_score_kernel``.  Broadcast products, not einsum/hmm: the K=3
    contraction would otherwise hit the MXU as a separate dot (materializing
    the (H, N, 3) transformed points in HBM); as broadcasts the whole chain
    fuses into a single reduce with no intermediate map.  Selectable via
    ``RansacConfig.scoring_impl = "fused"``; differentiable by plain
    autodiff.

    Everything is f32 deliberately — a bf16-transform variant was measured
    at 10% score deviation at full resolution (systematic per-hypothesis
    bias from rotation-entry quantization; see RansacConfig.scoring_impl).
    """
    Rsf = Rs.reshape(Rs.shape[0], 9).astype(jnp.float32)
    tsf = ts.astype(jnp.float32)
    X0 = coords[:, 0].astype(jnp.float32)[None, :]  # (1, N)
    X1 = coords[:, 1].astype(jnp.float32)[None, :]
    X2 = coords[:, 2].astype(jnp.float32)[None, :]
    px = pixels[:, 0].astype(jnp.float32)[None, :]
    py = pixels[:, 1].astype(jnp.float32)[None, :]
    f = jnp.asarray(f).astype(jnp.float32)
    cx = jnp.asarray(c[0]).astype(jnp.float32)
    cy = jnp.asarray(c[1]).astype(jnp.float32)

    def col(k):
        return Rsf[:, k][:, None]  # (H, 1)

    Yx = col(0) * X0 + col(1) * X1 + col(2) * X2 + tsf[:, 0][:, None]
    Yy = col(3) * X0 + col(4) * X1 + col(5) * X2 + tsf[:, 1][:, None]
    Yz = col(6) * X0 + col(7) * X1 + col(8) * X2 + tsf[:, 2][:, None]
    z = jnp.maximum(Yz, MIN_DEPTH)
    du = f * Yx / z + cx - px
    dv = f * Yy / z + cy - py
    err = jnp.sqrt(du * du + dv * dv + 1e-12)
    err = jnp.where(Yz < MIN_DEPTH, err + 1000.0, err)
    return jnp.sum(jax.nn.sigmoid(beta * (tau - err)), axis=1)


def _scores_xla_mirror(Rs, ts, coords, pixels, f, c, tau, beta):
    """f32 fused scores — the custom_vjp backward recompute for the Pallas
    kernel (gradients *of the kernel's math*, not a subtly different
    formula)."""
    return soft_inlier_scores_fused(Rs, ts, coords, pixels, f, c, tau, beta)


@partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _scores_pallas_vjp(Rs, ts, coords, pixels, f, c, tau, beta, interpret):
    return _scores_pallas_raw(Rs, ts, coords, pixels, f, c, tau, beta,
                              interpret)


def _scores_fwd(Rs, ts, coords, pixels, f, c, tau, beta, interpret):
    out = _scores_pallas_raw(Rs, ts, coords, pixels, f, c, tau, beta,
                             interpret)
    return out, (Rs, ts, coords, pixels, f, c)


def _scores_bwd(tau, beta, interpret, res, g):
    Rs, ts, coords, pixels, f, c = res
    _, vjp = jax.vjp(
        lambda *args: _scores_xla_mirror(*args, tau, beta),
        Rs, ts, coords, pixels, f, c,
    )
    return vjp(g)


_scores_pallas_vjp.defvjp(_scores_fwd, _scores_bwd)


@partial(jax.jit, static_argnames=("tau", "beta", "interpret"))
def soft_inlier_scores_pallas(
    Rs: jnp.ndarray,
    ts: jnp.ndarray,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    tau: float,
    beta: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Differentiable fused soft-inlier scores (see ``_scores_pallas_raw``
    for shapes and padding semantics; gradients via ``_scores_bwd``)."""
    return _scores_pallas_vjp(Rs, ts, coords, pixels,
                              jnp.float32(f), jnp.asarray(c, jnp.float32),
                              tau, beta, interpret)
