"""Fused Pallas TPU kernel for soft-inlier scoring.

The scoring stage — transform every cell by every hypothesis pose, project,
take the pixel error, sigmoid, reduce — is the FLOP- and bandwidth-dominant
stage of the pipeline once the minimal solves are optimized.  The XLA
version materializes the (n_hyps, n_cells) error map in HBM between fusions;
this kernel keeps everything in VMEM and writes only the (n_hyps,) scores.

Layout (see /opt/skills/guides/pallas_guide.md):
- hypotheses ride the sublane axis in blocks of 8 (f32 native tile height),
  poses packed as 12 floats (row-major R | t) per hypothesis;
- cells ride the lane axis in blocks of 512 (multiples of 128), coordinates
  and pixels pre-transposed to (3, N) / (2, N);
- the cell-block grid dimension is innermost and accumulates into the same
  (8, 1) output block (TPU grids are sequential, so revisiting is safe);
- the transform is done as broadcast outer products on the VPU — a (8, 512)
  tile of Y per axis from (8, 1) pose columns x (1, 512) coordinate rows —
  deliberately NOT an MXU matmul: K=3 contraction wastes the systolic array.

Gated behind ``RansacConfig.use_pallas_scoring`` (default off) until
validated on hardware; ``interpret=True`` runs the same kernel on CPU for
the equivalence tests.

Differentiable: a ``jax.custom_vjp`` pairs the fused forward with an
analytic XLA backward that recomputes the kernel's math op-for-op in f32
broadcast products (``_scores_xla_mirror``) and differentiates it — the
scoring backward is itself one fused elementwise+reduce XLA program, so a
hand-written backward kernel would save only the recompute, not a second
HBM round trip.  Training paths may therefore enable the kernel too.

ISSUE 8 adds the streaming SELECTION layer on top: a fused score+select
kernel (``soft_inlier_score_select`` / ``_score_select_kernel``) that
never writes even the (H,) score vector to HBM, its chunked XLA sibling
(bit-identical to the errmap argmax, CPU-measurable today), and the
chunked all-scores variant (``soft_inlier_scores_chunked``) that bounds
the training path's peak bytes to one hypothesis tile.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from esac_tpu.geometry.camera import MIN_DEPTH, reprojection_errors
from esac_tpu.ransac.scoring import reprojection_error_map, soft_inlier_score

HYP_BLOCK = 8
CELL_BLOCK = 512


def _tile_partial_scores(scal_ref, pose_ref, coords_ref, pixels_ref):
    """One (hyp-block, cell-block) tile of fused transform+project+score:
    the shared VPU body of the scoring and score+select kernels.

    scal_ref: (5, 1) SMEM — f, cx, cy, tau, beta.
    pose_ref: (HYP_BLOCK, 12) VMEM — rows [R00..R22, t0, t1, t2].
    coords_ref: (3, CELL_BLOCK) VMEM;  pixels_ref: (2, CELL_BLOCK) VMEM.
    Returns (HYP_BLOCK, 1) partial soft-inlier scores for this cell block.
    """
    f = scal_ref[0, 0]
    cx = scal_ref[1, 0]
    cy = scal_ref[2, 0]
    tau = scal_ref[3, 0]
    beta = scal_ref[4, 0]

    X0 = coords_ref[0, :][None, :]  # (1, C)
    X1 = coords_ref[1, :][None, :]
    X2 = coords_ref[2, :][None, :]
    px = pixels_ref[0, :][None, :]
    py = pixels_ref[1, :][None, :]

    def col(k):  # (H, 1) pose column
        return pose_ref[:, k][:, None]

    # Y = R X + t, broadcast (H,1) x (1,C) -> (H,C) per axis on the VPU.
    Yx = col(0) * X0 + col(1) * X1 + col(2) * X2 + col(9)
    Yy = col(3) * X0 + col(4) * X1 + col(5) * X2 + col(10)
    Yz = col(6) * X0 + col(7) * X1 + col(8) * X2 + col(11)

    z = jnp.maximum(Yz, MIN_DEPTH)
    du = f * Yx / z + cx - px
    dv = f * Yy / z + cy - py
    err = jnp.sqrt(du * du + dv * dv + 1e-12)
    err = jnp.where(Yz < MIN_DEPTH, err + 1000.0, err)
    return jnp.sum(
        jax.nn.sigmoid(beta * (tau - err)), axis=1, keepdims=True
    )  # (H, 1)


def _score_kernel(scal_ref, pose_ref, coords_ref, pixels_ref, out_ref):
    """Scoring-only kernel: accumulate tile scores over the cell grid dim
    into out_ref (HYP_BLOCK, 1)."""
    partial_scores = _tile_partial_scores(
        scal_ref, pose_ref, coords_ref, pixels_ref
    )

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = partial_scores

    @pl.when(j > 0)
    def _acc():
        out_ref[:] = out_ref[:] + partial_scores


def _pad_to(x: jnp.ndarray, axis: int, multiple: int, value: float) -> jnp.ndarray:
    n = x.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad, constant_values=value)


def _stage_pallas_inputs(Rs, ts, coords, pixels, f, c, tau, beta):
    """Pack poses/coords/pixels/scalars into the kernels' padded VMEM/SMEM
    layout.  Returns (poses (Hp, 12), coords_t (3, Np), pixels_t (2, Np),
    scalars (5, 1)).

    Padding cells are placed far behind the camera (err ~ 2000 px), so their
    sigmoid contribution underflows to exactly 0 and no correction is
    needed; padded (all-zero) poses give z = 0 < MIN_DEPTH -> the +1000 px
    branch -> score exactly 0 (callers slice or mask them off).
    """
    H = Rs.shape[0]
    poses = jnp.concatenate(
        [Rs.reshape(H, 9), ts.reshape(H, 3)], axis=1
    ).astype(jnp.float32)
    poses = _pad_to(poses, 0, HYP_BLOCK, 0.0)

    coords_t = coords.T.astype(jnp.float32)  # (3, N)
    pixels_t = pixels.T.astype(jnp.float32)  # (2, N)
    coords_t = _pad_to(coords_t, 1, CELL_BLOCK, 0.0)
    pixels_t = _pad_to(pixels_t, 1, CELL_BLOCK, 1e6)

    scalars = jnp.stack(
        [jnp.float32(f), c[0].astype(jnp.float32), c[1].astype(jnp.float32),
         jnp.float32(tau), jnp.float32(beta)]
    ).reshape(5, 1)
    return poses, coords_t, pixels_t, scalars


def _scores_pallas_raw(
    Rs: jnp.ndarray,
    ts: jnp.ndarray,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    tau: float,
    beta: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused soft-inlier scores. Rs: (H, 3, 3), ts: (H, 3), coords: (N, 3),
    pixels: (N, 2).  Returns (H,) float32 scores.

    Padding semantics: see :func:`_stage_pallas_inputs` (padded cells score
    exactly 0; padded hypotheses are sliced off the result).
    """
    H = Rs.shape[0]
    poses, coords_t, pixels_t, scalars = _stage_pallas_inputs(
        Rs, ts, coords, pixels, f, c, tau, beta
    )
    Hp = poses.shape[0]
    Np = coords_t.shape[1]

    grid = (Hp // HYP_BLOCK, Np // CELL_BLOCK)
    out = pl.pallas_call(
        _score_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((5, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((HYP_BLOCK, 12), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, CELL_BLOCK), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, CELL_BLOCK), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((HYP_BLOCK, 1), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Hp, 1), jnp.float32),
        interpret=interpret,
    )(scalars, poses, coords_t, pixels_t)
    return out[:H, 0]


def soft_inlier_scores_fused(Rs, ts, coords, pixels, f, c, tau, beta):
    """Fused soft-inlier scores as ONE XLA elementwise+reduce program.

    The kernel's math, op-for-op, as plain XLA: same broadcast-product
    transform, same MIN_DEPTH clamp, eps and behind-camera penalty as
    ``_score_kernel``.  Broadcast products, not einsum/hmm: the K=3
    contraction would otherwise hit the MXU as a separate dot (materializing
    the (H, N, 3) transformed points in HBM); as broadcasts the whole chain
    fuses into a single reduce with no intermediate map.  Selectable via
    ``RansacConfig.scoring_impl = "fused"``; differentiable by plain
    autodiff.

    Everything is f32 deliberately — a bf16-transform variant was measured
    at 10% score deviation at full resolution (systematic per-hypothesis
    bias from rotation-entry quantization; see RansacConfig.scoring_impl).
    """
    Rsf = Rs.reshape(Rs.shape[0], 9).astype(jnp.float32)
    tsf = ts.astype(jnp.float32)
    X0 = coords[:, 0].astype(jnp.float32)[None, :]  # (1, N)
    X1 = coords[:, 1].astype(jnp.float32)[None, :]
    X2 = coords[:, 2].astype(jnp.float32)[None, :]
    px = pixels[:, 0].astype(jnp.float32)[None, :]
    py = pixels[:, 1].astype(jnp.float32)[None, :]
    f = jnp.asarray(f).astype(jnp.float32)
    cx = jnp.asarray(c[0]).astype(jnp.float32)
    cy = jnp.asarray(c[1]).astype(jnp.float32)

    def col(k):
        return Rsf[:, k][:, None]  # (H, 1)

    Yx = col(0) * X0 + col(1) * X1 + col(2) * X2 + tsf[:, 0][:, None]
    Yy = col(3) * X0 + col(4) * X1 + col(5) * X2 + tsf[:, 1][:, None]
    Yz = col(6) * X0 + col(7) * X1 + col(8) * X2 + tsf[:, 2][:, None]
    z = jnp.maximum(Yz, MIN_DEPTH)
    du = f * Yx / z + cx - px
    dv = f * Yy / z + cy - py
    err = jnp.sqrt(du * du + dv * dv + 1e-12)
    err = jnp.where(Yz < MIN_DEPTH, err + 1000.0, err)
    return jnp.sum(jax.nn.sigmoid(beta * (tau - err)), axis=1)


def _scores_xla_mirror(Rs, ts, coords, pixels, f, c, tau, beta):
    """f32 fused scores — the custom_vjp backward recompute for the Pallas
    kernel (gradients *of the kernel's math*, not a subtly different
    formula)."""
    return soft_inlier_scores_fused(Rs, ts, coords, pixels, f, c, tau, beta)


@partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _scores_pallas_vjp(Rs, ts, coords, pixels, f, c, tau, beta, interpret):
    return _scores_pallas_raw(Rs, ts, coords, pixels, f, c, tau, beta,
                              interpret)


def _scores_fwd(Rs, ts, coords, pixels, f, c, tau, beta, interpret):
    out = _scores_pallas_raw(Rs, ts, coords, pixels, f, c, tau, beta,
                             interpret)
    return out, (Rs, ts, coords, pixels, f, c)


def _scores_bwd(tau, beta, interpret, res, g):
    Rs, ts, coords, pixels, f, c = res
    _, vjp = jax.vjp(
        lambda *args: _scores_xla_mirror(*args, tau, beta),
        Rs, ts, coords, pixels, f, c,
    )
    return vjp(g)


_scores_pallas_vjp.defvjp(_scores_fwd, _scores_bwd)


@partial(jax.jit, static_argnames=("tau", "beta", "interpret"))
def soft_inlier_scores_pallas(
    Rs: jnp.ndarray,
    ts: jnp.ndarray,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    tau: float,
    beta: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Differentiable fused soft-inlier scores (see ``_scores_pallas_raw``
    for shapes and padding semantics; gradients via ``_scores_bwd``)."""
    return _scores_pallas_vjp(Rs, ts, coords, pixels,
                              jnp.float32(f), jnp.asarray(c, jnp.float32),
                              tau, beta, interpret)


# --------------------------------------------------------------------------
# Fused score+select: stream hypotheses through selection (ROADMAP item 3).
#
# The errmap — and even the (H,) score vector — never round-trips through
# HBM: hypothesis blocks tile through VMEM carrying a running (max score,
# argmax index, winner pose) accumulator.  Selection tie-breaking matches
# ``jnp.argmax`` bit-for-bit: within a block the FIRST max wins (index-min
# over the block's maxima), across blocks only a strictly greater score
# displaces the running winner, and blocks are visited in index order
# (TPU grids are sequential).

# Index sentinel for the within-block tie-break min (far above any H).
_IDX_INF = 2 ** 30


def _score_select_kernel(scal_ref, nhyp_ref, pose_ref, coords_ref,
                         pixels_ref, best_score_ref, best_idx_ref,
                         best_pose_ref, acc_ref):
    """Fused score+select: accumulate each hyp block's scores over the cell
    grid dim in VMEM scratch, then fold the completed block into the
    running (max score, argmax index, winner pose) outputs.

    nhyp_ref: (1, 1) SMEM int32 — the REAL hypothesis count H (padded rows
    beyond it can never win).  best_score_ref (1, 1) f32, best_idx_ref
    (1, 1) int32, best_pose_ref (1, 12) f32: revisited every grid step
    (constant index_map), so they act as the cross-block accumulator.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    partial_scores = _tile_partial_scores(
        scal_ref, pose_ref, coords_ref, pixels_ref
    )

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _init_running():
        best_score_ref[0, 0] = jnp.float32(-jnp.inf)
        best_idx_ref[0, 0] = jnp.int32(0)
        best_pose_ref[:] = jnp.zeros_like(best_pose_ref)

    @pl.when(j == 0)
    def _init_acc():
        acc_ref[:] = partial_scores

    @pl.when(j > 0)
    def _acc():
        acc_ref[:] = acc_ref[:] + partial_scores

    @pl.when(j == nj - 1)
    def _fold_block():
        gidx = i * HYP_BLOCK + jax.lax.broadcasted_iota(
            jnp.int32, (HYP_BLOCK, 1), 0
        )
        valid = gidx < nhyp_ref[0, 0]
        s = jnp.where(valid, acc_ref[:], -jnp.inf)  # (HYP_BLOCK, 1)
        bmax = jnp.max(s)
        # First max wins inside the block (jnp.argmax contract).
        bidx = jnp.min(jnp.where(s == bmax, gidx, jnp.int32(_IDX_INF)))
        bpose = jnp.sum(
            jnp.where(gidx == bidx, pose_ref[:], 0.0),
            axis=0, keepdims=True,
        )  # (1, 12)

        # Strictly greater only: an equal later block never displaces the
        # earlier winner.  Block 0 always wins over the -inf init (every
        # kernel call has >= 1 real hypothesis in block 0).
        @pl.when(bmax > best_score_ref[0, 0])
        def _update():
            best_score_ref[0, 0] = bmax
            best_idx_ref[0, 0] = bidx
            best_pose_ref[:] = bpose


def _select_pallas_raw(
    Rs: jnp.ndarray,
    ts: jnp.ndarray,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    tau: float,
    beta: float,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused score+select over all hypotheses.  Shapes as in
    ``_scores_pallas_raw``; returns (best_idx () int32, best_score () f32,
    best_pose (12,) f32 — the winner's packed [R | t] row, bit-identical
    to the input row it was copied from)."""
    H = Rs.shape[0]
    poses, coords_t, pixels_t, scalars = _stage_pallas_inputs(
        Rs, ts, coords, pixels, f, c, tau, beta
    )
    Hp = poses.shape[0]
    Np = coords_t.shape[1]
    nhyp = jnp.full((1, 1), H, jnp.int32)

    grid = (Hp // HYP_BLOCK, Np // CELL_BLOCK)
    best_score, best_idx, best_pose = pl.pallas_call(
        _score_select_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((5, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((HYP_BLOCK, 12), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, CELL_BLOCK), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, CELL_BLOCK), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 12), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 12), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((HYP_BLOCK, 1), jnp.float32)],
        interpret=interpret,
    )(scalars, nhyp, poses, coords_t, pixels_t)
    return best_idx[0, 0], best_score[0, 0], best_pose[0]


def _hyp_tiles(chunk: int, *arrays):
    """Pad the shared leading (hypothesis) axis to a multiple of
    ``min(chunk, H)`` with zeros and reshape each array to
    (n_tiles, tile, ...).  Returns (tile, [tiled arrays]).

    Padded rows are finite GARBAGE, not guaranteed-zero scores: for
    rotation-matrix callers a zero R gives the behind-camera penalty
    (score exactly 0), but for axis-angle callers rodrigues(0) is the
    IDENTITY rotation and the padded row scores whatever X at t=0
    projects to.  Every caller must therefore mask padded indices out of
    selection (``gidx < H``) or slice the stacked result to ``[:H]`` —
    never reduce over the padded axis directly."""
    H = arrays[0].shape[0]
    T = int(max(1, min(chunk, H)))
    rem = (-H) % T
    out = []
    for a in arrays:
        if rem:
            a = jnp.concatenate(
                [a, jnp.zeros((rem,) + a.shape[1:], a.dtype)], axis=0
            )
        out.append(a.reshape((a.shape[0] // T, T) + a.shape[1:]))
    return T, out


def _select_chunked_raw(Rs, ts, coords, pixels, f, c, tau, beta, chunk):
    """Streaming score+select in plain XLA — the CPU-measurable sibling of
    the Pallas kernel: ``lax.scan`` over hypothesis tiles of the ERRMAP
    formulation (``reprojection_errors`` + sigmoid-sum, so per-hypothesis
    scores are bit-identical to the materializing "errmap" impl), carrying
    a running (max score, argmax index).  Tie-breaking matches
    ``jnp.argmax`` bit-for-bit: within a tile ``jnp.argmax`` picks the
    first max; across tiles only strictly-greater displaces.  Returns
    (best_idx () int32, best_score () f32)."""
    H = Rs.shape[0]
    T, (R_tiles, t_tiles) = _hyp_tiles(chunk, Rs, ts)

    def tile_scores(R_tile, t_tile):
        errs = jax.vmap(
            lambda R, t: reprojection_errors(R, t, coords, pixels, f, c)
        )(R_tile, t_tile)
        return soft_inlier_score(errs, tau, beta)

    def step(carry, xs):
        best_s, best_i, off = carry
        s = tile_scores(*xs)
        gidx = off + jnp.arange(T, dtype=jnp.int32)
        s = jnp.where(gidx < H, s, -jnp.inf)
        ti = jnp.argmax(s)
        take = s[ti] > best_s
        return (
            jnp.where(take, s[ti], best_s),
            jnp.where(take, gidx[ti], best_i),
            off + T,
        ), None

    init = (jnp.float32(-jnp.inf), jnp.int32(0), jnp.int32(0))
    (best_s, best_i, _), _ = jax.lax.scan(step, init, (R_tiles, t_tiles))
    return best_i, best_s


def soft_inlier_scores_chunked(rvecs, tvecs, coords, pixels, f, c, tau,
                               beta, impl: str = "errmap",
                               chunk: int = 64) -> jnp.ndarray:
    """All-hypotheses scores with the hypothesis axis tiled through a
    ``lax.scan``: per-hypothesis numbers bit-identical to the materializing
    ``impl`` ("errmap" | "fused") — each hypothesis's score is an
    independent reduction over cells, so tiling the batch axis changes no
    arithmetic — while the largest live intermediate is one
    (tile, n_cells) error tile instead of the full errmap.  Each tile is
    ``jax.checkpoint``'d so the BACKWARD pass recomputes tiles too instead
    of stacking per-step residuals back up to errmap size (the training
    path's bounded-peak-bytes contract under scoring_impl="fused_select").

    Takes axis-angle ``rvecs`` like the errmap path (rodrigues applied
    per tile is bit-identical to applying it to the full array — it is
    elementwise per hypothesis).  Returns (H,) scores.
    """
    H = rvecs.shape[0]
    _, (rv_tiles, tv_tiles) = _hyp_tiles(chunk, rvecs, tvecs)

    def tile_scores(rv, tv):
        if impl == "fused":
            from esac_tpu.geometry.rotations import rodrigues

            return soft_inlier_scores_fused(
                rodrigues(rv), tv, coords, pixels, f, c, tau, beta
            )
        errs = reprojection_error_map(rv, tv, coords, pixels, f, c)
        return soft_inlier_score(errs, tau, beta)

    tile_scores = jax.checkpoint(tile_scores)

    def step(carry, xs):
        return carry, tile_scores(*xs)

    _, ys = jax.lax.scan(step, None, (rv_tiles, tv_tiles))
    return ys.reshape(-1)[:H]


@partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _score_select(Rs, ts, coords, pixels, f, c, tau, beta, use_pallas,
                  chunk, interpret):
    if use_pallas:
        best_i, best_s, _ = _select_pallas_raw(
            Rs, ts, coords, pixels, f, c, tau, beta, interpret
        )
        return best_i, best_s
    return _select_chunked_raw(Rs, ts, coords, pixels, f, c, tau, beta,
                               chunk)


def _select_fwd(Rs, ts, coords, pixels, f, c, tau, beta, use_pallas, chunk,
                interpret):
    best_i, best_s = _score_select(Rs, ts, coords, pixels, f, c, tau, beta,
                                   use_pallas, chunk, interpret)
    return (best_i, best_s), (Rs, ts, coords, pixels, f, c, best_i)


def _select_bwd(tau, beta, use_pallas, chunk, interpret, res, g):
    """Backward of the fused-select forward: recompute ONLY the winner's
    score path (one hypothesis x all cells) and differentiate it — the
    gradient of an argmax-selected score flows through the selected branch
    alone, so nothing errmap-shaped is ever needed.  The recompute mirrors
    the engine that ran forward: kernel math (``soft_inlier_scores_fused``)
    for the Pallas kernel, errmap math for the chunked sibling."""
    Rs, ts, coords, pixels, f, c, best_i = res
    _, g_score = g  # best_idx is integer-valued: its cotangent is vacuous

    def winner_score(Rs_, ts_, coords_, pixels_, f_, c_):
        R, t = Rs_[best_i], ts_[best_i]
        if use_pallas:
            return soft_inlier_scores_fused(
                R[None], t[None], coords_, pixels_, f_, c_, tau, beta
            )[0]
        errs = reprojection_errors(R, t, coords_, pixels_, f_, c_)
        return soft_inlier_score(errs, tau, beta)

    _, vjp = jax.vjp(winner_score, Rs, ts, coords, pixels, f, c)
    return vjp(g_score)


_score_select.defvjp(_select_fwd, _select_bwd)


@partial(jax.jit, static_argnames=("tau", "beta", "use_pallas", "chunk",
                                   "interpret"))
def soft_inlier_score_select(
    Rs: jnp.ndarray,
    ts: jnp.ndarray,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    tau: float,
    beta: float,
    use_pallas: bool = False,
    chunk: int = 64,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Differentiable fused score+select: (best_idx, best_score) without
    materializing the errmap OR the (H,) score vector.

    ``use_pallas=True`` runs the VMEM kernel (``_select_pallas_raw``;
    ``interpret=True`` for off-TPU equivalence tests); ``use_pallas=False``
    runs the chunked XLA sibling whose winner is bit-identical to
    ``jnp.argmax`` of the errmap impl's scores, tie inputs included.
    Gradients recompute only the winner's score path (``_select_bwd``).
    """
    return _score_select(Rs, ts, coords, pixels,
                         jnp.float32(f), jnp.asarray(c, jnp.float32),
                         tau, beta, use_pallas, chunk, interpret)
