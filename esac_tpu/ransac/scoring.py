"""Soft-inlier scoring of pose hypotheses.

score_j = sum_over_cells sigmoid(beta * (tau - r_jc)) where r_jc is the
reprojection error of cell c under hypothesis j — the differentiable inlier
count from DSAC/ESAC (SURVEY.md §3.5).  On TPU the full (n_hyps, n_cells)
error map is one batched computation; gradients flow into the scene
coordinates analytically, replacing the reference's hand-derived C++
backward pass (SURVEY.md §2 #4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from esac_tpu.geometry.camera import reprojection_errors
from esac_tpu.geometry.rotations import rodrigues


def reprojection_error_map(
    rvecs: jnp.ndarray,
    tvecs: jnp.ndarray,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
) -> jnp.ndarray:
    """Per-hypothesis, per-cell reprojection errors.

    rvecs/tvecs: (n_hyps, 3); coords: (N, 3) predicted scene coordinates;
    pixels: (N, 2) fixed cell centers.  Returns (n_hyps, N) pixel errors.
    """
    Rs = rodrigues(rvecs)  # (n_hyps, 3, 3)
    return jax.vmap(
        lambda R, t: reprojection_errors(R, t, coords, pixels, f, c)
    )(Rs, tvecs)


def soft_inlier_score(
    errors: jnp.ndarray,
    tau: float,
    beta: float,
) -> jnp.ndarray:
    """Soft inlier count per hypothesis. errors: (..., N) -> (...)."""
    return jnp.sum(jax.nn.sigmoid(beta * (tau - errors)), axis=-1)


def subsample_cells(
    key: jax.Array,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    n_sub: int,
) -> tuple[jnp.ndarray, jnp.ndarray, float]:
    """Random cell subset for subsampled scoring (RansacConfig.score_cells).

    Returns (coords_sub, pixels_sub, scale) with scale = N/n_sub so
    subsampled soft-inlier counts stay comparable to full counts.
    """
    N = coords.shape[0]
    if not n_sub or n_sub >= N:
        return coords, pixels, 1.0
    sub = jax.random.permutation(key, N)[:n_sub]
    return coords[sub], pixels[sub], N / n_sub


def soft_inlier_weights(
    errors: jnp.ndarray,
    tau: float,
    beta: float,
) -> jnp.ndarray:
    """Per-cell soft inlier weights in [0, 1] (same sigmoid as the score)."""
    return jax.nn.sigmoid(beta * (tau - errors))
