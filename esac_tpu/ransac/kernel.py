"""The hypothesis kernel: sample -> solve -> score -> select -> refine.

One frame's whole differentiable-RANSAC loop as a single jitted function,
vmapped over the hypothesis axis.  This is the TPU replacement for the
reference's ``esac.forward``/``esac.backward`` C++ extension entry points
(SURVEY.md §2 #3-4, §3.5): where the reference crosses host<->GPU<->C++ per
frame, everything here stays on-chip, and ``jax.grad`` of
``dsac_train_loss`` provides the entire backward pass (analytic through
scoring and selection, autodiff-through-IRLS for refinement, no central
finite differences).

Batching conventions: all functions take ONE frame (coords (N, 3)); batch
with ``jax.vmap`` and shard the batch axis with ``pjit`` (streaming config #5
in BASELINE.md).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from esac_tpu.geometry.camera import pose_errors
from esac_tpu.geometry.pnp import solve_pnp_minimal
from esac_tpu.geometry.rotations import rodrigues
from esac_tpu.ransac.config import RansacConfig
from esac_tpu.ransac.refine import refine_soft_inliers
from esac_tpu.ransac.sampling import sample_correspondence_sets
from esac_tpu.ransac.scoring import (
    reprojection_error_map,
    soft_inlier_score,
    subsample_cells,
)


def _score_hypotheses(key, rvecs, tvecs, coords, pixels, f, c, cfg):
    """ALL-hypotheses soft-inlier scores, optionally on a cell subsample
    (cfg.score_cells) — the TRAINING-path scoring entry (the softmax
    expectation needs every score).  The ESAC multi-expert training path
    calls this too, so scale corrections stay in one place.

    Implementation is selected by cfg.scoring_impl (see RansacConfig; the
    deprecated use_pallas_scoring flag is resolved into it by
    ``RansacConfig.__post_init__``, never re-derived here) — every impl is
    differentiable.  "fused_select" runs the chunked+remat errmap math
    (``soft_inlier_scores_chunked``): numbers bit-identical to "errmap",
    peak live bytes bounded to one (score_chunk, n_cells) tile in forward
    AND backward.

    HBM note: the "errmap"/"fused" TRAINING paths still materialize the
    full (n_hyps, n_cells) reprojection-error map — the committed number in
    .jaxpr_ledger.json (scoring_errmap_grad).  INFERENCE entry points no
    longer call this: they stream scoring+selection through
    :func:`_infer_winner`, which never materializes the errmap.
    """
    coords_s, pixels_s, scale = subsample_cells(key, coords, pixels, cfg.score_cells)
    impl = cfg.scoring_impl
    if impl == "pallas":
        from esac_tpu.ransac.pallas_scoring import soft_inlier_scores_pallas

        return soft_inlier_scores_pallas(
            rodrigues(rvecs), tvecs, coords_s, pixels_s, f, c,
            cfg.tau, cfg.beta,
            interpret=jax.default_backend() != "tpu",
        ) * scale
    if impl == "fused":
        from esac_tpu.ransac.pallas_scoring import soft_inlier_scores_fused

        return soft_inlier_scores_fused(
            rodrigues(rvecs), tvecs, coords_s, pixels_s, f, c,
            cfg.tau, cfg.beta,
        ) * scale
    if impl == "fused_select":
        from esac_tpu.ransac.pallas_scoring import soft_inlier_scores_chunked

        return soft_inlier_scores_chunked(
            rvecs, tvecs, coords_s, pixels_s, f, c, cfg.tau, cfg.beta,
            impl="errmap", chunk=cfg.score_chunk,
        ) * scale
    if impl != "errmap":
        raise ValueError(f"unknown RansacConfig.scoring_impl: {impl!r}")
    errors = reprojection_error_map(rvecs, tvecs, coords_s, pixels_s, f, c)
    return soft_inlier_score(errors, cfg.tau, cfg.beta) * scale


def _infer_winner(key, rvecs, tvecs, coords, pixels, f, c, cfg):
    """Streaming score+select — the structure of EVERY inference entry
    point (ROADMAP item 3): hypotheses tile through scoring in
    (cfg.score_chunk, n_cells) chunks, so the (n_hyps, n_cells) errmap the
    argmax used to consume never materializes on any inference path.

    Returns ``(best, best_score, scores)``:

    - "errmap" / "fused": chunked scoring (per-hypothesis numbers
      bit-identical to the materializing formulation) still yields the full
      (n_hyps,) ``scores`` vector — n_hyps*4 bytes, NOT the errmap term —
      so result dicts keep their 'scores' field and every committed
      bit-parity pin survives unchanged.
    - "pallas": the fused scoring kernel (already errmap-free), argmax on
      its (n_hyps,) output.
    - "fused_select": full fusion — selection happens inside the stream
      (Pallas VMEM kernel on TPU, chunked XLA sibling elsewhere) and
      ``scores`` is None; only the winner's index and score exist.
      Winner tie-breaking matches ``jnp.argmax`` bit-for-bit.
    """
    coords_s, pixels_s, scale = subsample_cells(key, coords, pixels, cfg.score_cells)
    impl = cfg.scoring_impl
    if impl == "fused_select":
        from esac_tpu.ransac.pallas_scoring import soft_inlier_score_select

        best, best_score = soft_inlier_score_select(
            rodrigues(rvecs), tvecs, coords_s, pixels_s, f, c,
            cfg.tau, cfg.beta,
            use_pallas=jax.default_backend() == "tpu",
            chunk=cfg.score_chunk,
        )
        return best, best_score * scale, None
    if impl == "pallas":
        from esac_tpu.ransac.pallas_scoring import soft_inlier_scores_pallas

        scores = soft_inlier_scores_pallas(
            rodrigues(rvecs), tvecs, coords_s, pixels_s, f, c,
            cfg.tau, cfg.beta,
            interpret=jax.default_backend() != "tpu",
        ) * scale
    elif impl in ("errmap", "fused"):
        from esac_tpu.ransac.pallas_scoring import soft_inlier_scores_chunked

        scores = soft_inlier_scores_chunked(
            rvecs, tvecs, coords_s, pixels_s, f, c, cfg.tau, cfg.beta,
            impl=impl, chunk=cfg.score_chunk,
        ) * scale
    else:
        raise ValueError(f"unknown RansacConfig.scoring_impl: {impl!r}")
    best = jnp.argmax(scores)
    return best, scores[best], scores


def _split_score_key(key, cfg):
    """(hypothesis key, scoring-subsample key); no split when not subsampling
    so existing RNG streams stay bit-identical at score_cells=0."""
    if cfg.score_cells:
        return jax.random.split(key)
    return key, key


def generate_hypotheses(
    key: jax.Array,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig,
    idx: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sample minimal sets and solve PnP for every hypothesis.

    coords: (N, 3) scene coordinates, pixels: (N, 2).
    Returns rvecs, tvecs of shape (n_hyps, 3).

    ``idx`` ((n_hyps, 4) int32) injects precomputed correspondence sets —
    the sampling contract's injection point (SURVEY.md hard part #4), used
    to run jax and cpp backends on identical hypothesis sets.
    """
    if idx is None:
        idx = sample_correspondence_sets(key, cfg.n_hyps, coords.shape[0])
    X4 = coords[idx]  # (n_hyps, 4, 3)
    x4 = pixels[idx]  # (n_hyps, 4, 2)
    solve = jax.vmap(
        lambda Xi, xi: solve_pnp_minimal(Xi, xi, f, c, polish_iters=cfg.polish_iters)
    )
    return solve(X4, x4)


def pose_loss(
    rvec: jnp.ndarray,
    tvec: jnp.ndarray,
    R_gt: jnp.ndarray,
    t_gt: jnp.ndarray,
    cfg: RansacConfig,
) -> jnp.ndarray:
    """Combined pose loss: max(rot err deg, trans err * trans_scale), clamped.

    The max-combination aligns the loss surface with the 5cm/5deg acceptance
    metric (1 cm == 1 deg at trans_scale=100); the clamp bounds the influence
    of wild hypotheses in the training expectation.
    """
    r_err, t_err = pose_errors(rodrigues(rvec), tvec, R_gt, t_gt)
    return jnp.minimum(jnp.maximum(r_err, t_err * cfg.trans_scale), cfg.loss_clamp)


@partial(jax.jit, static_argnames=("cfg",))
def dsac_infer(
    key: jax.Array,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> dict:
    """Inference: stream-select the best-scoring hypothesis, refine it
    fully.  Scoring+selection go through :func:`_infer_winner`, so the
    (n_hyps, n_cells) errmap never materializes.

    Returns dict with 'rvec', 'tvec' (the refined winner), 'best' (index),
    'inlier_frac' of the winner, and 'scores' (n_hyps,) — except under
    scoring_impl="fused_select", where the score vector itself is fused
    away and the winner's scalar 'score' is returned instead.
    """
    key, k_sub = _split_score_key(key, cfg)
    rvecs, tvecs = generate_hypotheses(key, coords, pixels, f, c, cfg)
    best, best_score, scores = _infer_winner(
        k_sub, rvecs, tvecs, coords, pixels, f, c, cfg
    )
    rvec, tvec = refine_soft_inliers(
        rvecs[best],
        tvecs[best],
        coords,
        pixels,
        f,
        c,
        cfg.tau,
        cfg.beta,
        iters=cfg.refine_iters,
    )
    n_cells = coords.shape[0]
    out = {
        "rvec": rvec,
        "tvec": tvec,
        "best": best,
        "inlier_frac": best_score / n_cells,
    }
    if scores is None:
        out["score"] = best_score
    else:
        out["scores"] = scores
    return out


@partial(jax.jit, static_argnames=("cfg",))
def dsac_infer_frames(
    keys: jax.Array,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> dict:
    """Frames-major inference: the whole batch rides ONE dispatch.

    keys (B,) typed PRNG keys, coords (B, N, 3), pixels (B, N, 2), f (B,)
    per-frame focals, c (2,) shared principal point.  Sampling, P3P,
    scoring, argmax selection and the winner-only IRLS loop each run once
    per *dispatch*, vmapped over frames — the amortization lever of
    DESIGN.md §9: the serial small-tensor chain's op-latency floor is paid
    per dispatch, not per frame.  Per-frame results match ``dsac_infer``
    semantically; the serving path (esac_tpu.serve) additionally guarantees
    bit-identical results across frame-batch sizes by keeping every
    dispatch at >= 2 physical lanes (serve.batching.MIN_LANES).
    """
    return jax.vmap(
        lambda k, co, px, fi: dsac_infer(k, co, px, fi, c, cfg)
    )(keys, coords, pixels, f)


@partial(jax.jit, static_argnames=("cfg",))
def dsac_train_loss(
    key: jax.Array,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    R_gt: jnp.ndarray,
    t_gt: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> tuple[jnp.ndarray, dict]:
    """Training: expected pose loss under softmax hypothesis selection.

    E_{j ~ softmax(alpha * score)} [ pose_loss(refine_light(h_j)) ].

    Unlike the reference — which refines only the selected hypothesis because
    CPU refinement is expensive — every hypothesis gets a light IRLS
    refinement inside the expectation (cheap when vmapped on TPU), which
    lowers estimator variance.  Gradients flow to ``coords`` through (a) the
    minimal solves, (b) the soft-inlier scores inside the selection softmax,
    and (c) the refinement residuals.  Differentiate with ``jax.grad`` wrt
    ``coords`` (or wrt network params through them).

    Returns (loss, aux) where aux holds 'expected_loss', 'best_loss',
    'selection_probs', 'scores'.
    """
    key, k_sub = _split_score_key(key, cfg)
    rvecs, tvecs = generate_hypotheses(key, coords, pixels, f, c, cfg)
    scores = _score_hypotheses(k_sub, rvecs, tvecs, coords, pixels, f, c, cfg)
    probs = jax.nn.softmax(cfg.alpha * scores)

    refine_one = lambda rv, tv: refine_soft_inliers(  # noqa: E731
        rv, tv, coords, pixels, f, c, cfg.tau, cfg.beta,
        iters=cfg.train_refine_iters,
    )
    if cfg.remat:
        refine_one = jax.checkpoint(refine_one)
    rvecs_r, tvecs_r = jax.vmap(refine_one)(rvecs, tvecs)
    losses = jax.vmap(lambda rv, tv: pose_loss(rv, tv, R_gt, t_gt, cfg))(
        rvecs_r, tvecs_r
    )
    expected = jnp.sum(probs * losses)
    aux = {
        "expected_loss": expected,
        "best_loss": losses[jnp.argmax(scores)],
        "selection_probs": probs,
        "scores": scores,
        "entropy": -jnp.sum(probs * jnp.log(probs + 1e-12)),
    }
    return expected, aux
