"""Multi-expert ESAC: gating-routed expert sample consensus.

Reference counterpart: the mixture-of-experts hypothesis loop of
``esac.forward``/``backward`` (SURVEY.md §0, §3.3): draw an expert per
hypothesis from the gating distribution, run only drawn experts (host-side
sparsity), score each hypothesis on its expert's own coordinate map, select
globally, and push a REINFORCE gradient into the gating net.

TPU-first redesign: for M <= ~a dozen experts per device, running *all*
experts densely beats host-side sparsity (no data-dependent shapes, full MXU
utilization), so:

- ``esac_infer`` / ``esac_train_loss(mode='dense')`` allocate ``cfg.n_hyps``
  hypotheses to EVERY expert (the reference's "256 hyp/expert", BASELINE.md
  config #2), score within-expert, and combine across experts.  In dense
  training the gating gradient is *exact* — total loss = sum_m g_m L_m is
  directly differentiable — eliminating the REINFORCE variance entirely
  (SURVEY.md hard part #5).
- ``esac_train_loss(mode='sampled')`` reproduces the reference's estimator:
  categorical expert draw per hypothesis + score-function (REINFORCE)
  gradient with an expected-loss baseline, for parity testing and for
  regimes where dense compute is wasteful.

Expert sharding across a TPU mesh (M ~ 50, BASELINE.md config #4) lives in
``esac_tpu.parallel``; the functions here are its per-shard body.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from esac_tpu.ransac.config import RansacConfig
from esac_tpu.ransac.kernel import (
    _infer_winner,
    _score_hypotheses,
    _split_score_key,
    generate_hypotheses,
    pose_loss,
)
from esac_tpu.ransac.refine import refine_soft_inliers
from esac_tpu.ransac.sampling import sample_expert_indices
from esac_tpu.ransac.scoring import soft_inlier_score


def _per_expert_hypotheses(key, coords_all, pixels, f, c, cfg,
                           score_key=None, idx=None):
    """cfg.n_hyps hypotheses per expert. coords_all: (M, N, 3).

    Returns rvecs, tvecs (M, n_hyps, 3) and scores (M, n_hyps), each
    hypothesis scored on its own expert's coordinate map (optionally on a
    shared cell subsample, cfg.score_cells — the same cells for every expert
    so cross-expert scores stay comparable).  Expert-sharded callers must
    pass a replicated ``score_key`` so the shared-cells invariant holds
    *across shards* too (their ``key`` is already folded per shard).
    """
    M = coords_all.shape[0]
    if score_key is None:
        key, k_sub = _split_score_key(key, cfg)
    else:
        k_sub = score_key
    keys = jax.random.split(key, M)
    if idx is None:
        rvecs, tvecs = jax.vmap(
            lambda k, co: generate_hypotheses(k, co, pixels, f, c, cfg)
        )(keys, coords_all)
    else:
        rvecs, tvecs = jax.vmap(
            lambda k, co, ix: generate_hypotheses(k, co, pixels, f, c, cfg, idx=ix)
        )(keys, coords_all, idx)
    scores = jax.vmap(
        lambda rv, tv, co: _score_hypotheses(k_sub, rv, tv, co, pixels, f, c, cfg)
    )(rvecs, tvecs, coords_all)
    return rvecs, tvecs, scores


def _per_expert_winners(key, coords_all, pixels, f, c, cfg,
                        score_key=None, idx=None):
    """Inference sibling of :func:`_per_expert_hypotheses`: generate
    cfg.n_hyps hypotheses per expert, then STREAM scoring+selection per
    expert (``kernel._infer_winner``) instead of materializing the errmap.

    Returns ``(rvecs, tvecs, best_j, best_s, scores)``: poses (M, n_hyps,
    3), per-expert winner index/score (M,), and the (M, n_hyps) score
    matrix — None exactly when cfg.scoring_impl == "fused_select" (full
    fusion: only the winners exist).  The global winner is
    ``m* = argmax(best_s)``, ``j* = best_j[m*]`` — bit-identical to the
    flat argmax over (M * n_hyps) scores, ties included: within an expert
    the stream keeps the first max, across experts ``jnp.argmax`` on
    (M,) keeps the first expert attaining the max.  Key discipline as in
    ``_per_expert_hypotheses`` (shared score-subsample key).
    """
    M = coords_all.shape[0]
    if score_key is None:
        key, k_sub = _split_score_key(key, cfg)
    else:
        k_sub = score_key
    keys = jax.random.split(key, M)
    if idx is None:
        rvecs, tvecs = jax.vmap(
            lambda k, co: generate_hypotheses(k, co, pixels, f, c, cfg)
        )(keys, coords_all)
    else:
        rvecs, tvecs = jax.vmap(
            lambda k, co, ix: generate_hypotheses(k, co, pixels, f, c, cfg, idx=ix)
        )(keys, coords_all, idx)
    best_j, best_s, scores = jax.vmap(
        lambda rv, tv, co: _infer_winner(k_sub, rv, tv, co, pixels, f, c, cfg)
    )(rvecs, tvecs, coords_all)
    return rvecs, tvecs, best_j, best_s, scores


@partial(jax.jit, static_argnames=("cfg",))
def esac_infer(
    key: jax.Array,
    gating_logits: jnp.ndarray,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> dict:
    """Inference over M experts: global argmax of soft-inlier score.

    gating_logits: (M,) — reported (and usable for expert top-k pruning by
    the caller), but selection is by consensus score: with all experts
    computed, the best-supported hypothesis wins regardless of the gate,
    which strictly dominates the reference's drawn-subset argmax.

    Returns dict with 'rvec', 'tvec', 'expert' (winning expert index),
    'gating_probs', 'inlier_frac', and 'scores' (M, n_hyps) — except under
    scoring_impl="fused_select", where scoring streams through selection
    and the winner's scalar 'score' is returned instead.
    """
    rvecs, tvecs, best_j, best_s, scores = _per_expert_winners(
        key, coords_all, pixels, f, c, cfg
    )
    m_star = jnp.argmax(best_s)
    j_star = best_j[m_star]
    rvec, tvec = refine_soft_inliers(
        rvecs[m_star, j_star],
        tvecs[m_star, j_star],
        coords_all[m_star],
        pixels,
        f,
        c,
        cfg.tau,
        cfg.beta,
        iters=cfg.refine_iters,
    )
    out = {
        "rvec": rvec,
        "tvec": tvec,
        "expert": m_star,
        "gating_probs": jax.nn.softmax(gating_logits),
        "inlier_frac": best_s[m_star] / pixels.shape[0],
    }
    if scores is None:
        out["score"] = best_s[m_star]
    else:
        out["scores"] = scores
    return out


@partial(jax.jit, static_argnames=("cfg",))
def esac_infer_frames(
    keys: jax.Array,
    gating_logits: jnp.ndarray,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> dict:
    """Frames-major ``esac_infer``: B frames in ONE dispatch.

    keys (B,) typed PRNG keys, gating_logits (B, M), coords_all
    (B, M, N, 3), pixels (B, N, 2), f (B,) per-frame focals, c (2,)
    shared.  P3P, the global argmax and the winner-only IRLS refine run
    once per dispatch vmapped over frames (DESIGN.md §9's frame-axis
    amortization); per-frame semantics are ``esac_infer``'s, with every
    output gaining a leading (B,) axis.
    """
    return jax.vmap(
        lambda k, g, ca, px, fi: esac_infer(k, g, ca, px, fi, c, cfg)
    )(keys, gating_logits, coords_all, pixels, f)


def _prior_slot_winner(k_sub, prior_rvecs, prior_tvecs, prior_valid,
                       coords, pixels, f, c, cfg):
    """Best of the P motion-prior candidate poses on ONE expert's
    coordinate map (ISSUE 20, DESIGN.md §23): the priors score through
    the SAME ``_score_hypotheses`` math as the sampled stream — same
    ``k_sub`` subsample cells, same scale — so a prior's score is
    directly comparable with ``_infer_winner``'s streamed best.  Invalid
    slots mask to ``-inf``; returns ``(pj, ps)``, the winning prior
    index and its masked score (``-inf`` when every slot is invalid).
    """
    scores = _score_hypotheses(
        k_sub, prior_rvecs, prior_tvecs, coords, pixels, f, c, cfg
    )
    masked = jnp.where(prior_valid, scores, -jnp.inf)
    pj = jnp.argmax(masked)
    return pj, masked[pj]


@partial(jax.jit, static_argnames=("cfg",))
def esac_infer_prior(
    key: jax.Array,
    gating_logits: jnp.ndarray,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    prior_rvecs: jnp.ndarray,
    prior_tvecs: jnp.ndarray,
    prior_valid: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> dict:
    """``esac_infer`` with a static-count prior-hypothesis slot
    (ISSUE 20): ``prior_rvecs``/``prior_tvecs`` (P, 3) are motion-model
    propagated candidate poses entering as TRACED arguments with a
    ``prior_valid`` (P,) mask, so tracked, cold and lost-track frames
    all share one compiled program.

    The sampled stream is byte-for-byte ``esac_infer``'s (same
    hypothesis and subsample RNG keys, same streamed selection); the P
    priors are scored per expert on that expert's own map and appended
    AFTER the sampled slots in the conceptual flat order — a prior
    replaces an expert's streamed winner only on a STRICTLY greater
    score, and ``jnp.argmax`` across experts keeps the first expert, so
    tie-breaking matches the flat argmax over [sampled..., priors...].
    With an all-invalid mask every prior scores ``-inf``, selection and
    the refine inputs coincide exactly with ``esac_infer``'s, and the
    outputs are bit-identical (the DESIGN.md §23 parity pin, same
    cross-program precedent as the routed K=M pin).

    Extra outputs: ``prior_hit`` (did a prior win selection) and
    ``prior_slot`` (winning prior index, or P when the sampled stream
    won).
    """
    P = prior_rvecs.shape[0]
    k_hyp, k_sub = _split_score_key(key, cfg)
    rvecs, tvecs, best_j, best_s, scores = _per_expert_winners(
        k_hyp, coords_all, pixels, f, c, cfg, score_key=k_sub
    )
    p_j, p_s = jax.vmap(
        lambda co: _prior_slot_winner(
            k_sub, prior_rvecs, prior_tvecs, prior_valid, co, pixels, f, c,
            cfg,
        )
    )(coords_all)                      # (M,), (M,)
    is_prior = p_s > best_s            # strict: sampled slots come first
    ext_s = jnp.where(is_prior, p_s, best_s)
    m_star = jnp.argmax(ext_s)
    j_star = best_j[m_star]
    hit = is_prior[m_star]
    rv0 = jnp.where(hit, prior_rvecs[p_j[m_star]], rvecs[m_star, j_star])
    tv0 = jnp.where(hit, prior_tvecs[p_j[m_star]], tvecs[m_star, j_star])
    rvec, tvec = refine_soft_inliers(
        rv0, tv0, coords_all[m_star], pixels, f, c, cfg.tau, cfg.beta,
        iters=cfg.refine_iters,
    )
    out = {
        "rvec": rvec,
        "tvec": tvec,
        "expert": m_star,
        "gating_probs": jax.nn.softmax(gating_logits),
        "inlier_frac": ext_s[m_star] / pixels.shape[0],
        "prior_hit": hit,
        "prior_slot": jnp.where(hit, p_j[m_star], P).astype(jnp.int32),
    }
    if scores is None:
        out["score"] = ext_s[m_star]
    else:
        out["scores"] = scores
    return out


@partial(jax.jit, static_argnames=("cfg",))
def esac_infer_frames_prior(
    keys: jax.Array,
    gating_logits: jnp.ndarray,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    prior_rvecs: jnp.ndarray,
    prior_tvecs: jnp.ndarray,
    prior_valid: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> dict:
    """Frames-major :func:`esac_infer_prior`: B frames, each with its own
    (P, 3) prior-pose slate and (P,) validity mask, in ONE dispatch —
    the session-serving sibling of :func:`esac_infer_frames` (shapes as
    there, priors gaining a leading (B,) axis)."""
    return jax.vmap(
        lambda k, g, ca, px, fi, pr, pt, pv: esac_infer_prior(
            k, g, ca, px, fi, c, pr, pt, pv, cfg
        )
    )(keys, gating_logits, coords_all, pixels, f,
      prior_rvecs, prior_tvecs, prior_valid)


def _expected_losses_per_expert(rvecs, tvecs, scores, coords_all, pixels, f, c, R_gt, t_gt, cfg):
    """Within-expert softmax-selection expectation of the refined pose loss.

    Returns (M,) expected losses and (M, nh) per-hypothesis losses.
    """

    def one_expert(rv, tv, sc, co):
        probs = jax.nn.softmax(cfg.alpha * sc)
        refine_one = lambda r, t: refine_soft_inliers(  # noqa: E731
            r, t, co, pixels, f, c, cfg.tau, cfg.beta,
            iters=cfg.train_refine_iters,
        )
        if cfg.remat:
            refine_one = jax.checkpoint(refine_one)
        rv_r, tv_r = jax.vmap(refine_one)(rv, tv)
        losses = jax.vmap(lambda r, t: pose_loss(r, t, R_gt, t_gt, cfg))(rv_r, tv_r)
        if not cfg.grad_through_refine:
            # Selection-path-only backward (matches the cpp training backend).
            losses = jax.lax.stop_gradient(losses)
        return jnp.sum(probs * losses), losses

    return jax.vmap(one_expert)(rvecs, tvecs, scores, coords_all)


@partial(jax.jit, static_argnames=("cfg", "k"))
def esac_infer_topk(
    key: jax.Array,
    gating_logits: jnp.ndarray,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
    k: int = 4,
) -> dict:
    """Inference with gating-pruned experts: only the top-k experts by gating
    probability generate and score hypotheses.

    The dense ``esac_infer`` is preferable for small M; for large ensembles
    on a single chip (e.g. Aachen's ~50 clusters) this recovers the
    reference's sparse-compute behavior with static shapes: a gather of k
    coordinate maps instead of data-dependent expert sets.  A miss by the
    gating net (true expert outside top-k) fails the frame, exactly as the
    reference's drawn-subset policy can.
    """
    M = coords_all.shape[0]
    k = min(k, M)
    _, top = jax.lax.top_k(gating_logits, k)
    coords_k = coords_all[top]  # (k, N, 3)
    out = esac_infer(key, gating_logits[top], coords_k, pixels, f, c, cfg)
    return {
        **out,
        "expert": top[out["expert"]],
        "experts_evaluated": top,
        # Full M-way distribution, matching esac_infer — NOT renormalized
        # over the pruned subset.  Note 'scores' (absent under
        # scoring_impl="fused_select", which streams the winner) stays
        # (k, n_hyps): rows align with 'experts_evaluated', not with
        # expert index.
        "gating_probs": jax.nn.softmax(gating_logits),
    }


@partial(jax.jit, static_argnames=("cfg", "k"))
def esac_infer_topk_frames(
    keys: jax.Array,
    gating_logits: jnp.ndarray,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
    k: int = 4,
) -> dict:
    """Frames-major ``esac_infer_topk``: gating-pruned experts, B frames in
    one dispatch.  Shapes as in :func:`esac_infer_frames`; each frame's
    top-k expert subset is selected from its own gating row."""
    return jax.vmap(
        lambda kk, g, ca, px, fi: esac_infer_topk(
            kk, g, ca, px, fi, c, cfg, k=k
        )
    )(keys, gating_logits, coords_all, pixels, f)


def select_topk_experts(gating_logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Per-frame top-``k`` expert ids by gating logit, sorted ASCENDING by
    global expert index.  gating_logits (..., M) -> (..., k) int32.

    The ascending sort is load-bearing for the routed serve path's K=M
    bit-parity pin: with every expert selected, the slot layout becomes
    exactly 0..M-1, so the routed hypothesis loop evaluates the same
    (expert, key) pairs in the same order as the dense path and reduces to
    it bit-for-bit (tests/test_serve_routed.py).
    """
    _, top = jax.lax.top_k(gating_logits, k)
    return jnp.sort(top, axis=-1).astype(jnp.int32)


def routed_serve_capacity(cfg: RansacConfig, k: int, num_experts: int) -> int:
    """Static per-expert frame capacity of the routed serve programs.

    ``cfg.serve_capacity > 0`` wins; otherwise auto-size to 2x the
    balanced per-expert load at the LARGEST frame bucket,
    ``ceil(2 * k * max_bucket / M)``.  Two invariants, both required by
    the serve-path bit-parity contract:

    - **Bucket-independent.**  The capacity must be one constant per
      (cfg, K) — never a function of the dispatch's frame bucket — or a
      request's surviving (frame, expert) pairs would depend on which
      bucket it rode (a larger bucket's laxer capacity would keep pairs a
      smaller bucket drops).
    - **>= 2 block lanes.**  Expert blocks run CNN forwards at batch width
      ``capacity``; a collapsed width-1 batch specializes differently
      under XLA (the serve.batching.MIN_LANES measurement), so the floor
      keeps block results per-lane stable across capacities.
    """
    big = max(2, max(cfg.frame_buckets))
    cap = cfg.serve_capacity if cfg.serve_capacity > 0 \
        else -(-2 * k * big // num_experts)
    return max(2, min(cap, big))


def _routed_frame_candidates(key, co_sel, sel, live, px, fi, c, cfg_k, M):
    """Candidate stage of the capacity-routed hypothesis loop: global-index
    RNG streams, generate + STREAMED score+select over the K gathered
    expert maps (``kernel._infer_winner`` per slot), ``-inf`` masking of
    non-live slots at the slot level.

    Shared VERBATIM by :func:`_routed_frame_winner` (hence
    :func:`esac_infer_routed_frames` and
    ``parallel.make_esac_infer_routed_frames_sharded``) and
    :func:`_routed_frame_winner_prior`, so the sampled candidate stream —
    (expert, key) pairs, scores, masking — is structurally identical
    across all routed entries.  Returns
    ``(k_sub, rvecs, tvecs, best_j, best_s, scores)`` with ``best_s``
    live-masked and ``scores`` the masked (K, nh) matrix (None under
    scoring_impl="fused_select").
    """
    k_hyp, k_sub = _split_score_key(key, cfg_k)
    keys_sel = jax.random.split(k_hyp, M)[sel]  # global-index streams
    rvecs, tvecs = jax.vmap(
        lambda kk, co: generate_hypotheses(kk, co, px, fi, c, cfg_k)
    )(keys_sel, co_sel)
    best_j, best_s, scores = jax.vmap(
        lambda rv, tv, co: _infer_winner(k_sub, rv, tv, co, px, fi, c, cfg_k)
    )(rvecs, tvecs, co_sel)
    best_s = jnp.where(live, best_s, -jnp.inf)
    if scores is not None:
        scores = jnp.where(live[:, None], scores, -jnp.inf)
    return k_sub, rvecs, tvecs, best_j, best_s, scores


def _routed_frame_winner(key, co_sel, sel, live, px, fi, c, cfg_k, M):
    """One frame of the capacity-routed hypothesis loop:
    :func:`_routed_frame_candidates` + winner-only refine.

    Shared VERBATIM by :func:`esac_infer_routed_frames` and
    ``parallel.make_esac_infer_routed_frames_sharded`` so their bit-level
    agreement on evaluated pairs is structural, not merely pinned by the
    (slow) cross-path test.  ``cfg_k`` is the budget-reallocated config;
    returns ``(rvec, tvec, scores, mi, best)`` — refined winner pose,
    masked (K, nh) scores (None under scoring_impl="fused_select"),
    winning slot index, winning score.

    Selection is bit-identical to the old flat argmax over the masked
    (K, nh) matrix: a live slot's streamed winner is its row's first max,
    ``jnp.argmax`` over per-slot winners keeps the first slot on ties, and
    a frame whose every slot dropped resolves to (mi=0, j=0) exactly as
    ``argmax`` over an all ``-inf`` matrix does.
    """
    _, rvecs, tvecs, best_j, best_s, scores = _routed_frame_candidates(
        key, co_sel, sel, live, px, fi, c, cfg_k, M
    )
    mi = jnp.argmax(best_s)
    # All-dropped frame: every masked winner is -inf and argmax lands on
    # slot 0; pin j to 0 to match the flat-argmax failure output.
    j = jnp.where(live[mi], best_j[mi], 0)
    rvec, tvec = refine_soft_inliers(
        rvecs[mi, j], tvecs[mi, j], co_sel[mi], px, fi, c,
        cfg_k.tau, cfg_k.beta, iters=cfg_k.refine_iters,
    )
    return rvec, tvec, scores, mi, best_s[mi]


def _routed_frame_winner_prior(key, co_sel, sel, live, px, fi, c, cfg_k, M,
                               prior_rvecs, prior_tvecs, prior_valid):
    """:func:`_routed_frame_winner` with the static-count prior slot
    (ISSUE 20): the P motion-prior poses are scored on each LIVE slot's
    gathered coordinate map through the same ``k_sub`` subsample as the
    sampled stream, masked by validity AND slot liveness, and a prior
    replaces a slot's streamed winner only on a STRICTLY greater score —
    so with an all-invalid mask selection, the failure pin (mi=0, j=0)
    and the refine inputs coincide exactly with
    :func:`_routed_frame_winner` (the DESIGN.md §23 parity pin).

    Returns ``(rvec, tvec, scores, mi, best, hit, pj)`` — the winner
    tuple plus whether a prior won and which slot it came from.
    """
    k_sub, rvecs, tvecs, best_j, best_s, scores = _routed_frame_candidates(
        key, co_sel, sel, live, px, fi, c, cfg_k, M
    )
    p_j, p_s = jax.vmap(
        lambda co: _prior_slot_winner(
            k_sub, prior_rvecs, prior_tvecs, prior_valid, co, px, fi, c,
            cfg_k,
        )
    )(co_sel)                           # (K,), (K,)
    p_s = jnp.where(live, p_s, -jnp.inf)
    is_prior = p_s > best_s             # strict: sampled slots come first
    ext_s = jnp.where(is_prior, p_s, best_s)
    mi = jnp.argmax(ext_s)
    hit = is_prior[mi]
    j = jnp.where(live[mi], best_j[mi], 0)
    rv0 = jnp.where(hit, prior_rvecs[p_j[mi]], rvecs[mi, j])
    tv0 = jnp.where(hit, prior_tvecs[p_j[mi]], tvecs[mi, j])
    rvec, tvec = refine_soft_inliers(
        rv0, tv0, co_sel[mi], px, fi, c,
        cfg_k.tau, cfg_k.beta, iters=cfg_k.refine_iters,
    )
    return rvec, tvec, scores, mi, ext_s[mi], hit, p_j[mi]


@partial(jax.jit, static_argnames=("cfg",))
def esac_infer_routed_frames(
    keys: jax.Array,
    gating_logits: jnp.ndarray,
    coords_sel: jnp.ndarray,
    selected: jnp.ndarray,
    kept: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> dict:
    """Frames-major hypothesis loop over capacity-routed expert subsets —
    the RANSAC stage of the gating-first routed serve programs
    (DESIGN.md §11; registry.make_routed_scene_bucket_fn runs the CNNs and
    the capacity dispatch upstream via
    ``parallel.route_frames_to_experts``).

    keys (B,) typed PRNG keys; gating_logits (B, M); coords_sel
    (B, K, N, 3) the selected experts' coordinate maps, gathered back from
    the per-expert capacity blocks; selected (B, K) int32 global expert
    ids, sorted ascending (``select_topk_experts``); kept (B, K) bool —
    False where the capacity dispatch dropped the pair; pixels (B, N, 2);
    f (B,); c (2,) shared.

    Semantics are ``esac_infer_topk_frames``'s with two extensions:

    - **Budget reallocation**: each evaluated expert runs
      ``cfg.n_hyps * M // K`` hypotheses, so the TOTAL per-frame budget is
      fixed at ``M * cfg.n_hyps`` independent of K — routing buys CNN
      sparsity, not a smaller search.
    - **Drop masking**: dropped slots score ``-inf`` (they can never win;
      their gathered coords are finite garbage by construction) and
      surface in ``experts_evaluated`` as the sentinel ``M`` — the same
      accounting contract as ``parallel.esac_infer_routed``.  A frame
      whose every slot dropped fails with finite garbage, like a gating
      miss.

    At K == M (with nothing dropped) ``selected`` is 0..M-1, the budget
    factor is 1, and every per-expert RNG stream — keyed by GLOBAL expert
    index via ``jax.random.split(key, M)[selected]`` — coincides with the
    dense path's, so the result is bit-identical to
    :func:`esac_infer_frames` (pinned in tests/test_serve_routed.py).
    """
    import dataclasses

    M = gating_logits.shape[-1]
    K = selected.shape[-1]
    nh = max(1, (cfg.n_hyps * M) // K)
    cfg_k = dataclasses.replace(cfg, n_hyps=nh)

    def one_frame(key, logits, co_sel, sel, kp, px, fi):
        rvec, tvec, scores, mi, best = _routed_frame_winner(
            key, co_sel, sel, kp, px, fi, c, cfg_k, M
        )
        out = {
            "rvec": rvec,
            "tvec": tvec,
            "expert": sel[mi],
            "experts_evaluated": jnp.where(kp, sel, M).astype(jnp.int32),
            "gating_probs": jax.nn.softmax(logits),
            "inlier_frac": best / px.shape[0],
        }
        # Full fusion (scoring_impl="fused_select"): only the winner's
        # score exists; otherwise the masked (K, nh) matrix rides along.
        if scores is None:
            out["score"] = best
        else:
            out["scores"] = scores
        return out

    return jax.vmap(one_frame)(
        keys, gating_logits, coords_sel, selected, kept, pixels, f
    )


@partial(jax.jit, static_argnames=("cfg",))
def esac_infer_routed_frames_prior(
    keys: jax.Array,
    gating_logits: jnp.ndarray,
    coords_sel: jnp.ndarray,
    selected: jnp.ndarray,
    kept: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    prior_rvecs: jnp.ndarray,
    prior_tvecs: jnp.ndarray,
    prior_valid: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> dict:
    """:func:`esac_infer_routed_frames` with a static-count
    prior-hypothesis slot (ISSUE 20, DESIGN.md §23): per-frame (P, 3)
    motion-prior pose slates with a (P,) validity mask enter as traced
    arguments, so tracked / cold / lost-track frames share ONE compiled
    program per (bucket, K, n_hyps).  Budget reallocation, drop masking
    and the ``experts_evaluated`` accounting contract are inherited
    verbatim (the sampled candidate stage is
    :func:`_routed_frame_candidates`, shared with the non-prior entry);
    with an all-invalid mask the outputs are bit-identical to
    :func:`esac_infer_routed_frames`.

    Extra outputs per frame: ``prior_hit`` and ``prior_slot`` (winning
    prior index, or P when the sampled stream won).
    """
    import dataclasses

    M = gating_logits.shape[-1]
    K = selected.shape[-1]
    P = prior_rvecs.shape[-2]
    nh = max(1, (cfg.n_hyps * M) // K)
    cfg_k = dataclasses.replace(cfg, n_hyps=nh)

    def one_frame(key, logits, co_sel, sel, kp, px, fi, p_rv, p_tv, p_va):
        rvec, tvec, scores, mi, best, hit, pj = _routed_frame_winner_prior(
            key, co_sel, sel, kp, px, fi, c, cfg_k, M, p_rv, p_tv, p_va
        )
        out = {
            "rvec": rvec,
            "tvec": tvec,
            "expert": sel[mi],
            "experts_evaluated": jnp.where(kp, sel, M).astype(jnp.int32),
            "gating_probs": jax.nn.softmax(logits),
            "inlier_frac": best / px.shape[0],
            "prior_hit": hit,
            "prior_slot": jnp.where(hit, pj, P).astype(jnp.int32),
        }
        if scores is None:
            out["score"] = best
        else:
            out["scores"] = scores
        return out

    return jax.vmap(one_frame)(
        keys, gating_logits, coords_sel, selected, kept, pixels, f,
        prior_rvecs, prior_tvecs, prior_valid
    )


@partial(jax.jit, static_argnames=("cfg", "mode"))
def esac_train_loss(
    key: jax.Array,
    gating_logits: jnp.ndarray,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    R_gt: jnp.ndarray,
    t_gt: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
    mode: str = "dense",
    idx: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, dict]:
    """End-to-end expected pose loss, differentiable wrt coords AND gating.

    dense:   loss = sum_m softmax(gating)_m * E_j[pose_loss]  — exact gating
             gradient, no sampling variance (TPU-native default).
    sampled: reference-parity estimator — experts drawn per hypothesis,
             REINFORCE (score-function) term with expected-loss baseline
             carries the gating gradient (SURVEY.md §0 training stage 3).

    ``idx`` ((M, n_hyps, 4) int32, dense mode only) injects correspondence
    sets for backend-parity tests.
    """
    g = jax.nn.softmax(gating_logits)

    if mode == "dense":
        k_hyp, _ = jax.random.split(key)
        rvecs, tvecs, scores = _per_expert_hypotheses(
            k_hyp, coords_all, pixels, f, c, cfg, idx=idx
        )
        exp_losses, losses = _expected_losses_per_expert(
            rvecs, tvecs, scores, coords_all, pixels, f, c, R_gt, t_gt, cfg
        )
        total = jnp.sum(g * exp_losses)
        aux = {
            "expected_loss": total,
            "per_expert_loss": exp_losses,
            "gating_probs": g,
            "scores": scores,
        }
        return total, aux

    if mode != "sampled":
        raise ValueError(f"unknown mode {mode!r}")
    if idx is not None:
        raise ValueError("idx injection is dense-mode only")

    k_draw, k_hyp = jax.random.split(key)
    M, N = coords_all.shape[0], coords_all.shape[1]
    experts = sample_expert_indices(k_draw, g, cfg.n_hyps)  # (n_hyps,)
    coords_sel = coords_all[experts]  # (n_hyps, N, 3)

    # One hypothesis per drawn expert map: reuse the single-expert generator
    # by folding the hypothesis index into the key.
    from esac_tpu.geometry.pnp import solve_pnp_minimal
    from esac_tpu.ransac.sampling import sample_correspondence_sets

    idx = sample_correspondence_sets(k_hyp, cfg.n_hyps, N)  # (n_hyps, 4)
    X4 = jnp.take_along_axis(coords_sel, idx[:, :, None], axis=1)
    x4 = pixels[idx]
    rvecs, tvecs = jax.vmap(
        lambda Xi, xi: solve_pnp_minimal(Xi, xi, f, c, polish_iters=cfg.polish_iters)
    )(X4, x4)

    # Score each hypothesis on its own expert's map.
    from esac_tpu.geometry.camera import reprojection_errors
    from esac_tpu.geometry.rotations import rodrigues

    errors = jax.vmap(
        lambda rv, tv, co: reprojection_errors(rodrigues(rv), tv, co, pixels, f, c)
    )(rvecs, tvecs, coords_sel)
    scores = soft_inlier_score(errors, cfg.tau, cfg.beta)
    probs = jax.nn.softmax(cfg.alpha * scores)

    refine_one = lambda rv, tv, co: refine_soft_inliers(  # noqa: E731
        rv, tv, co, pixels, f, c, cfg.tau, cfg.beta,
        iters=cfg.train_refine_iters,
    )
    if cfg.remat:
        refine_one = jax.checkpoint(refine_one)
    rvecs_r, tvecs_r = jax.vmap(refine_one)(rvecs, tvecs, coords_sel)
    losses = jax.vmap(lambda rv, tv: pose_loss(rv, tv, R_gt, t_gt, cfg))(
        rvecs_r, tvecs_r
    )
    expected = jnp.sum(probs * losses)

    # Score-function estimator for the discrete expert draw:
    # grad_phi E ~ sum_j p_j * (loss_j - b) * grad_phi log g[e_j].
    # Baseline choice matters: the selection-weighted expectation itself makes
    # p_j*(loss_j - b) vanish by construction (the softmax concentrates where
    # loss ~ b), killing the signal; the *unweighted* mean loss keeps good
    # hypotheses strongly negative and garbage ones positive, which is the
    # variant that empirically recovers the true gating direction in a
    # handful of draws.
    log_g = jnp.log(g + 1e-12)
    baseline = jax.lax.stop_gradient(jnp.mean(losses))
    weights = jax.lax.stop_gradient(probs * (losses - baseline))
    reinforce = jnp.sum(weights * log_g[experts])
    # Add only the *gradient* of the REINFORCE term, not its value.
    total = expected + reinforce - jax.lax.stop_gradient(reinforce)

    aux = {
        "expected_loss": expected,
        "drawn_experts": experts,
        "gating_probs": g,
        "scores": scores,
    }
    return total, aux
