"""The vmap'd differentiable-RANSAC hypothesis kernel.

This package replaces the reference's C++/OpenMP/OpenCV torch extension
(SURVEY.md §2 #3-5, §3.5): hypothesis sampling, minimal PnP solves,
soft-inlier scoring, softmax/argmax selection and pose refinement all run as
one XLA program, vmapped over the hypothesis axis on TPU instead of looping
over OpenMP threads on the host.
"""

from esac_tpu.ransac.config import RansacConfig
from esac_tpu.ransac.sampling import sample_correspondence_sets
from esac_tpu.ransac.scoring import reprojection_error_map, soft_inlier_score
from esac_tpu.ransac.refine import refine_soft_inliers
from esac_tpu.ransac.kernel import (
    dsac_infer,
    dsac_infer_frames,
    dsac_train_loss,
    generate_hypotheses,
    pose_loss,
)
from esac_tpu.ransac.esac import (
    esac_infer,
    esac_infer_frames,
    esac_infer_frames_prior,
    esac_infer_prior,
    esac_infer_routed_frames,
    esac_infer_routed_frames_prior,
    esac_infer_topk,
    esac_infer_topk_frames,
    esac_train_loss,
    routed_serve_capacity,
    select_topk_experts,
)

__all__ = [
    "RansacConfig",
    "sample_correspondence_sets",
    "reprojection_error_map",
    "soft_inlier_score",
    "refine_soft_inliers",
    "generate_hypotheses",
    "dsac_infer",
    "dsac_infer_frames",
    "dsac_train_loss",
    "esac_infer",
    "esac_infer_frames",
    "esac_infer_frames_prior",
    "esac_infer_prior",
    "esac_infer_routed_frames",
    "esac_infer_routed_frames_prior",
    "esac_infer_topk",
    "esac_infer_topk_frames",
    "esac_train_loss",
    "pose_loss",
    "routed_serve_capacity",
    "select_topk_experts",
]
