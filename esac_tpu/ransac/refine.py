"""Soft-inlier IRLS pose refinement.

The reference refines the winning pose by re-solving PnP on the hard inlier
set until convergence, capped at ~100 iterations, and differentiates the
result by central finite differences (SURVEY.md §3.5).  The TPU-native
equivalent is IRLS with *soft* inlier weights: recompute per-cell sigmoid
weights, take one weighted Gauss-Newton step, repeat a fixed number of
rounds.  Fixed iteration counts keep it jit/vmap-safe; softness keeps it
differentiable end-to-end, so ``jax.grad`` replaces the finite-difference
machinery exactly where the reference needed it most.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from esac_tpu.geometry.camera import reprojection_errors
from esac_tpu.geometry.pnp import refine_pose_gn_R
from esac_tpu.geometry.rotations import rodrigues, so3_log
from esac_tpu.ransac.scoring import soft_inlier_weights


@partial(jax.jit, static_argnames=("iters", "gn_steps_per_iter", "stop_weight_grad"))
def refine_soft_inliers(
    rvec: jnp.ndarray,
    tvec: jnp.ndarray,
    coords: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    tau: float,
    beta: float,
    iters: int = 8,
    gn_steps_per_iter: int = 1,
    stop_weight_grad: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """IRLS: weights <- sigmoid(beta*(tau - r)); one weighted GN step; repeat.

    ``stop_weight_grad`` blocks gradient flow through the weights (but not
    through the residuals), the usual IRLS trick to keep the backward pass
    cheap and stable; the loss gradient still reaches every coordinate
    through the weighted residuals.
    """

    # Carry the rotation MATRIX through the IRLS scan: converting to/from
    # axis-angle every iteration would run so3_log's branchy near-pi path
    # inside the vmapped hot loop for nothing.
    def body(carry, _):
        R, tv = carry
        errs = reprojection_errors(R, tv, coords, pixels, f, c)
        w = soft_inlier_weights(errs, tau, beta)
        if stop_weight_grad:
            w = jax.lax.stop_gradient(w)
        R, tv = refine_pose_gn_R(
            R, tv, coords, pixels, f, c, weights=w, iters=gn_steps_per_iter
        )
        return (R, tv), None

    (R, tvec), _ = jax.lax.scan(body, (rodrigues(rvec), tvec), None, length=iters)
    return so3_log(R), tvec
