"""Correspondence-set sampling for hypothesis generation.

The reference's C++ loop draws 4 random output pixels per hypothesis with a
per-OpenMP-thread RNG and a rejection retry on degenerate sets (SURVEY.md
§2 #5, §3.5).

Sampling contract (the cross-backend reproducibility contract, SURVEY.md
hard part #4): given (key, n_hyps, N), the default sampler draws an
(n_hyps, 4) table of **independent uniform** cell indices in one
``jax.random.randint`` call — with-replacement, so ~6/N of hypotheses
contain a duplicate index; those degenerate sets are rejected by the
solver's branch penalties + scoring, not by resampling.  The exact
without-replacement variant (``sample_correspondence_sets_exact``,
Gumbel-top-4 per hypothesis under ``fold_in(key, j)``) exists for tests; it
costs a length-N top-k per hypothesis and is not the default.  Backends
cannot share bit-identical streams with the C++ path; they are compared
statistically (same score/pose distributions) instead.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_hyps", "n_cells", "set_size"))
def sample_correspondence_sets(
    key: jax.Array,
    n_hyps: int,
    n_cells: int,
    set_size: int = 4,
) -> jnp.ndarray:
    """Draw ``n_hyps`` sets of ``set_size`` indices in [0, n_cells).

    Returns (n_hyps, set_size) int32.

    Independent uniform draws, NOT without-replacement: a Gumbel-top-k (exact
    without-replacement) costs a length-``n_cells`` top-k per hypothesis —
    ~2.5 ms for 256x4800 on a v5e chip, a quarter of the whole kernel budget
    — while the collision probability of 4 independent draws from thousands
    of cells is ~6/n_cells (~0.1%), and a collided (degenerate) sample is
    already handled by the solver's branch penalties + RANSAC scoring, the
    same way the reference tolerates its occasional degenerate draws.
    """
    return jax.random.randint(key, (n_hyps, set_size), 0, n_cells)


@partial(jax.jit, static_argnames=("n_hyps", "n_cells", "set_size"))
def sample_correspondence_sets_exact(
    key: jax.Array,
    n_hyps: int,
    n_cells: int,
    set_size: int = 4,
) -> jnp.ndarray:
    """Exact without-replacement variant (Gumbel top-k); slower, for tests."""
    keys = jax.random.split(key, n_hyps)

    def one(k):
        g = jax.random.gumbel(k, (n_cells,))
        _, idx = jax.lax.top_k(g, set_size)
        return idx

    return jax.vmap(one)(keys)


def sample_expert_indices(
    key: jax.Array,
    gating_probs: jnp.ndarray,
    n_hyps: int,
) -> jnp.ndarray:
    """Draw one expert index per hypothesis from the gating distribution.

    gating_probs: (M,) softmax output of the gating network.  Returns
    (n_hyps,) int32.  This is the discrete draw that gets a score-function
    (REINFORCE) gradient during end-to-end training (SURVEY.md §0 step 1).
    """
    return jax.random.categorical(
        key, jnp.log(gating_probs + 1e-12), shape=(n_hyps,)
    )
