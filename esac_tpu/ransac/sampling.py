"""Correspondence-set sampling for hypothesis generation.

The reference's C++ loop draws 4 random output pixels per hypothesis with a
per-OpenMP-thread RNG (SURVEY.md §2 #5, §3.5).  Here every hypothesis gets
its own fold of a single JAX PRNG key, and "4 distinct indices out of N" is a
Gumbel-top-4: add i.i.d. Gumbel noise to a flat logit field and take top-k.
That is an exact without-replacement uniform sample, fully batched — no
rejection loop, no host RNG state.

Sampling contract (the cross-backend reproducibility contract, SURVEY.md
hard part #4): given (key, n_hyps, N), hypothesis j uses
``jax.random.fold_in(key, j)`` and draws indices via Gumbel-top-4 over N
cells.  Backends cannot share bit-identical streams with the C++ path; they
are compared statistically (same distribution) instead.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_hyps", "n_cells", "set_size"))
def sample_correspondence_sets(
    key: jax.Array,
    n_hyps: int,
    n_cells: int,
    set_size: int = 4,
) -> jnp.ndarray:
    """Draw ``n_hyps`` sets of ``set_size`` distinct indices in [0, n_cells).

    Returns (n_hyps, set_size) int32.
    """
    keys = jax.random.split(key, n_hyps)

    def one(k):
        g = jax.random.gumbel(k, (n_cells,))
        _, idx = jax.lax.top_k(g, set_size)
        return idx

    return jax.vmap(one)(keys)


def sample_expert_indices(
    key: jax.Array,
    gating_probs: jnp.ndarray,
    n_hyps: int,
) -> jnp.ndarray:
    """Draw one expert index per hypothesis from the gating distribution.

    gating_probs: (M,) softmax output of the gating network.  Returns
    (n_hyps,) int32.  This is the discrete draw that gets a score-function
    (REINFORCE) gradient during end-to-end training (SURVEY.md §0 step 1).
    """
    return jax.random.categorical(
        key, jnp.log(gating_probs + 1e-12), shape=(n_hyps,)
    )
