"""Shared plumbing for the entry scripts (train_expert / train_gating /
train_esac / test_esac at the repo root).

The reference's scripts are argparse CLIs over a common dataset layout
(SURVEY.md §2 #9-12); these helpers keep the four scripts thin and their
flag surface consistent, including the ``--backend {jax,cpp}`` switch the
build adds (BASELINE.json north star).
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from esac_tpu.data.datasets import batch_frames, open_scene
from esac_tpu.models import ExpertNet, GatingNet

# Architecture presets: "ref" is the reference-scale net (SURVEY.md §2 #1),
# "test" is sized for CPU smoke runs and CI.
EXPERT_PRESETS = {
    "ref": dict(stem_channels=(64, 128, 256), head_channels=512, head_depth=4),
    "small": dict(stem_channels=(32, 64, 128), head_channels=256, head_depth=3),
    "test": dict(stem_channels=(16, 32, 64), head_channels=64, head_depth=2),
}
GATING_PRESETS = {
    "ref": dict(channels=(32, 64, 128, 256)),
    # Between test and ref: enough capacity for many-way (~50-scene)
    # routing at toy resolutions without ref's depth (which collapsed to
    # uniform logits at 48x64 / lr 1e-3 in the ep50 runs — see
    # experiments/ep50_gating_v2.sh header).
    "small": dict(channels=(16, 32, 64)),
    "test": dict(channels=(8, 16)),
}


def common_parser(desc: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=desc)
    p.add_argument("--backend", choices=("jax", "cpp"), default="jax",
                   help="hypothesis-loop implementation (cpp = host CPU reference path)")
    p.add_argument("--root", default="datasets", help="dataset root directory")
    p.add_argument("--size", choices=tuple(EXPERT_PRESETS), default="ref",
                   help="network size preset")
    p.add_argument("--iterations", type=int, default=1000)
    p.add_argument("--learningrate", type=float, default=1e-4)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend for the NN compute as well")
    p.add_argument("--resume", action="store_true",
                   help="resume from the output checkpoint (params, optimizer "
                        "state and iteration; data/RNG streams fast-forward so "
                        "the trajectory matches an uninterrupted run)")
    p.add_argument("--stop-after", type=int, default=0,
                   help="checkpoint and exit after this many iterations THIS "
                        "invocation (0 = run to --iterations); --iterations "
                        "still sets the LR schedule, so a stopped+resumed run "
                        "reproduces the uninterrupted trajectory")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="also save the resume-capable train state every N "
                        "iterations (0 = only at the end); long runs on "
                        "remote accelerators should set this so a relay "
                        "stall or preemption costs at most N iterations")
    p.add_argument("--frames", type=int, default=0,
                   help="synthetic scenes only: frames rendered per scene "
                        "(0 = the SyntheticScene default; on-disk datasets "
                        "have fixed frame counts and ignore this)")
    p.add_argument("--res", type=int, nargs=2, default=None,
                   metavar=("H", "W"),
                   help="synthetic scenes only: render resolution "
                        "(default 96 128; reference-scale runs use 192 256)")
    return p


def add_scoring_impl_arg(p: argparse.ArgumentParser) -> None:
    """--scoring-impl for the scripts that run the hypothesis loop
    (train_esac.py / test_esac.py); stage-1/2 trainers build no RansacConfig
    so the flag would be dead weight in common_parser."""
    p.add_argument("--scoring-impl", choices=("errmap", "fused", "pallas"),
                   default="errmap",
                   help="hypothesis-scoring implementation (jax backend): "
                        "errmap = reference-parity error map, fused = one "
                        "fused XLA broadcast+reduce program, pallas = the "
                        "hand-written TPU VMEM kernel; all differentiable "
                        "(see RansacConfig.scoring_impl)")


def scene_kwargs(args) -> dict:
    """open_scene kwargs from the synthetic-scale flags (--frames/--res)."""
    kw = {}
    if getattr(args, "frames", 0):
        kw["n_frames"] = args.frames
    if getattr(args, "res", None):
        kw["height"], kw["width"] = args.res
    return kw


def maybe_force_cpu(args) -> None:
    if getattr(args, "cpu", False):
        jax.config.update("jax_platforms", "cpu")


def make_expert(size: str, scene_center, dtype=None) -> ExpertNet:
    kw = dict(EXPERT_PRESETS[size], scene_center=tuple(float(x) for x in scene_center))
    if dtype is not None:
        kw["compute_dtype"] = dtype
    return ExpertNet(**kw)


def make_gating(size: str, num_experts: int, dtype=None) -> GatingNet:
    kw = dict(GATING_PRESETS[size], num_experts=num_experts)
    if dtype is not None:
        kw["compute_dtype"] = dtype
    return GatingNet(**kw)


def scene_center_of(ds, n_probe: int = 8) -> np.ndarray:
    """Mean GT scene coordinate over a few frames (the per-scene offset the
    expert regresses around, as the reference initializes with the scene
    translation).  Scenes without GT coords (the outdoor/no-depth path)
    fall back to the mean camera center, the only scene-frame anchor the
    pose list provides."""
    cs, cams = [], []
    for i in np.linspace(0, len(ds) - 1, min(n_probe, len(ds))).astype(int):
        f = ds[int(i)]
        if f.coords_gt is not None:
            cs.append(f.coords_gt.reshape(-1, 3).mean(axis=0))
        else:
            from esac_tpu.geometry import rodrigues

            R = np.asarray(rodrigues(jnp.asarray(f.rvec)))
            cams.append(-R.T @ np.asarray(f.tvec))
    if cs:
        return np.stack(cs).mean(axis=0)
    if cams:
        return np.stack(cams).mean(axis=0).astype(np.float32)
    return np.zeros(3, dtype=np.float32)


def epoch_batches(rng: np.random.Generator, n: int, batch: int):
    """Yield random index batches forever."""
    while True:
        yield rng.integers(0, n, size=batch)


__all__ = [
    "common_parser",
    "maybe_force_cpu",
    "make_expert",
    "make_gating",
    "scene_center_of",
    "epoch_batches",
    "batch_frames",
    "open_scene",
    "scene_kwargs",
]
