"""esac_tpu — TPU-native expert-sample-consensus camera re-localization.

A from-scratch JAX/Flax/XLA rebuild of the capabilities of vislearn/esac
(ICCV 2019, "Expert Sample Consensus Applied to Camera Re-Localization").
The reference implements its hypothesis loop as a CPU-bound C++/OpenMP/OpenCV
torch extension (see SURVEY.md §2 #3-7; the reference mount was empty, so
paths there are reconstructed, not verified); here the whole pipeline —
scene-coordinate regression, 4-point PnP, soft-inlier scoring, selection and
refinement — is pure JAX, `vmap`'d over hypotheses and compiled by XLA into a
single TPU dispatch.

Subpackages (landing incrementally; only those importable in this tree exist)
-----------
- ``geometry``  : rotations, camera projection, pose metrics, differentiable PnP
- ``ransac``    : the vmap'd hypothesis kernel (sample → solve → score → refine)
- ``models``    : Flax expert FCN + gating network
- ``parallel``  : device-mesh sharding of expert ensembles, pose all-reduce
- ``data``      : synthetic scenes + dataset loaders (7-Scenes / 12-Scenes / Aachen)
- ``train``     : three-stage training (expert init, gating init, end-to-end)
"""

__version__ = "0.1.0"
