"""Mesh construction and canonical shardings.

Axis convention: ``("data", "expert")`` — data-parallel frames on the outer
axis (DCN-friendly), expert shards on the inner axis (ICI-friendly), so the
winning-pose all-reduce and any expert-map gathers ride the faster fabric,
following the standard mesh layout recipe (outer = slower interconnect).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map moved from jax.experimental to the jax namespace upstream; this
# container's jax (0.4.x) only has the experimental spelling.  Every
# shard_map in the package goes through this alias so the code works on
# both (and the graft-lint jaxpr auditor can trace the sharded train step).
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]


def ensure_virtual_devices(n: int) -> None:
    """Best-effort: give this CPU process ``n`` virtual devices.

    Must run before the backend initializes (first ``jax.devices()`` /
    array op); afterwards it is a silent no-op and the caller's
    ``device_count`` check fires instead.  Newer jax spells this
    ``jax_num_cpu_devices``; this container's 0.4.x only honors the
    ``XLA_FLAGS`` host-platform flag (the same one tests/conftest.py sets).
    """
    import os

    try:
        jax.config.update("jax_num_cpu_devices", n)
        return
    except Exception:
        pass
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def make_mesh(
    n_data: int = 1,
    n_expert: int | None = None,
    devices: list | None = None,
) -> Mesh:
    """Build a ("data", "expert") mesh.

    Uses all available devices by default; pass ``devices`` to build over a
    subset (e.g. a dry run asked for fewer devices than the process has).
    """
    n_dev = len(devices) if devices is not None else jax.device_count()
    if n_expert is None:
        n_expert = n_dev // n_data
    if n_data * n_expert != n_dev:
        raise ValueError(
            f"mesh {n_data}x{n_expert} != device count {n_dev}"
        )
    if devices is None:
        # Topology-aware ordering: on a real slice this maps mesh axes onto
        # the ICI torus so the expert-axis collectives ride adjacent links.
        dev_grid = mesh_utils.create_device_mesh((n_data, n_expert))
    else:
        # Explicit subset (dry runs): enumeration order is all we have.
        dev_grid = np.asarray(devices, dtype=object).reshape(n_data, n_expert)
    return Mesh(dev_grid, axis_names=("data", "expert"))


def expert_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (expert) axis: coords_all (M, ...), stacked params."""
    return NamedSharding(mesh, P("expert"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis of per-frame data."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
