"""Expert-sharded ESAC inference: the winning-pose argmax all-reduce.

BASELINE.md config #4: experts sharded over the mesh; every device generates
and scores hypotheses for its local experts only, refines its local best,
and the globally best pose is selected by an argmax all-reduce over the
``expert`` axis — ``lax.pmax`` on the score, deterministic tie-break on the
global expert index, ``lax.psum`` of the masked winner pose.  This is the
single real cross-chip collective of the workload (SURVEY.md §2), expressed
with ``shard_map`` so the communication pattern is explicit and rides ICI.

Two inference paths:

- ``esac_infer_sharded`` — dense: every device scores ALL of its local
  experts' coordinate maps.  Right for small M (the all-experts consensus
  strictly dominates subset selection) and for callers that precompute the
  coordinate stack.
- ``esac_infer_routed`` — gating-routed (SURVEY.md §2 EP row: "gating routes
  each query image to device-local experts"; §7 hard part #3): each device
  runs the expert CNN forwards for only its top-``capacity`` local experts
  by gating mass — static-shaped, MoE-capacity-style.  This is the sparse
  compute the gating network exists to buy (at Aachen's M=50 the dense path
  spends ~M/ (D*capacity) times the necessary expert compute per frame).
  Semantics match ``ransac.esac.esac_infer_topk``: consensus argmax over
  the evaluated subset; a gating miss (true expert not selected) fails the
  frame, exactly as the reference's drawn-subset policy can.  Capacity
  overflow — more than ``capacity`` of the global top experts colocated on
  one device — drops the overflow experts (the MoE capacity trade), which
  is the one divergence from global top-k and is surfaced via the returned
  ``experts_evaluated``.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from esac_tpu.parallel.mesh import shard_map
from esac_tpu.ransac.config import RansacConfig
from esac_tpu.ransac.esac import _per_expert_winners, _routed_frame_winner
from esac_tpu.ransac.kernel import _split_score_key
from esac_tpu.ransac.refine import refine_soft_inliers


def _winner_allreduce(local_score, g_expert, rvec, tvec, M, axis="expert"):
    """The argmax all-reduce: pmax the score over ``axis``, break ties toward
    the smallest global expert index, psum the winner-masked pose.  The one
    real cross-chip collective of the workload — shared by the dense and
    routed paths so selection semantics cannot diverge.  Works elementwise
    over any leading batch shape (scores (…,), poses (…, 3))."""
    best = jax.lax.pmax(local_score, axis)
    tie = jnp.where(local_score >= best, g_expert, M)
    win = jax.lax.pmin(tie, axis)
    is_w = (g_expert == win).astype(rvec.dtype)[..., None]
    rvec_g = jax.lax.psum(rvec * is_w, axis)
    tvec_g = jax.lax.psum(tvec * is_w, axis)
    return rvec_g, tvec_g, win, best


def esac_infer_sharded(
    mesh: Mesh,
    key: jax.Array,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
    gating_logits: jnp.ndarray | None = None,
):
    """Sharded multi-expert inference. coords_all: (M, N, 3), M divisible by
    the mesh's ``expert`` axis size.  Returns (rvec, tvec, expert, score) —
    replicated on all devices.

    ``gating_logits`` (M,), replicated: accepted for surface parity with the
    single-chip ``esac_infer`` — selection stays consensus-by-score over ALL
    experts (which strictly dominates gated subsets when everything is
    computed anyway); callers that want gating to PRUNE compute use
    ``esac_infer_routed``.
    """
    del gating_logits  # consensus path: reported upstream, not used here
    n_exp_shards = mesh.shape["expert"]
    M = coords_all.shape[0]
    if M % n_exp_shards != 0:
        raise ValueError(f"M={M} not divisible by expert shards {n_exp_shards}")
    return _sharded_infer_fn(mesh, cfg)(
        key, coords_all, pixels, jnp.asarray(f), jnp.asarray(c)
    )


@lru_cache(maxsize=None)
def _sharded_infer_fn(mesh: Mesh, cfg: RansacConfig):
    """The jitted shard_map body behind :func:`esac_infer_sharded`, cached
    per (mesh, cfg) so repeated direct calls reuse ONE compiled program
    instead of rebuilding (and retracing) the wrapper every call — the
    graft-lint R9 retrace hazard.  ``f``/``c`` ride as traced replicated
    arguments (the same inversion as the ``_dynamic`` frames entry), so the
    cache key needs no array state; per-shape specialization stays inside
    the one jit cache."""
    n_exp_shards = mesh.shape["expert"]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P("expert"), P(), P(), P()),
        out_specs=(P(), P(), P(), P()),
    )
    def body(k, coords_local, px, f, c):
        # Split the scoring-subsample key BEFORE the per-shard fold_in: the
        # cross-shard argmax compares soft-inlier scores, which are only
        # comparable if every shard scores on the same random cell subset.
        # Only the hypothesis key differs per shard.
        shard_id = jax.lax.axis_index("expert")
        m_local = coords_local.shape[0]
        M = m_local * n_exp_shards
        k_hyp, k_sub = _split_score_key(k, cfg)
        k_local = jax.random.fold_in(k_hyp, shard_id)
        rvecs, tvecs, best_j, best_s, _ = _per_expert_winners(
            k_local, coords_local, px, f, c, cfg, score_key=k_sub,
        )  # (m_local, nh, 3) poses, (m_local,) streamed winners

        # Local winner + full refinement (each device refines one pose);
        # the per-expert streamed winners reduce exactly like the old flat
        # argmax (first-max-wins at every level).
        mi = jnp.argmax(best_s)
        j = best_j[mi]
        rvec, tvec = refine_soft_inliers(
            rvecs[mi, j], tvecs[mi, j], coords_local[mi], px, f, c,
            cfg.tau, cfg.beta, iters=cfg.refine_iters,
        )
        local_score = best_s[mi]
        global_expert = shard_id * m_local + mi

        return _winner_allreduce(local_score, global_expert, rvec, tvec, M)

    return jax.jit(body)


def make_esac_infer_sharded_frames(
    mesh: Mesh,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
    as_tree: bool = False,
):
    """Build the frames-major sharded inference entry (built ONCE so the
    serving path gets a stable jit cache: one compile per frame bucket).

    Returned callable takes ``(keys, coords_all, pixels, f)`` with keys
    (B,) typed PRNG keys, coords_all (B, M, N, 3) — M divisible by the
    mesh's expert axis — pixels (B, N, 2) and f (B,) per-frame focals, and
    returns a dict of replicated (B,)-leading results (rvec, tvec, expert,
    score).  Per shard, the per-frame local-winner work is vmapped over B
    so P3P/selection/refine run once per dispatch, then the batched argmax
    all-reduce (`_winner_allreduce` is elementwise over leading axes)
    selects each frame's global winner.  ``as_tree=True`` makes it a
    one-argument callable over a frame-stacked tree (leaves ``key``,
    ``coords_all``, ``pixels``, ``f``) — the MicroBatchDispatcher contract
    (serve.make_sharded_serve_fn).

    Implementation: binds ``c`` over the registry-backed
    :func:`make_esac_infer_sharded_frames_dynamic` (c as a traced,
    replicated argument), so the single-scene and multi-scene paths share
    ONE shard_map body and cannot diverge.
    """
    infer_dyn = make_esac_infer_sharded_frames_dynamic(mesh, cfg)
    c = jnp.asarray(c)

    def infer_tree(batch):
        return infer_dyn(batch, c)

    infer_tree._cache_size = infer_dyn._cache_size

    if as_tree:
        return infer_tree

    def infer(keys, coords_all, pixels, f):
        return infer_tree({
            "key": keys, "coords_all": coords_all, "pixels": pixels, "f": f,
        })

    return infer


def make_esac_infer_sharded_frames_dynamic(
    mesh: Mesh,
    cfg: RansacConfig = RansacConfig(),
):
    """Registry-backed variant of :func:`make_esac_infer_sharded_frames`:
    the principal point is a TRACED, replicated argument instead of a
    closure constant, so ONE compiled program (per frame bucket) serves
    every scene that shares shapes and ``cfg`` — hot-swapping a scene's
    camera never recompiles (esac_tpu.registry wires the per-scene ``c``
    from its device weight cache).  Returned callable:
    ``fn(batch, c) -> dict`` with ``batch`` the frame-stacked tree of
    :func:`make_esac_infer_sharded_frames` (leaves ``key``, ``coords_all``,
    ``pixels``, ``f``) and ``c`` the (2,) principal point.
    """
    n_shards = mesh.shape["expert"]
    specs = {
        "key": P(), "coords_all": P(None, "expert"), "pixels": P(), "f": P(),
    }

    @partial(
        shard_map, mesh=mesh, in_specs=(specs, P()),
        out_specs=(P(), P(), P(), P()),
    )
    def body(batch, c):
        coords_local = batch["coords_all"]  # (B, m_local, N, 3)
        m_local = coords_local.shape[1]
        M = m_local * n_shards
        shard_id = jax.lax.axis_index("expert")

        def one_frame(k, coords_m, px, fi):
            # Key discipline as in make_esac_infer_sharded_frames: the
            # score-subsample key splits BEFORE the per-shard fold.
            k_hyp, k_sub = _split_score_key(k, cfg)
            k_local = jax.random.fold_in(k_hyp, shard_id)
            rvecs, tvecs, best_j, best_s, _ = _per_expert_winners(
                k_local, coords_m, px, fi, c, cfg, score_key=k_sub,
            )
            mi = jnp.argmax(best_s)
            j = best_j[mi]
            rvec, tvec = refine_soft_inliers(
                rvecs[mi, j], tvecs[mi, j], coords_m[mi], px, fi, c,
                cfg.tau, cfg.beta, iters=cfg.refine_iters,
            )
            return rvec, tvec, best_s[mi], shard_id * m_local + mi

        rvec, tvec, local_score, g_expert = jax.vmap(one_frame)(
            batch["key"], coords_local, batch["pixels"], batch["f"]
        )
        return _winner_allreduce(local_score, g_expert, rvec, tvec, M)

    @jax.jit
    def infer_tree(batch, c):
        M = batch["coords_all"].shape[1]
        if M % n_shards != 0:
            raise ValueError(
                f"M={M} not divisible by expert shards {n_shards}"
            )
        rvec, tvec, expert, score = body(batch, jnp.asarray(c))
        return {"rvec": rvec, "tvec": tvec, "expert": expert, "score": score}

    return infer_tree


def esac_infer_sharded_frames(
    mesh: Mesh,
    keys: jax.Array,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
) -> dict:
    """Direct-call frames-major sharded inference (shapes as documented on
    :func:`make_esac_infer_sharded_frames`).  Rebuilds the shard_map body
    per call, matching ``esac_infer_sharded``'s surface; serving callers
    wanting a stable jit cache should hold the built fn instead."""
    return make_esac_infer_sharded_frames(mesh, c, cfg)(
        keys, coords_all, pixels, f
    )


def pad_experts_for_mesh(e_stack, centers, n_shards: int):
    """Pad stacked expert params / scene centers so the expert count divides
    ``n_shards``.

    Padding repeats expert 0's params (cheapest valid tree); pad the gating
    logits per batch with :func:`pad_gating_logits` — ``esac_infer_routed``
    masks slots whose logit is -inf out of the score argmax, so a padded
    expert can be *selected* into a slot (when a shard holds fewer real
    experts than ``capacity``) but can never win.  Returns
    (e_stack, centers, M_padded).
    """
    M = centers.shape[0]
    M_pad = ((M + n_shards - 1) // n_shards) * n_shards
    extra = M_pad - M
    if extra == 0:
        return e_stack, centers, M
    e_stack = jax.tree.map(
        lambda x: jnp.concatenate(
            [x, jnp.repeat(x[:1], extra, axis=0)], axis=0
        ),
        e_stack,
    )
    centers = jnp.concatenate(
        [centers, jnp.repeat(centers[:1], extra, axis=0)], axis=0
    )
    return e_stack, centers, M_pad


def pad_gating_logits(logits: jnp.ndarray, M_pad: int) -> jnp.ndarray:
    """Pad the last (expert) axis of gating logits to ``M_pad`` with -inf —
    the per-batch companion of :func:`pad_experts_for_mesh` (params/centers
    are padded once; logits are produced per batch by the gating net)."""
    extra = M_pad - logits.shape[-1]
    if extra == 0:
        return logits
    pad = jnp.full(logits.shape[:-1] + (extra,), -jnp.inf, logits.dtype)
    return jnp.concatenate([logits, pad], axis=-1)


def route_frames_to_experts(selected: jnp.ndarray, num_experts: int,
                            capacity: int):
    """The MoE capacity dispatch shared by the routed SERVE paths: assign
    each (frame, selected-expert) pair a slot in that expert's fixed-size
    frame block, dropping overflow deterministically.

    ``selected``: (B, K) int32 global expert ids per frame (distinct within
    a frame — ``ransac.esac.select_topk_experts`` output); ``capacity`` is
    the static per-expert block width C.  Drop priority is FRAME INDEX:
    frame b's slot in expert m's block is the count of earlier frames that
    also selected m, and slots >= C drop.  That rule is what makes the
    serve-path bucket-invariance contract hold: tail padding appends pad
    frames AFTER every real frame, so a pad lane can occupy capacity only
    behind all real claimants and can never displace a real (frame, expert)
    pair (pinned in tests/test_serve_routed.py).

    Returns ``(kept, pos, slot_frame, slot_valid)``:

    - ``kept``       (B, K) bool — pair survived capacity;
    - ``pos``        (B, K) int32 — slot index in the expert's block
      (meaningful where ``kept``; clip before gathering);
    - ``slot_frame`` (M, C) int32 — frame index riding each block slot
      (0-filled where invalid: finite-garbage compute, masked downstream);
    - ``slot_valid`` (M, C) bool.

    Everything is static-shaped (one_hot + cumsum + comparisons); both the
    single-chip routed bucket programs (registry/serving.py) and the
    expert-sharded routed serve path below dispatch through this function,
    so their drop semantics cannot diverge.
    """
    B, K = selected.shape
    onehot = jax.nn.one_hot(selected, num_experts, dtype=jnp.int32)  # (B,K,M)
    mask = onehot.sum(axis=1)  # (B, M) in {0, 1}: frame b selected expert m
    # Earlier-frames-first positions: frame b's slot in m's block is the
    # number of frames < b that selected m.
    order = jnp.cumsum(mask, axis=0) - mask  # (B, M)
    kept_bm = (mask == 1) & (order < capacity)
    pos = jnp.take_along_axis(order, selected, axis=1).astype(jnp.int32)
    kept = jnp.take_along_axis(kept_bm, selected, axis=1)
    slot_hit = (
        kept_bm.T[:, None, :]
        & (order.T[:, None, :] == jnp.arange(capacity)[None, :, None])
    )  # (M, C, B)
    slot_valid = slot_hit.any(axis=-1)
    slot_frame = jnp.argmax(slot_hit, axis=-1).astype(jnp.int32)
    return kept, pos, slot_frame, slot_valid


def make_esac_infer_routed_frames_sharded(
    mesh: Mesh,
    expert_apply,
    e_stack,
    centers: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
    k: int = 4,
    capacity: int | None = None,
):
    """Expert-sharded, frames-major, gating-first routed SERVE path.

    The sharded sibling of ``registry.make_routed_scene_bucket_fn``: per
    frame the global top-``k`` experts by gating are selected, each shard
    runs CNN forwards only for its LOCAL selected experts — routed through
    :func:`route_frames_to_experts` into fixed ``capacity``-frame blocks
    (one batched forward per local expert instead of per-(frame, expert)
    param gathers) — and the winner rides the shared
    :func:`_winner_allreduce`.  ``capacity`` defaults to
    ``ransac.esac.routed_serve_capacity(cfg, k, M)``.

    Returned callable: ``infer(keys, gating_logits, images, focals,
    pixels, c) -> dict`` with keys (B,) typed PRNG keys, gating_logits
    (B, M) and images (B, H, W, 3) replicated, focals (B,), pixels (N, 2),
    c (2,); outputs are (B,)-leading and replicated, with
    ``experts_evaluated`` (B, k) global ids (sentinel M = dropped) exactly
    matching the single-chip routed program's accounting.  Per-frame
    hypothesis work (``k`` slots x the reallocated budget) is replicated
    across shards — the CNN forwards are what this path shards; right when
    the expert networks dominate, which is the routed regime's premise.
    RNG: per-expert hypothesis streams are keyed by GLOBAL expert index
    (no per-shard fold), so evaluated pairs score bit-identically to the
    single-chip routed program.
    """
    import dataclasses

    from esac_tpu.ransac.esac import (
        routed_serve_capacity,
        select_topk_experts,
    )

    n_shards = mesh.shape["expert"]
    M = centers.shape[0]
    if M % n_shards != 0:
        raise ValueError(
            f"M={M} not divisible by expert shards {n_shards}; "
            "pad with pad_experts_for_mesh"
        )
    m_local = M // n_shards
    k = min(k, M)
    cap = (capacity if capacity is not None
           else routed_serve_capacity(cfg, k, M))
    nh = max(1, (cfg.n_hyps * M) // k)
    cfg_k = dataclasses.replace(cfg, n_hyps=nh)

    e_specs = jax.tree.map(lambda _: P("expert"), e_stack)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), e_specs, P("expert"), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
    )
    def body(keys_B, logits_B, images_B, focals_B, e_local, centers_local,
             px, c_pt):
        shard_id = jax.lax.axis_index("expert")
        lo = shard_id * m_local
        selected = select_topk_experts(logits_B, k)  # (B, k), replicated calc
        kept, pos, slot_frame, slot_valid = route_frames_to_experts(
            selected, M, cap
        )
        # Only this shard's expert rows of the global block table.
        slot_frame_l = jax.lax.dynamic_slice(
            slot_frame, (lo, 0), (m_local, cap)
        )
        blocks = images_B[slot_frame_l]  # (m_local, C, H, W, 3)
        coords_b = jax.vmap(expert_apply)(e_local, blocks)
        coords_b = coords_b.reshape(m_local, cap, -1, 3) \
            + centers_local[:, None, None, :]
        is_local = (selected >= lo) & (selected < lo + m_local)  # (B, k)
        live = kept & is_local
        sel_l = jnp.clip(selected - lo, 0, m_local - 1)
        coords_sel = coords_b[sel_l, jnp.minimum(pos, cap - 1)]  # (B,k,N,3)

        def one_frame(key, logits, co_sel, sel, lv, fi):
            rvec, tvec, scores, mi, best = _routed_frame_winner(
                key, co_sel, sel, lv, px, fi, c_pt, cfg_k, M
            )
            # A shard with no live slot for this frame must lose the
            # all-reduce and never collide in the tie-break — EXCEPT when
            # the whole frame dropped on every shard: then the shard
            # owning sel[0] claims it (all scores are -inf, so the
            # tie-break elects that unique claimant), matching the
            # single-chip entry's failed-frame output `sel[argmax(-inf)]
            # == sel[0]` — 'expert' stays a real 0..M-1 id and exactly
            # one shard's (finite-garbage) pose survives the psum.
            owner0 = (sel[0] >= lo) & (sel[0] < lo + m_local)
            g_expert = jnp.where(
                lv.any(), sel[mi], jnp.where(owner0, sel[0], M)
            )
            return rvec, tvec, best, g_expert

        rvec, tvec, local_score, g_expert = jax.vmap(one_frame)(
            keys_B, logits_B, coords_sel, selected, live, focals_B
        )
        rvec_g, tvec_g, win, best = _winner_allreduce(
            local_score, g_expert, rvec, tvec, M + 1
        )
        # Each (frame, slot) pair is owned by exactly one shard; pmin over
        # the expert axis recovers the owner's verdict (M = dropped).
        evaluated_local = jnp.where(live, selected, M)
        evaluated = jax.lax.pmin(evaluated_local, "expert")
        return rvec_g, tvec_g, win, best, evaluated

    jit_body = jax.jit(body)

    def infer(keys, gating_logits, images, focals, pixels, c):
        if gating_logits.shape[-1] != M:
            raise ValueError(
                f"gating_logits last dim {gating_logits.shape[-1]} != "
                f"expert count {M}"
            )
        rvec, tvec, expert, score, evaluated = jit_body(
            keys, gating_logits, images, focals, e_stack, centers,
            pixels, jnp.asarray(c),
        )
        return {
            "rvec": rvec,
            "tvec": tvec,
            "expert": expert,
            "score": score,
            "experts_evaluated": evaluated,
        }

    infer._cache_size = jit_body._cache_size
    return infer


def esac_infer_routed(
    mesh: Mesh,
    expert_apply,
    e_stack,
    centers: jnp.ndarray,
    capacity: int,
    cfg: RansacConfig = RansacConfig(),
):
    """Build the gating-routed sharded inference function (config #4).

    ``expert_apply(params, images) -> (B, h, w, 3)`` is the expert network
    forward; ``e_stack`` is the stacked param tree with leading axis M
    (divisible by the mesh's expert axis — use :func:`pad_experts_for_mesh`),
    ``centers`` (M, 3) the per-expert scene centers, ``capacity`` the static
    number of local experts each device runs per frame.

    Returns ``infer(key, gating_logits, images, focals, pixels, c) -> dict``
    where ``gating_logits`` is (B, M) and ``images`` (B, H, W, 3), both
    replicated, ``focals`` (B,) per-frame focal lengths, ``pixels`` the
    (N, 2) output-cell pixel grid and ``c`` the (2,) principal point; the
    result dict (all replicated) has:

    - ``rvec``/``tvec``: (B, 3) winning refined poses,
    - ``expert``: (B,) winning global expert index,
    - ``score``: (B,) winning soft-inlier score,
    - ``experts_evaluated``: (B, n_shards * capacity) global indices of the
      experts whose CNN actually ran for each frame — the compute-tracking
      record (gating misses and capacity drops are visible here).

    Per-frame expert compute is ``n_shards * capacity`` CNN forwards instead
    of M.  Scoring stays cross-shard comparable: the score-cell subsample key
    is split BEFORE the per-shard fold, as in ``esac_infer_sharded``.
    """
    n_shards = mesh.shape["expert"]
    M = centers.shape[0]
    if M % n_shards != 0:
        raise ValueError(
            f"M={M} not divisible by expert shards {n_shards}; "
            "pad with pad_experts_for_mesh"
        )
    m_local = M // n_shards
    cap = min(capacity, m_local)

    e_specs = jax.tree.map(lambda _: P("expert"), e_stack)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), e_specs, P("expert"), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
    )
    def body(k, logits_B, images_B, focals_B, e_local, centers_local, px,
             c_pt):
        shard_id = jax.lax.axis_index("expert")
        k_hyp, k_sub = _split_score_key(k, cfg)
        k_shard = jax.random.fold_in(k_hyp, shard_id)

        def one_frame(args):
            fi, logits, image, focal = args
            g = jax.nn.softmax(logits)  # (M,) — padded entries exactly 0
            g_local = jax.lax.dynamic_slice(
                g, (shard_id * m_local,), (m_local,)
            )
            l_local = jax.lax.dynamic_slice(
                logits, (shard_id * m_local,), (m_local,)
            )
            _, top_local = jax.lax.top_k(g_local, cap)
            # Padding detector: ONLY pad_gating_logits' -inf entries are
            # ineligible to win.  A real expert whose softmax mass underflows
            # to exact zero (logit gap > ~88 in f32) stays eligible — its
            # consensus score decides, matching esac_infer_topk, which has
            # no mass cutoff.
            is_real = jnp.isfinite(l_local[top_local])
            # Only the selected experts' CNNs run — the routed sparsity.
            params_c = jax.tree.map(lambda x: x[top_local], e_local)
            centers_c = centers_local[top_local]
            coords_c = jax.lax.map(
                lambda pc: expert_apply(pc[0], image[None])[0] + pc[1],
                (params_c, centers_c),
            )  # (cap, h, w, 3)
            coords_c = coords_c.reshape(cap, -1, 3)
            k_frame = jax.random.fold_in(k_shard, fi)
            rvecs, tvecs, best_j, best_s, _ = _per_expert_winners(
                k_frame, coords_c, px, focal, c_pt, cfg, score_key=k_sub,
            )  # (cap, nh, 3) poses, (cap,) streamed winners
            # Padding slots (a shard with fewer real experts than capacity)
            # must not win on consensus score.
            best_s = jnp.where(is_real, best_s, -jnp.inf)
            mi = jnp.argmax(best_s)
            # All-padding shard: match the flat argmax over an all -inf
            # matrix, which lands on (0, 0).
            j = jnp.where(is_real[mi], best_j[mi], 0)
            rvec, tvec = refine_soft_inliers(
                rvecs[mi, j], tvecs[mi, j], coords_c[mi], px, focal, c_pt,
                cfg.tau, cfg.beta, iters=cfg.refine_iters,
            )
            return (rvec, tvec, best_s[mi],
                    shard_id * m_local + top_local[mi],
                    shard_id * m_local + top_local)

        B = images_B.shape[0]
        rvec, tvec, local_score, g_expert, evaluated = jax.lax.map(
            one_frame,
            (jnp.arange(B), logits_B, images_B, focals_B),
        )  # (B,3) (B,3) (B,) (B,) (B,cap)

        # Batched argmax all-reduce over the expert axis (elementwise on B).
        rvec_g, tvec_g, win, best = _winner_allreduce(
            local_score, g_expert, rvec, tvec, M
        )
        # Assemble the per-frame evaluated sets via a scatter + psum (the
        # psum output is statically replicated, which the VMA check accepts
        # where an all_gather's output is not inferred as such).
        slots = jnp.zeros((B, n_shards, evaluated.shape[1]), evaluated.dtype)
        slots = jax.lax.dynamic_update_slice(
            slots, evaluated[:, None, :], (0, shard_id, 0)
        )
        evaluated_all = jax.lax.psum(slots, "expert").reshape(B, -1)
        return rvec_g, tvec_g, win, best, evaluated_all

    jit_body = jax.jit(body)

    def infer(key, gating_logits, images, focals, pixels, c):
        if gating_logits.shape[-1] != M:
            # Catch the pad_experts_for_mesh-without-pad_gating_logits
            # mistake loudly: dynamic_slice would CLAMP the out-of-range
            # shard starts and silently route every shard into the same
            # trailing window of the unpadded logits.
            raise ValueError(
                f"gating_logits last dim {gating_logits.shape[-1]} != padded "
                f"expert count {M}; run pad_gating_logits(logits, {M}) "
                "alongside pad_experts_for_mesh"
            )
        rvec, tvec, expert, score, evaluated = jit_body(
            key, gating_logits, images, focals, e_stack, centers, pixels, c
        )
        return {
            "rvec": rvec,
            "tvec": tvec,
            "expert": expert,
            "score": score,
            "experts_evaluated": evaluated,
        }

    return infer
