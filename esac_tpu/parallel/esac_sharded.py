"""Expert-sharded ESAC inference: the winning-pose argmax all-reduce.

BASELINE.md config #4: experts sharded over the mesh; every device generates
and scores hypotheses for its local experts only, refines its local best,
and the globally best pose is selected by an argmax all-reduce over the
``expert`` axis — ``lax.pmax`` on the score, deterministic tie-break on the
global expert index, ``lax.psum`` of the masked winner pose.  This is the
single real cross-chip collective of the workload (SURVEY.md §2), expressed
with ``shard_map`` so the communication pattern is explicit and rides ICI.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from esac_tpu.ransac.config import RansacConfig
from esac_tpu.ransac.esac import _per_expert_hypotheses
from esac_tpu.ransac.kernel import _split_score_key
from esac_tpu.ransac.refine import refine_soft_inliers


def esac_infer_sharded(
    mesh: Mesh,
    key: jax.Array,
    coords_all: jnp.ndarray,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig = RansacConfig(),
):
    """Sharded multi-expert inference. coords_all: (M, N, 3), M divisible by
    the mesh's ``expert`` axis size.  Returns (rvec, tvec, expert, score) —
    replicated on all devices.
    """
    n_exp_shards = mesh.shape["expert"]
    M = coords_all.shape[0]
    if M % n_exp_shards != 0:
        raise ValueError(f"M={M} not divisible by expert shards {n_exp_shards}")
    m_local = M // n_exp_shards

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P("expert"), P()),
        out_specs=(P(), P(), P(), P()),
    )
    def body(k, coords_local, px):
        # Split the scoring-subsample key BEFORE the per-shard fold_in: the
        # cross-shard argmax compares soft-inlier scores, which are only
        # comparable if every shard scores on the same random cell subset.
        # Only the hypothesis key differs per shard.
        shard_id = jax.lax.axis_index("expert")
        k_hyp, k_sub = _split_score_key(k, cfg)
        k_local = jax.random.fold_in(k_hyp, shard_id)
        rvecs, tvecs, scores = _per_expert_hypotheses(
            k_local, coords_local, px, f, c, cfg, score_key=k_sub,
        )  # (m_local, nh, 3), (m_local, nh)

        # Local winner + full refinement (each device refines one pose).
        flat = jnp.argmax(scores.reshape(-1))
        mi, j = flat // scores.shape[1], flat % scores.shape[1]
        rvec, tvec = refine_soft_inliers(
            rvecs[mi, j], tvecs[mi, j], coords_local[mi], px, f, c,
            cfg.tau, cfg.beta, iters=cfg.refine_iters,
        )
        local_score = scores[mi, j]
        global_expert = shard_id * m_local + mi

        # Argmax all-reduce over the expert axis: pmax the score, break ties
        # toward the smallest expert index, psum the masked winner.
        best_score = jax.lax.pmax(local_score, "expert")
        tie_idx = jnp.where(local_score >= best_score, global_expert, M)
        win_idx = jax.lax.pmin(tie_idx, "expert")
        is_winner = (global_expert == win_idx).astype(rvec.dtype)
        rvec_g = jax.lax.psum(rvec * is_winner, "expert")
        tvec_g = jax.lax.psum(tvec * is_winner, "expert")
        return rvec_g, tvec_g, win_idx, best_score

    return jax.jit(body)(key, coords_all, pixels)
