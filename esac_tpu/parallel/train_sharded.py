"""Sharded end-to-end ESAC training: EP + DP over a device mesh.

The SPMD training body behind BASELINE.md configs #4/#5: stacked expert
params sharded over the mesh's ``expert`` axis, the frame batch over the
``data`` axis, gating replicated.  Experts run locally on their shard's
frames; an ``all_gather`` over the expert axis assembles each frame's full
(M, cells, 3) coordinate stack (the EP collective, riding ICI on hardware);
``shard_map`` differentiability gives the backward pass the transposed
collectives (reduce-scatter of expert grads, psum of data grads) for free.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from esac_tpu.ransac.config import RansacConfig
from esac_tpu.ransac.esac import esac_train_loss


def make_sharded_esac_loss(
    mesh,
    expert_net,
    gating_net,
    e_params_template,
    g_params_template,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig,
    mode: str = "dense",
):
    """Build ``loss(e_params, g_params, images, R_gts, t_gts, key)`` shard_mapped
    over ``mesh``.

    e_params_template: stacked expert params (leading axis M, divisible by
    the mesh's expert-axis size); used only for tree structure.
    Batch size must be divisible by the data-axis size.
    """
    M_total = jax.tree.leaves(e_params_template)[0].shape[0]
    n_exp_shards = mesh.shape["expert"]
    if M_total % n_exp_shards != 0:
        raise ValueError(f"M={M_total} not divisible by expert axis {n_exp_shards}")

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P("expert"), e_params_template),
            jax.tree.map(lambda _: P(), g_params_template),
            P("data"),
            P("data", None, None),
            P("data"),
            P(),
        ),
        out_specs=P(),
    )
    def sharded_loss(e_p_local, g_p, images_local, R_gt_local, t_gt_local, key):
        b_local = images_local.shape[0]
        logits = gating_net.apply(g_p, images_local)  # (b_local, M_total)
        # Local experts on local frames (serial scan keeps convs full-size —
        # vmapping over conv kernels lowers to constraint-laden grouped convs).
        coords_local = jax.lax.map(
            lambda p: expert_net.apply(p, images_local), e_p_local
        )  # (m_local, b_local, h, w, 3)
        coords_all = jax.lax.all_gather(
            coords_local, "expert", axis=0, tiled=True
        )  # (M_total, b_local, h, w, 3)
        coords_all = jnp.swapaxes(coords_all, 0, 1).reshape(
            b_local, M_total, -1, 3
        )
        keys = jax.random.split(
            jax.random.fold_in(key, jax.lax.axis_index("data")), b_local
        )
        losses, _ = jax.vmap(
            lambda k, lg, ca, Rg, tg: esac_train_loss(
                k, lg, ca, pixels, f, c, Rg, tg, cfg, mode
            )
        )(keys, logits, coords_all, R_gt_local, t_gt_local)
        return jax.lax.pmean(jnp.mean(losses), ("data", "expert"))

    return sharded_loss


def shard_esac_params(mesh, e_params, g_params):
    """Place stacked expert params on the expert axis, gating replicated."""
    e_sharded = jax.device_put(
        e_params, jax.tree.map(lambda _: NamedSharding(mesh, P("expert")), e_params)
    )
    g_sharded = jax.device_put(
        g_params, jax.tree.map(lambda _: NamedSharding(mesh, P()), g_params)
    )
    return e_sharded, g_sharded
