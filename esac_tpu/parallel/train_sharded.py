"""Sharded end-to-end ESAC training: EP + DP over a device mesh.

The SPMD training body behind BASELINE.md configs #4/#5: stacked expert
params sharded over the mesh's ``expert`` axis, the frame batch over the
``data`` axis, gating replicated.

Two expert-compute policies (SURVEY.md §2 EP row, §7 hard part #3):

- **dense** (``capacity=None``): every local expert runs on every local
  frame; an ``all_gather`` over the expert axis assembles each frame's full
  (M, cells, 3) coordinate stack (the EP collective, riding ICI on
  hardware).  Exact gating gradient; right for M up to ~a dozen.
- **routed** (``capacity=k``): per frame, only the top-k local experts by
  gating mass run their CNN — the training-side counterpart of
  ``esac_infer_routed``.  No coordinate all_gather at all: each shard
  contributes its selected experts' ``g_m * L_m`` terms and the cross-shard
  combine is a scalar ``psum``.  At config #4's M=50 over 8 devices with
  capacity 2 that is 16/50 of the expert compute and none of the
  (M, b, h, w, 3) gather bandwidth.  The loss equals dense's
  ``sum_m g_m L_m`` truncated to the selected experts, so when the
  selection covers all nonzero gating mass the value AND gradients match
  dense exactly (pinned in tests/test_parallel.py); a gate that spreads
  mass past capacity gets a biased-low estimate, the standard
  capacity-routing trade.

``shard_map`` differentiability gives the backward pass the transposed
collectives (reduce-scatter of expert grads, psum of data grads) for free.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from esac_tpu.parallel.mesh import shard_map
from esac_tpu.ransac.config import RansacConfig
from esac_tpu.ransac.esac import (
    _expected_losses_per_expert, esac_train_loss,
)
from esac_tpu.ransac.kernel import (
    _score_hypotheses, _split_score_key, generate_hypotheses,
)


def make_sharded_esac_loss(
    mesh,
    expert_net,
    gating_net,
    e_params_template,
    g_params_template,
    pixels: jnp.ndarray,
    f: jnp.ndarray,
    c: jnp.ndarray,
    cfg: RansacConfig,
    mode: str = "dense",
    capacity: int | None = None,
):
    """Build ``loss(e_params, g_params, images, R_gts, t_gts, key)`` shard_mapped
    over ``mesh``.

    e_params_template: stacked expert params (leading axis M, divisible by
    the mesh's expert-axis size); used only for tree structure.
    Batch size must be divisible by the data-axis size.

    ``capacity`` switches to gating-routed expert compute (see module doc);
    it requires ``mode="dense"`` — the sampled/REINFORCE estimator draws
    experts from the full categorical and has no per-device top-k structure.
    """
    M_total = jax.tree.leaves(e_params_template)[0].shape[0]
    n_exp_shards = mesh.shape["expert"]
    if M_total % n_exp_shards != 0:
        raise ValueError(f"M={M_total} not divisible by expert axis {n_exp_shards}")
    m_local = M_total // n_exp_shards
    if capacity:
        if mode != "dense":
            raise ValueError("capacity routing requires mode='dense'")
        cap = min(capacity, m_local)

    in_specs = (
        jax.tree.map(lambda _: P("expert"), e_params_template),
        jax.tree.map(lambda _: P(), g_params_template),
        P("data"),
        P("data", None, None),
        P("data"),
        P(),
    )

    def frame_keys(key, b_local):
        """Per-frame hypothesis keys, identical in both policies."""
        return jax.random.split(
            jax.random.fold_in(key, jax.lax.axis_index("data")), b_local
        )

    @partial(shard_map, mesh=mesh, in_specs=in_specs, out_specs=P())
    def sharded_loss(e_p_local, g_p, images_local, R_gt_local, t_gt_local, key):
        b_local = images_local.shape[0]
        logits = gating_net.apply(g_p, images_local)  # (b_local, M_total)
        # Local experts on local frames (serial scan keeps convs full-size —
        # vmapping over conv kernels lowers to constraint-laden grouped convs).
        coords_local = jax.lax.map(
            lambda p: expert_net.apply(p, images_local), e_p_local
        )  # (m_local, b_local, h, w, 3)
        coords_all = jax.lax.all_gather(
            coords_local, "expert", axis=0, tiled=True
        )  # (M_total, b_local, h, w, 3)
        coords_all = jnp.swapaxes(coords_all, 0, 1).reshape(
            b_local, M_total, -1, 3
        )
        keys = frame_keys(key, b_local)
        losses, _ = jax.vmap(
            lambda k, lg, ca, Rg, tg: esac_train_loss(
                k, lg, ca, pixels, f, c, Rg, tg, cfg, mode
            )
        )(keys, logits, coords_all, R_gt_local, t_gt_local)
        return jax.lax.pmean(jnp.mean(losses), ("data", "expert"))

    @partial(shard_map, mesh=mesh, in_specs=in_specs, out_specs=P())
    def sharded_routed_loss(e_p_local, g_p, images_local, R_gt_local,
                            t_gt_local, key):
        b_local = images_local.shape[0]
        shard_id = jax.lax.axis_index("expert")
        logits = gating_net.apply(g_p, images_local)  # (b_local, M_total)
        keys = frame_keys(key, b_local)

        def one_frame(args):
            k, logits_i, image, R_gt, t_gt = args
            # RNG bit-exactly mirrors the dense path so routed == dense when
            # capacity covers the gating mass: esac_train_loss splits off
            # k_hyp, _per_expert_hypotheses splits (keys, k_sub), and the
            # per-expert key is split(k2, M)[m] at the GLOBAL expert index —
            # materialize all M keys (M x 4 bytes, trivial) and gather.
            k_hyp, _ = jax.random.split(k)
            k2, k_sub = _split_score_key(k_hyp, cfg)
            keys_all = jax.random.split(k2, M_total)

            g = jax.nn.softmax(logits_i)  # (M_total,)
            g_local = jax.lax.dynamic_slice(
                g, (shard_id * m_local,), (m_local,)
            )
            _, top_local = jax.lax.top_k(g_local, cap)
            gm = shard_id * m_local + top_local  # global expert indices
            # Only the selected experts' CNNs run — the routed sparsity.
            # Per-frame selection forces per-frame (batch-1) forwards; the
            # saving is b*M -> b*cap forwards and no coordinate all_gather.
            params_c = jax.tree.map(lambda x: x[top_local], e_p_local)
            coords_c = jax.lax.map(
                lambda p: expert_net.apply(p, image[None])[0], params_c
            ).reshape(cap, -1, 3)
            keys_c = keys_all[gm]
            rvecs, tvecs = jax.vmap(
                lambda kk, co: generate_hypotheses(kk, co, pixels, f, c, cfg)
            )(keys_c, coords_c)
            scores = jax.vmap(
                lambda rv, tv, co: _score_hypotheses(
                    k_sub, rv, tv, co, pixels, f, c, cfg
                )
            )(rvecs, tvecs, coords_c)
            exp_losses, _ = _expected_losses_per_expert(
                rvecs, tvecs, scores, coords_c, pixels, f, c, R_gt, t_gt, cfg
            )
            # This shard's share of sum_m g_m L_m (gradient flows into the
            # gating logits through the gathered softmax mass).
            return jnp.sum(g[gm] * exp_losses)

        partial_losses = jax.lax.map(
            one_frame, (keys, logits, images_local, R_gt_local, t_gt_local)
        )  # (b_local,)
        return jax.lax.pmean(
            jax.lax.psum(jnp.mean(partial_losses), "expert"), "data"
        )

    return sharded_routed_loss if capacity else sharded_loss


def shard_esac_params(mesh, e_params, g_params):
    """Place stacked expert params on the expert axis, gating replicated."""
    e_sharded = jax.device_put(
        e_params, jax.tree.map(lambda _: NamedSharding(mesh, P("expert")), e_params)
    )
    g_sharded = jax.device_put(
        g_params, jax.tree.map(lambda _: NamedSharding(mesh, P()), g_params)
    )
    return e_sharded, g_sharded
