"""Multi-host initialization: extend the mesh across hosts (DCN axis).

The reference is strictly single-process (SURVEY.md §2: no NCCL/MPI/Gloo);
scaling this framework across hosts needs only standard JAX distributed
bootstrap — the mesh abstraction and every collective in
``esac_tpu.parallel`` are host-count agnostic.  Layout guidance: keep the
``expert`` axis within a slice (its argmax all-reduce is latency-sensitive
and should ride ICI) and put the ``data`` axis across slices (gradient
pmeans tolerate DCN latency), which `make_mesh`'s (data, expert) ordering
already encodes.

Cannot be exercised in this single-host container; kept deliberately thin
over `jax.distributed` so there is nothing here to rot.
"""

from __future__ import annotations

import jax


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Initialize JAX distributed (TPU pods auto-detect all arguments).

    Call once per process before any other jax use.  Returns a summary dict
    {'process_index', 'process_count', 'local_devices', 'global_devices'}.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
