"""Device-mesh parallelism for expert ensembles and frame batches.

The reference has NO distributed layer — a single process with OpenMP threads
(SURVEY.md §2 "Parallelism strategies", §5 "Distributed communication
backend").  The TPU-native scaling axes are:

- **EP (expert parallel)**: experts sharded over the mesh's ``expert`` axis;
  the one real cross-chip collective is the argmax all-reduce that selects
  the globally best hypothesis (BASELINE.md config #4: "50 experts sharded
  over v4-8, all-reduce winning pose") — implemented with ``shard_map`` +
  ``lax.pmax``/``lax.psum`` so it rides ICI.
- **DP (data parallel)**: frame batches sharded over the ``data`` axis
  (BASELINE.md config #5, streaming relocalization) via ``NamedSharding``;
  XLA inserts gradient psums.
- **Hypothesis parallel**: ``vmap`` *within* a chip — thousands of
  hypotheses per XLA dispatch; this axis never needs communication.

TP / PP / SP / CP / ring attention / Ulysses: **not applicable** to this
workload — there is no sequence axis and no layer too large for one chip;
see PARALLELISM.md at the repo root for the explicit mapping.
"""

from esac_tpu.parallel.mesh import make_mesh, expert_sharding, batch_sharding
from esac_tpu.parallel.esac_sharded import (
    esac_infer_routed, esac_infer_sharded, esac_infer_sharded_frames,
    make_esac_infer_routed_frames_sharded, make_esac_infer_sharded_frames,
    make_esac_infer_sharded_frames_dynamic, pad_experts_for_mesh,
    pad_gating_logits, route_frames_to_experts,
)
from esac_tpu.parallel.multihost import initialize_multihost
from esac_tpu.parallel.train_sharded import make_sharded_esac_loss, shard_esac_params

__all__ = [
    "make_mesh",
    "expert_sharding",
    "batch_sharding",
    "esac_infer_routed",
    "esac_infer_sharded",
    "esac_infer_sharded_frames",
    "initialize_multihost",
    "make_esac_infer_routed_frames_sharded",
    "make_esac_infer_sharded_frames",
    "make_esac_infer_sharded_frames_dynamic",
    "make_sharded_esac_loss",
    "pad_experts_for_mesh",
    "pad_gating_logits",
    "route_frames_to_experts",
    "shard_esac_params",
]
