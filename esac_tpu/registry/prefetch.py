"""Predictive weight prefetch: tier admissions driven by the request
stream instead of demand faults.

`.registry_swap.json` pins the problem: a device warm hit is the ~3ms
class, a disk cold load the ~29ms class — and ROADMAP item 1 states the
consequence at fleet scale: with thousands of scenes behind one device
budget, the fault rate IS the tail latency.  Every serving PR so far
bounded latency *given* warm weights; this module decides *which*
weights are warm.

A :class:`WeightPrefetcher` is a background thread over a
:class:`~esac_tpu.registry.serving.SceneRegistry`:

- **Fed by arrivals, never by the hot path.**  The dispatcher calls
  :meth:`observe` once per scene-carrying submission — OUTSIDE its own
  lock, a bounded-deque append that never blocks and never raises (the
  ``arrival_sink`` contract in serve/dispatcher.py).  Everything else
  happens on the prefetch thread.
- **Recency/frequency scores.**  Each cycle folds the drained arrivals
  into per-scene exponentially-decayed counters (half-life
  ``halflife_s``) — the score ranking is a frequency ranking that
  forgets, so a scene that WAS hot ages out instead of pinning budget
  forever.
- **Tier admissions ahead of the fault.**  The top ``device_scenes``
  ranked scenes are promoted into the device cache, the top
  ``host_scenes`` into the host tier, at most
  ``max_device_per_cycle``/``max_host_per_cycle`` issues per cycle —
  strictly bounded, sequential on this one thread.  Promotions ride the
  SAME per-key load futures as demand faults
  (``DeviceWeightCache.get`` / ``HostWeightTier.get_or_load``), so a
  prefetch in flight coalesces with the demand fault it predicted onto
  one load, a mispredicted load can never double-load, and a stalled or
  failing prefetch is isolated exactly like a stalled cold load: it
  stalls THIS thread (and that scene's own demand), never the dispatch
  path, and a failure caches nothing.
- **Health-aware targets.**  Scene -> entries resolution goes through
  ``SceneRegistry.prefetch_targets``: the active version plus any
  in-flight canary (a canary's weights prefetch like any other
  version), minus breaker-tripped keys (never re-stage known-bad
  weights the breaker just purged).
- **Every decision published.**  ``stats()`` rides obs as the
  ``prefetch`` collector: issued/hit/wasted per tier, failures, cycle
  count.

Pure host code: no jax import at module level (the device staging
happens inside ``DeviceWeightCache.get``), no jitted surfaces (nothing
here is an R11 entry point).  Lock discipline (R10/R12/R13): the one
instance lock guards scores/arrivals/credit/counters; cache, tier,
manifest and health locks are only ever taken with the prefetcher lock
RELEASED (targets are snapshotted under the lock, loads run outside) —
the prefetcher adds lock NODES to the committed ``.lock_graph.json``,
never edges.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time

from esac_tpu.obs.trace import issuer_scope


@dataclasses.dataclass(frozen=True)
class PrefetchPolicy:
    """Host-side knobs of the predictive prefetcher.  Like SLOPolicy and
    HealthPolicy it deliberately does NOT ride RansacConfig — nothing
    here may touch the compiled-program hash."""

    # Cycle period of the background thread.  Admissions land between
    # request faults; shorter = fresher, at more wakeups.
    interval_ms: float = 20.0
    # Half-life of the per-scene arrival score decay: the window over
    # which "popular" is judged.
    halflife_s: float = 5.0
    # How many top-ranked scenes to keep DEVICE-resident ahead of their
    # faults.  The operator sizes this to the device byte budget
    # (budget_bytes // scene_nbytes); the cache's LRU still rules — a
    # prefetcher can only stage, never pin.
    device_scenes: int = 2
    # How many top-ranked scenes to keep HOST-resident (None = every
    # scene ever seen; the host tier's own byte budget still rules).
    host_scenes: int | None = None
    # Per-cycle issue caps: the strict bound on concurrent prefetch work
    # (one thread runs them sequentially; these bound each cycle's
    # staging burst so a ranking flip cannot stampede the loader).
    max_device_per_cycle: int = 2
    max_host_per_cycle: int = 4
    # At most this many top-ranked scenes are EXAMINED for host
    # admissions per cycle (each examination resolves the scene through
    # the manifest/health locks): at the fleet scale this module
    # targets — thousands of tracked scenes — an unbounded scan would
    # hammer the serving host's locks every interval even with nothing
    # to stage.  Scenes beyond the window are admitted as they rank up,
    # or on demand (review finding).
    host_scan_limit: int = 64
    # A key the prefetcher just staged is not re-issued for this long:
    # when the device budget is tight, a tail fault can evict a
    # just-promoted hot scene and an eager prefetcher would re-promote
    # it immediately — a promote/evict ping-pong that burns the serving
    # host's cycles for no locality gain.  The cooldown turns that loop
    # into at most one re-promotion per window; a DEMAND fault for the
    # key is never throttled (it rides cache.get as always).
    repromote_cooldown_s: float = 0.25
    # Arrivals buffered between cycles (bounded: a stalled prefetch
    # thread must never grow host memory).
    arrivals_window: int = 10_000

    def __post_init__(self):
        if self.interval_ms <= 0 or self.halflife_s <= 0:
            raise ValueError("interval_ms and halflife_s must be > 0")
        if self.device_scenes < 0:
            raise ValueError(f"device_scenes {self.device_scenes} < 0")
        if self.host_scenes is not None and self.host_scenes < 0:
            raise ValueError(f"host_scenes {self.host_scenes} < 0")
        if self.max_device_per_cycle < 0 or self.max_host_per_cycle < 0:
            raise ValueError("per-cycle caps must be >= 0")
        if self.arrivals_window < 1:
            raise ValueError(f"arrivals_window {self.arrivals_window} < 1")
        if self.host_scan_limit < 1:
            raise ValueError(f"host_scan_limit {self.host_scan_limit} < 1")
        if self.repromote_cooldown_s < 0:
            raise ValueError(
                f"repromote_cooldown_s {self.repromote_cooldown_s} < 0"
            )


class WeightPrefetcher:
    """Background tier-admission driver over a SceneRegistry (see the
    module docstring).  ``start()`` spawns the thread;
    :meth:`run_cycle` is the deterministic single-cycle entry the tests
    drive directly.  ``close()`` stops and joins."""

    def __init__(self, registry, policy: PrefetchPolicy = PrefetchPolicy(),
                 clock=time.monotonic):
        self._registry = registry
        self._policy = policy
        self._clock = clock
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._arrivals: collections.deque = collections.deque(
            maxlen=policy.arrivals_window
        )
        self._scores: dict[str, float] = {}
        self._scored_at: float = clock()
        # key -> tier ("device"|"host") of an issued prefetch that has
        # not yet been claimed by an arrival (hit) or fallen out of
        # residency unclaimed (wasted).
        self._credit: dict = {}
        # key -> last prefetch-issue time (the re-promotion cooldown).
        self._last_issue: dict = {}
        self._stop = False
        self._thread: threading.Thread | None = None
        self.prefetch_issued = collections.Counter()   # by tier
        self.prefetch_hits = 0
        self.prefetch_wasted = 0
        self.prefetch_failures = 0
        self.feed_errors = 0
        self.posterior_feeds = 0
        self.cycles = 0

    # ---- the arrival feed (dispatcher hot path; must never block) ----

    def observe(self, scene) -> None:
        """One scene arrival.  Called by the dispatcher OUTSIDE its own
        lock; a bounded append under this lock — O(1), non-blocking,
        never raises on any input."""
        try:
            t = self._clock()
            with self._lock:
                self._arrivals.append((scene, t, 1.0))
        except Exception:  # noqa: BLE001 — the feed must never hurt serving
            with self._lock:
                self.feed_errors += 1

    def observe_candidates(self, weights) -> None:
        """Posterior-weighted arrivals from the retrieval front (ISSUE
        18, DESIGN.md §22): ``weights`` is ``[(scene, p), ...]`` over
        one image request's candidate posterior.  Each scene's score
        credit is scaled by its posterior mass — an ambiguous query
        stages its runner-up scenes AHEAD of the fault, at a fraction
        of a full arrival, so retrieval uncertainty ranks below real
        demand but above nothing.  Same contract as :meth:`observe`:
        bounded, non-blocking, never raises."""
        try:
            t = self._clock()
            items = [(scene, t, float(w)) for scene, w in weights
                     if w > 0.0]
            with self._lock:
                self._arrivals.extend(items)
                self.posterior_feeds += 1
        except Exception:  # noqa: BLE001 — the feed must never hurt serving
            with self._lock:
                self.feed_errors += 1

    # ---- lifecycle ----

    def start(self) -> "WeightPrefetcher":
        with self._wake:
            if self._thread is None and not self._stop:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="esac-prefetch",
                )
                self._thread.start()
        return self

    def close(self, timeout_s: float | None = 5.0) -> None:
        """Stop the prefetch thread and join it for up to ``timeout_s``.
        A thread wedged inside a stalled load is ABANDONED, never killed
        (the dispatcher-watchdog idiom; it is a daemon thread, and a
        stale cycle completing later is harmless — admissions are
        idempotent and ``_stop`` ends its loop) — an unbounded join here
        would hand the load's wedge to the caller."""
        with self._wake:
            self._stop = True
            self._wake.notify_all()
            thread = self._thread
        # Join OUTSIDE the lock (R13): the thread may be re-acquiring it.
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _loop(self) -> None:
        interval_s = self._policy.interval_ms / 1e3
        while True:
            with self._wake:
                if self._stop:
                    return
                self._wake.wait(interval_s)
                if self._stop:
                    return
            try:
                self.run_cycle()
            except Exception:  # noqa: BLE001 — a sick cycle must not kill the thread
                with self._lock:
                    self.prefetch_failures += 1

    # ---- the cycle ----

    def _fold_arrivals_locked(self, now: float) -> list:
        """Decay scores to ``now`` and fold the buffered arrivals in
        (lock held).  Returns the drained arrival list for credit
        accounting."""
        drained = list(self._arrivals)
        self._arrivals.clear()
        decay = math.exp(-math.log(2.0) * max(now - self._scored_at, 0.0)
                         / self._policy.halflife_s)
        for s in list(self._scores):
            v = self._scores[s] * decay
            if v < 1e-6:
                del self._scores[s]
            else:
                self._scores[s] = v
        self._scored_at = now
        for scene, t, w in drained:
            back = math.exp(-math.log(2.0) * max(now - t, 0.0)
                            / self._policy.halflife_s)
            self._scores[scene] = self._scores.get(scene, 0.0) + back * w
        return drained

    def run_cycle(self) -> dict:
        """One prefetch cycle: fold arrivals -> rank -> bounded device /
        host admissions -> credit accounting.  Loads and staging happen
        with NO prefetcher lock held, through the cache/tier per-key
        futures.  Returns the cycle's decision record (issued keys per
        tier) — the deterministic hook the tests drive."""
        now = self._clock()
        pol = self._policy
        cache = self._registry.cache
        tier = getattr(cache, "tier", None)
        with self._lock:
            drained = self._fold_arrivals_locked(now)
            scores = dict(self._scores)
            credit = dict(self._credit)
            cooled = {
                k for k, t in self._last_issue.items()
                if now - t < pol.repromote_cooldown_s
            }
        ranked = sorted(scores, key=lambda s: (-scores[s], s))
        # Credit the arrivals that a still-resident prefetch absorbed:
        # the prediction was right and the fault never happened.
        hits = []
        for scene, _t, _w in drained:
            for key in list(credit):
                if key[0] == scene and (key in cache or
                                        (tier is not None and key in tier)):
                    hits.append(key)
                    del credit[key]
        issued = {"device": [], "host": []}
        failures = 0
        device_targets = ranked[:pol.device_scenes]
        host_n = len(ranked) if pol.host_scenes is None else pol.host_scenes
        # The scan itself is bounded, not just the issues: every scene
        # examined costs prefetch_targets (health + manifest locks).
        host_targets = ranked[:min(host_n, pol.host_scan_limit)]
        # Issuer mark (ISSUE 15): every per-key load future this cycle
        # creates records the prefetcher as its issuer, so a traced
        # demand fault coalescing onto it is annotated
        # "prefetch-coalesced" instead of reading as a plain disk wait.
        with issuer_scope("prefetch"):
            for scene in device_targets:
                if len(issued["device"]) >= pol.max_device_per_cycle:
                    break
                for entry in self._registry.prefetch_targets(scene):
                    if len(issued["device"]) >= pol.max_device_per_cycle:
                        break
                    if entry.key in cache or entry.key in cooled:
                        continue
                    try:
                        cache.get(entry)  # rides the per-key load future
                        issued["device"].append(entry.key)
                    except Exception:  # noqa: BLE001 — a mispredicted/faulted load is counted, never fatal
                        failures += 1
            if tier is not None:
                for scene in host_targets:
                    if len(issued["host"]) >= pol.max_host_per_cycle:
                        break
                    for entry in self._registry.prefetch_targets(scene):
                        if len(issued["host"]) >= pol.max_host_per_cycle:
                            break
                        if entry.key in tier or entry.key in cache:
                            continue
                        try:
                            cache.preload_host(entry)
                            issued["host"].append(entry.key)
                        except Exception:  # noqa: BLE001
                            failures += 1
        # Wasted: credited keys that left BOTH tiers before any arrival
        # claimed them — the misprediction record.
        wasted = [
            key for key in credit
            if key not in cache and (tier is None or key not in tier)
        ]
        with self._lock:
            for key in hits:
                if key in self._credit:
                    del self._credit[key]
                    self.prefetch_hits += 1
            for key in wasted:
                if key in self._credit:
                    del self._credit[key]
                    self.prefetch_wasted += 1
            for tier_name in ("device", "host"):
                for key in issued[tier_name]:
                    self.prefetch_issued[tier_name] += 1
                    self._credit[key] = tier_name
                    self._last_issue[key] = now
            # Prune expired cooldown stamps: keyed by fleet, but stale
            # (scene, version) keys from old promotes must not pin host
            # memory forever.
            for key in [k for k, t in self._last_issue.items()
                        if now - t >= pol.repromote_cooldown_s]:
                del self._last_issue[key]
            self.prefetch_failures += failures
            self.cycles += 1
        return issued

    # ---- observability ----

    def scores(self) -> dict:
        with self._lock:
            return dict(self._scores)

    def bind_obs(self, metrics, name: str = "prefetch") -> None:
        """Publish the decision stream into an obs MetricsRegistry
        (DESIGN.md §14) as a pull collector."""
        metrics.register_collector(name, self.stats)

    def stats(self) -> dict:
        with self._lock:
            return {
                "issued_device": int(self.prefetch_issued["device"]),
                "issued_host": int(self.prefetch_issued["host"]),
                "hits": self.prefetch_hits,
                "wasted": self.prefetch_wasted,
                "failures": self.prefetch_failures,
                "feed_errors": self.feed_errors,
                "posterior_feeds": self.posterior_feeds,
                "cycles": self.cycles,
                "in_credit": len(self._credit),
                "tracked_scenes": len(self._scores),
                "pending_arrivals": len(self._arrivals),
            }
